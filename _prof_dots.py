import time
import numpy as np
import jax, jax.numpy as jnp
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel, lm_loss

def timeit(f, *a, n=6):
    float(f(*a)[0]); float(f(*a)[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = f(*a)
    float(out[0])
    return (time.perf_counter() - t0) / n * 1000

S, B = 1024, 8
ids = np.random.randint(0, 50304, (B, S)).astype(np.int32)
for policy in ("dots", None):
    cfg = GPT2Config(vocab_size=50304, n_positions=S, n_embd=1280, n_layer=36,
                     n_head=20, dtype=jnp.bfloat16, scan_layers=True,
                     remat=True, remat_policy=policy)
    model = GPT2LMHeadModel(cfg)
    try:
        params = jax.jit(lambda: model.init(jax.random.PRNGKey(0), ids[:1])["params"])()
        jax.block_until_ready(params)
        @jax.jit
        def fwdbwd(p, x):
            def loss_fn(p):
                return lm_loss(model.apply({"params": p}, x), x)
            return jax.value_and_grad(loss_fn)(p)
        tb = timeit(fwdbwd, params, ids)
        fl = 6 * cfg.num_params() * B * S + 12 * 36 * S * 1280 * B * S
        print(f"large policy={policy}: {tb:.0f}ms mfu {fl/(tb/1e3)/197e12*100:.1f}%", flush=True)
    except Exception as e:
        print(f"large policy={policy}: FAILED {str(e)[:80]}", flush=True)
