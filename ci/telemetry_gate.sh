#!/usr/bin/env bash
# Observability fast gate (ISSUE 12 satellite): the jax-free telemetry
# plumbing regressions — a broken --compare path, a viewer that grew a
# jax import, a prometheus page real scrapers reject, metric names that
# rotted out of the docs — gate in <30 s without a bench run or an
# accelerator. Wire it next to ci/regression_gate.sh (which gates the
# MEASURED headline numbers; this script gates the instrumentation).
#
# Usage:
#   ci/telemetry_gate.sh [PRIOR.json] [CANDIDATE.json]
#
# Defaults: the newest two BENCH_r*.json in the repo (identity compare
# when only one exists). Exit nonzero on any failure.
set -eu

REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)
cd "${REPO_DIR}"

newest=$(ls -1 BENCH_r*.json 2>/dev/null | sort | tail -n 2)
PRIOR=${1:-$(echo "${newest}" | head -n 1)}
CANDIDATE=${2:-$(echo "${newest}" | tail -n 1)}
if [ -z "${PRIOR}" ] || [ -z "${CANDIDATE}" ]; then
    echo "telemetry_gate: no BENCH_r*.json artifacts and no args" >&2
    exit 2
fi

echo "== [1/4] bench compare path (jax-free, ${PRIOR} -> ${CANDIDATE})"
# the recorded artifacts span PRs with real metric movement; the gate
# here is "the compare path runs and exits 0 or 3", not the diff itself
rc=0
python bench.py --compare "${PRIOR}" --candidate "${CANDIDATE}" \
    --regression-threshold 0.05 >/dev/null || rc=$?
if [ "${rc}" != 0 ] && [ "${rc}" != 3 ]; then
    echo "telemetry_gate: compare path failed (rc=${rc})" >&2
    exit 1
fi
echo "   ok (rc=${rc})"

echo "== [2/4] viewer import guard (poisoned jax + numpy stubs)"
python - <<'EOF'
import os, subprocess, sys, tempfile
d = tempfile.mkdtemp(prefix="poisoned_deps_")
for name in ("jax", "numpy"):
    with open(os.path.join(d, name + ".py"), "w") as fh:
        fh.write("raise ImportError('poisoned: the viewer must not "
                 "import " + name + "')\n")
env = dict(os.environ)
env["PYTHONPATH"] = d + os.pathsep + env.get("PYTHONPATH", "")
r = subprocess.run(
    [sys.executable, "-c", "import deepspeed_tpu.telemetry.view"],
    env=env, capture_output=True, text=True)
if r.returncode != 0:
    sys.stderr.write("viewer import chain pulled jax/numpy:\n" + r.stderr)
    sys.exit(1)
print("   ok (stdlib-only import chain)")
EOF

echo "== [3/4] perfetto export golden round-trip (poisoned stubs)"
# ISSUE 19: the exporter is deterministic and stdlib-only — render the
# checked-in 2-rank golden dumps via the CLI under poisoned jax/numpy
# and byte-diff against the golden JSON. Regenerate on purposeful
# schema changes with ci/make_perfetto_golden.py.
python - <<'EOF'
import filecmp, os, subprocess, sys, tempfile
d = tempfile.mkdtemp(prefix="poisoned_deps_")
for name in ("jax", "numpy"):
    with open(os.path.join(d, name + ".py"), "w") as fh:
        fh.write("raise ImportError('poisoned: the perfetto export "
                 "path must not import " + name + "')\n")
env = dict(os.environ)
env["PYTHONPATH"] = d + os.pathsep + env.get("PYTHONPATH", "")
out = os.path.join(d, "perfetto_out.json")
r = subprocess.run(
    [sys.executable, "-m", "deepspeed_tpu.telemetry.view",
     "ci/perfetto_golden_dump_rank0.jsonl",
     "ci/perfetto_golden_dump_rank1.jsonl",
     "--format", "perfetto", "--out", out],
    env=env, capture_output=True, text=True)
if r.returncode != 0:
    sys.stderr.write("perfetto export CLI failed:\n" + r.stderr)
    sys.exit(1)
if not filecmp.cmp(out, "ci/perfetto_golden.json", shallow=False):
    sys.stderr.write(
        "perfetto export drifted from ci/perfetto_golden.json — "
        "nondeterminism or an unannounced schema change; if the "
        "change is intentional, regenerate with "
        "ci/make_perfetto_golden.py\n")
    sys.exit(1)
print("   ok (byte-identical to golden, stdlib-only)")
EOF

echo "== [4/4] prometheus grammar + metric-name drift tests"
JAX_PLATFORMS=cpu python -m pytest tests/test_metric_names.py -q \
    -p no:cacheprovider -p no:randomly

echo "telemetry_gate: PASS"
