#!/usr/bin/env bash
# Bench regression gate (PR 6's `bench.py --compare`, runnable as ONE
# command in CI — ISSUE 7 satellite).
#
# Usage:
#   ci/regression_gate.sh PRIOR.json CANDIDATE.json [THRESHOLD]
#
#   PRIOR.json      the baseline result document — a bench-native JSON
#                   (what `python bench.py` prints as its last complete
#                   JSON line) or a driver-captured BENCH_rXX.json
#                   ({"parsed": {...}})
#   CANDIDATE.json  the result document under test, same formats
#   THRESHOLD       fractional worsening that fails the gate
#                   (default 0.05 = 5%)
#
# Exit codes:
#   0  no common headline metric regressed past the threshold
#   3  at least one metric regressed (bench.py's compare exit code)
#   2  usage / unreadable input
#
# The gated metric set is bench.py's headline_metrics(); since r09 it
# includes ``onebit_comm.bytes_reduction`` (ISSUE 10: the hierarchical
# exchange's slow-hop bytes-on-wire reduction, >= 4x — gate against
# BENCH_r09.json or newer to arm it), and since r10
# ``serving.elastic_recovered_fraction`` (ISSUE 11: every request
# survives one replica kill + one graceful drain, must stay 1.0) —
# gate against BENCH_r10.json or newer to arm that one. Since r15 it
# includes ``zero3_hier.inter_bytes_reduction`` (ISSUE 16: the
# link-aware ZeRO-3 prefetch stream's modeled slow-hop bytes vs the
# FLAT single-ring baseline, >= 2x at 2x4 — gate against
# BENCH_r15.json or newer to arm it). Since r16 it includes
# ``serving.disagg_xproc_ttft_p99`` (ISSUE 17: TTFT p99 of the
# disaggregated trace with the handoff crossing 2 REAL OS processes as
# versioned wire frames over the gloo host-bytes collective — gate
# against BENCH_r16.json or newer to arm it). Since r18 it includes
# ``serving.decode_scaleout_tok_s_ratio`` (ISSUE 18: world-3
# aggregate decode tok/s over world-2's single decode rank on the
# LPT-balanced targeted transport, >= 1.6x — gate against
# BENCH_r18.json or newer to arm it). Since r19 it includes
# ``nvme_xl.max_params_b`` (ISSUE 20: largest param count parked +
# twice re-streamed through the O_DIRECT NVMe tier on one chip, must
# stay >= 10B) and ``nvme_param.o_direct_stall_share`` (the O_DIRECT
# pipelined leg's exposed-stall share of the step — the honest-cache
# counterpart of the buffered stall gate) — gate against
# BENCH_r19.json or newer to arm both.
#
# The --candidate path never imports jax and finishes in <2 s, so this
# runs on artifact files on any CI box. Typical wiring:
#
#   python bench.py > bench_out.txt          # on the perf machine
#   tail -n 2 bench_out.txt | head -n 1 > candidate.json
#   ci/regression_gate.sh BENCH_r06.json candidate.json || exit $?
set -u

if [ "$#" -lt 2 ]; then
    echo "usage: $0 PRIOR.json CANDIDATE.json [THRESHOLD]" >&2
    exit 2
fi

PRIOR=$1
CANDIDATE=$2
THRESHOLD=${3:-0.05}
REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)

exec python "${REPO_DIR}/bench.py" \
    --compare "${PRIOR}" \
    --candidate "${CANDIDATE}" \
    --regression-threshold "${THRESHOLD}"
