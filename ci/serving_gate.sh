#!/usr/bin/env bash
# Serving-transport fast gate (ISSUE 17 satellite): the cross-process
# handoff fabric's seconds-scale regressions — a wire-codec change
# that breaks byte-exact round-trips (or silently reads an
# incompatible version instead of refusing it), a HandoffPacket golden
# that drifts from the pool layout (fp32 and int8), a router/* or
# serving/* metric rename that leaves docs/observability.md stale.
# Wire it next to ci/fault_gate.sh (recovery machinery) and
# ci/telemetry_gate.sh (instrumentation): this script gates the WIRE.
# Since ISSUE 18 step 2 also covers the addressed-frame codec
# (dst-targeted vs broadcast delivery + wasted-bytes accounting over
# the loopback fabric) and the N-rank LPT balancer fast tests
# (least-loaded placement, per-rank inflight caps, per-episode
# decode_blocked latching). The REAL-process acceptance legs
# (32-handoff parity + byte-counter cost model; the 3-process
# world-independent wire-cost pin; supervisor SIGKILL of a decode
# rank in world=3 re-balanced onto the survivor token-lossless) live
# in tests/test_serving_transport.py -m slow and ride the full suite.
#
# Usage: ci/serving_gate.sh
# Exit nonzero on any failure. Budget: < 10 s end to end.
set -eu

REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)
cd "${REPO_DIR}"
export JAX_PLATFORMS=cpu

echo "== [1/3] wire codec import guard (no jax backend touch)"
# the codec runs in the LAUNCHER-adjacent bench/parse paths too; like
# the supervisor (ci/fault_gate.sh), encoding/decoding frames must
# never initialize a jax backend (transitive module import is
# tolerated — a LIVE backend is not)
python - <<'EOF'
import sys
from deepspeed_tpu.serving.transport import (FRAME_BASE_NBYTES,
                                             WIRE_VERSION,
                                             decode_frames,
                                             encode_frame,
                                             frame_nbytes)
buf = encode_frame("done", {"rid": 7, "tokens": [1, 2, 3]},
                   src=1, dst=0)
(frame,) = decode_frames(buf)
assert frame["doc"]["rid"] == 7 and frame_nbytes(frame) == len(buf)
assert WIRE_VERSION == 1 and FRAME_BASE_NBYTES > 0
backends = sys.modules.get("jax._src.xla_bridge")
live = getattr(backends, "_backends", None) if backends else None
assert not live, "codec round-trip initialized a jax backend"
print("   ok (round-trip clean, no backend initialized)")
EOF

echo "== [2/3] wire format + HandoffPacket goldens (fp32/int8, prefix-shared)"
python -m pytest tests/test_serving_transport.py -q -m "not slow" \
    -p no:cacheprovider -p no:randomly

echo "== [3/3] metric-name drift (router/* + serving/* vs docs)"
python -m pytest tests/test_metric_names.py -q \
    -k "router or handoff_serving" -p no:cacheprovider -p no:randomly

echo "serving_gate: PASS"
