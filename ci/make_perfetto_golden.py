#!/usr/bin/env python
"""Regenerate the Perfetto-export golden triplet (ISSUE 19 satellite).

Writes two synthetic per-rank flight-recorder dumps — fixed
timestamps, fixed span ids, the same causal shape a 2-process
disaggregated handoff produces (admit → prefill → handoff_out →
transport_encode on rank 0, handoff_in → tick on rank 1) — and the
exporter's output for them:

    ci/perfetto_golden_dump_rank0.jsonl
    ci/perfetto_golden_dump_rank1.jsonl
    ci/perfetto_golden.json

ci/telemetry_gate.sh round-trips the dumps through
``view --format perfetto`` under poisoned jax/numpy stubs and
byte-diffs against the golden JSON — a nondeterministic exporter, a
jax import on the export path, or an unannounced schema change all
fail the gate. Re-run THIS script (and eyeball the diff) when the
trace-event mapping changes on purpose. The dump shape is mirrored by
``_golden_dumps`` in tests/test_trace_plane.py.
"""

import json
import os
import sys

CI_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(CI_DIR))

RANK0 = [
    {"kind": "dump_header", "rule": "worker_exit", "dump_id": 1,
     "source": "rank0e0", "ts": 100.0,
     "provenance": {"git_sha": "abc1234", "hostname": "hostA"},
     "restart_epoch": 0},
    {"ts": 100.0, "kind": "admit", "rid": 0, "trace": "t0",
     "replica": 0, "span_id": "p0-1", "seq": 1},
    {"ts": 100.2, "kind": "prefill", "rid": 0, "trace": "t0",
     "replica": 0, "prefill_s": 0.15, "span_id": "p0-2",
     "parent_span": "p0-1", "seq": 2},
    {"ts": 100.3, "kind": "handoff_out", "rid": 0, "trace": "t0",
     "replica": 0, "span_id": "p0-3", "parent_span": "p0-1",
     "seq": 3},
    {"ts": 100.31, "kind": "transport_encode", "rid": 0,
     "trace": "t0", "dst": 1, "nbytes": 4096, "dur_s": 0.01,
     "span_id": "p0-4", "parent_span": "p0-3", "seq": 4},
    {"ts": 100.9, "kind": "finish", "rid": 0, "trace": "t0",
     "replica": 0, "reason": "length", "span_id": "p0-5",
     "parent_span": "p0-1", "seq": 5},
]
RANK1 = [
    {"kind": "dump_header", "rule": "worker_exit", "dump_id": 1,
     "source": "rank1e0", "ts": 100.0,
     "provenance": {"git_sha": "abc1234", "hostname": "hostA"},
     "restart_epoch": 0},
    {"ts": 100.4, "kind": "handoff_in", "rid": 0, "trace": "t0",
     "replica": 0, "span_id": "d1-1", "parent_span": "p0-4",
     "seq": 1},
    {"ts": 100.5, "kind": "tick", "steps": 1, "active": 1,
     "tick_s": 0.05, "replica": 0, "seq": 2},
]


def main():
    paths = []
    for name, evs in (("perfetto_golden_dump_rank0.jsonl", RANK0),
                      ("perfetto_golden_dump_rank1.jsonl", RANK1)):
        p = os.path.join(CI_DIR, name)
        with open(p, "w") as fh:
            fh.write("\n".join(json.dumps(e) for e in evs) + "\n")
        paths.append(p)
    from deepspeed_tpu.telemetry import perfetto
    doc = perfetto.export(paths)
    assert perfetto.orphan_spans(
        [e for evs in (RANK0, RANK1) for e in evs
         if e["kind"] != "dump_header"]) == []
    golden = os.path.join(CI_DIR, "perfetto_golden.json")
    with open(golden, "w") as fh:
        fh.write(perfetto.dumps(doc) + "\n")
    for p in paths + [golden]:
        print("wrote", os.path.relpath(p, os.path.dirname(CI_DIR)))


if __name__ == "__main__":
    main()
