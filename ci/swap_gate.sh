#!/usr/bin/env bash
# NVMe swap-tier fast gate (ISSUE 20 satellite): the O_DIRECT alignment
# layer, the buffered-fallback latch, and the swapper contracts that
# ride on them — gated in <10 s without an accelerator or a bench run.
# Wire it next to ci/telemetry_gate.sh (instrumentation) and
# ci/regression_gate.sh (measured headlines); this script gates the
# I/O-path CORRECTNESS those headlines depend on.
#
# Usage:
#   ci/swap_gate.sh
#
# Exit nonzero on any failure.
set -eu

REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)
cd "${REPO_DIR}"

echo "== [1/2] aio + swapper import guard (poisoned jax stub)"
# ops/native/aio.py promises jax-free importability (the swap tier must
# construct before — and survive without — an accelerator stack), and
# the swapper module keeps jax behind function-local imports. A jax
# import creeping into either module chain fails here, not in prod.
python - <<'EOF'
import os, subprocess, sys, tempfile
d = tempfile.mkdtemp(prefix="poisoned_deps_")
with open(os.path.join(d, "jax.py"), "w") as fh:
    fh.write("raise ImportError('poisoned: the swap tier must not "
             "import jax at module level')\n")
env = dict(os.environ)
env["PYTHONPATH"] = d + os.pathsep + env.get("PYTHONPATH", "")
r = subprocess.run(
    [sys.executable, "-c",
     "import deepspeed_tpu.ops.native.aio; "
     "import deepspeed_tpu.runtime.swap_tensor.swapper"],
    env=env, capture_output=True, text=True)
if r.returncode != 0:
    sys.stderr.write("swap-tier import chain pulled jax:\n" + r.stderr)
    sys.exit(1)
print("   ok (jax-free import chain)")
EOF

echo "== [2/2] O_DIRECT alignment / fallback / swapper contract tests"
# the snapshot case needs jax — the tier-1 run owns it; everything else
# in the file is accelerator-free and fast
JAX_PLATFORMS=cpu python -m pytest tests/test_o_direct.py -q \
    -k "not snapshot" -p no:cacheprovider -p no:randomly

echo "swap_gate: PASS"
