#!/usr/bin/env bash
# Fault-tolerance fast gate (ISSUE 15 satellite): the self-healing
# plane's regressions — a fault-injection point that stopped firing, a
# hang watchdog that no longer trips (or trips on the compile-exempt
# first region), a supervisor state machine that leaks orphans/stale
# heartbeats or loses the crash-loop bound, a rendezvous retry that
# started retrying config errors — gate in seconds without an engine
# compile or a 2-process rendezvous. Wire it next to
# ci/regression_gate.sh (measured numbers) and ci/telemetry_gate.sh
# (instrumentation): this script gates the RECOVERY machinery. The
# slow 2-process acceptance legs (SIGKILL auto-recovery with the loss
# trajectory preserved; in-collective hang detection) live in
# tests/test_fault_tolerance.py -m slow and ride the full suite.
#
# Usage: ci/fault_gate.sh
# Exit nonzero on any failure.
set -eu

REPO_DIR=$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)
cd "${REPO_DIR}"

echo "== [1/2] supervisor/hang import guard (no jax backend touch)"
# the supervisor runs in the LAUNCHER process; on a TPU-VM libtpu takes
# an exclusive per-process lock, so importing these modules must never
# initialize a jax backend (module import alone is tolerated)
python - <<'EOF'
import sys
import deepspeed_tpu.runtime.elastic.supervisor as sup
import deepspeed_tpu.runtime.elastic.hang as hang
from deepspeed_tpu.runtime.elastic import faults
assert hang.EXIT_HANG != sup.EXIT_CRASH_LOOP
jax = sys.modules.get("jax")
if jax is not None:
    # imported transitively is fine; an INITIALIZED backend is not
    backends = sys.modules.get("jax._src.xla_bridge")
    live = getattr(backends, "_backends", None) if backends else None
    assert not live, "supervisor import chain initialized a jax backend"
print("   ok (no backend initialized)")
EOF

echo "== [2/2] fast fault-tolerance tests (injection registry, hang"
echo "   watchdog, supervisor state machine, rendezvous retry, viewer)"
JAX_PLATFORMS=cpu python -m pytest tests/test_fault_tolerance.py -q \
    -m 'not slow' -p no:cacheprovider -p no:randomly

echo "fault_gate: PASS"
