import time
import numpy as np
import jax, jax.numpy as jnp
import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

dev = jax.devices()[0]
mesh = make_mesh(MeshConfig(data=1), devices=[dev])
seq, B = 1024, 8
model_cfg = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=1024,
                       n_layer=24, n_head=16, dtype=jnp.bfloat16,
                       scan_layers=True, remat=True)
cfg = {"train_batch_size": B, "zero_optimization": {"stage": 3},
       "bf16": {"enabled": True}, "gradient_clipping": 1.0,
       "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
       "steps_per_print": 1000}
model = GPT2LMHeadModel(model_cfg)
engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
rng = np.random.RandomState(0)
batch = {"input_ids": rng.randint(0, 50304, size=(B, seq)).astype(np.int32)}
batch_j = jax.tree_util.tree_map(jnp.asarray, batch)
engine._ensure_ready(batch_j)

r = jax.random.PRNGKey(1)

# time grads-only compiled fn
g = engine._jit_grads_batch(engine.state, batch_j, r)
float(g[1])
t0 = time.perf_counter()
for _ in range(5):
    g = engine._jit_grads_batch(engine.state, batch_j, r)
float(g[1])
print(f"grads_batch: {(time.perf_counter()-t0)/5*1000:.1f}ms", flush=True)

# time full train step compiled fn (donating copies of state)
st, m = engine._jit_train_batch(engine.state, batch_j, r)
float(m["loss"])
t0 = time.perf_counter()
for _ in range(5):
    st, m = engine._jit_train_batch(st, batch_j, r)
float(m["loss"])
print(f"train_batch jit: {(time.perf_counter()-t0)/5*1000:.1f}ms", flush=True)

engine.state = st
# full wrapper
t0 = time.perf_counter()
for _ in range(5):
    engine.train_batch(batch)
jax.block_until_ready(engine.state.params)
print(f"train_batch wrapper: {(time.perf_counter()-t0)/5*1000:.1f}ms", flush=True)
