"""Packaging — the reference ships setup.py with AOT op builds (setup.py:89);
here there is nothing to precompile for the JAX path, and the native C++
host libraries (deepspeed_tpu/csrc) build lazily via the op builder at
first use (deepspeed_tpu/ops/native)."""

from setuptools import setup, find_packages

setup(
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native large-model training framework "
                "(DeepSpeed-capability rebuild on JAX/XLA/Pallas)",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax", "flax", "numpy"],
    entry_points={
        "console_scripts": [
            "dstpu=deepspeed_tpu.launcher.runner:main",
            "dstpu_launch=deepspeed_tpu.launcher.launch:main",
            "dstpu_report=deepspeed_tpu.env_report:main",
            "dstpu_elastic=deepspeed_tpu.elasticity.cli:main",
        ],
    },
)
