"""Unified-telemetry demo: a 20-step GPT-2 run with every observability
gate on — the per-step metrics registry exporting a JSONL stream +
TensorBoard-or-JSONL scalars, span/phase annotations, MFU from the
compiled step's cost analysis, and a programmatic XLA trace window over
steps [2, 4).

Run:  python examples/observability_demo.py --out /tmp/telemetry_demo

Artifacts under --out:
- ``telemetry_rank0.jsonl``  — one snapshot line per steps_per_print
  boundary ({ts, rank, step, metrics}); the scalar stream to merge/plot
- ``scalars/``               — SummaryEventWriter output (TensorBoard
  events when tensorboard is installed, tagged JSONL otherwise)
- ``trace/``                 — the XLA trace window (open in
  perfetto / tensorboard-profile; span + named_scope labels inside)
- ``metrics.prom``           — final Prometheus-format dump
- stdout                     — the final registry snapshot as JSON
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.models import gpt2 as gpt2_lib
from deepspeed_tpu.telemetry import prometheus_text


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/telemetry_demo")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4)
    args = ap.parse_args()

    model_cfg = gpt2_lib.gpt2_tiny(dtype=jnp.float32, scan_layers=True)
    config = {
        "train_batch_size": args.batch,
        "steps_per_print": 5,
        # measurement mode: real fenced per-phase forward/backward/
        # optimizer times feed the span/train/* histograms
        "wall_clock_breakdown": True,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "monitor": {
            "jsonl_path": os.path.join(args.out, "telemetry_rank0.jsonl"),
        },
        "tensorboard": {
            "enabled": True,
            "output_path": os.path.join(args.out, "scalars"),
            "job_name": "observability_demo",
        },
        "profiling": {
            "trace_dir": os.path.join(args.out, "trace"),
            "trace_steps": [2, 4],
        },
    }
    model = gpt2_lib.GPT2LMHeadModel(model_cfg)
    engine, _, _, _ = dstpu.initialize(config=config, model=model)

    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, model_cfg.vocab_size,
        size=(args.batch, model_cfg.n_positions)).astype(np.int32)}
    for _ in range(args.steps):
        engine.train_batch(batch)

    snap = engine.telemetry_flush(batch)
    with open(os.path.join(args.out, "metrics.prom"), "w") as f:
        f.write(prometheus_text(snapshot=snap))
    print(json.dumps(snap, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
