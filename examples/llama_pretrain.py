"""LLaMA pretraining example — the reference trains LLaMA-family models
through HF + ZeRO (deepspeed/module_inject/containers/llama.py supplies
the serving side); here the in-tree flax family
(deepspeed_tpu/models/llama.py) trains under ZeRO-2/3 with optional
tensor/sequence parallel axes, on synthetic token streams.

Run:  python examples/llama_pretrain.py --steps 20 --zero 3
GQA:  python examples/llama_pretrain.py --kv-heads 2
Multi-host: dstpu --hostfile hf examples/llama_pretrain.py --zero 3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.models import llama as llama_lib


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="0 = MHA; fewer than --heads = GQA")
    ap.add_argument("--zero", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--repeat-batch", action="store_true",
                    help="train on one fixed batch (smoke-test convergence)")
    dstpu.add_config_arguments(ap)
    args = ap.parse_args()

    model_cfg = llama_lib.LlamaConfig(
        vocab_size=2048, hidden_size=args.hidden,
        intermediate_size=int(args.hidden * 8 / 3 // 32 * 32) or 64,
        n_layers=args.layers, n_heads=args.heads,
        n_kv_heads=args.kv_heads, max_seq_len=max(args.seq, 128),
        dtype=jnp.bfloat16, remat=True, loss_chunk=min(args.seq, 512))
    config = {
        "train_batch_size": args.batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": args.zero},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 3e-4, "weight_decay": 0.01}},
        "mesh": {"data": -1, "model": args.tp, "seq": args.sp},
        "steps_per_print": 5,
    }
    engine, _, _, _ = dstpu.initialize(
        config=config, model=llama_lib.LlamaForCausalLM(model_cfg))

    rng = np.random.RandomState(0)
    fixed = {"input_ids": rng.randint(
        0, model_cfg.vocab_size,
        size=(args.batch, args.seq)).astype(np.int32)}
    first = None
    for step in range(args.steps):
        batch = fixed if args.repeat_batch else {"input_ids": rng.randint(
            0, model_cfg.vocab_size,
            size=(args.batch, args.seq)).astype(np.int32)}
        loss = engine.train_batch(batch)
        if first is None:
            first = float(loss)
    print(f"first loss: {first:.4f}")
    print(f"final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
