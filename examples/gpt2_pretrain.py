"""GPT-2 pretraining example — the Megatron-GPT2 configs of the reference
perf harness (BASELINE.json config 3): GPT-2 under ZeRO-2/3 with optional
tensor/sequence parallel axes, on synthetic token streams.

Run:  python examples/gpt2_pretrain.py --model medium --zero 3 --steps 20
Multi-host: dstpu --hostfile hf examples/gpt2_pretrain.py --zero 3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.models import gpt2 as gpt2_lib


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="small",
                    choices=["tiny", "small", "medium", "large", "xl"])
    ap.add_argument("--zero", type=int, default=2)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--repeat-batch", action="store_true",
                    help="train on one fixed batch (smoke-test convergence)")
    dstpu.add_config_arguments(ap)
    args = ap.parse_args()

    cfg_fn = {"tiny": gpt2_lib.gpt2_tiny, "small": gpt2_lib.gpt2_small,
              "medium": gpt2_lib.gpt2_medium, "large": gpt2_lib.gpt2_large,
              "xl": gpt2_lib.gpt2_xl}[args.model]
    model_cfg = cfg_fn(dtype=jnp.bfloat16, remat=True,
                       n_positions=max(args.seq, 128))
    config = {
        "train_batch_size": args.batch,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": args.zero},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.01}},
        "mesh": {"data": -1, "model": args.tp, "seq": args.sp},
        "steps_per_print": 5,
    }
    engine, _, _, _ = dstpu.initialize(
        config=config, model=gpt2_lib.GPT2LMHeadModel(model_cfg))

    rng = np.random.RandomState(0)
    fixed = {"input_ids": rng.randint(
        0, model_cfg.vocab_size,
        size=(args.batch, args.seq)).astype(np.int32)}
    first = None
    for step in range(args.steps):
        batch = fixed if args.repeat_batch else {"input_ids": rng.randint(
            0, model_cfg.vocab_size,
            size=(args.batch, args.seq)).astype(np.int32)}
        loss = engine.train_batch(batch)
        if first is None:
            first = float(loss)
    print(f"first loss: {first:.4f}")
    print(f"final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
