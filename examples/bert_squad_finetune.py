"""BERT SQuAD-style fine-tuning example — the reference's BingBertSquad e2e
(BASELINE.json config 2): BertForQuestionAnswering through the fused encoder
layer, ZeRO-1, synthetic QA spans (swap in real SQuAD features via any
loader yielding the same dict).

Run: python examples/bert_squad_finetune.py [--steps N] [--zero 1]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.models.bert import bert_tiny, BertForQuestionAnswering


def qa_loss(outputs, batch):
    start_logits, end_logits = outputs

    def span_nll(logits, pos):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, pos[:, None], axis=-1).mean()

    return span_nll(start_logits, batch["start_positions"]) \
        + span_nll(end_logits, batch["end_positions"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--zero", type=int, default=1)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--repeat-batch", action="store_true",
                    help="train on one fixed batch (smoke-test convergence)")
    dstpu.add_config_arguments(ap)
    args = ap.parse_args()

    model_cfg = bert_tiny(max_position_embeddings=args.seq)
    config = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": args.zero},
        "optimizer": {"type": "Adam", "params": {"lr": 3e-5}},
        "scheduler": {"type": "WarmupDecayLR",
                      "params": {"warmup_num_steps": 5,
                                 "total_num_steps": args.steps}},
        "steps_per_print": 5,
    }

    model = BertForQuestionAnswering(model_cfg)

    def loss_fn(params, batch):
        outputs = model.apply({"params": params}, batch["input_ids"],
                              batch["attention_mask"])
        return qa_loss(outputs, batch)

    engine, _, _, _ = dstpu.initialize(config=config, model=model,
                                       loss_fn=loss_fn)

    rng = np.random.RandomState(0)

    def sample():
        return {
            "input_ids": rng.randint(0, model_cfg.vocab_size,
                                     (8, args.seq)).astype(np.int32),
            "attention_mask": np.ones((8, args.seq), np.int32),
            "start_positions": rng.randint(0, args.seq, (8,)).astype(np.int32),
            "end_positions": rng.randint(0, args.seq, (8,)).astype(np.int32),
        }

    fixed = sample()
    first = None
    for step in range(args.steps):
        loss = engine.train_batch(fixed if args.repeat_batch else sample())
        if first is None:
            first = float(loss)
    print(f"first loss: {first:.4f}")
    print(f"final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
