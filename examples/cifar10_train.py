"""CIFAR10-class example — the reference's DeepSpeedExamples/cifar entry
(BASELINE.json config 1): a small conv/MLP classifier trained through the
engine on synthetic 32x32x3 data (no dataset download; swap in real CIFAR
via any loader yielding (images, labels)).

Run: python examples/cifar10_train.py [--steps N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp
import flax.linen as nn

import deepspeed_tpu as dstpu


class Net(nn.Module):
    @nn.compact
    def __call__(self, x):                      # [B, 32, 32, 3]
        x = nn.Conv(32, (3, 3))(x)
        x = nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = nn.Conv(64, (3, 3))(x)
        x = nn.relu(nn.max_pool(x, (2, 2), strides=(2, 2)))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256)(x))
        return nn.Dense(10)(x)


def synthetic_cifar(n=512, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, 32, 32, 3).astype(np.float32)
    ys = rng.randint(0, 10, size=(n,)).astype(np.int32)
    return [(xs[i], ys[i]) for i in range(n)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    dstpu.add_config_arguments(ap)
    args = ap.parse_args()

    config = args.deepspeed_config or {
        "train_batch_size": 32,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
        "steps_per_print": 10,
    }
    engine, _, loader, _ = dstpu.initialize(
        args=args, config=config, model=Net(),
        training_data=synthetic_cifar())
    it = iter(dstpu.runtime.dataloader.RepeatingLoader(loader))
    first = None
    for step in range(args.steps):
        loss = engine.train_batch(next(it))
        if first is None:
            first = float(loss)
    print(f"first loss: {first:.4f}")
    print(f"final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
