"""Pipeline-parallel GPT-2 training example — the reference's
PipelineModule/LayerSpec workflow (deepspeed/runtime/pipe) on the 1F1B SPMD
executor.

Two equivalent routes:
  --route model    GPT2PipeModel (the in-tree pipelined GPT-2)
  --route generic  a LayerSpec-built PipelineModule whose homogeneous
                   trunk is lowered onto the executor automatically

Run (defaults: pipe=2 x data=2 on 4 virtual CPU devices; add --tp 2 and
force 8 devices for the full 3D mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python examples/gpt2_pipeline.py --route model --steps 10
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# honor JAX_PLATFORMS=cpu even on machines whose sitecustomize pre-selects
# a hardware plugin (env alone does not switch an already-latched platform)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--route", default="model", choices=["model", "generic"])
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--pipe", type=int, default=2)
    ap.add_argument("--data", type=int, default=2)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--microbatches", type=int, default=2)
    args = ap.parse_args()

    n_dev = args.pipe * args.data * args.tp
    devs = jax.devices()[:n_dev]
    assert len(devs) == n_dev, (
        f"need {n_dev} devices (set "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={n_dev})")
    mesh = make_mesh(MeshConfig(pipe=args.pipe, data=args.data,
                                model=args.tp), devices=devs)

    cfg = {
        "train_batch_size": 4 * args.data,
        "zero_optimization": {"stage": 1},
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
        "steps_per_print": 5,
    }

    rng = np.random.RandomState(0)
    if args.route == "model":
        from deepspeed_tpu.models.gpt2 import GPT2Config
        from deepspeed_tpu.models.gpt2_pipe import GPT2PipeModel
        mcfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=128,
                          n_layer=4, n_head=4, dtype=jnp.bfloat16)
        model = GPT2PipeModel(mcfg, mesh,
                              num_microbatches=args.microbatches)
        batch = {"input_ids": rng.randint(
            0, 512, (4 * args.data, 128)).astype(np.int32)}
    else:
        import flax.linen as nn
        from deepspeed_tpu import PipelineModule, LayerSpec

        def loss_fn(out, y):
            logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
            return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

        layers = [LayerSpec(nn.Dense, 64)] + \
            [LayerSpec(nn.Dense, 64) for _ in range(4)] + \
            [LayerSpec(nn.Dense, 8)]
        model = PipelineModule(layers=layers, loss_fn=loss_fn,
                               num_microbatches=args.microbatches)
        batch = (rng.randn(4 * args.data, 64).astype(np.float32),
                 rng.randint(0, 8, (4 * args.data,)).astype(np.int32))

    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
    first = None
    for step in range(args.steps):
        loss = engine.train_batch(batch)
        if first is None:
            first = float(jax.device_get(loss))
    print(f"first loss: {first:.4f}")
    print(f"final loss after {args.steps} steps: "
          f"{float(jax.device_get(loss)):.4f}")


if __name__ == "__main__":
    main()
