"""CI-style guards on suite collection.

1. The whole suite must COLLECT cleanly: a single bad import (e.g. the
   `from jax import shard_map` that broke tests/test_csr.py on the
   pinned jax 0.4.37) silently gates every test in the affected module;
   with `--continue-on-collection-errors` in the tier-1 runner the suite
   still "passes" while whole files never run.
2. Every test FILE that slow-marks anything must still collect at least
   one fast (non-slow) test: the tier-1 runner deselects `-m 'not
   slow'`, so a file whose tests all drift behind @pytest.mark.slow
   drops out of tier-1 entirely — coverage evaporating one decorator at
   a time, with the suite still green.

Both guards read ONE subprocess collection (`--collect-only -q -m 'not
slow'`): it fails loudly on any collection error, reports the total
collected count (before deselection), and lists the surviving fast node
ids per file.
"""

import functools
import os
import re
import subprocess
import sys

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TESTS_DIR)


@functools.lru_cache(maxsize=1)
def _collect_fast():
    """(total_collected, {file -> fast node count}) from one subprocess
    collection — shared by both guards (a full re-collect costs ~35 s of
    suite imports, and the tier-1 wall is a real budget)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", "-p", "no:xdist",
         "-p", "no:randomly", "tests/"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    out = proc.stdout + proc.stderr
    # without --continue-on-collection-errors any collection error → rc != 0
    assert proc.returncode == 0, \
        f"collection failed (rc={proc.returncode}):\n{out[-4000:]}"
    # "N/M tests collected (X deselected)" with -m; "M tests collected"
    # without any deselection
    m = re.search(r"(?:(\d+)/)?(\d+) tests collected", out)
    assert m, out[-2000:]
    total = int(m.group(2))
    fast_per_file = {}
    for line in proc.stdout.splitlines():
        if "::" in line:
            fname = line.split("::", 1)[0].split("/")[-1]
            fast_per_file[fname] = fast_per_file.get(fname, 0) + 1
    return total, fast_per_file


def test_suite_collects_without_errors():
    total, _ = _collect_fast()
    assert total >= 438, total


def test_slow_marked_files_keep_fast_coverage():
    _, fast_per_file = _collect_fast()
    slow_files = []
    for name in sorted(os.listdir(TESTS_DIR)):
        if not (name.startswith("test_") and name.endswith(".py")):
            continue
        with open(os.path.join(TESTS_DIR, name)) as f:
            if "pytest.mark.slow" in f.read():
                slow_files.append(name)
    assert slow_files, "expected at least one slow-marked file in tests/"
    orphaned = [f for f in slow_files if not fast_per_file.get(f)]
    assert not orphaned, (
        f"these files slow-mark tests and no longer collect ANY fast "
        f"test — tier-1 lost them entirely: {orphaned}. Keep (or add) a "
        f"fast sibling test per file, or un-mark something.")
