"""CI-style guard: the whole suite must COLLECT cleanly.

A single bad import (e.g. the `from jax import shard_map` that broke
tests/test_csr.py on the pinned jax 0.4.37) silently gates every test in
the affected module; with `--continue-on-collection-errors` in the tier-1
runner the suite still "passes" while whole files never run. This test
re-collects the suite in a subprocess and fails loudly on any collection
error, so a future incompatible import cannot hide."""

import os
import re
import subprocess
import sys


def test_suite_collects_without_errors():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(tests_dir)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-p", "no:cacheprovider", "tests/"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600)
    out = proc.stdout + proc.stderr
    # without --continue-on-collection-errors any collection error → rc != 0
    assert proc.returncode == 0, \
        f"collection failed (rc={proc.returncode}):\n{out[-4000:]}"
    m = re.search(r"(\d+) tests collected", out)
    assert m, out[-2000:]
    assert int(m.group(1)) >= 438, out[-2000:]
