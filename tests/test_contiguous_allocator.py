"""ContiguousMemoryAllocator tests — alloc/release/merge/defragment
semantics of the reference arena (zero/contiguous_memory_allocator.py:9)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import (
    ContiguousMemoryAllocator,
)


def test_alloc_and_release_roundtrip():
    a = ContiguousMemoryAllocator(100)
    t1, v1 = a.allocate_tensor(40)
    t2, v2 = a.allocate_tensor(40)
    assert a.total_free == 20
    v1[:] = 1.0
    v2[:] = 2.0
    a.release_tensor(t1)
    assert a.total_free == 60
    np.testing.assert_array_equal(a.get_tensor(t2), np.full(40, 2.0))


def test_free_block_merging():
    a = ContiguousMemoryAllocator(100)
    t1, _ = a.allocate_tensor(30)
    t2, _ = a.allocate_tensor(30)
    t3, _ = a.allocate_tensor(30)
    a.release_tensor(t1)
    a.release_tensor(t3)       # tail merge with the trailing 10
    a.release_tensor(t2)       # middle release merges everything
    assert a.free_blocks == {0: 100}


def test_defragment_preserves_contents():
    a = ContiguousMemoryAllocator(100)
    ids = []
    for i in range(5):
        tid, v = a.allocate_tensor(20)
        v[:] = float(i)
        ids.append(tid)
    # free alternating tensors → fragmentation: free total 40, largest 20
    a.release_tensor(ids[1])
    a.release_tensor(ids[3])
    assert a._largest_free() == 20
    # needs 40 contiguous → triggers defragment
    tid, v = a.allocate_tensor(40)
    v[:] = 9.0
    for i in (0, 2, 4):
        np.testing.assert_array_equal(a.get_tensor(ids[i]),
                                      np.full(20, float(i)))
    np.testing.assert_array_equal(a.get_tensor(tid), np.full(40, 9.0))
    assert a.total_free == 0


def test_exhaustion_asserts():
    a = ContiguousMemoryAllocator(10)
    a.allocate_tensor(8)
    with pytest.raises(AssertionError):
        a.allocate_tensor(4)


def test_views_alias_arena():
    a = ContiguousMemoryAllocator(16)
    tid, v = a.allocate_tensor(16)
    v[:] = 7.0
    assert a.buffer[0] == 7.0
