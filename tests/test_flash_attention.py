"""Pallas kernel numerics vs jnp reference — the reference's
test_cuda_forward.py / test_cuda_backward.py methodology (CUDA-vs-HF becomes
Pallas-interpret-vs-jnp, SURVEY §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.attention import reference_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention


def _qkv(shape=(2, 2, 128, 32), seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, interpret=True, block_q=64,
                          block_k=64)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_reference(causal):
    q, k, v = _qkv(shape=(1, 2, 128, 16))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True,
                                       block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=5e-3, atol=5e-4)


def test_flash_uneven_shape_falls_back():
    q, k, v = _qkv(shape=(1, 1, 100, 16))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True, block_q=64,
                          block_k=64)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_blocksparse_kernel_dense_layout_matches_reference():
    q, k, v = _qkv(shape=(1, 2, 128, 16))
    layout = np.ones((2, 4, 4), np.int64)  # block 32, fully dense
    out = blocksparse_attention(q, k, v, layout, block=32, interpret=True)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_blocksparse_kernel_respects_layout():
    q, k, v = _qkv(shape=(1, 1, 128, 16), seed=3)
    layout = np.zeros((1, 4, 4), np.int64)
    for i in range(4):
        layout[0, i, i] = 1
    out = blocksparse_attention(q, k, v, layout, block=32, interpret=True)
    # block-diagonal attention == attention computed per 32-wide chunk
    for i in range(4):
        sl = slice(32 * i, 32 * (i + 1))
        ref = reference_attention(q[:, :, sl], k[:, :, sl], v[:, :, sl])
        np.testing.assert_allclose(np.asarray(out[:, :, sl]), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


def test_chunked_kernels_match_reference():
    """The long-S chunked kernels (third grid dim, revisited fp32 output
    accumulation) must match the jnp reference fwd AND grads — forced via
    chunk= on small shapes so CI covers the same code path the S*D > 256k
    dispatch takes on hardware."""
    from deepspeed_tpu.ops.attention import reference_attention
    rng = np.random.RandomState(0)
    B, H, S, D = 2, 2, 256, 16
    q = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, S, D), jnp.float32)
    for causal in (False, True):
        def loss_k(q, k, v):
            o = flash_attention(q, k, v, causal=causal, block_q=64,
                                block_k=64, chunk=128, interpret=True)
            return jnp.sum(jnp.sin(o))

        def loss_r(q, k, v):
            return jnp.sum(jnp.sin(reference_attention(q, k, v,
                                                       causal=causal)))

        v1, g1 = jax.value_and_grad(loss_k, argnums=(0, 1, 2))(q, k, v)
        v2, g2 = jax.value_and_grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        np.testing.assert_allclose(v1, v2, rtol=2e-5, atol=2e-5)
        for a, b, name in zip(g1, g2, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5,
                                       err_msg=f"causal={causal} d{name}")


def test_auto_chunk_dispatch(monkeypatch):
    """The S*D*itemsize budget dispatch really selects the chunked path
    (and its chunk satisfies the divisibility constraints) — exercised in
    CI by shrinking the budget instead of allocating 32k sequences."""
    import importlib
    fa = importlib.import_module(
        "deepspeed_tpu.ops.pallas.flash_attention")
    calls = {}
    real = fa._flash_fwd_chunked

    def spy(q, k, v, scale, causal, block_q, block_k, chunk, interpret):
        calls["chunk"] = chunk
        return real(q, k, v, scale, causal, block_q, block_k, chunk,
                    interpret)

    monkeypatch.setattr(fa, "_flash_fwd_chunked", spy)
    # dispatch cutoff shrunk so S=512 routes to the chunked path, and
    # chunk budget/2 // (D*itemsize) = 128 rows -> candidate 128 picked
    monkeypatch.setattr(fa, "_UNCHUNKED_ROW_BYTES", 128 * 2 * 16 * 4)
    monkeypatch.setattr(fa, "_CHUNK_ROW_BYTES", 128 * 2 * 16 * 4)
    from deepspeed_tpu.ops.attention import reference_attention
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 512, 16), jnp.float32)
    o = fa.flash_attention(q, q, q, causal=True, block_q=64, block_k=64,
                           interpret=True)
    assert calls.get("chunk") == 128, calls
    ref = reference_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_user_chunk_validation():
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    q = jnp.zeros((1, 1, 192, 16), jnp.float32)
    with pytest.raises(ValueError, match="chunk"):
        flash_attention(q, q, q, block_q=64, block_k=64, chunk=128,
                        interpret=True)


def test_flash_gqa_forward_matches_reference():
    """Hkv < H: the kernel consumes REDUCED-head K/V via Hkv-aware block
    maps. Numerics must equal the repeat-then-attend reference."""
    B, H, Hkv, S, D = 2, 8, 2, 128, 32
    q, _, _ = _qkv((B, H, S, D), seed=1)
    _, k, v = _qkv((B, Hkv, S, D), seed=2)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          block_q=64, block_k=64)
    ref = reference_attention(q, k, v, causal=True)   # repeats internally
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_gqa_forward_never_materializes_full_head_kv():
    """The GQA memory promise (models/llama.py): the forward's
    pallas_call streams K/V at [B*Hkv, S, D] — no full-head copy exists
    anywhere in the forward jaxpr."""
    B, H, Hkv, S, D = 2, 8, 2, 128, 32
    q, _, _ = _qkv((B, H, S, D), seed=1)
    _, k, v = _qkv((B, Hkv, S, D), seed=2)

    jaxpr = jax.make_jaxpr(
        lambda a, b, c: flash_attention(a, b, c, causal=True,
                                        interpret=True, block_q=64,
                                        block_k=64))(q, k, v)

    def walk(jx):
        for eqn in jx.eqns:
            yield eqn
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    yield from walk(sub.jaxpr)

    pallas_eqns = [e for e in walk(jaxpr.jaxpr)
                   if "pallas" in e.primitive.name]
    assert pallas_eqns, "flash kernel not dispatched"
    kv_shape = (B * Hkv, S, D)
    full_shape = (B * H, S, D)
    kv_ins = [tuple(v_.aval.shape) for v_ in pallas_eqns[0].invars]
    assert kv_ins.count(kv_shape) == 2, kv_ins   # k and v, reduced
    # nothing anywhere in the fwd COMPUTES a full-head K/V-sized array:
    # the only producers of that shape are q's own flatten-reshape and
    # the attention output o passing through the wrapper levels — no
    # repeat/broadcast/gather (what a K/V head-repeat lowers to)
    producers = {e.primitive.name for e in walk(jaxpr.jaxpr)
                 for ov in e.outvars
                 if tuple(ov.aval.shape) == full_shape}
    # (custom_vjp_call spells itself custom_vjp_call_jaxpr on jax <= 0.4.x)
    assert producers <= {"reshape", "custom_vjp_call",
                         "custom_vjp_call_jaxpr", "pallas_call"}, producers


def test_flash_gqa_backward_matches_reference():
    """dk/dv come back at the REDUCED head count (summed over the rep
    query heads); grads must match autodiff through the reference."""
    B, H, Hkv, S, D = 1, 4, 2, 128, 32
    q, _, _ = _qkv((B, H, S, D), seed=3)
    _, k, v = _qkv((B, Hkv, S, D), seed=4)

    def loss_fl(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True, block_q=64,
                                       block_k=64).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(
            q, k, v, causal=True).astype(jnp.float32) ** 2)

    g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert g_fl[1].shape == (B, Hkv, S, D)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)
