"""BERT model family tests — fwd shapes, MLM training convergence through the
engine, scan/remat variants (reference: tests/unit/modeling.py fixtures +
BingBertSquad e2e, SURVEY §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.models.bert import (
    bert_tiny, BertForPreTraining, BertForQuestionAnswering,
    BertForSequenceClassification, BertModel, mlm_loss, pretraining_loss,
)


def _batch(cfg, B=4, S=32, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.int32)
    mask[:, S - 4:] = 0
    types = np.zeros((B, S), np.int32)
    labels = np.full((B, S), -100, np.int32)
    mlm_pos = rng.rand(B, S) < 0.15
    labels[mlm_pos] = ids[mlm_pos]
    return {"input_ids": jnp.asarray(ids),
            "attention_mask": jnp.asarray(mask),
            "token_type_ids": jnp.asarray(types),
            "mlm_labels": jnp.asarray(labels),
            "nsp_labels": jnp.asarray(rng.randint(0, 2, (B,)).astype(np.int32))}


@pytest.mark.parametrize("pre_ln", [False, True])
def test_backbone_shapes(pre_ln):
    cfg = bert_tiny(pre_layer_norm=pre_ln, dtype=jnp.float32)
    model = BertModel(cfg)
    b = _batch(cfg)
    params = model.init(jax.random.PRNGKey(0), b["input_ids"])
    seq, pooled = model.apply(params, b["input_ids"], b["attention_mask"],
                              b["token_type_ids"])
    assert seq.shape == (4, 32, cfg.hidden_size)
    assert pooled.shape == (4, cfg.hidden_size)
    n_actual = sum(int(np.prod(a.shape))
                   for a in jax.tree_util.tree_leaves(params))
    assert n_actual == cfg.num_params(), (n_actual, cfg.num_params())


def test_pretraining_heads_and_tying():
    cfg = bert_tiny(dtype=jnp.float32)
    model = BertForPreTraining(cfg)
    b = _batch(cfg)
    params = model.init(jax.random.PRNGKey(0), b["input_ids"])
    mlm, nsp = model.apply(params, b["input_ids"], b["attention_mask"],
                           b["token_type_ids"])
    assert mlm.shape == (4, 32, cfg.vocab_size)
    assert nsp.shape == (4, 2)
    # tied decoder: no independent [V, E] decoder matrix in the param tree
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    big = [(p, a.shape) for p, a in flat
           if a.ndim == 2 and cfg.vocab_size in a.shape]
    assert len(big) == 1, f"expected only the embedding table, got {big}"
    loss = pretraining_loss((mlm, nsp), b)
    assert np.isfinite(float(loss))


def test_qa_and_classification_heads():
    cfg = bert_tiny(dtype=jnp.float32)
    b = _batch(cfg)
    qa = BertForQuestionAnswering(cfg)
    params = qa.init(jax.random.PRNGKey(0), b["input_ids"])
    start, end = qa.apply(params, b["input_ids"], b["attention_mask"])
    assert start.shape == end.shape == (4, 32)
    clf = BertForSequenceClassification(cfg, num_labels=3)
    params = clf.init(jax.random.PRNGKey(0), b["input_ids"])
    logits = clf.apply(params, b["input_ids"], b["attention_mask"])
    assert logits.shape == (4, 3)


def test_scan_matches_loop():
    """scan_layers must be a pure compilation-strategy choice."""
    kw = dict(dtype=jnp.float32, num_hidden_layers=2)
    cfg_loop = bert_tiny(scan_layers=False, **kw)
    cfg_scan = bert_tiny(scan_layers=True, **kw)
    b = _batch(cfg_loop)
    m_loop, m_scan = BertModel(cfg_loop), BertModel(cfg_scan)
    p_loop = m_loop.init(jax.random.PRNGKey(0), b["input_ids"])
    seq_l, _ = m_loop.apply(p_loop, b["input_ids"])
    # restack the per-layer params into the scan layout (leading layer axis)
    enc = p_loop["params"]["encoder"]
    layer_keys = sorted(k for k in enc if "TransformerLayer" in k)
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                           *[enc[k] for k in layer_keys])
    scan_init = m_scan.init(jax.random.PRNGKey(0), b["input_ids"])
    scan_enc = scan_init["params"]["encoder"]["layer"]
    inner_name = next(iter(scan_enc))
    p_scan = {"params": {**p_loop["params"],
                         "encoder": {"layer": {inner_name: stacked}}}}
    seq_s, _ = m_scan.apply(p_scan, b["input_ids"])
    np.testing.assert_allclose(np.asarray(seq_l), np.asarray(seq_s),
                               rtol=2e-5, atol=2e-5)


def test_bert_trains_through_engine():
    """MLM loss decreases over a few steps under the engine (ZeRO-2, fp32
    for CPU determinism)."""
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    cfg = bert_tiny(dtype=jnp.float32)
    model = BertForPreTraining(cfg)
    ds_config = {
        "train_batch_size": 4,
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
    }
    b = _batch(cfg)

    def loss_fn(params, batch):
        outputs = model.apply({"params": params}, batch["input_ids"],
                              batch["attention_mask"],
                              batch["token_type_ids"])
        return pretraining_loss(outputs, batch)

    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine = DeepSpeedEngine(model=model, config=ds_config, mesh=mesh,
                             loss_fn=loss_fn, rng=jax.random.PRNGKey(0))
    losses = [float(engine.train_batch(b)) for _ in range(8)]
    assert losses[-1] < losses[0], losses
