"""O_DIRECT swap tier (ISSUE 20): the alignment layer, the latched
buffered fallback, and the swapper contracts that ride on them.

The contracts under test:

- **alignment**: leaf sizes that are not page multiples roundtrip
  bit-exactly (aligned body zero-copy + one bounced tail; fully
  unaligned buffers bounce whole); physical swap-file sizes round up to
  the page while ``meta`` keeps the exact bytes; sub-``block_size``
  tails and multi-chunk bodies split without breaking alignment.
- **fallback**: a filesystem that rejects O_DIRECT latches the process
  to buffered I/O with exactly ONE warning, a ``swap/o_direct_fallback``
  counter bump and flight-recorder breadcrumb — then everything still
  works (degrade loudly, never fail CI on an overlay FS).
- **honesty gates**: active O_DIRECT never issues fadvise (there is no
  page cache to warm); ``drain_writes`` + fsync does per-fd data fsync
  only for buffered fds and one dirent fsync when direct fds are
  pending; the snapshotter truncates direct-written shards back to the
  exact byte count the crc/loader format expects.
- **scratch hygiene**: pid-scoped swap dirs left by a SIGKILLed process
  are reclaimed at the next construction (the finalizer never ran).

Everything except the snapshot test stays jax-free — ci/swap_gate.sh
runs the fast tier of this file without an accelerator stack.
"""

import errno
import os
import types

import ml_dtypes
import numpy as np
import pytest

from deepspeed_tpu.ops.native import aio
from deepspeed_tpu.ops.native.aio import (
    ALIGNMENT, AsyncIOHandle, align_up, aligned_empty, fd_is_direct,
    o_direct_fallback_latched, reset_o_direct_fallback_for_tests)
from deepspeed_tpu.runtime.swap_tensor.swapper import (
    OptimizerStateSwapper, PartitionedParamSwapper, TensorSwapper,
    sweep_stale_pid_dirs)
from deepspeed_tpu.telemetry import default_recorder, default_registry


@pytest.fixture(autouse=True)
def _fresh_latch():
    reset_o_direct_fallback_for_tests()
    yield
    reset_o_direct_fallback_for_tests()


def _cfg(**kw):
    kw.setdefault("o_direct", True)
    return types.SimpleNamespace(**kw)


# -- the alignment layer ---------------------------------------------------

def test_align_helpers():
    assert align_up(1) == ALIGNMENT
    assert align_up(ALIGNMENT) == ALIGNMENT
    assert align_up(ALIGNMENT + 1) == 2 * ALIGNMENT
    buf = aligned_empty(100)
    assert buf.nbytes == 100
    assert buf.ctypes.data % ALIGNMENT == 0


def test_arena_reuses_buffers():
    arena = aio.AlignedArena()
    l1 = arena.lease(1000)
    cap = l1.cap
    l1.release()
    before = arena.allocated_bytes
    l2 = arena.lease(1000)          # free-list pop, no new mmap
    assert l2.cap == cap and arena.allocated_bytes == before
    l2.release()


@pytest.mark.parametrize("nbytes", [1, 7, 4096, 4097, 12345, 999999])
def test_handle_roundtrip_odd_sizes(tmp_path, nbytes):
    h = AsyncIOHandle(o_direct=True)
    path = str(tmp_path / "x.bin")
    src = np.random.default_rng(nbytes).integers(
        0, 255, nbytes, dtype=np.uint8)
    assert h.sync_pwrite(src, path) == nbytes
    if not o_direct_fallback_latched():
        # files written under O_DIRECT keep page-rounded physical sizes
        assert os.path.getsize(path) == align_up(nbytes)
    out = np.empty_like(src)
    h.sync_pread(out, path)
    np.testing.assert_array_equal(src, out)


def test_aligned_buffer_submits_zero_copy(tmp_path):
    h = AsyncIOHandle(o_direct=True)
    path = str(tmp_path / "z.bin")
    src = aligned_empty(8 * ALIGNMENT)
    src[:] = np.arange(src.nbytes, dtype=np.uint64).view(np.uint8)[
        :src.nbytes]
    h.sync_pwrite(src, path)
    out = aligned_empty(src.nbytes)
    h.sync_pread(out, path)
    np.testing.assert_array_equal(src, out)
    if not o_direct_fallback_latched():
        assert h.stats["direct_zero_copy"] == 2
        assert h.stats["direct_bounced"] == 0


def test_sub_block_tail_chunking(tmp_path):
    """A transfer larger than block_size with an unaligned tail: the
    aligned body splits into block_size chunks (the C splitter must
    only ever see single-piece submissions) and the tail bounces as one
    aligned rewrite."""
    h = AsyncIOHandle(block_size=ALIGNMENT, o_direct=True)
    path = str(tmp_path / "t.bin")
    nbytes = 3 * ALIGNMENT + 100
    src = aligned_empty(nbytes)
    src[:] = np.random.default_rng(0).integers(0, 255, nbytes,
                                               dtype=np.uint8)
    h.sync_pwrite(src, path)
    out = aligned_empty(nbytes)
    h.sync_pread(out, path)
    np.testing.assert_array_equal(src, out)
    if not o_direct_fallback_latched():
        assert h.stats["direct_tail_bounced"] == 2


def test_device_bandwidth_gauges_set(tmp_path):
    default_registry().reset()
    h = AsyncIOHandle(o_direct=True)
    src = aligned_empty(4 * ALIGNMENT)
    src[:] = 7
    h.sync_pwrite(src, str(tmp_path / "g.bin"))
    h.sync_pread(src, str(tmp_path / "g.bin"))
    if not o_direct_fallback_latched():
        assert default_registry().peek_gauge("swap/device_write_mb_s") > 0
        assert default_registry().peek_gauge("swap/device_read_mb_s") > 0


# -- the latched fallback --------------------------------------------------

def _reject_o_direct(monkeypatch):
    real_open = os.open

    def fake_open(path, flags, *a, **kw):
        if flags & os.O_DIRECT:
            raise OSError(errno.EINVAL, "Invalid argument", str(path))
        return real_open(path, flags, *a, **kw)

    monkeypatch.setattr(os, "open", fake_open)


def test_fallback_latches_once_and_degrades(tmp_path, monkeypatch):
    _reject_o_direct(monkeypatch)
    default_registry().reset()
    default_recorder().clear()
    warned = []
    real_warn = aio.logger.warning
    monkeypatch.setattr(
        aio.logger, "warning",
        lambda msg, *a: (warned.append(msg % a if a else msg),
                         real_warn(msg, *a)))
    h = AsyncIOHandle(o_direct=True)
    src = np.arange(5000, dtype=np.uint8)
    h.sync_pwrite(src, str(tmp_path / "a.bin"))
    assert o_direct_fallback_latched()
    assert not h.direct_active
    # a second handle on the latched process: no second warning
    h2 = AsyncIOHandle(o_direct=True)
    h2.sync_pwrite(src, str(tmp_path / "b.bin"))
    warnings = [m for m in warned if "O_DIRECT unsupported" in m]
    assert len(warnings) == 1
    counters = default_registry().snapshot()["counters"]
    assert counters.get("swap/o_direct_fallback", 0) >= 1
    assert any(e["kind"] == "o_direct_fallback"
               for e in default_recorder().events())
    # degraded handles still do correct buffered I/O, byte-exact sizes
    assert os.path.getsize(tmp_path / "a.bin") == src.nbytes
    out = np.empty_like(src)
    h.sync_pread(out, str(tmp_path / "a.bin"))
    np.testing.assert_array_equal(src, out)


def test_fallback_reset_helper(tmp_path, monkeypatch):
    _reject_o_direct(monkeypatch)
    h = AsyncIOHandle(o_direct=True)
    h.sync_pwrite(np.zeros(10, np.uint8), str(tmp_path / "x.bin"))
    assert o_direct_fallback_latched()
    reset_o_direct_fallback_for_tests()
    assert not o_direct_fallback_latched()


# -- swapper contracts -----------------------------------------------------

def test_param_swapper_odd_leaves_stream(tmp_path):
    rng = np.random.default_rng(3)
    leaves = [rng.standard_normal(n).astype(np.float32)
              for n in (1000, 1024, 12345, 3, 99999)]
    sw = PartitionedParamSwapper(str(tmp_path), aio_config=_cfg(),
                                 pipeline_read=True, pipeline_write=True,
                                 buffer_count=4)
    sw.write_all(leaves)
    seen = []
    for i, view in sw.swap_in_stream():
        seen.append(i)
        np.testing.assert_array_equal(view, leaves[i])
    assert seen == list(range(len(leaves)))
    if not o_direct_fallback_latched():
        for i, leaf in enumerate(leaves):
            assert os.path.getsize(sw._path(i)) == align_up(leaf.nbytes)
    sw.release()


def test_param_swapper_buffer_count_floor(tmp_path):
    """buffer_count=1 clamps to the 2-slot double-buffer minimum and
    the sliding window still streams more leaves than slots."""
    rng = np.random.default_rng(4)
    leaves = [rng.standard_normal(n).astype(np.float32)
              for n in (100, 5000, 77, 4096, 9, 131072)]
    sw = PartitionedParamSwapper(str(tmp_path), aio_config=_cfg(),
                                 buffer_count=1)
    assert sw.buffer_count == 2
    sw.write_all(leaves)
    for i, view in sw.swap_in_stream():
        np.testing.assert_array_equal(view, leaves[i])
    sw.release()


def test_param_swapper_int8_bf16_leaves(tmp_path):
    rng = np.random.default_rng(5)
    leaves = [
        rng.integers(-128, 127, 12345, dtype=np.int8),
        rng.standard_normal(4097).astype(ml_dtypes.bfloat16),
        rng.standard_normal((33, 65)).astype(ml_dtypes.bfloat16),
    ]
    sw = PartitionedParamSwapper(str(tmp_path), aio_config=_cfg(),
                                 pipeline_write=True)
    sw.write_all(leaves)
    # write-behind the updated values, then force the disk path
    for i, a in enumerate(leaves):
        sw.write_behind(i, a)
    sw.drain_writes()
    sw._cache.clear()
    for i, view in sw.swap_in_stream():
        assert view.dtype == leaves[i].dtype
        np.testing.assert_array_equal(view, leaves[i])
    sw.release()


def test_no_fadvise_under_active_o_direct(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(os, "posix_fadvise",
                        lambda *a, **kw: calls.append(a))
    leaves = [np.arange(1000, dtype=np.float32)]
    sw = PartitionedParamSwapper(str(tmp_path / "d"), aio_config=_cfg())
    sw.write_all(leaves)
    list(sw.swap_in_stream())
    sw.release()
    if not o_direct_fallback_latched():
        assert calls == []
    # the buffered tier keeps its readahead pass
    sb = PartitionedParamSwapper(str(tmp_path / "b"))
    sb.write_all(leaves)
    list(sb.swap_in_stream())
    assert calls
    sb.release()


def test_drain_writes_dirent_fsync_only(tmp_path, monkeypatch):
    """Under active O_DIRECT the drain fence must not data-fsync the
    swap fds (completed direct writes are on the device) — one dirent
    fsync covers the name/metadata durability."""
    leaves = [np.arange(5000, dtype=np.float32),
              np.arange(64, dtype=np.float32)]
    sw = PartitionedParamSwapper(str(tmp_path), aio_config=_cfg(),
                                 pipeline_write=True, fsync=True)
    if not sw.handle.direct_active:
        pytest.skip("O_DIRECT unavailable on this filesystem")
    for i, a in enumerate(leaves):
        sw.write_behind(i, a)       # preallocation fsyncs happen here
    sw.drain_writes()
    fsynced = []
    monkeypatch.setattr(os, "fsync", lambda fd: fsynced.append(fd))
    for i, a in enumerate(leaves):
        sw.write_behind(i, a)       # same sizes: no prealloc re-fsync
    sw.drain_writes()
    # exactly one fsync — the directory, not the (direct) data fds
    assert len(fsynced) == 1
    assert not any(fd in fsynced for fd in sw._wfds.values())
    sw.release()


def test_optimizer_swapper_o_direct_roundtrip(tmp_path):
    osw = OptimizerStateSwapper(str(tmp_path), aio_config=_cfg(),
                                pipeline_write=True)
    rng = np.random.default_rng(6)
    shapes = [(12345,), (7,), (4096,)]
    for lid, s in enumerate(shapes):
        osw.init_state(lid, s)
    wrote = {}
    for lid, s in enumerate(shapes):
        m, v = osw.fetch(lid)
        assert np.all(m == 0) and np.all(v == 0)
        m[:] = rng.standard_normal(s).astype(np.float32)
        v[:] = np.abs(rng.standard_normal(s)).astype(np.float32)
        wrote[lid] = (np.array(m), np.array(v))
        osw.store(lid, m, v)
    osw.drain_writes()
    for lid in range(len(shapes)):
        osw.prefetch(lid)
        m, v = osw.fetch(lid)
        np.testing.assert_array_equal(m, wrote[lid][0])
        np.testing.assert_array_equal(v, wrote[lid][1])


def test_tensor_swapper_o_direct(tmp_path):
    ts = TensorSwapper(str(tmp_path), aio_config=_cfg())
    a = np.random.default_rng(7).standard_normal(777).astype(np.float32)
    ts.swap_out("x", a)
    out = np.empty_like(a)
    np.testing.assert_array_equal(ts.swap_in("x", out), a)
    ts.prefetch("x", out)
    np.testing.assert_array_equal(ts.swap_in("x", out), a)
    ts.release()


# -- scratch hygiene -------------------------------------------------------

def test_stale_pid_dir_sweep(tmp_path):
    # a pid that cannot exist (> pid_max) stands in for a SIGKILLed one
    dead = tmp_path / "param_swap_999999999"
    dead.mkdir()
    (dead / "param_0.swp").write_bytes(b"x")
    mine = tmp_path / f"param_swap_{os.getpid()}"
    mine.mkdir()
    other = tmp_path / "param_swap_notapid"
    other.mkdir()
    swept = sweep_stale_pid_dirs(str(tmp_path), "param_swap")
    assert swept == ["param_swap_999999999"]
    assert not dead.exists()
    assert mine.exists() and other.exists()


def test_constructor_sweeps_stale_dirs(tmp_path):
    dead = tmp_path / "zero_swap_999999999"
    dead.mkdir()
    TensorSwapper(str(tmp_path))
    assert not dead.exists()
    dead2 = tmp_path / "param_swap_999999999"
    dead2.mkdir()
    PartitionedParamSwapper(str(tmp_path))
    assert not dead2.exists()


# -- config validation -----------------------------------------------------

def test_aio_config_o_direct_validation():
    from deepspeed_tpu.config.config import AioConfig, DeepSpeedConfigError
    assert AioConfig({}).o_direct is False
    assert AioConfig({"aio": {"o_direct": True}}).o_direct is True
    with pytest.raises(DeepSpeedConfigError):
        AioConfig({"aio": {"o_direct": "yes"}})
    with pytest.raises(DeepSpeedConfigError):
        AioConfig({"aio": {"o_direct": True, "block_size": 4096 + 512}})
    with pytest.raises(DeepSpeedConfigError):
        AioConfig({"aio": {"block_size": 0}})
    # buffered mode keeps accepting unaligned block sizes
    assert AioConfig({"aio": {"block_size": 4096 + 512}}).block_size


# -- snapshot honesty (jax needed) ----------------------------------------

def test_snapshot_o_direct_exact_sizes_and_load(tmp_path):
    jnp = pytest.importorskip("jax.numpy")
    from deepspeed_tpu.runtime.elastic.snapshot import (
        AsyncSnapshotter, SnapshotReader)
    trees = {
        "model_states": {"params": {
            "w": jnp.asarray(np.random.RandomState(0).randn(8, 17),
                             jnp.bfloat16),
            "b": jnp.asarray(np.arange(33, dtype=np.float32))}},
        "optim_states": {"opt_state": {}, "scaler": {},
                         "global_step": jnp.int32(3),
                         "skipped_steps": jnp.int32(0)},
    }
    sp = AsyncSnapshotter(str(tmp_path), aio_config=_cfg(), fsync=True)
    if not getattr(sp._handle, "direct_active", False):
        pytest.skip("O_DIRECT unavailable on this filesystem")
    sp.begin("t1", trees)
    final, _ = sp.finalize()
    # direct writes land page-rounded; finalize must truncate each
    # shard back to the exact nbytes the crc/loader format expects
    import json as _json
    with open(os.path.join(final, "manifest.json")) as fh:
        man = _json.load(fh)
    import glob as _glob
    shards = _glob.glob(os.path.join(final, "*.bin"))
    assert shards
    for p in shards:
        assert os.path.getsize(p) % ALIGNMENT != 0 or \
            os.path.getsize(p) == align_up(os.path.getsize(p))
    reader = SnapshotReader(final)   # verify=True: crc over exact bytes
    state, _ = reader.state_and_meta()
    reader.close()
    np.testing.assert_array_equal(
        np.asarray(state["params"]["b"]), np.arange(33, dtype=np.float32))
    assert man["tag"] == "t1"
