"""Involuntary-rematerialization pins for the dryrun detector.

``__graft_entry__.dryrun_multichip`` fails the whole dryrun when XLA's
SPMD partitioner reports "Involuntary full rematerialization" (a
sharding-spec mismatch that compiles into a replicate-then-reshard —
a full-tensor broadcast per step on a real ICI mesh). These tests pin
WHICH programs are clean vs. still tripping, so regressions (and the
eventual fix) are individually visible:

- the 1F1B pipe-only shard_map program (PR 1's known follow-up) is now
  CLEAN — its per-leaf pipe specs no longer force a reshard — and must
  stay that way;
- the expert-parallel MoE train step (dp x ep x tp) is now ALSO CLEAN
  (PR 3): the layer-scan carry and the pos-embedding broadcast pin to
  the batch layout on both primal and cotangent edges (gpt2._carry_pin),
  and the token->expert regroup routes its batch-major <-> expert-major
  flips through REPLICATED anchors (moe._expert_mesh_pin) — direct
  tiled<->tiled conversion between the (data x expert)-iota and
  expert-transposed device orders is unconvertible for the partitioner
  and was the source of the remat. The former strict xfail is now a
  plain pin and must stay clean.

The C++ partitioner logs to stderr (not python logging), so each probe
compiles its program in a subprocess and greps captured stderr — the
same channel the dryrun detector reads.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REMAT_MSG = "Involuntary full rematerialization"


def _compile_probe(body: str, n_devices: int) -> str:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", body], env=env,
                          cwd=REPO_ROOT, capture_output=True, text=True,
                          timeout=420)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    return proc.stdout + proc.stderr


def test_pipeline_1f1b_pipe_only_shard_map_remat_clean():
    """The pipeline-perf sweep's grad program (pipe-only shard_map,
    S=4) must compile with NO involuntary remat — pins PR 1's spec fix
    so it can't silently regress."""
    out = _compile_probe(textwrap.dedent("""
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
        from deepspeed_tpu.parallel.pipeline_1f1b import pipeline_1f1b

        S, layers, d, mb = 4, 2, 64, 8
        devices = jax.devices()[:S]
        mesh = make_mesh(MeshConfig(pipe=S, data=1), devices=devices)
        rng = jax.random.PRNGKey(0)
        w = jax.random.normal(rng, (S, layers, d, d)) * 0.2

        def stage_fn(sp, x):
            def layer(h, wi):
                return jnp.tanh(h @ wi), None
            y, _ = jax.lax.scan(layer, x, sp)
            return y

        M = 4 * S

        def loss(p, xx):
            return jnp.mean(pipeline_1f1b(stage_fn, p, xx, mesh) ** 2)

        x = jax.random.normal(rng, (M, mb, d))
        jax.block_until_ready(jax.jit(jax.grad(loss))(w, x))
        print("COMPILED_OK")
    """), n_devices=4)
    assert "COMPILED_OK" in out
    assert REMAT_MSG not in out, out[-3000:]


@pytest.mark.slow
def test_moe_expert_parallel_step_remat_clean():
    """The dp2 x ep2 x tp2 MoE train step (dryrun_multichip's third
    config) compiles without involuntary remat — fixed in PR 3 by the
    carry/pos batch-layout pins (models/gpt2.py) plus the MoE regroup's
    replicated anchors (moe/layer.py); this pin keeps it that way."""
    out = _compile_probe(textwrap.dedent("""
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import deepspeed_tpu as dstpu
        from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

        devices = jax.devices()[:8]
        mesh = make_mesh(MeshConfig(data=2, expert=2, model=2),
                         devices=devices)
        moe_cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=128,
                             n_layer=2, n_head=4, dtype=jnp.bfloat16,
                             scan_layers=True, moe_experts=4)
        cfg = {"train_batch_size": 4,
               "zero_optimization": {"stage": 1},
               "bf16": {"enabled": True},
               "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}}}
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=GPT2LMHeadModel(moe_cfg), mesh=mesh)
        batch = {"input_ids": np.random.RandomState(2).randint(
            0, 512, size=(4, 128)).astype(np.int32)}
        float(jax.device_get(engine.train_batch(batch)))
        print("COMPILED_OK")
    """), n_devices=8)
    assert "COMPILED_OK" in out
    assert REMAT_MSG not in out, out[-3000:]
