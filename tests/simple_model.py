"""Model-zoo fixtures — the analog of the reference's tests/unit/simple_model.py
(SimpleModel, LinearStack, pipeline variants; SURVEY §4)."""

import numpy as np
import jax.numpy as jnp
import flax.linen as nn


class SimpleModel(nn.Module):
    """Two-layer MLP classifier (reference SimpleModel: Linear+CrossEntropy)."""
    hidden_dim: int = 16
    n_classes: int = 4

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden_dim)(x)
        h = nn.relu(h)
        return nn.Dense(self.n_classes)(h)


class LinearStack(nn.Module):
    """Stack of equal Linear layers (reference LinearStack — used for
    pipeline partitioning tests)."""
    num_layers: int = 4
    hidden_dim: int = 16

    @nn.compact
    def __call__(self, x):
        for _ in range(self.num_layers):
            x = nn.Dense(self.hidden_dim, use_bias=False)(x)
        return x


def random_dataset(n=64, dim=8, n_classes=4, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, dim).astype(np.float32)
    ys = rng.randint(0, n_classes, size=(n,)).astype(np.int32)
    return [(xs[i], ys[i]) for i in range(n)]


def random_batch(batch_size=8, dim=8, n_classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(batch_size, dim).astype(np.float32),
            rng.randint(0, n_classes, size=(batch_size,)).astype(np.int32))


def token_batch(batch_size=4, seq=16, vocab=512, seed=0):
    rng = np.random.RandomState(seed)
    return {"input_ids": rng.randint(0, vocab, size=(batch_size, seq)).astype(np.int32)}


def base_config(**overrides):
    cfg = {
        "train_batch_size": 8,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 100,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(overrides)
    return cfg
