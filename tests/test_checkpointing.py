"""Sharded checkpoint format unit tests (multi-process behavior:
tests/test_multiprocess_dist.py::test_sharded_checkpoint_two_processes_and_resize)."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime import checkpointing as ckpt


class _State:
    def __init__(self, params, opt_state):
        self.params = params
        self.opt_state = opt_state
        self.scaler = {"loss_scale": jnp.float32(1.0)}
        self.global_step = jnp.int32(3)
        self.skipped_steps = jnp.int32(0)


def _roundtrip(tmp_path, params, opt):
    ckpt.save_checkpoint(str(tmp_path), "t", _State(params, opt),
                         {"global_steps": 3})
    state, meta = ckpt.load_checkpoint(str(tmp_path))
    return state, meta


def test_bf16_leaves_roundtrip(tmp_path):
    """npz cannot store ml_dtypes arrays (bfloat16 -> void '|V2'); the raw
    byte encoding must bring them back bit-exact."""
    params = {"w": jnp.asarray(
        np.random.RandomState(0).randn(8, 16), jnp.bfloat16),
        "b": jnp.zeros((16,), jnp.float32)}
    opt = {"exp_avg": {"w": jnp.asarray(
        np.random.RandomState(1).randn(8, 16), jnp.bfloat16)}}
    state, meta = _roundtrip(tmp_path, params, opt)
    assert state["params"]["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(state["params"]["w"], np.float32),
        np.asarray(params["w"], np.float32))
    np.testing.assert_array_equal(
        np.asarray(state["opt_state"]["exp_avg"]["w"], np.float32),
        np.asarray(opt["exp_avg"]["w"], np.float32))
    assert int(state["global_step"]) == 3
    assert meta["global_steps"] == 3


def test_sharded_save_load_across_mesh(tmp_path):
    """Save from an 8-device sharded state, reload windows under a
    different sharding and without shardings at all."""
    if len(jax.devices()) < 8:
        pytest.skip("need 8 devices")
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    mesh = make_mesh(MeshConfig(data=8))
    sh = NamedSharding(mesh, P(None, "data"))
    w = jax.device_put(
        jnp.asarray(np.random.RandomState(0).randn(8, 32), jnp.float32), sh)
    state, _ = _roundtrip(tmp_path, {"w": w}, {})
    np.testing.assert_array_equal(np.asarray(state["params"]["w"]),
                                  np.asarray(w))
    # reload through explicit shardings on a different layout
    reader = ckpt.ShardedCheckpoint(os.path.join(str(tmp_path), "t"))
    sh2 = NamedSharding(mesh, P("data", None))
    tree = reader.assemble("model_states", {"params": {"w": sh2}})
    reader.close()
    np.testing.assert_array_equal(np.asarray(tree["params"]["w"]),
                                  np.asarray(w))


def test_missing_shard_file_raises(tmp_path):
    """A deleted shard file must fail the load loudly, not resume from
    uninitialized memory."""
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    ckpt.save_checkpoint(str(tmp_path), "t", _State(params, {}), {})
    tag_dir = os.path.join(str(tmp_path), "t")
    os.remove(os.path.join(tag_dir, "model_states_shard_0.npz"))
    with pytest.raises((IOError, FileNotFoundError, KeyError)):
        state, _ = ckpt.load_checkpoint(str(tmp_path))
        np.asarray(state["params"]["w"])


def test_zero_to_fp32_reads_sharded_format(tmp_path):
    from deepspeed_tpu.utils import zero_to_fp32 as z2f
    params = {"w": jnp.asarray(
        np.random.RandomState(2).randn(4, 8), jnp.bfloat16)}
    ckpt.save_checkpoint(str(tmp_path), "t", _State(params, {}), {})
    sd = z2f.get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    assert sd["w"].dtype == np.float32
    np.testing.assert_allclose(sd["w"],
                               np.asarray(params["w"], np.float32))
