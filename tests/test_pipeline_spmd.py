"""SPMD pipeline integration tests: the 1F1B executor behind
GPT2PipeModel matches sequential execution exactly (fwd + grads), and
GPT2PipeModel trains under the engine on a pipe×data mesh.
(Executor-level schedule/numerics tests: test_pipeline_1f1b.py.)"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
from deepspeed_tpu.parallel.pipeline_1f1b import (
    pipeline_1f1b as spmd_pipeline, stack_stage_params, unstack_stage_params)
from tests.simple_model import base_config


def _mesh42():
    return make_mesh(MeshConfig(pipe=4, data=2))


def _stage_fn(p, x):
    def layer(h, w):
        return jnp.tanh(h @ w), None
    h, _ = jax.lax.scan(layer, x, p)
    return h


def test_pipeline_forward_matches_sequential():
    mesh = _mesh42()
    L, D, M, mb = 8, 16, 4, 4
    Ws = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D))
    out = spmd_pipeline(_stage_fn, stack_stage_params(Ws, 4), x, mesh)

    h = x.reshape(M * mb, D)
    for i in range(L):
        h = jnp.tanh(h @ Ws[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(h.reshape(M, mb, D)),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_backward_matches_sequential():
    mesh = _mesh42()
    L, D, M, mb = 8, 16, 4, 2
    Ws = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D))
    stacked = stack_stage_params(Ws, 4)

    g_pipe = jax.grad(
        lambda W: jnp.sum(spmd_pipeline(_stage_fn, W, x, mesh) ** 2))(stacked)
    g_pipe = unstack_stage_params(g_pipe)

    def loss_seq(W):
        h = x.reshape(M * mb, D)
        for i in range(L):
            h = jnp.tanh(h @ W[i])
        return jnp.sum(h ** 2)
    g_seq = jax.grad(loss_seq)(Ws)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_single_stage_path():
    mesh = make_mesh(MeshConfig(data=8))
    L, D = 4, 8
    Ws = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.1
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 4, D))
    out = spmd_pipeline(_stage_fn, stack_stage_params(Ws, 1), x, mesh)
    h = x.reshape(8, D)
    for i in range(L):
        h = jnp.tanh(h @ Ws[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(h.reshape(2, 4, D)),
                               rtol=1e-5)


def test_stack_unstack_roundtrip():
    Ws = jnp.arange(24.0).reshape(6, 2, 2)
    stacked = stack_stage_params(Ws, 3)
    assert stacked.shape == (3, 2, 2, 2)
    np.testing.assert_array_equal(np.asarray(unstack_stage_params(stacked)),
                                  np.asarray(Ws))
    with pytest.raises(AssertionError):
        stack_stage_params(Ws, 4)


def test_gpt2_pipe_model_matches_plain_gpt2():
    """Pipeline execution is numerically the same model as plain GPT-2."""
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
    from deepspeed_tpu.models.gpt2_pipe import GPT2PipeModel
    mesh = make_mesh(MeshConfig(pipe=4, data=2))
    cfg = gpt2_tiny(dtype=jnp.float32, n_layer=4)
    plain = GPT2LMHeadModel(cfg)
    pipe = GPT2PipeModel(cfg, mesh, num_microbatches=2)

    ids = np.random.RandomState(0).randint(0, 512, (4, 16)).astype(np.int32)
    variables = plain.init(jax.random.PRNGKey(0), ids)
    logits_plain = plain.apply(variables, ids)

    pipe_params = pipe.init(jax.random.PRNGKey(0), ids)
    logits_pipe = pipe.apply(pipe_params, ids)
    np.testing.assert_allclose(np.asarray(logits_plain),
                               np.asarray(logits_pipe), rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt2_pipe_trains_under_engine():
    from deepspeed_tpu.models.gpt2 import gpt2_tiny
    from deepspeed_tpu.models.gpt2_pipe import GPT2PipeModel
    mesh = make_mesh(MeshConfig(pipe=2, data=2, model=2))
    cfg_json = {
        "train_batch_size": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
    }
    model = GPT2PipeModel(gpt2_tiny(dtype=jnp.float32, n_layer=4), mesh,
                          num_microbatches=2)
    engine, _, _, _ = dstpu.initialize(config=cfg_json, model=model, mesh=mesh)
    ids = np.random.RandomState(0).randint(0, 512, (4, 16)).astype(np.int32)
    l0 = float(engine.train_batch({"input_ids": ids}))
    for _ in range(8):
        l1 = float(engine.train_batch({"input_ids": ids}))
    assert np.isfinite(l1) and l1 < l0
    # stage params are actually sharded over the pipe axis
    h = engine.state.params["h_stages"]
    leaf = jax.tree_util.tree_leaves(h)[0]
    assert "pipe" in str(leaf.sharding.spec)
