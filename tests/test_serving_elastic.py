"""Elastic preemption-tolerant serving (ISSUE 11).

Covers the drain-or-snapshot subsystem end to end:

- snapshot/restore token parity, same and DIFFERENT slot counts
  (direct slot rebuilds + replay requeues), prefix hit-rate preserved
  across restore;
- SIGTERM mid-serve through the real signal path: grace-budget drain
  vs immediate snapshot, and the mid-spec-tick rollback pin — no
  drafted-but-unverified token ever appears in a restored stream, for
  BOTH drafters;
- the two-rename commit crash window (previous snapshot survives);
- abort()/drain() page-leak fence;
- ReplicaPool: mid-prefill and mid-spec-verify replica crashes
  recovered from committed snapshots (token-lossless), bounded retry
  dropping a poisoned request, watchdog-trip scale-up + idle
  scale-down, one latched dump per injected fault;
- config validation for serving.elastic / serving.autoscale;
- the dump viewer's drain -> snapshot -> restore -> requeue timeline.
"""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu.serving as serving
from deepspeed_tpu.config.config import (DeepSpeedConfigError,
                                         ServingConfig)
from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.serving import elastic
from deepspeed_tpu.serving.drafter import ModelDrafter, NGramDrafter
from deepspeed_tpu.serving.elastic import ElasticServingController
from deepspeed_tpu.serving.replica_pool import ReplicaPool
from deepspeed_tpu.telemetry.anomaly import Watchdog
from deepspeed_tpu.telemetry.recorder import default_recorder


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    default_recorder().configure(enabled=True, capacity=4096)
    default_recorder().clear()
    yield
    faults.clear()


# ------------------------------------------------------ engine fixture

def _gpt2_cfg():
    from deepspeed_tpu.models.gpt2 import GPT2Config
    return GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                      n_layer=2, n_head=4, dtype=jnp.float32,
                      param_dtype=jnp.float32, scan_layers=True)


@pytest.fixture(scope="module")
def gpt2_el():
    """(cfg, params, make): batchers over shared per-geometry adapters
    (compiled programs live on the adapter — tier-1 budget)."""
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    cfg = _gpt2_cfg()
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    adapters = {}

    def make(slots=2, **kw):
        sv = {"slots": slots, "page_size": 8, "max_pages_per_slot": 8}
        sv.update(kw.pop("serving", {}))
        key = tuple(sorted(sv.items()))
        if key not in adapters:
            adapters[key] = serving.build_engine(
                "gpt2", cfg, params, config={"serving": sv}).adapter
        return serving.ContinuousBatcher(adapters[key], **kw)

    return cfg, params, make


def _reqs(n=4, max_new=12, seed=0, eos=None):
    rs = np.random.RandomState(seed)
    lens = rs.choice([5, 9, 14, 21], n)
    return [serving.Request(
        i, rs.randint(0, 256, size=(int(lens[i]),)).astype(np.int32),
        max_new_tokens=max_new, eos_token_id=eos) for i in range(n)]


def _clone(reqs):
    return [serving.Request(r.rid, r.prompt,
                            max_new_tokens=r.max_new_tokens,
                            eos_token_id=r.eos_token_id,
                            temperature=r.temperature,
                            arrival_time=r.arrival_time) for r in reqs]


def _ref_streams(make, reqs, **kw):
    eng = make(**kw)
    return {rid: r.tokens().tolist()
            for rid, r in eng.serve(_clone(reqs)).items()}


def _drive(cb, done=None, max_rounds=500):
    done = {} if done is None else done
    rounds = 0
    while cb.pending and not cb.preempted and rounds < max_rounds:
        for r in cb.step():
            done[r.rid] = r
        rounds += 1
    return done


# ------------------------------------------------- config validation


def test_serving_elastic_config_validation():
    def cfg(el):
        return ServingConfig({"serving": {"elastic": el}})

    ok = cfg({"snapshot_path": "/tmp/x", "grace_secs": 5,
              "max_retries": 2, "backoff_s": 0.1,
              "interval_ticks": 4, "signals": "SIGTERM"})
    assert ok.elastic.enabled and ok.elastic.grace_secs == 5.0
    assert ok.elastic.signals == ("SIGTERM",)   # no per-char iteration
    assert not ServingConfig({"serving": {}}).elastic.enabled
    with pytest.raises(DeepSpeedConfigError):
        cfg("nvme:/path")                        # not a dict
    with pytest.raises(DeepSpeedConfigError):
        cfg({})                                  # enabled, no path
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "grace_secs": 0})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "grace_secs": "soon"})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "max_retries": -1})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "backoff_s": -0.5})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "interval_ticks": -2})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "keep": 0})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"snapshot_path": "/tmp/x", "signals": ["alarm"]})


def test_serving_autoscale_config_validation():
    def cfg(a):
        return ServingConfig({"serving": {"autoscale": a}})

    ok = cfg({"min_replicas": 2, "max_replicas": 4})
    assert ok.autoscale.min_replicas == 2
    assert ok.autoscale.scale_signal == "watchdog"
    with pytest.raises(DeepSpeedConfigError):
        cfg(["watchdog"])                        # not a dict
    with pytest.raises(DeepSpeedConfigError):
        cfg({"min_replicas": 0})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"min_replicas": 3, "max_replicas": 2})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"min_replicas": "a few"})
    with pytest.raises(DeepSpeedConfigError):
        cfg({"scale_signal": "vibes"})


# ------------------------------------------------------- abort / drain


def test_abort_and_drain_release_pages(gpt2_el):
    _cfg, _params, make = gpt2_el
    cb = make(slots=2)
    reqs = _reqs(4, max_new=16, seed=3)
    for r in reqs:
        cb.submit(r)
    for _ in range(2):
        cb.step()
    active_rid = next(s.request.rid for s in cb.slots if s.active)
    queued_rid = cb.queue[0].rid
    got = cb.abort(active_rid)
    assert got is not None and got.finish_reason == "aborted"
    assert got.generated                     # committed tokens intact
    got_q = cb.abort(queued_rid)
    assert got_q is not None and got_q.finish_reason == "aborted"
    assert cb.abort("nonsense") is None
    rest = cb.drain()
    assert all(r.finish_reason == "aborted" for r in rest)
    assert cb.pending == 0
    # the leak fence: every page back in the pool
    cb.cache.sweep_prefix_cache()
    assert cb.cache.free_pages == cb.cache.num_blocks - 1
    kinds = [e["kind"] for e in default_recorder().events()]
    assert kinds.count("serving_abort") == 2 + len(rest)


# ------------------------------------------- snapshot / restore parity


def test_snapshot_restore_different_slot_counts(gpt2_el, tmp_path):
    """Snapshot a 2-slot engine mid-flight, restore onto a 1-slot AND
    a 3-slot engine: direct slot rebuilds + replay requeues, greedy
    token-for-token parity with the uninterrupted run either way."""
    _cfg, _params, make = gpt2_el
    reqs = _reqs(4, max_new=12, seed=0)
    ref = _ref_streams(make, reqs, slots=2)

    from deepspeed_tpu.runtime.elastic.snapshot import AsyncSnapshotter
    cb = make(slots=2)
    done = {}
    for r in _clone(reqs):
        cb.submit(r)
    for _ in range(5):
        for r in cb.step():
            done[r.rid] = r
    snap = AsyncSnapshotter(str(tmp_path / "snaps"), fsync=False)
    path = elastic.snapshot_serving(cb, snap, "t1")
    host, kv = elastic.load_serving_snapshot(path)
    assert host["slots"] or host["queued"]

    for slots in (1, 3):
        target = make(slots=slots)
        out = elastic.restore_serving(target, host, kv)
        if slots == 1:
            assert len(out["restored"]) == 1 and out["requeued"]
        merged = dict(done)
        _drive(target, merged)
        for rid, toks in ref.items():
            assert merged[rid].tokens().tolist() == toks, \
                (slots, rid)


def test_restore_preserves_prefix_hit_rate(gpt2_el, tmp_path):
    """The prefix index survives the snapshot/restore hop: a restored
    engine keeps serving repeat-prefix admissions from resident pages
    (the acceptance criterion's hit-rate-preserved leg)."""
    _cfg, _params, make = gpt2_el
    rs = np.random.RandomState(7)
    shared = rs.randint(0, 256, size=(19,)).astype(np.int32)
    prompts = [np.concatenate([shared,
                               rs.randint(0, 256, size=(3,))
                               .astype(np.int32)]) for _ in range(3)]
    mk = (lambda i: serving.Request(i, prompts[i], max_new_tokens=6))
    cb = make(slots=2, prefix_cache=True)
    cb.serve([mk(0), mk(1)])
    assert cb.cache.prefix_stats["hit_pages"] > 0

    from deepspeed_tpu.runtime.elastic.snapshot import AsyncSnapshotter
    snap = AsyncSnapshotter(str(tmp_path / "snaps"), fsync=False)
    path = elastic.snapshot_serving(cb, snap, "t1")
    host, kv = elastic.load_serving_snapshot(path)
    assert host["prefix"]["full"]            # resident entries captured

    fresh = make(slots=2, prefix_cache=True)
    ref = {rid: r.tokens().tolist()
           for rid, r in make(slots=2).serve([mk(2)]).items()}
    out = elastic.restore_serving(fresh, host, kv)
    assert out["dropped_prefix_pages"] == 0
    before = fresh.cache.prefix_stats["hit_pages"]
    done = fresh.serve([mk(2)])
    assert fresh.cache.prefix_stats["hit_pages"] > before  # still hits
    assert done[2].tokens().tolist() == ref[2]   # and stays lossless


def test_sampled_snapshot_restore_is_deterministic(gpt2_el, tmp_path):
    """ISSUE 14 satellite (the PR-11 caveat fix): SAMPLED
    (temperature > 0) requests restore deterministically. The per-
    request sample_key + cumulative committed-token count persisted in
    the snapshot docs make every token's sampling key
    fold_in(sample_key, global_index) — so both the direct slot
    rebuild AND the replay requeue regenerate the uninterrupted run's
    exact sampled stream (previously they drew fresh rng)."""
    _cfg, _params, make = gpt2_el
    from deepspeed_tpu.runtime.elastic.snapshot import AsyncSnapshotter
    reqs = [serving.Request(r.rid, r.prompt, max_new_tokens=14,
                            temperature=0.8) for r in _reqs(4, seed=21)]
    ref = _ref_streams(make, reqs, slots=2)
    # sanity: the streams are actually sampled, not greedy
    greedy = _ref_streams(
        make, [serving.Request(r.rid, r.prompt, max_new_tokens=14)
               for r in reqs], slots=2)
    assert any(ref[i] != greedy[i] for i in ref)

    src = make(slots=2)
    done = {}
    for r in _clone(reqs):
        src.submit(r)
    for _ in range(4):
        for r in src.step():
            done[r.rid] = r
    snap = AsyncSnapshotter(str(tmp_path / "snaps"), fsync=False)
    path = elastic.snapshot_serving(src, snap, "t1")
    host, kv = elastic.load_serving_snapshot(path)
    assert host["slots"], "something must still be in flight"
    for doc in host["slots"] + host["queued"]:
        assert doc["sample_key"] is not None      # persisted identity
        assert doc["committed_total"] == len(doc["generated"])
    # 1-slot target: direct rebuild AND replay requeue paths both run
    target = make(slots=1)
    merged = dict(done)
    elastic.restore_serving(target, host, kv)
    _drive(target, merged)
    for rid, toks in ref.items():
        assert merged[rid].tokens().tolist() == toks, rid


# --------------------------------------------------- SIGTERM mid-serve


def _elastic_cb(make, tmp_path, grace_secs, name="s", interval_ticks=0,
                wd=None, **mk_kw):
    cb = make(**mk_kw)
    ctrl = ElasticServingController(
        cb, str(tmp_path / name), grace_secs=grace_secs,
        interval_ticks=interval_ticks, fsync=False, watchdog=wd)
    cb.attach_elastic(ctrl)
    return cb, ctrl


def test_sigterm_with_grace_drains_everything(gpt2_el, tmp_path):
    _cfg, _params, make = gpt2_el
    reqs = _reqs(2, max_new=10, seed=1)   # both fit the slots: pure
    ref = _ref_streams(make, reqs, slots=2)            # drain, no
    wd = Watchdog(str(tmp_path / "dumps"), source="serving")  # leftover
    cb, ctrl = _elastic_cb(make, tmp_path, grace_secs=3600.0, wd=wd,
                           interval_ticks=1)
    try:
        with faults.kill_at_serving_tick(1):
            done = cb.serve(_clone(reqs))
        assert cb.preempted
        assert {r: done[r].tokens().tolist() for r in done} == ref
        assert ctrl.last_snapshot_dir is None      # nothing left over
        evs = [e for e in default_recorder().events()
               if e["kind"] == "serving_drain"]
        assert len(evs) == 1 and evs[0]["drained"] == 2 \
            and evs[0]["left"] == 0
        assert wd.trips.get("preempt") == 1        # exactly one dump
        # a clean drain PRUNES stale periodic snapshots: recovery must
        # find nothing, or it would replay completed requests
        assert elastic.load_latest_serving(ctrl.snapshot_dir) is None
    finally:
        ctrl.close()


@pytest.mark.parametrize("drafter_kind", ["ngram", "model"])
def test_sigterm_mid_spec_tick_rolls_back_to_committed(
        gpt2_el, tmp_path, drafter_kind):
    """SIGTERM lands between speculative rounds: the snapshot must
    hold only COMMITTED (verified) tokens — every snapshotted stream
    is a strict prefix of the uninterrupted greedy run — and the
    restored engines (a DIFFERENT slot count) finish token-for-token
    identical. One latched preempt dump per injected fault."""
    _cfg, _params, make = gpt2_el
    reqs = _reqs(2, max_new=14, seed=2)
    ref = _ref_streams(make, reqs, slots=2)

    def mk_drafter(slots):
        if drafter_kind == "ngram":
            return NGramDrafter(slots)
        # same checkpoint as the target (the alignment contract is
        # what's under test); the drafter's slot count must match the
        # engine it serves
        return ModelDrafter(make(slots=slots).adapter)

    wd = Watchdog(str(tmp_path / "dumps"), source="serving")
    cb, ctrl = _elastic_cb(make, tmp_path, grace_secs=1e-3, wd=wd,
                           drafter=mk_drafter(2), spec_tokens=3)
    try:
        with faults.kill_at_serving_tick(2):
            done = cb.serve(_clone(reqs))
        assert cb.preempted and ctrl.last_snapshot_dir is not None
        assert wd.trips.get("preempt") == 1
        host, kv = elastic.load_serving_snapshot(ctrl.last_snapshot_dir)
        assert host["slots"]                 # something was in flight
        for sd in host["slots"]:
            stream = list(sd["prompt"]) + list(sd["generated"])
            full = ref[sd["rid"]]
            # committed-only: a drafted-but-unverified token would
            # break the prefix property against the greedy reference
            assert stream == full[:len(stream)]
            assert len(stream) < len(full)
        # restore on a DIFFERENT slot count with a fresh drafter
        target = make(slots=3, drafter=mk_drafter(3), spec_tokens=3)
        merged = {rid: r for rid, r in done.items()}
        elastic.restore_serving(target, host, kv)
        _drive(target, merged)
        for rid, toks in ref.items():
            assert merged[rid].tokens().tolist() == toks, rid
    finally:
        ctrl.close()


def test_periodic_snapshots_and_crash_between_renames(gpt2_el,
                                                      tmp_path):
    """interval_ticks commits snapshots while serving; a crash between
    the commit renames of a LATER snapshot leaves the previous
    generation loadable (the two-rename window, serving flavor)."""
    _cfg, _params, make = gpt2_el
    from deepspeed_tpu.runtime.elastic.snapshot import AsyncSnapshotter
    reqs = _reqs(3, max_new=16, seed=4)
    cb, ctrl = _elastic_cb(make, tmp_path, grace_secs=3600.0,
                           name="periodic", interval_ticks=2)
    try:
        for r in _clone(reqs):
            cb.submit(r)
        done = {}
        rounds = 0
        while cb.pending and ctrl.last_snapshot_dir is None \
                and rounds < 200:
            for r in cb.step():
                done[r.rid] = r
            rounds += 1
        assert ctrl.last_snapshot_dir is not None    # periodic commit
        first = ctrl.last_snapshot_dir
        host1, _kv1 = elastic.load_serving_snapshot(first)

        # a later snapshot dies between its two renames: the commit
        # never publishes, the first generation stays the newest valid
        snap = ctrl.snapshotter
        with faults.crash_between_renames():
            with pytest.raises(faults.SimulatedCrash):
                elastic.snapshot_serving(cb, snap, "doomed")
        got = elastic.load_latest_serving(str(tmp_path / "periodic"))
        assert got is not None
        host, _kv, cand = got
        assert os.path.basename(cand) == os.path.basename(first)
        assert [d["rid"] for d in host["slots"]] == \
            [d["rid"] for d in host1["slots"]]
    finally:
        ctrl.close()


def test_snapshot_tick_end_fires_and_viewer_renders(gpt2_el, tmp_path):
    """The serving elastic lifecycle renders as a timeline: drain ->
    snapshot -> restore -> requeue (+ abort) rows from a real event
    stream, through the stdlib-only viewer."""
    from deepspeed_tpu.telemetry import view
    _cfg, _params, make = gpt2_el
    reqs = _reqs(4, max_new=12, seed=5)
    cb, ctrl = _elastic_cb(make, tmp_path, grace_secs=1e-3, name="v")
    try:
        for r in _clone(reqs):
            cb.submit(r)
        cb.step()
        cb.abort(reqs[3].rid)
        ctrl.request_preemption("test")
        _drive(cb)
        assert cb.preempted and ctrl.last_snapshot_dir
        host, kv = elastic.load_serving_snapshot(ctrl.last_snapshot_dir)
        target = make(slots=1)
        elastic.restore_serving(target, host, kv)
    finally:
        ctrl.close()
    dump = tmp_path / "events.jsonl"
    with open(dump, "w") as fh:
        for ev in default_recorder().events():
            fh.write(json.dumps(ev, default=repr) + "\n")
    lines = "\n".join(view.render(str(dump)))
    for kind in ("serving_drain", "serving_snapshot", "serving_restore",
                 "serving_requeue", "serving_abort"):
        assert kind in lines, kind
    assert "drained" in lines and "requeued" in lines


# -------------------------------------------------------- replica pool


def _pool_factory(make, tmp_path, slots=2, interval_ticks=2, wd_dir=None,
                  registry=None, drafter_fn=None, **wd_kw):
    def factory(rid):
        kw = {}
        if drafter_fn is not None:
            kw["drafter"] = drafter_fn()
            kw["spec_tokens"] = 3
        wd = None
        if wd_dir is not None:
            wd = Watchdog(os.path.join(wd_dir, f"r{rid}"),
                          source=f"serving_r{rid}", registry=registry,
                          **wd_kw)
        cb = make(slots=slots, registry=registry, watchdog=wd, **kw)
        cb.attach_elastic(ElasticServingController(
            cb, str(tmp_path / f"replica_{rid}"), grace_secs=30.0,
            interval_ticks=interval_ticks, fsync=False,
            install_signals=False))
        return cb
    return factory


def _run_pool(pool, reqs, fault_round=None, fault=None, max_rounds=800):
    for r in reqs:
        pool.submit(r)
    rounds = 0
    while pool.pending and rounds < max_rounds:
        pool.step()
        rounds += 1
        if fault_round is not None and rounds == fault_round:
            fault(pool)
    return pool.done


def test_pool_recovers_mid_prefill_crash(gpt2_el, tmp_path):
    """A replica dying inside admission (pages allocated, prefill not
    dispatched) is recovered from its last committed snapshot; every
    request completes token-identical; the pool watchdog dumps exactly
    once per fault and re-arms for the next."""
    _cfg, _params, make = gpt2_el
    reqs = _reqs(6, max_new=16, seed=6)
    ref = _ref_streams(make, reqs, slots=2)
    wd = Watchdog(str(tmp_path / "pool_dumps"), source="pool")
    pool = ReplicaPool(_pool_factory(make, tmp_path), n_replicas=2,
                       min_replicas=1, max_replicas=2,
                       scale_signal="none", watchdog=wd)
    try:
        crash = faults.crash_replica_mid_prefill()   # exactly ONE
        armed = [False]                              # admission crashes

        def fault(_p):
            armed[0] = True
            crash.__enter__()

        done = _run_pool(pool, _clone(reqs), fault_round=2, fault=fault)
        if armed[0]:
            crash.__exit__(None, None, None)
        assert pool.stats["kills"] == 1
        assert len(done) == len(reqs) and not pool.lost
        for rid, toks in ref.items():
            assert done[rid].tokens().tolist() == toks, rid
        assert wd.trips.get("preempt") == pool.stats["kills"]
    finally:
        pool.close()


def test_pool_recovers_mid_spec_verify_crash(gpt2_el, tmp_path):
    """Mid-spec-verify death: the round's drafted tokens were never
    committed, so the snapshot-restored streams stay greedy-identical
    (the speculative flavor of the zero-committed-token-loss pin)."""
    _cfg, _params, make = gpt2_el
    reqs = _reqs(4, max_new=14, seed=8)
    ref = _ref_streams(make, reqs, slots=2)
    pool = ReplicaPool(
        _pool_factory(make, tmp_path, drafter_fn=lambda: NGramDrafter(2)),
        n_replicas=2, min_replicas=1, max_replicas=2,
        scale_signal="none")
    try:
        crash = faults.crash_replica_mid_spec_verify(at_round=1)

        def fault(_p):
            crash.__enter__()

        done = _run_pool(pool, _clone(reqs), fault_round=2, fault=fault)
        crash.__exit__(None, None, None)
        assert pool.stats["kills"] >= 1
        assert len(done) == len(reqs) and not pool.lost
        for rid, toks in ref.items():
            assert done[rid].tokens().tolist() == toks, rid
    finally:
        pool.close()


def test_pool_bounded_retry_drops_poisoned_request(gpt2_el, tmp_path):
    """A request that kills every replica that admits it is dropped
    after max_retries (bounded, backed-off) — the rest of the traffic
    completes; the pool respawns to min_replicas after each kill."""
    _cfg, _params, make = gpt2_el
    reqs = _reqs(3, max_new=8, seed=9)
    innocents, poison_req = reqs[:2], reqs[2]
    pool = ReplicaPool(_pool_factory(make, tmp_path, interval_ticks=0),
                       n_replicas=1, min_replicas=1, max_replicas=1,
                       scale_signal="none", max_retries=2,
                       backoff_s=0.0)
    try:
        done = _run_pool(pool, _clone(innocents))
        assert sorted(done) == sorted(r.rid for r in innocents)
        # every admission of the poisoned request kills its replica;
        # the pool respawns to min_replicas each time and gives up
        # after max_retries re-serves
        with faults.crash_replica_mid_prefill(match_rid=poison_req.rid,
                                              times=None):
            _run_pool(pool, _clone([poison_req]))
        assert poison_req.rid in pool.lost
        assert pool.stats["kills"] == 3        # initial + 2 retries
        assert poison_req.rid not in pool.done
    finally:
        pool.close()


def test_pool_autoscale_up_on_trips_and_down_when_idle(gpt2_el,
                                                       tmp_path):
    """Scale-up rides the latched watchdog rules (pool exhaustion /
    TTFT blowup trips); scale-down drains a replica through the
    snapshot path after the idle hysteresis — both bounded and both
    recorded as replica_scale events."""
    _cfg, _params, make = gpt2_el
    # 1 slot + tiny pool per replica: a burst saturates instantly
    factory = _pool_factory(make, tmp_path, slots=1, interval_ticks=0,
                            wd_dir=str(tmp_path / "wd"),
                            ttft_factor=1.5, ttft_min_s=0.0001,
                            min_samples=2)
    pool = ReplicaPool(factory, n_replicas=1, min_replicas=1,
                       max_replicas=3, scale_signal="watchdog",
                       scale_down_idle_rounds=3)
    try:
        reqs = _reqs(8, max_new=8, seed=10)
        done = _run_pool(pool, _clone(reqs))
        assert len(done) == len(reqs)
        assert pool.stats["scale_ups"] >= 1
        assert len(pool.replicas) <= 3
        # idle rounds after the burst: down to min_replicas
        for _ in range(40):
            pool.step()
            if len(pool.replicas) == 1 and not pool._draining:
                break
        assert len(pool.replicas) == 1
        assert pool.stats["scale_downs"] >= 1
        kinds = [(e["kind"], e.get("direction"))
                 for e in default_recorder().events()
                 if e["kind"] == "replica_scale"]
        assert ("replica_scale", "up") in kinds
        assert ("replica_scale", "down") in kinds
    finally:
        pool.close()


def test_build_engine_wires_elastic_from_config(gpt2_el, tmp_path):
    cfg, params, _make = gpt2_el
    eng = serving.build_engine(
        "gpt2", cfg, params,
        config={"serving": {
            "slots": 2, "page_size": 8, "max_pages_per_slot": 8,
            "elastic": {"snapshot_path": str(tmp_path / "s"),
                        "grace_secs": 5.0, "interval_ticks": 3,
                        "fsync": False}}})
    try:
        assert eng.elastic is not None
        assert eng.elastic.grace_secs == 5.0
        assert eng.elastic.interval_ticks == 3
        assert not eng.preempted
    finally:
        eng.elastic.close()


# ----------------------------------------- request tracing (ISSUE 12)


def test_trace_id_stitches_kill_restore_across_replica_dumps(
        gpt2_el, tmp_path):
    """The ISSUE 12 tracing proof: requests born on one replica keep
    their submit-time trace_id through kill -> snapshot-restore/requeue
    -> finish on a survivor, and telemetry/view.py stitches the single
    per-trace timeline out of TWO dump files (one taken at the kill,
    one at the end — overlapping ring contents, deduplicated) with
    zero orphaned events: every submitted trace appears, every
    timeline closes with a finish."""
    from deepspeed_tpu.telemetry import view

    _cfg, _params, make = gpt2_el
    reqs = _reqs(6, max_new=12, seed=12)
    ref = _ref_streams(make, reqs, slots=2)
    # the reference engine's own lifecycle events (with their own
    # trace ids) must not leak into the dumps under test
    default_recorder().clear()
    pool = ReplicaPool(_pool_factory(make, tmp_path, interval_ticks=1),
                       n_replicas=2, min_replicas=1, max_replicas=2,
                       scale_signal="none")
    wd = Watchdog(str(tmp_path / "trace_dumps"), source="pool")
    try:
        work = _clone(reqs)
        for r in work:
            pool.submit(r)
        # every request got a trace id AT SUBMIT, frozen in the ledger
        traces = {r.rid: r.trace_id for r in work}
        assert all(traces.values())
        assert len(set(traces.values())) == len(work)
        for rid, doc in pool._ledger.items():
            assert doc["trace_id"] == traces[rid]

        for _ in range(3):
            pool.step()
        victim = next(iter(pool.replicas))
        victims = {rid for rid, rep in pool._assign.items()
                   if rep == victim and rid not in pool.done}
        assert victims, "victim replica should hold requests"
        pool.kill_replica(victim, reason="trace_test")
        dump_a = wd.force_dump("mid_run")      # the at-the-kill dump

        rounds = 0
        while pool.pending and rounds < 800:
            pool.step()
            rounds += 1
        dump_b = wd.force_dump("end_of_run")   # the end-of-run dump
        done = pool.done
        assert len(done) == len(reqs) and not pool.lost

        # identity survived the handoff; streams are token-lossless
        for rid, r in done.items():
            assert r.trace_id == traces[rid], rid
            assert r.tokens().tolist() == ref[rid], rid

        # the viewer stitches the two dumps into per-trace timelines
        headers, events, _ = view.load_dumps([dump_a, dump_b])
        assert len(headers) == 2
        seqs = [e["seq"] for e in events if "seq" in e]
        assert len(seqs) == len(set(seqs)), "overlap not deduplicated"
        timelines = view.trace_timelines(events)
        # zero orphaned events: every stitched trace is one we
        # submitted, every submitted trace shows up and closes
        assert set(timelines) == set(traces.values())
        for rid, tid in traces.items():
            evs = timelines[tid]
            assert view._trace_outcome(evs).startswith("finished"), rid
            assert all(ev.get("trace") == tid or
                       tid in (ev.get("traces") or ()) for ev in evs)
        # at least one victim crossed replicas (direct restore lands
        # its finish on the survivor; requeues re-admit there)
        crossed = [rid for rid in victims
                   if len({ev["replica"] for ev in timelines[traces[rid]]
                           if ev.get("replica") is not None}) > 1]
        assert crossed, "no victim trace shows two replicas"
        text = "\n".join(view.render([dump_a, dump_b]))
        assert "request traces" in text
        assert f"trace {traces[crossed[0]]}" in text
    finally:
        pool.close()


def test_restored_and_replayed_requests_keep_their_trace_id(
        gpt2_el, tmp_path):
    """Unit-level pin of the persistence contract: capture -> restore
    rebuilds direct slots with the original trace_id, and the replay
    path (resume_request) carries it through the requeue prompt."""
    _cfg, _params, make = gpt2_el
    from deepspeed_tpu.runtime.elastic.snapshot import AsyncSnapshotter
    src = make(slots=2)
    # budget large enough that nothing finishes before the snapshot
    # (a finished request rightly never lands in one)
    reqs = _reqs(3, max_new=40, seed=13)
    for r in reqs:
        src.submit(r)
    src.step()
    snap = AsyncSnapshotter(str(tmp_path / "snap"), fsync=False)
    path = elastic.snapshot_serving(src, snap, "t0")
    host, kv = elastic.load_serving_snapshot(path)
    for doc in host["slots"] + host["queued"]:
        assert doc["trace_id"] is not None
    dst = make(slots=1)                 # forces the requeue path too
    res = elastic.restore_serving(dst, host, kv)
    by_rid = {r.rid: r for r in res["restored"] + res["requeued"]}
    for r in reqs:
        assert by_rid[r.rid].trace_id == r.trace_id, r.rid
    # a fresh doc with no trace stays None-safe
    doc = dict(elastic._req_doc(reqs[0]), trace_id=None)
    assert elastic.resume_request(doc).trace_id is None


def test_pool_metrics_snapshot_aggregates_replicas(gpt2_el, tmp_path):
    """ReplicaPool.metrics_snapshot(): pool TTFT percentiles over the
    replicas' merged raw reservoirs, per-replica utilization rows, and
    the lost/retried/recovered counters (what the serving bench embeds
    as pool_telemetry)."""
    _cfg, _params, make = gpt2_el
    pool = ReplicaPool(_pool_factory(make, tmp_path, interval_ticks=0),
                       n_replicas=2, min_replicas=1, max_replicas=2,
                       scale_signal="none")
    try:
        reqs = _reqs(6, max_new=6, seed=14)
        done = _run_pool(pool, _clone(reqs))
        assert len(done) == len(reqs)
        snap = pool.metrics_snapshot()
        assert snap["replicas"] == 2
        assert set(snap["per_replica"]) == set(pool.replicas)
        for row in snap["per_replica"].values():
            assert 0.0 <= row["slot_utilization"] <= 1.0
        # merged reservoirs: every admission's TTFT observation counted
        assert snap["pool_ttft_s"]["count"] == len(reqs)
        assert snap["pool_ttft_s"]["p99"] >= snap["pool_ttft_s"]["p50"]
        assert snap["done"] == len(reqs)
        assert snap["lost"] == 0 and snap["retried"] == 0
        assert snap["slot_utilization"] == 0.0   # drained pool
    finally:
        pool.close()
