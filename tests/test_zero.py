"""ZeRO tests — the role of the reference's test_zero.py: every stage
trains, stages agree numerically with stage 0, and state is actually
sharded over the data axis (8 virtual CPU devices)."""

import numpy as np
import jax
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig, DATA_AXIS
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner, shard_spec_for_leaf
from jax.sharding import PartitionSpec as P

from tests.simple_model import SimpleModel, random_batch, base_config


def make_engine(stage, mesh=None, extra=None):
    cfg = base_config(train_batch_size=8)
    # tiny test params sit below the default persistence threshold
    # (reference ZERO_PARAM_PERSISTENCE_THRESHOLD) — force sharding
    cfg["zero_optimization"] = {"stage": stage,
                                "stage3_param_persistence_threshold": 0}
    if extra:
        cfg.update(extra)
    mesh = mesh or make_mesh(MeshConfig(data=8))
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(hidden_dim=32),
                                       mesh=mesh)
    return engine


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stage_trains(stage):
    engine = make_engine(stage)
    batch = random_batch(batch_size=8)
    l0 = float(engine.train_batch(batch))
    for _ in range(15):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0, f"stage {stage}: loss did not decrease"


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_stage0(stage):
    batch = random_batch(batch_size=8)
    e0 = make_engine(0)
    es = make_engine(stage)
    for _ in range(5):
        l0 = e0.train_batch(batch)
        ls = es.train_batch(batch)
    np.testing.assert_allclose(float(l0), float(ls), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(e0.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(es.state.params))):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_zero1_opt_state_is_sharded():
    engine = make_engine(1)
    engine.train_batch(random_batch(batch_size=8))
    # the big Dense kernel moments should be sharded over 'data'
    m = engine.state.opt_state["exp_avg"]
    leaves = jax.tree_util.tree_leaves(m)
    sharded = [l for l in leaves
               if any(DATA_AXIS in (ax if isinstance(ax, tuple) else (ax,))
                      for ax in l.sharding.spec if ax is not None)]
    assert sharded, "no optimizer-state leaf is sharded over the data axis"
    # params remain replicated at stage 1
    for p in jax.tree_util.tree_leaves(engine.state.params):
        assert all(ax is None for ax in p.sharding.spec), p.sharding


def test_zero3_params_sharded():
    engine = make_engine(3)
    engine.train_batch(random_batch(batch_size=8))
    leaves = jax.tree_util.tree_leaves(engine.state.params)
    sharded = [l for l in leaves
               if any(ax is not None for ax in l.sharding.spec)]
    assert sharded, "stage 3 should shard parameters at rest"


def test_shard_spec_for_leaf():
    # largest divisible dim gets the data axis
    assert shard_spec_for_leaf((16, 64), 8) == P(None, "data")
    assert shard_spec_for_leaf((64, 16), 8) == P("data", None)
    # indivisible → replicated
    assert shard_spec_for_leaf((3, 5), 8) == P(None, None)
    # respects existing TP axis
    assert shard_spec_for_leaf((64, 64), 8, base_spec=P(None, "model")) == \
        P("data", "model")
    # below persistence threshold → untouched
    assert shard_spec_for_leaf((64,), 8, min_size=1000) == P(None)


def test_partitioner_stage_rules():
    mesh = make_mesh(MeshConfig(data=8))
    params = {"w": np.zeros((64, 32), np.float32), "b": np.zeros((32,), np.float32)}

    z0 = ZeroPartitioner(mesh, 0)
    assert all(all(a is None for a in s)
               for s in jax.tree_util.tree_leaves(
                   z0.param_specs(params),
                   is_leaf=lambda x: isinstance(x, P)))

    z3 = ZeroPartitioner(mesh, 3)
    specs = z3.param_specs(params)
    assert specs["w"] == P("data", None)

    z2 = ZeroPartitioner(mesh, 2)
    # stage 2: params replicated, grads sharded
    assert z2.param_specs(params)["w"] == P(None, None)
    assert z2.grad_specs(params)["w"] == P("data", None)


def test_zero_offload_cpu_optimizer_config():
    engine = make_engine(2, extra={
        "zero_optimization": {"stage": 2,
                              "offload_optimizer": {"device": "cpu"}}})
    assert engine._config.zero_config.offload_optimizer.enabled
    batch = random_batch(batch_size=8)
    l0 = float(engine.train_batch(batch))
    assert np.isfinite(l0)


def test_fully_specified_batch_config_multi_device():
    """Reference-style config with all three batch params + dp=8 mesh
    (regression: pre-config used world_size=1 and failed the triangle)."""
    import deepspeed_tpu as dstpu
    mesh = make_mesh(MeshConfig(data=8))
    cfg = {"train_batch_size": 16, "train_micro_batch_size_per_gpu": 1,
           "gradient_accumulation_steps": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch(batch_size=16)
    assert np.isfinite(float(engine.train_batch(batch)))


def test_mesh_from_config_section():
    """Mesh built from the json 'mesh' section when none is passed."""
    import deepspeed_tpu as dstpu
    cfg = {"train_batch_size": 8, "mesh": {"data": 4, "model": 2},
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(hidden_dim=32))
    assert engine.mesh.shape["data"] == 4 and engine.mesh.shape["model"] == 2
    assert np.isfinite(float(engine.train_batch(random_batch(batch_size=8))))


@pytest.mark.slow
def test_stage3_persistence_threshold_sweep():
    """SURVEY §7's stage-3 'hard part' knob: sweeping
    stage3_param_persistence_threshold moves leaves between sharded and
    replicated monotonically, and classification follows leaf size
    exactly (reference stage3.py:287-310 keeps small params resident).

    Slow (ISSUE 8 tier-1 wall consolidation): one engine compile per
    sweep point, ~14 s. Tier-1 keeps the knob's two sides pinned by
    test_zero3_params_sharded (threshold 0 shards) and
    tests/test_prefetch.py's below-threshold fallback test (a huge
    threshold keeps leaves replicated); the monotonic sweep re-runs
    with -m slow."""
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")

    def sharded_leaves(threshold):
        cfg = {
            "train_batch_size": 8,
            "zero_optimization": {
                "stage": 3,
                "stage3_param_persistence_threshold": threshold},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "steps_per_print": 1000,
        }
        mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=GPT2LMHeadModel(gpt2_tiny()), mesh=mesh)
        batch = {"input_ids": np.random.RandomState(0).randint(
            0, 512, (8, 64)).astype(np.int32)}
        engine.train_batch(batch)
        out = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                engine.state.params)[0]:
            name = "/".join(str(getattr(k, "key", k)) for k in path)
            specs = leaf.sharding.spec if hasattr(leaf.sharding, "spec") \
                else ()
            out[name] = (int(np.prod(leaf.shape)),
                         any(s is not None for s in specs))
        return out

    by_thresh = {t: sharded_leaves(t) for t in (0, 4096, 10**9)}
    counts = {t: sum(sharded for _, sharded in v.values())
              for t, v in by_thresh.items()}
    # monotone: lower threshold → more leaves sharded; huge → none
    assert counts[0] >= counts[4096] >= counts[10**9] == 0, counts
    assert counts[0] > counts[4096], counts
    # classification is exactly by size at the midpoint (divisibility
    # permitting: leaves the partitioner cannot split stay replicated)
    for name, (numel, sharded) in by_thresh[4096].items():
        if numel >= 4096 and by_thresh[0][name][1]:
            assert sharded, (name, numel)
        if numel < 4096:
            assert not sharded, (name, numel)
