"""Quantizer kernel + MoQ + eigenvalue tests (reference: test_moq_*,
csrc/quantization kernel tests, runtime/quantize.py semantics)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.quantizer import (
    ds_quantizer, quantize, quantize_jnp, quantize_packed, dequantize_packed)
from deepspeed_tpu.runtime.quantize import Quantizer
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue


# -- kernel ----------------------------------------------------------------

@pytest.mark.parametrize("bits", [4, 8])
@pytest.mark.parametrize("groups", [1, 4])
@pytest.mark.parametrize("sym", [True, False])
def test_pallas_kernel_matches_jnp(bits, groups, sym):
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 64), jnp.float32)
    a = quantize(x, bits=bits, groups=groups, sym=sym)
    b = quantize_jnp(x, bits=bits, groups=groups, sym=sym)
    # reduction ordering of the scale max differs → 1-ULP wiggle allowed
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("sym", [True, False])
def test_quantization_error_shrinks_with_bits(sym):
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 32), jnp.float32)
    errs = []
    for bits in (2, 4, 8):
        q = quantize_jnp(x, bits=bits, groups=4, sym=sym)
        errs.append(float(jnp.abs(q - x).max()))
    assert errs[0] > errs[1] > errs[2]
    assert errs[2] < 0.05


def test_quantize_idempotent():
    """Quantizing an already-quantized tensor is a fixed point (nearest)."""
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 8), jnp.float32)
    q1 = quantize_jnp(x, bits=8, groups=2)
    q2 = quantize_jnp(q1, bits=8, groups=2)
    np.testing.assert_allclose(np.asarray(q1), np.asarray(q2), atol=1e-7)


def test_stochastic_rounding_unbiased():
    """E[sr_quantize(x)] ≈ x — the property the reference's SR kernels exist
    for (csrc/quantization ds_sr_quantize)."""
    # anchor 1.0 fixes the 2-bit scale at 1.0 (levels -2,-1,0,1); then the
    # 0.3 entries stochastically round to 0 or 1 with E[q]=0.3
    x = np.full((4, 128), 0.3, np.float32)
    x[:, 0] = 1.0
    x = jnp.asarray(x)
    acc = np.zeros((4, 128), np.float64)
    n = 200
    for i in range(n):
        q = quantize(x, bits=2, groups=4, stochastic=True,
                     key=jax.random.PRNGKey(i))
        acc += np.asarray(q, np.float64)
    mean = acc[:, 1:] / n
    assert abs(mean.mean() - 0.3) < 0.02
    # nearest rounding deterministically gives 0 for those entries
    nearest = float(quantize_jnp(x, bits=2, groups=4)[0, 1])
    assert nearest == 0.0


def test_packed_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(3), (16, 16), jnp.float32)
    for sym in (True, False):
        qdata, scale, zero = quantize_packed(x, bits=8, groups=4, sym=sym)
        assert qdata.dtype == (jnp.int8 if sym else jnp.uint8)
        back = dequantize_packed(qdata, scale, zero, x.shape)
        assert float(jnp.abs(back - x).max()) < 0.05


def test_ds_quantizer_api():
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 32), jnp.float32)
    q = ds_quantizer(x, groups=2, bit_num=8)
    assert q.shape == x.shape and q.dtype == x.dtype


# -- MoQ schedule ----------------------------------------------------------

def test_moq_progressive_bit_reduction():
    q = Quantizer(q_start_bits=6, q_target_bits=4, q_period=10, q_groups=2,
                  layer_num=0)
    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (16, 16))}
    bits_seen = []
    for step in range(12):
        params = q.quantize_tree(params)
        bits_seen.append(q.q_start_bits[0])
    assert bits_seen[0] == 6
    assert bits_seen[-1] == 4                      # reached target
    assert sorted(set(bits_seen), reverse=True) == [6, 5, 4]
    # period doubled twice
    assert q.q_period[0] == 40
    assert not q.any_precision_switch()


def test_moq_quantizes_only_2d_floats():
    q = Quantizer(q_start_bits=4, q_target_bits=4, q_period=1)
    w = jax.random.normal(jax.random.PRNGKey(0), (4, 4), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (4,), jnp.float32)
    params = {"w": w, "b": b, "step": jnp.zeros((), jnp.int32)}
    out = q.quantize_tree(params)
    assert not np.allclose(np.asarray(out["w"]), np.asarray(w))  # quantized
    assert len(np.unique(np.asarray(out["w"]))) <= 16
    np.testing.assert_array_equal(np.asarray(out["b"]),
                                  np.asarray(b))                 # untouched
    assert out["step"].dtype == jnp.int32


def test_moq_overflow_skips():
    q = Quantizer(q_start_bits=4, q_target_bits=4, q_period=1)
    w = jax.random.normal(jax.random.PRNGKey(0), (4, 4), jnp.float32)
    out = q.quantize_tree({"w": w}, overflow=True)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(w))


def test_moq_mixed_fp16_blend():
    q = Quantizer(q_start_bits=2, q_target_bits=2, q_period=1000,
                  q_mixed_fp16=True, q_change_ratio=0.5)
    params = {"w": jnp.ones((4, 4)) * 0.3}
    full_q = float(quantize_jnp(params["w"], bits=2, groups=1)[0, 0])
    out1 = float(q.quantize_tree(params)["w"][0, 0])       # ratio 0.5 blend
    out2 = float(q.quantize_tree(params)["w"][0, 0])       # ratio 0.0 → full
    assert abs(out1 - (0.5 * 0.3 + 0.5 * full_q)) < 1e-6
    assert abs(out2 - full_q) < 1e-6


def test_moq_eigenvalue_adjusts_period():
    q = Quantizer(q_start_bits=8, q_target_bits=4, q_period=100,
                  q_eigenvalue=True, layer_num=2)
    q.eigenvalue_adjust([2.0, 0.5])   # layer0 sharp, layer1 flat
    assert q.q_period[0] > q.q_period[1]


# -- eigenvalue ------------------------------------------------------------

def test_power_iteration_quadratic():
    """For loss = 0.5 xᵀ A x the Hessian is A; power iteration must find
    max |eig|."""
    A = np.diag([5.0, 2.0, 1.0]).astype(np.float32)

    def loss(x):
        return 0.5 * x @ jnp.asarray(A) @ x

    ev = Eigenvalue(max_iter=200, tol=1e-5, stability=0.0,
                    layer_name="x", layer_num=1)
    x0 = jnp.ones((3,), jnp.float32)
    lam = ev.compute_eigenvalue(loss, x0)
    assert abs(lam - 5.0) < 1e-2


def test_layerwise_eigenvalues():
    """Per-layer curvature must align with layer indices (layer_1's block
    has the sharper Hessian here) even with interleaved non-layer blocks."""
    def loss(params):
        enc = params["encoder"]
        return 0.5 * (1.0 * jnp.sum(enc["layer_0"]["w"] ** 2)
                      + 3.0 * jnp.sum(enc["layer_1"]["w"] ** 2)
                      + 7.0 * jnp.sum(params["embeddings"]["e"] ** 2))

    ev = Eigenvalue(max_iter=100, tol=1e-5, stability=0.0,
                    layer_name="encoder.layer", layer_num=2)
    params = {"embeddings": {"e": jnp.ones((4,))},
              "encoder": {"layer_0": {"w": jnp.ones((4,))},
                          "layer_1": {"w": jnp.ones((4,))}}}
    blocks = ev.find_layer_blocks(params)
    assert [b[0] for b in blocks] == ["layer_0", "layer_1"]
    lams = ev.compute_layer_eigenvalues(loss, params)
    # layer blocks only — embeddings' 7.0 curvature must NOT leak in
    assert abs(lams[0] - 1.0) < 1e-2 and abs(lams[1] - 3.0) < 1e-2


def test_find_layer_blocks_on_bert():
    from deepspeed_tpu.models.bert import bert_tiny, BertModel
    cfg = bert_tiny(dtype=jnp.float32, num_hidden_layers=3)
    model = BertModel(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ev = Eigenvalue(layer_name="encoder.layer", layer_num=3)
    blocks = ev.find_layer_blocks(params)
    assert len(blocks) == 3
    assert all("TransformerLayer" in b[0] for b in blocks)


def test_moq_overflow_consumes_no_budget():
    """Overflow steps must not advance the MoQ schedule (regression)."""
    q = Quantizer(q_start_bits=8, q_target_bits=4, q_period=10)
    w = jax.random.normal(jax.random.PRNGKey(0), (4, 4), jnp.float32)
    for _ in range(5):
        q.quantize_tree({"w": w}, overflow=True)
    assert q.qsteps == 0 and q.q_start_bits[0] == 8


# -- engine integration ----------------------------------------------------

def test_moq_through_engine():
    """quantize_training config quantizes weights after schedule_offset."""
    import deepspeed_tpu as dstpu
    from tests.simple_model import SimpleModel, random_batch, base_config
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    cfg = base_config()
    cfg["quantize_training"] = {
        "enabled": True,
        "quantize_bits": {"start_bits": 5, "target_bits": 4},
        "quantize_schedule": {"quantize_period": 1, "schedule_offset": 2},
        "quantize_groups": 1,
    }
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch(batch_size=8)
    for _ in range(4):
        engine.train_batch(batch)
    assert engine.quantizer is not None
    assert engine.quantizer.qsteps > 0
    # weights now land on a small quantized grid: few distinct values
    w = np.asarray(jax.device_get(
        jax.tree_util.tree_leaves(engine.state.params)[0]), np.float32)
    if w.ndim == 2:
        assert len(np.unique(np.round(w, 6))) <= 2 ** 6
