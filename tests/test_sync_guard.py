"""Static no-forced-sync guard (ISSUE 4 satellite): the telemetry spans
are honest only if nothing in a hot path forces a device sync per step.
This test pins that property by grepping the hot-path code for host
readbacks — ``float(...)`` / ``.item(...)`` / ``np.asarray(...)`` /
``jax.device_get`` / ``block_until_ready`` — and failing on any
occurrence that is not explicitly annotated ``# sync-ok: <reason>``.

The annotation is the point: every deliberate readback (the serving
scheduler consuming sampled tokens, the steps_per_print boundary fence,
the config-gated trace-window close) is visible and justified in
source, and a NEW unannotated one — the easy way to silently serialize
dispatch against execution — fails CI instead of landing.

Scope (the per-step hot paths):
- ``deepspeed_tpu/parallel/*.py`` (overlap buckets, prefetch pipeline,
  mesh/attention helpers traced into train steps),
- ``deepspeed_tpu/serving/*.py`` (the continuous-batching scheduler,
  including its watchdog hooks; ISSUE 9 grows this glob's coverage to
  the speculative drafters ``drafter.py`` — the ngram drafter must
  stay pure-host and the model drafter's proposal readback is the
  scheduler's existing sampled-token fence — and the prefix-index/COW
  admission path in ``paged_cache.py``, whose page-content probes are
  deliberate host verification at admission, not per-tick syncs),
- ``deepspeed_tpu/telemetry/*.py`` (recording must never sync — ISSUE
  6 extends this to the flight recorder ``recorder.py``, the anomaly
  watchdog ``anomaly.py`` and the dump viewer ``view.py``: rule
  evaluation and dumping consume host scalars their callers already
  read at existing fences),
- ``deepspeed_tpu/runtime/swap_tensor/*.py`` (PR 5: the pipelined swap
  schedules run on the per-step path; their d2h parks and staging-slot
  fences are deliberate and annotated),
- the train-fn builders + per-step methods of ``runtime/engine.py``,
  including the NVMe swap-schedule methods
  (``_train_batch_instrumented`` is excluded: it is the
  wall_clock_breakdown MEASUREMENT mode, whose per-phase fences are
  the documented price of turning that flag on).
"""

import inspect
import pathlib
import re
import textwrap

import deepspeed_tpu

PKG = pathlib.Path(deepspeed_tpu.__file__).parent

FORBIDDEN = re.compile(
    r"(?<![\w.])float\("        # device scalar -> host float
    r"|\.item\("                # torch/np-style scalar readback
    r"|(?<!j)np\.asarray\("     # device array -> host np (jnp.asarray ok)
    r"|jax\.device_get\("
    r"|(?<![\w.])device_get\("
    r"|block_until_ready")

ALLOW = "sync-ok"

HOT_GLOBS = ("parallel/*.py", "serving/*.py", "telemetry/*.py",
             "runtime/swap_tensor/*.py",
             # ISSUE 7: the elastic snapshot layer runs at step
             # boundaries — staging copies and swap-file reads are
             # deliberate host work, device readbacks must be annotated
             "runtime/elastic/*.py",
             # ISSUE 8: the fused matmul+collective kernels trace into
             # every fused_matmul-mode train step — dispatch must stay
             # sync-free (breadcrumbs/counters are trace-time host work)
             "ops/pallas/fused_collective.py")

# engine units scanned via inspect (robust to line moves)
HOT_ENGINE_METHODS = (
    "train_batch", "forward", "backward", "step",
    "_build_jit_fns", "_build_overlap_train_fn",
    "_build_prefetch_train_fn", "_build_compressed_train_fn",
    "_build_sparse_train_fn", "_local_grad_accumulator",
    "_apply_grads", "_telemetry_step", "_telemetry_fold",
    "_telemetry_mfu", "_telemetry_memory_gauges", "_telemetry_export",
    # PR 5: the NVMe swap-schedule methods (park/unpark run per step;
    # the swapper's own d2h/fences live in runtime/swap_tensor/ above)
    "_ensure_params_resident", "_park_params", "_param_swap_order",
    "_make_param_swapper",
    # ISSUE 7: the elastic snapshot hook runs at every step boundary —
    # its stall accounting must stay host-timer-only (the snapshot
    # staging d2h lives in runtime/elastic/snapshot.py above)
    "_elastic_step", "_elastic_commit", "_begin_snapshot",
    "_snapshot_trees", "_make_snapshotter", "_preempt_finalize",
    "_preempt_agreed",
)


def _statements(source):
    """Group physical lines into logical statements (paren depth +
    backslash continuations) so an allow-comment on ANY line of a
    multiline statement covers exactly THAT statement — a blanket
    neighbouring-line whitelist would let an unannotated readback ride
    next to an annotated one. Depth counting is naive about brackets
    inside string literals; the scanned modules keep them balanced (the
    self-test below pins the grouping behaviour)."""
    lines = source.splitlines()
    stmts, cur, start, depth, cont = [], [], 0, 0, False
    for i, line in enumerate(lines):
        if not cur:
            start = i
        cur.append(line)
        code = line.split("#", 1)[0]
        depth += sum(code.count(c) for c in "([{") \
            - sum(code.count(c) for c in ")]}")
        cont = code.rstrip().endswith("\\")
        if depth <= 0 and not cont:
            stmts.append((start, cur))
            cur, depth = [], 0
    if cur:
        stmts.append((start, cur))
    return stmts


def _check(name, source):
    bad = []
    for start, stmt in _statements(source):
        code = "\n".join(l.split("#", 1)[0] for l in stmt)
        if FORBIDDEN.search(code) and not any(ALLOW in l for l in stmt):
            bad.append(f"{name}:{start + 1}: {stmt[0].strip()}")
    return bad


def test_hot_path_modules_have_no_unannotated_syncs():
    bad = []
    for pattern in HOT_GLOBS:
        for path in sorted(PKG.glob(pattern)):
            bad += _check(str(path.relative_to(PKG.parent)),
                          path.read_text())
    assert not bad, (
        "unannotated host readback(s) in hot-path modules — either hoist "
        "them out of the per-step path or annotate '# sync-ok: <reason>' "
        "with a justification:\n" + "\n".join(bad))


def test_engine_train_paths_have_no_unannotated_syncs():
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    bad = []
    for meth in HOT_ENGINE_METHODS:
        fn = inspect.unwrap(getattr(DeepSpeedEngine, meth))
        src = textwrap.dedent(inspect.getsource(fn))
        bad += _check(f"DeepSpeedEngine.{meth}", src)
    assert not bad, (
        "unannotated host readback(s) in engine per-step paths:\n"
        + "\n".join(bad))


def test_guard_regex_catches_the_patterns():
    """The guard itself must keep teeth: each forbidden form is caught,
    the allowed forms are not."""
    assert _check("x", "v = float(loss)\n")
    assert _check("x", "v = loss.item()\n")
    assert _check("x", "v = np.asarray(dev_arr)\n")
    assert _check("x", "v = jax.device_get(x)\n")
    assert _check("x", "jax.block_until_ready(x)\n")
    assert not _check("x", "v = jnp.asarray(host)\n")
    assert not _check("x", "v = np.float32(1.0)\n")
    assert not _check("x", "x: float = 0.0\n")
    assert not _check("x", "v = float(loss)  # sync-ok: boundary fence\n")
    # annotation on the continuation line covers a multiline statement
    assert not _check("x", "v = np.asarray(\n    a)  # sync-ok: host\n")
    # …but covers ONLY that statement: an unannotated readback on the
    # next physical line must still fail (the adjacency-whitelist hole)
    assert _check("x", "a = 1  # sync-ok: x\nv = float(dev)\n")
    assert _check("x", "v = np.asarray(\n    a)  # sync-ok: host\n"
                       "w = jax.device_get(b)\n")
