"""Tile-granularity fused matmul+collective kernels (ISSUE 8,
ops/pallas/fused_collective.py).

Numerics contract: both kernels must reproduce a plain ``jnp.einsum``
over the gathered full weight to fp32 partial-sum rounding — across
backends (the lax decomposed ring and the pallas kernels in interpret
mode), shard dims, transposes, dtypes (fp32/bf16), uneven chunk
shapes, and mesh sizes 2/4/8. The custom-VJP pairing must match dense
autodiff, with dW returned as the shard-shaped SUM over the axis (the
prefetch pipeline's sharded-leaf contract). The real-chip Mosaic
lowering (``interpret=False``) is the slow/skipif-gated test at the
bottom — the ROADMAP axon backlog item.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import shard_map
from deepspeed_tpu.ops.pallas import fused_collective as fc


def _mesh(n):
    devs = jax.devices()
    assert len(devs) >= n
    return Mesh(np.asarray(devs[:n]), ("data",))


def _cfg(n, backend, tile_m=8, interpret=True):
    return fc.CollectiveMatmulConfig(
        axis_name="data", axis_size=n, backend=backend, tile_m=tile_m,
        min_shard_bytes=0, interpret=interpret)


def _run_ag(n, dtype, shard_dim, transpose_w, backend, M=32, K=48, N=64,
            tile_m=8, interpret=True):
    """all_gather_matmul vs einsum over the gathered weight; returns
    max abs error."""
    mesh = _mesh(n)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(M, N if transpose_w else K)
                    .astype(np.float32) * 0.1, dtype)
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1, dtype)
    ref = x.astype(jnp.float32) @ \
        (w.T if transpose_w else w).astype(jnp.float32)
    cfg = _cfg(n, backend, tile_m, interpret)

    def f(x_l, w_l):
        return fc.all_gather_matmul(
            x_l, w_l, shard_dim=shard_dim, axis_name="data", axis_size=n,
            transpose_w=transpose_w, cfg=cfg, out_dtype=jnp.float32)

    wspec = P("data", None) if shard_dim == 0 else P(None, "data")
    g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), wspec),
                          out_specs=P(), check_vma=False))
    return float(jnp.max(jnp.abs(g(x, w) - ref)))


def _run_rs(n, dtype, shard_dim, backend, M=32, K=48, N=64, tile_m=8):
    """matmul_reduce_scatter vs the dense lhs^T @ rhs (x axis_size:
    identical local operands, so the SUM over the axis is n * dense);
    returns max abs error on the reassembled full gradient."""
    mesh = _mesh(n)
    rng = np.random.RandomState(1)
    lhs = jnp.asarray(rng.randn(M, K).astype(np.float32) * 0.1, dtype)
    rhs = jnp.asarray(rng.randn(M, N).astype(np.float32) * 0.1, dtype)
    ref = lhs.astype(jnp.float32).T @ rhs.astype(jnp.float32) * n
    cfg = _cfg(n, backend)

    def f(l, r):
        return fc.matmul_reduce_scatter(
            l, r, shard_dim=shard_dim, axis_name="data", axis_size=n,
            cfg=cfg)

    out_spec = P("data", None) if shard_dim == 0 else P(None, "data")
    g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), P()),
                          out_specs=out_spec, check_vma=False))
    return float(jnp.max(jnp.abs(g(lhs, rhs) - ref)))


# ---------------------------------------------------------------------------
# all-gather+matmul numerics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shard_dim", [0, 1])
@pytest.mark.parametrize("transpose_w", [False, True])
@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_ag_matmul_matches_einsum(shard_dim, transpose_w, backend):
    err = _run_ag(4, jnp.float32, shard_dim, transpose_w, backend)
    assert err < 1e-5, err


@pytest.mark.parametrize("n", [2, 8])
@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_ag_matmul_mesh_sizes(n, backend):
    assert _run_ag(n, jnp.float32, 0, False, backend) < 1e-5
    assert _run_ag(n, jnp.float32, 1, False, backend) < 1e-5


@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_ag_matmul_bf16(backend):
    # bf16 inputs, fp32 accumulation: tolerance is bf16 input rounding
    assert _run_ag(4, jnp.bfloat16, 0, False, backend) < 5e-2
    assert _run_ag(4, jnp.bfloat16, 1, True, backend) < 5e-2


@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_ag_matmul_uneven_chunks(backend):
    # K=56 over n=8 -> 7-wide chunks; M=24 with tile_m=7 exercises the
    # divisor clamp (7 does not divide 24; largest divisor <= 7 is 6)
    assert _run_ag(8, jnp.float32, 0, False, backend,
                   M=24, K=56, N=40, tile_m=7) < 1e-5


# ---------------------------------------------------------------------------
# matmul+reduce-scatter numerics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shard_dim", [0, 1])
@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_mm_rs_matches_dense(shard_dim, backend):
    assert _run_rs(4, jnp.float32, shard_dim, backend) < 1e-5


@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_mm_rs_mesh_sizes_and_bf16(backend):
    assert _run_rs(2, jnp.float32, 0, backend) < 1e-5
    assert _run_rs(8, jnp.float32, 1, backend) < 1e-5
    assert _run_rs(4, jnp.bfloat16, 0, backend, M=24, K=32, N=16) < 5e-2


# ---------------------------------------------------------------------------
# custom-VJP pairing (the prefetch pipeline's grad contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shard_dim", [0, 1])
@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_collective_matmul_vjp_matches_dense(shard_dim, backend):
    n, M, K, N = 4, 16, 32, 24
    mesh = _mesh(n)
    rng = np.random.RandomState(2)
    x = rng.randn(n * M, K).astype(np.float32) * 0.1
    w = rng.randn(K, N).astype(np.float32) * 0.1
    cfg = _cfg(n, backend)

    def local_loss(x_l, w_l):
        y = fc.collective_matmul(x_l, w_l, shard_dim=shard_dim,
                                 axis_name="data", axis_size=n, cfg=cfg)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def f(x_l, w_l):
        loss = local_loss(x_l, w_l)
        gx, gw = jax.grad(local_loss, argnums=(0, 1))(x_l, w_l)
        return jax.lax.psum(loss, "data"), gx, gw

    wspec = P("data", None) if shard_dim == 0 else P(None, "data")
    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=(P("data", None), wspec),
                          out_specs=(P(), P("data", None), wspec),
                          check_vma=False))
    loss, gx, gw = g(jnp.asarray(x), jnp.asarray(w))

    def ref_loss(x_r, w_r):
        return jnp.sum((x_r @ w_r) ** 2)

    rl = ref_loss(jnp.asarray(x), jnp.asarray(w))
    rgx, rgw = jax.grad(ref_loss, argnums=(0, 1))(
        jnp.asarray(x), jnp.asarray(w))
    # dW comes back as the SUM over the axis (each device contributed
    # its local batch rows exactly once -> reassembled == dense total)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rgx),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rgw),
                               atol=2e-4, rtol=1e-4)


@pytest.mark.parametrize("backend", ["lax", "fused"])
def test_collective_matmul_vjp_bf16(backend):
    """bf16 primal / bf16 dW contract: the matmul+RS accumulates the
    true partial sums in fp32 and rounds ONCE to the param dtype on
    output — dW must land within bf16 rounding of the dense fp32
    gradient (the prefetch fused-leaf contract under grad_dtype=bf16)."""
    n, M, K, N = 4, 16, 32, 24
    mesh = _mesh(n)
    rng = np.random.RandomState(5)
    x = (rng.randn(n * M, K) * 0.1).astype(np.float32)
    w = (rng.randn(K, N) * 0.1).astype(np.float32)
    cfg = _cfg(n, backend)

    def local_loss(x_l, w_l):
        y = fc.collective_matmul(x_l, w_l, shard_dim=0,
                                 axis_name="data", axis_size=n, cfg=cfg)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def f(x_l, w_l):
        gw = jax.grad(local_loss, argnums=1)(x_l, w_l)
        return gw

    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=(P("data", None), P("data", None)),
                          out_specs=P("data", None), check_vma=False))
    gw = g(jnp.asarray(x, jnp.bfloat16), jnp.asarray(w, jnp.bfloat16))
    assert gw.dtype == jnp.bfloat16
    rgw = jax.grad(lambda wr: jnp.sum((
        jnp.asarray(x, jnp.bfloat16).astype(jnp.float32)
        @ wr.astype(jnp.float32)) ** 2))(jnp.asarray(w, jnp.bfloat16))
    np.testing.assert_allclose(np.asarray(gw, np.float32),
                               np.asarray(rgw, np.float32),
                               rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# dispatch plumbing
# ---------------------------------------------------------------------------

def test_infer_shard_dim():
    assert fc.infer_shard_dim((16, 8), 16, 8, 4) is None     # full
    assert fc.infer_shard_dim((4, 8), 16, 8, 4) == 0
    assert fc.infer_shard_dim((16, 2), 16, 8, 4) == 1
    with pytest.raises(ValueError):
        fc.infer_shard_dim((5, 8), 16, 8, 4)


def test_gather_scope_nesting():
    assert fc.gather_ctx() is None
    c1 = fc.CollectiveMatmulConfig(axis_size=2)
    c2 = fc.CollectiveMatmulConfig(axis_size=4)
    with fc.gather_scope(c1):
        assert fc.gather_ctx() is c1
        with fc.gather_scope(c2):
            assert fc.gather_ctx() is c2
        assert fc.gather_ctx() is c1
    assert fc.gather_ctx() is None


def test_backend_validation():
    with pytest.raises(ValueError):
        fc.all_gather_matmul(
            jnp.zeros((4, 8)), jnp.zeros((4, 4)), shard_dim=0,
            axis_name="data", axis_size=2,
            cfg=fc.CollectiveMatmulConfig(backend="nope"))


def test_auto_backend_feasibility_gates():
    """backend="auto" must route through the lax ring when the pallas
    kernel is infeasible: the contracting kernel's VMEM chunk stash
    over budget, or unaligned lane minors on compiled (non-interpret)
    hardware. The gates are pure host math — pinned directly."""
    cfg = fc.CollectiveMatmulConfig(vmem_budget_bytes=8 << 20)
    # (1024, 4096) fp32 shard x n=4 -> 64 MiB full W: over budget when
    # contracting (full-W stash); the non-contracting kernel's 2
    # chunk-sized comm slots (2 x 16 MiB) are over budget too
    assert fc._ag_auto_fallback(cfg, (1024, 4096), 4, True, 4,
                                True) == "vmem_budget"
    assert fc._ag_auto_fallback(cfg, (1024, 4096), 4, False, 4,
                                True) == "vmem_budget"
    # (256, 1024) fp32 shard -> 2 x 1 MiB comm slots: inside budget
    assert fc._ag_auto_fallback(cfg, (256, 1024), 4, False, 4,
                                True) is None
    # unaligned minors: fine in interpret, unlower on real Mosaic —
    # BOTH shard dims count (each is a lane minor in some variant of
    # the fwd/dx/dW kernel family, e.g. a dim-0 shard's row count is
    # the x-block minor of the contracting forward)
    assert fc._ag_auto_fallback(cfg, (128, 120), 4, False, 4,
                                True) is None
    assert fc._ag_auto_fallback(cfg, (128, 120), 4, False, 4,
                                False) == "lane_alignment"
    assert fc._ag_auto_fallback(cfg, (96, 2304), 4, False, 4,
                                False) == "lane_alignment"
    assert fc._ag_auto_fallback(cfg, (128, 256), 4, False, 4,
                                False) is None
    # RS: acc + 2 carry slots of fp32 shard scratch
    assert fc._rs_auto_fallback(cfg, 8192, 4096, True, 4,
                                True) == "vmem_budget"
    assert fc._rs_auto_fallback(cfg, 512, 256, True, 4, True) is None
    assert fc._rs_auto_fallback(cfg, 512, 240, True, 4,
                                False) == "lane_alignment"
    assert fc._rs_auto_fallback(cfg, 520, 256, True, 4,
                                False) == "lane_alignment"
    assert fc._rs_auto_fallback(cfg, 512, 256, True, 4, False) is None


def test_single_device_bypasses_collectives():
    # n == 1: plain dot, no axis binding required
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
    w = jnp.asarray(np.random.RandomState(1).randn(8, 6), jnp.float32)
    y = fc.all_gather_matmul(x, w, shard_dim=0, axis_name="data",
                             axis_size=1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w),
                               atol=1e-6)
    g = fc.matmul_reduce_scatter(x, x, shard_dim=0,
                                 axis_name="data", axis_size=1)
    np.testing.assert_allclose(np.asarray(g), np.asarray(x.T @ x),
                               atol=1e-6)


# ---------------------------------------------------------------------------
# CollectiveDense (models/gpt2.py) — the body-side consumer
# ---------------------------------------------------------------------------

def test_collective_dense_is_dense_outside_scope():
    import flax.linen as nn
    from deepspeed_tpu.models.gpt2 import CollectiveDense
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16), jnp.float32)
    d_ref = nn.Dense(24, dtype=jnp.float32)
    d_col = CollectiveDense(24, dtype=jnp.float32)
    p_ref = d_ref.init(jax.random.PRNGKey(0), x)
    p_col = d_col.init(jax.random.PRNGKey(0), x)
    assert jax.tree_util.tree_structure(p_ref) == \
        jax.tree_util.tree_structure(p_col)
    np.testing.assert_array_equal(np.asarray(d_ref.apply(p_ref, x)),
                                  np.asarray(d_col.apply(p_col, x)))


def test_collective_dense_consumes_shard_in_scope():
    from deepspeed_tpu.models.gpt2 import CollectiveDense
    n = 4
    mesh = _mesh(n)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 16), jnp.float32)
    dense = CollectiveDense(24, dtype=jnp.float32)
    params = dense.init(jax.random.PRNGKey(0), x)["params"]
    full = dense.apply({"params": params}, x)
    cfg = _cfg(n, "lax")

    def f(x_l, k_shard, b):
        with fc.gather_scope(cfg):
            return dense.apply(
                {"params": {"kernel": k_shard, "bias": b}}, x_l)

    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=(P(), P(None, "data"), P()),
                          out_specs=P(), check_vma=False))
    out = g(x, params["kernel"], params["bias"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               atol=2e-6)


# ---------------------------------------------------------------------------
# real-chip Mosaic lowering (ROADMAP axon backlog)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Mosaic lowering of the in-kernel RDMA ring "
                           "(ppermute-inside-pallas + the neighbor "
                           "credit protocol) needs a real TPU slice")
def test_fused_kernels_real_chip_parity():
    """interpret=False parity for BOTH kernels on a real slice: the
    compiled Mosaic ring (RDMA + credit semaphores, which interpret
    mode skips) against the lax decomposed-ring reference."""
    n = len(jax.devices())
    assert n >= 2
    for shard_dim in (0, 1):
        e_f = _run_ag(n, jnp.float32, shard_dim, False, "fused",
                      M=256, K=128 * n, N=256, tile_m=128,
                      interpret=False)
        assert e_f < 1e-4, (shard_dim, e_f)
    mesh = _mesh(n)
    rng = np.random.RandomState(3)
    lhs = jnp.asarray(rng.randn(256, 128 * n).astype(np.float32))
    rhs = jnp.asarray(rng.randn(256, 256).astype(np.float32))
    for shard_dim in (0, 1):
        outs = {}
        for backend in ("lax", "fused"):
            cfg = fc.CollectiveMatmulConfig(
                "data", n, backend, 128, 0, False)

            def f(l, r):
                return fc.matmul_reduce_scatter(
                    l, r, shard_dim=shard_dim, axis_name="data",
                    axis_size=n, cfg=cfg)

            out_spec = P("data", None) if shard_dim == 0 \
                else P(None, "data")
            g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), P()),
                                  out_specs=out_spec, check_vma=False))
            outs[backend] = np.asarray(g(lhs, rhs))
        np.testing.assert_allclose(outs["fused"], outs["lax"],
                                   rtol=1e-5, atol=1e-4)
