"""Flight recorder + anomaly watchdog (ISSUE 6): bounded ring
semantics, fence-point rule evaluation, one-shot dumps for the three
injected anomalies (NaN loss through a real engine boundary, a seeded
swap-stall spike, a throttled-tick TTFT blowup through the serving
scheduler), and the dump viewer. All fast — the only engine compile is
the SimpleModel step the telemetry tests already pay."""

import json
import os
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.telemetry import view
from deepspeed_tpu.telemetry.anomaly import RollingOutlierRule, Watchdog
from deepspeed_tpu.telemetry.recorder import (FlightRecorder,
                                              default_recorder)
from tests.simple_model import SimpleModel, base_config


# --------------------------------------------------------------- recorder

def test_recorder_ring_is_bounded_and_ordered():
    rec = FlightRecorder(capacity=64)
    for i in range(200):
        rec.record("step", step=i)
    evs = rec.events()
    assert len(evs) == 64
    assert [e["step"] for e in evs] == list(range(136, 200))
    # seq is monotonic and survives the ring wrap
    assert [e["seq"] for e in evs] == list(range(137, 201))


def test_recorder_disabled_is_a_noop_and_configure_flips():
    rec = FlightRecorder(capacity=64, enabled=False)
    rec.record("x")
    assert len(rec) == 0
    rec.configure(enabled=True)
    rec.record("x")
    assert len(rec) == 1
    rec.configure(capacity=128)          # resize keeps events
    assert len(rec) == 1 and rec.capacity == 128


def test_recorder_step_context_stamps_events():
    rec = FlightRecorder()
    rec.set_step(7)
    rec.record("span", tag="t", dur_s=0.1)
    rec.record("loss", step=9, loss=1.0)   # explicit step wins
    evs = rec.events()
    assert evs[0]["step"] == 7 and evs[1]["step"] == 9


def test_recorder_thread_safety():
    rec = FlightRecorder(capacity=4096)

    def worker(k):
        for i in range(200):
            rec.record("t", worker=k, i=i)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = rec.events()
    assert len(evs) == 800
    assert len({e["seq"] for e in evs}) == 800     # no lost updates


# ------------------------------------------------------------- rule logic

def test_rolling_outlier_rule_warmup_trip_latch_rearm():
    r = RollingOutlierRule("x", factor=3.0, min_samples=4, window=16)
    assert r.observe(100.0) is None      # warming: even a huge value
    for _ in range(4):
        assert r.observe(0.1) is None
    det = r.observe(10.0)
    assert det and det["value"] == 10.0 and det["threshold"] > 0
    assert r.observe(10.0) is None       # latched
    assert r.observe(0.1) is None        # re-arms (and feeds baseline)
    assert r.observe(10.0)               # trips again


def test_rolling_outlier_rule_absolute_floor():
    r = RollingOutlierRule("x", factor=3.0, min_value=0.05,
                           min_samples=2)
    r.observe(0.001)
    r.observe(0.001)
    assert r.observe(0.01) is None       # 10x baseline but under floor
    assert r.observe(0.2)                # over both


# ------------------------------------------------- watchdog + dump format

def _prefilled_recorder(n=40):
    rec = FlightRecorder(capacity=256)
    for i in range(n):
        rec.record("step", step=i, tokens=128, swap_stall_s=0.01)
    return rec


def _dump_files(d):
    return sorted(f for f in os.listdir(d) if f.startswith("flight_"))


def test_swap_stall_spike_produces_exactly_one_dump(tmp_path):
    """Satellite: a seeded swap-stall spike -> one dump with the last
    >= 32 ring events; repeated spikes in the same episode stay
    latched."""
    rec = _prefilled_recorder(40)
    w = Watchdog(str(tmp_path), recorder=rec, source="train",
                 min_samples=4)
    for _ in range(8):
        assert w.observe_swap_stall(0.01) is None
    path = w.observe_swap_stall(1.0)     # the seeded spike
    assert path and os.path.exists(path)
    assert w.observe_swap_stall(1.0) is None    # latched
    assert _dump_files(tmp_path) == [os.path.basename(path)]
    header, events, skipped = view.load_dump(path)
    assert skipped == 0
    assert header["rule"] == "swap_stall_outlier"
    assert header["dump_id"] == 1 and header["source"] == "train"
    assert header["detail"]["value"] == 1.0
    assert len(events) >= 32             # the last >=32 ring events
    assert events == rec.events()[:len(events)]  # pre-anomaly history
    assert w.snapshot()["trips"] == {"swap_stall_outlier": 1}


def test_step_time_outlier_and_dump_counters(tmp_path):
    from deepspeed_tpu.telemetry import MetricsRegistry
    reg = MetricsRegistry()
    w = Watchdog(str(tmp_path), recorder=_prefilled_recorder(),
                 registry=reg, min_samples=4)
    for _ in range(6):
        assert w.observe_step_time(0.1) is None
    assert w.observe_step_time(0.5)      # > 3x baseline
    snap = reg.snapshot()
    assert snap["counters"]["watchdog/dumps"] == 1
    assert snap["counters"]["watchdog/trips/step_time_outlier"] == 1
    assert snap["gauges"]["watchdog/last_dump_id"] == 1


def test_nan_latch_and_unwritable_dir_is_nonfatal(tmp_path):
    w = Watchdog(os.path.join(str(tmp_path), "no", "such", "dir"),
                 recorder=_prefilled_recorder())
    # makedirs creates it — use a FILE as the dir to force the failure
    blocker = tmp_path / "blocked"
    blocker.write_text("x")
    w2 = Watchdog(str(blocker), recorder=_prefilled_recorder())
    assert w2.check_loss(np.nan) is None          # dump failed...
    assert w2.dump_id == 1                        # ...trip still counted
    assert w2.check_loss(np.inf) is None          # latched
    assert w2.check_loss(1.0) is None             # finite re-arms
    assert w2.check_loss(np.nan) is None and w2.dump_id == 2
    assert w.check_loss(1.0) is None and w.dump_id == 0


# ----------------------------------------------- anomaly 1: NaN loss (e2e)

def test_forced_nan_loss_dumps_once_through_engine_boundary(tmp_path):
    """A real engine run: finite steps build >= 32 ring events, then a
    batch of infs drives the loss non-finite — the steps_per_print
    boundary readback (the fence the engine already pays) trips the
    watchdog exactly once, and the dump renders in the viewer."""
    default_recorder().clear()
    dump_dir = str(tmp_path / "flight")
    cfg = base_config(steps_per_print=1)
    cfg["monitor"] = {"enabled": False,
                      "flight_recorder": {"capacity": 512},
                      # step_time_factor raised way past CPU-harness
                      # jitter: THIS test is about the NaN rule, and a
                      # contended box can legitimately produce a 3x
                      # step-time outlier during warmup (observed flake)
                      "watchdog": {"dump_dir": dump_dir,
                                   "min_samples": 4,
                                   "step_time_factor": 100.0}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    assert engine.watchdog is not None
    rs = np.random.RandomState(0)
    batch = (rs.randn(8, 8).astype(np.float32),
             rs.randint(0, 4, size=(8,)).astype(np.int32))
    for _ in range(12):
        engine.train_batch(batch)
    assert not (os.path.isdir(dump_dir) and _dump_files(dump_dir))
    bad = (np.full((8, 8), np.inf, np.float32), batch[1])
    for _ in range(3):                   # NaN persists: still ONE dump
        engine.train_batch(bad)
    files = _dump_files(dump_dir)
    assert len(files) == 1, files
    path = os.path.join(dump_dir, files[0])
    header, events, _ = view.load_dump(path)
    assert header["rule"] == "nan_loss"
    assert len(events) >= 32
    kinds = {e["kind"] for e in events}
    assert {"span", "step", "loss"} <= kinds
    # the engine's serving-style snapshot surfaces the trip
    assert engine.watchdog.dump_id == 1
    assert engine.watchdog.last_anomaly["rule"] == "nan_loss"
    # viewer renders the real dump
    out = _render_lines(path)
    assert "nan_loss" in out and "per-step phase attribution" in out


def _render_lines(path):
    return "\n".join(view.render(path, tail_events=4))


# ------------------------------------- anomaly 2+3: serving TTFT / pool

class _StubAdapter:
    """Host-only adapter: instant prefill/tick, so the scheduler (and
    only the scheduler) is under test. Matches the adapter protocol the
    ContinuousBatcher drives."""

    def __init__(self, spec):
        self.spec = spec

    def make_cache(self):
        from deepspeed_tpu.serving.paged_cache import PagedKVCache
        return PagedKVCache(self.spec)

    def max_prompt_len(self):
        return 4096

    def prefill(self, pool, ids, length, pages):
        return pool, np.zeros((16,), np.float32)

    def tick(self, pool, toks, pos, pt, seeds, idxs, temps, steps=1):
        return pool, np.ones((steps, self.spec.slots), np.int32), None


def _serving_engine(tmp_path, num_blocks=0, min_samples=4):
    from deepspeed_tpu.serving.paged_cache import PagedCacheSpec
    from deepspeed_tpu.serving.engine import ContinuousBatcher
    spec = PagedCacheSpec(n_layers=1, kv_heads=1, head_dim=4,
                          page_size=4, max_pages_per_slot=4, slots=2,
                          num_blocks=num_blocks, dtype=jnp.float32)
    rec = _prefilled_recorder(40)
    w = Watchdog(str(tmp_path), recorder=rec, source="serving",
                 min_samples=min_samples)
    return ContinuousBatcher(_StubAdapter(spec), recorder=rec,
                             watchdog=w), w, rec


def test_throttled_tick_ttft_blowup_dumps_once(tmp_path):
    """Baseline TTFTs from fast admissions, then one request whose
    admission was throttled (its clock started long before the
    scheduler got to it) — the TTFT rule trips exactly once at the
    admission sweep, and metrics_snapshot surfaces dump_id /
    last-anomaly."""
    from deepspeed_tpu.serving.engine import Request
    eng, w, _ = _serving_engine(tmp_path)
    for i in range(6):                   # fast-TTFT baseline
        eng.submit(Request(i, np.zeros((4,), np.int32),
                           max_new_tokens=2))
        while eng.pending:
            eng.step()
    snap = eng.metrics_snapshot()
    assert snap["dump_id"] == 0 and snap["last_anomaly"] is None
    late = Request("late", np.zeros((4,), np.int32), max_new_tokens=2)
    eng.submit(late)
    late._t_submit = time.monotonic() - 30.0   # throttled for 30 s
    while eng.pending:
        eng.step()
    files = _dump_files(tmp_path)
    assert len(files) == 1 and "ttft_blowup" in files[0]
    header, events, _ = view.load_dump(os.path.join(str(tmp_path),
                                                    files[0]))
    assert header["rule"] == "ttft_blowup"
    assert header["detail"]["rid"] == "late"
    assert len(events) >= 32
    snap = eng.metrics_snapshot()
    assert snap["dump_id"] == 1
    assert snap["last_anomaly"]["rule"] == "ttft_blowup"
    assert snap["watchdog"]["trips"] == {"ttft_blowup": 1}


def test_page_pool_exhaustion_dumps_once_and_rearms(tmp_path):
    """Two requests that cannot share the pool: the second's blocked
    admission trips page_pool_exhausted ONCE (latched across retries);
    after the pool frees and an admission succeeds the rule re-arms."""
    from deepspeed_tpu.serving.engine import Request
    eng, w, rec = _serving_engine(tmp_path, num_blocks=7)  # 6 usable
    eng.submit(Request(0, np.zeros((8,), np.int32), max_new_tokens=8))
    eng.submit(Request(1, np.zeros((8,), np.int32), max_new_tokens=8))
    done = {}
    for _ in range(40):
        for r in eng.step():
            done[r.rid] = r
        if not eng.pending:
            break
    assert set(done) == {0, 1}
    files = _dump_files(tmp_path)
    assert len(files) == 1 and "page_pool_exhausted" in files[0]
    assert not w._pool_tripped           # re-armed by the later admit
    kinds = [e["kind"] for e in rec.events()]
    assert "pool_exhausted" in kinds and "finish" in kinds
    # request lifecycle is in the ring: admit -> prefill -> finish
    admits = [e for e in rec.events() if e["kind"] == "admit"]
    assert {e["rid"] for e in admits} == {0, 1}


def test_serving_events_render_request_timelines(tmp_path):
    from deepspeed_tpu.serving.engine import Request
    eng, w, rec = _serving_engine(tmp_path)
    eng.submit(Request(3, np.zeros((4,), np.int32), max_new_tokens=3))
    while eng.pending:
        eng.step()
    path = w.force_dump("manual")
    out = _render_lines(path)
    assert "per-request timelines" in out
    assert "prompt_toks" in out and "length" in out   # finish reason


def test_recorder_disabled_engine_records_nothing(tmp_path):
    """monitor.flight_recorder.enabled=false: the hot-path record()
    calls all no-op (the recorder-off cost is one branch — the bench's
    <1% overhead contract)."""
    default_recorder().clear()
    cfg = base_config(steps_per_print=1)
    cfg["monitor"] = {"enabled": False,
                      "flight_recorder": {"enabled": False}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    assert engine.watchdog is None
    rs = np.random.RandomState(0)
    batch = (rs.randn(8, 8).astype(np.float32),
             rs.randint(0, 4, size=(8,)).astype(np.int32))
    for _ in range(3):
        engine.train_batch(batch)
    assert len(default_recorder()) == 0
    default_recorder().configure(enabled=True)   # undo for later tests


# ------------------------------------------------------------------ config

def test_monitor_subblock_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    c = DeepSpeedConfig({"train_batch_size": 4})
    mc = c.monitor_config
    assert mc.flight_recorder.enabled and mc.flight_recorder.capacity \
        == 4096
    assert not mc.watchdog.enabled
    c = DeepSpeedConfig({"train_batch_size": 4,
                         "monitor": {"enabled": False,
                                     "watchdog": {"dump_dir": "/tmp/x"}}})
    assert c.monitor_config.watchdog.enabled     # own gate, not monitor's
    assert not c.monitor_config.enabled
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 4,
                         "monitor": {"flight_recorder": {"capacity": 8}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 4,
                         "monitor": {"watchdog":
                                     {"step_time_factor": 0.5}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 4,
                         "monitor": {"jsonl_max_files": 0}})


# ----------------------------------------------------------------- viewer

def test_view_cli_on_synthetic_dump_and_missing_file(tmp_path, capsys):
    path = str(tmp_path / "d.jsonl")
    t0 = 1000.0
    lines = [
        {"kind": "dump_header", "rule": "step_time_outlier",
         "dump_id": 2, "source": "train", "ts": t0, "n_events": 4,
         "detail": {"value": 0.9, "threshold": 0.3}},
        {"kind": "span", "tag": "train/step_dispatch", "dur_s": 0.01,
         "step": 5, "ts": t0, "seq": 1},
        {"kind": "step", "step": 5, "tokens": 1024,
         "swap_stall_s": 0.002, "ts": t0, "seq": 2},
        {"kind": "loss", "step": 5, "loss": 2.5, "ts": t0, "seq": 3},
        {"kind": "swap_in", "step": 5, "bytes_read": 2 ** 20,
         "cache_hit_bytes": 0, "leaves": 3, "ts": t0, "seq": 4},
        "this line is not json",
    ]
    with open(path, "w") as fh:
        for l in lines:
            fh.write((l if isinstance(l, str) else json.dumps(l))
                     + "\n")
    assert view.main([path, "--events", "2"]) == 0
    out = capsys.readouterr().out
    assert "step_time_outlier" in out
    assert "step_dispatch" in out and "2.5" in out
    assert "swap-tier I/O per step" in out
    assert "1 unparseable line(s) skipped" in out
    assert view.main([str(tmp_path / "missing.jsonl")]) == 2


def test_view_renders_comm_bytes_column_and_hierarchy_plan(tmp_path):
    """ISSUE 10 satellite: step events carrying the hierarchical comm
    cost model render a per-step comm-bytes column in the phase table;
    the onebit_freeze ring event marks the transition and the
    comm_hierarchy_plan breadcrumb shows up with the bucket plans."""
    import json
    path = tmp_path / "comm.jsonl"
    events = [
        {"kind": "comm_hierarchy_plan", "buckets": 1, "compressed": 1,
         "inter": 2, "intra": 4, "policy": "always"},
        {"kind": "step", "step": 1, "tokens": 128,
         "comm_intra_bytes": 2 * 2**20, "comm_inter_bytes": 1 * 2**20},
        {"kind": "onebit_freeze", "step": 2, "freeze_step": 1,
         "hierarchical": True},
        {"kind": "step", "step": 2, "tokens": 128,
         "comm_intra_bytes": 2 * 2**20, "comm_inter_bytes": 65536},
        {"kind": "loss", "step": 2, "loss": 1.5},
    ]
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    out = _render_lines(str(path))
    assert "comm_mb" in out
    assert "comm_phase" in out and "freeze" in out
    assert "comm_hierarchy_plan" in out
    # 3 MiB on step 1; the post-freeze step shrinks
    lines = [ln for ln in out.splitlines() if ln.strip().startswith("1 ")]
    assert any("3" in ln for ln in lines), out
