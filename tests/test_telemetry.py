"""Unified-telemetry tests (ISSUE 4): registry snapshot/reset semantics,
async-safe spans, exporters (JSONL / SummaryEventWriter bridge /
Prometheus), flops-profiler MFU math, the engine's per-step scalar
stream, and a CPU smoke of the programmatic XLA trace window."""

import json
import os
import threading

import numpy as np
import jax
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.telemetry import (
    MetricsRegistry, JsonlExporter, SummaryBridge, prometheus_text,
    span, TraceWindow, default_registry)
from tests.simple_model import SimpleModel, base_config


# --------------------------------------------------------------- registry

def test_registry_counter_gauge_histogram_semantics():
    r = MetricsRegistry()
    r.counter("a/steps").inc()
    r.counter("a/steps").inc(2)
    r.gauge("a/g").set(3.5)
    r.gauge("a/hwm").set_max(1.0)
    r.gauge("a/hwm").set_max(0.25)        # lower — HWM must hold
    for v in range(1, 101):
        r.histogram("a/h").observe(v / 100.0)
    snap = r.snapshot()
    assert snap["counters"]["a/steps"] == 3.0
    assert snap["gauges"]["a/g"] == 3.5
    assert snap["gauges"]["a/hwm"] == 1.0
    h = snap["histograms"]["a/h"]
    assert h["count"] == 100 and abs(h["sum"] - 50.5) < 1e-9
    assert h["min"] == 0.01 and h["max"] == 1.0
    assert abs(h["p50"] - 0.5) <= 0.02 and h["p99"] >= 0.98
    # the same name returns the same metric object
    assert r.counter("a/steps") is r.counter("a/steps")


def test_registry_snapshot_prefix_filter_and_reset():
    r = MetricsRegistry()
    r.counter("train/x").inc()
    r.counter("serving/y").inc()
    assert set(r.snapshot(prefix="serving/")["counters"]) == {"serving/y"}
    r.reset()
    snap = r.snapshot()
    assert not snap["counters"] and not snap["histograms"]


def test_histogram_reservoir_bounded_but_totals_exact():
    r = MetricsRegistry()
    h = r.histogram("h", maxlen=8)
    for v in range(100):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 100 and s["sum"] == sum(range(100))
    assert s["p50"] >= 92       # percentiles over the RECENT reservoir


def test_spans_record_host_time_and_are_thread_safe():
    r = MetricsRegistry()

    def worker(tag, n):
        for _ in range(n):
            with span(tag, registry=r):
                pass

    threads = [threading.Thread(target=worker, args=(f"t{i}", 50))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = r.snapshot()
    for i in range(4):
        assert snap["histograms"][f"span/t{i}"]["count"] == 50


# --------------------------------------------------------------- exporters

def test_jsonl_exporter_events_carry_ts_rank_step(tmp_path):
    r = MetricsRegistry()
    r.counter("c").inc(7)
    path = str(tmp_path / "m.jsonl")
    ex = JsonlExporter(path, r)
    ex.export(step=3)
    ex.export(step=4)
    ex.close()
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2
    assert lines[0]["step"] == 3 and lines[1]["step"] == 4
    assert lines[0]["ts"] > 0 and isinstance(lines[0]["rank"], int)
    assert lines[0]["metrics"]["counters"]["c"] == 7.0


def test_summary_bridge_and_jsonl_fallback_tagging(tmp_path, monkeypatch):
    import sys
    # force the JSONL fallback (and skip the ~15s torch import):
    # a None sys.modules entry makes the tensorboard import raise
    monkeypatch.setitem(sys.modules, "torch.utils.tensorboard", None)
    from deepspeed_tpu.utils.monitor import SummaryEventWriter
    r = MetricsRegistry()
    r.gauge("train/mfu").set(0.42)
    r.histogram("train/step_time_s").observe(0.1)
    w = SummaryEventWriter(str(tmp_path), "job")
    assert w._tb is None
    SummaryBridge(w, r).export(step=5)
    w.close()
    events = [json.loads(l)
              for l in open(os.path.join(w.log_dir, "events.jsonl"))]
    tags = {e["tag"] for e in events}
    assert "train/mfu" in tags and "train/step_time_s/p50" in tags
    # satellite: every fallback event self-identifies for merge
    for e in events:
        assert e["ts"] > 0 and isinstance(e["rank"], int)
        assert e["step"] == 5


def test_prometheus_text_dump():
    r = MetricsRegistry()
    r.counter("train/steps").inc(3)
    r.gauge("serving/queue_depth").set(2)
    r.histogram("train/step_time_s").observe(0.25)
    text = prometheus_text(r)
    assert "# TYPE train_steps counter\ntrain_steps 3.0" in text
    assert "# TYPE serving_queue_depth gauge" in text
    assert 'train_step_time_s{quantile="0.5"} 0.25' in text
    assert "train_step_time_s_count 1" in text


def test_prometheus_help_type_pairs_and_label_escaping():
    """ISSUE 6 satellite: every family carries a # HELP line right
    before its # TYPE line (the order scrapers expect), the HELP text
    preserves the original /-separated path, and label values escape
    backslash/quote/newline per the exposition format."""
    from deepspeed_tpu.telemetry.registry import _prom_escape_label
    r = MetricsRegistry()
    r.counter("train/steps").inc(3)
    r.histogram("serving/ttft_s").observe(0.5)
    lines = prometheus_text(r).splitlines()
    helps = [i for i, l in enumerate(lines) if l.startswith("# HELP ")]
    assert helps, lines
    for i in helps:
        name = lines[i].split()[2]
        assert lines[i + 1] == f"# TYPE {name} " \
            + lines[i + 1].split()[-1]
    # the lossy name mangling is recoverable from HELP
    assert any("# HELP train_steps" in l and "train/steps" in l
               for l in lines)
    # one HELP/TYPE per family even with quantile samples following
    assert sum(1 for l in lines if l.startswith("# TYPE serving_ttft_s "
                                                )) == 1
    assert _prom_escape_label('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert 'quantile="0.99"' in prometheus_text(r)


def test_jsonl_exporter_rotation_bounds_disk(tmp_path):
    """ISSUE 6 satellite: with max_bytes set the stream rotates
    logrotate-style and total files never exceed max_files — a
    multi-hour run cannot grow one unbounded file."""
    r = MetricsRegistry()
    r.counter("c").inc()
    path = str(tmp_path / "m.jsonl")
    ex = JsonlExporter(path, r, max_bytes=512, max_files=3)
    for step in range(60):
        ex.export(step=step)
    ex.close()
    files = sorted(os.listdir(tmp_path))
    assert "m.jsonl" in files
    assert "m.jsonl.1" in files and "m.jsonl.2" in files
    assert len(files) == 3                    # oldest fell off the end
    for f in files:
        p = os.path.join(str(tmp_path), f)
        assert os.path.getsize(p) <= 512 + 256   # one event of slack
        for line in open(p):
            assert json.loads(line)["metrics"]["counters"]["c"] == 1.0
    # rotation keeps the newest events in the live file
    last = [json.loads(l) for l in open(path)]
    assert last == [] or last[-1]["step"] == 59


def test_jsonl_exporter_rotation_off_by_default(tmp_path):
    r = MetricsRegistry()
    path = str(tmp_path / "m.jsonl")
    ex = JsonlExporter(path, r)
    for step in range(20):
        ex.export(step=step)
    ex.close()
    assert sorted(os.listdir(tmp_path)) == ["m.jsonl"]


# --------------------------------------------------------------- MFU math

def test_model_flops_per_token_known_shape():
    from deepspeed_tpu.profiling.flops_profiler import model_flops_per_token
    from deepspeed_tpu.models.gpt2 import GPT2Config
    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=64,
                     n_layer=2, n_head=2)
    # 6 * (L*12*E^2 + V*E) + 12*L*S*E, by hand:
    expected = 6 * (2 * 12 * 64 * 64 + 512 * 64) + 12 * 2 * 128 * 64
    assert model_flops_per_token(cfg) == expected
    # bench.py must resolve through the same canonical copy
    import bench
    assert bench.model_flops_per_token(cfg) == expected


def test_mfu_math_and_peak_table():
    from deepspeed_tpu.profiling.flops_profiler import (
        mfu, peak_device_flops, PEAK_BF16_FLOPS)
    peak = peak_device_flops()          # fallback on CPU backends
    assert peak in set(PEAK_BF16_FLOPS.values()) | {197e12}
    assert mfu(peak / 2.0, 1.0) == pytest.approx(0.5)
    assert mfu(peak, 2.0) == pytest.approx(0.5)       # flops/s halves
    assert mfu(peak, 1.0, n_devices=4) == pytest.approx(0.25)
    assert mfu(peak, 0.0) == 0.0


# ------------------------------------------------- engine + trace window

def test_engine_scalar_stream_mfu_and_trace_window(tmp_path):
    """One tiny engine exercises the whole integration: per-step
    counters, boundary window folds (step-time histogram, throughput
    gauges), MFU priced from the compiled step's cost analysis, memory
    gauges, the JSONL stream, and a 2-step XLA trace window."""
    default_registry().reset()
    jsonl = str(tmp_path / "tel.jsonl")
    cfg = base_config(steps_per_print=2)
    cfg["monitor"] = {"jsonl_path": jsonl}
    cfg["profiling"] = {"trace_dir": str(tmp_path / "trace"),
                        "trace_steps": [1, 3]}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    batch = (np.random.RandomState(0).randn(8, 8).astype(np.float32),
             np.zeros((8,), np.int32))
    for _ in range(6):
        engine.train_batch(batch)
    snap = engine.telemetry_flush(batch)

    assert snap["counters"]["train/steps"] == 6
    assert snap["counters"]["train/samples"] == 48
    # boundary folds at steps 2/4/6 — the first window (contains the
    # compile) is dropped, later ones observed
    assert snap["histograms"]["train/step_time_s"]["count"] >= 2
    assert snap["histograms"]["span/train/step_dispatch"]["count"] == 6
    assert snap["gauges"]["train/samples_per_sec"] > 0
    # MFU priced (monitor gate on): exact flops from cost analysis
    assert snap["gauges"]["train/flops_per_step"] > 0
    assert snap["gauges"]["train/mfu"] >= 0
    assert snap["gauges"]["memory/host_max_rss_mb"] > 0

    events = [json.loads(l) for l in open(jsonl)]
    assert len(events) >= 3
    assert {"ts", "rank", "step", "metrics"} <= set(events[0])

    # trace window: dir non-empty after the [1, 3) capture
    assert snap["counters"]["profiling/trace_windows"] == 1
    n_files = sum(len(fs) for _, _, fs in os.walk(tmp_path / "trace"))
    assert n_files > 0


def test_engine_without_gates_records_but_never_prices_or_exports():
    """No monitor/profiling config: counters still move (snapshot is
    always available) but no cost-analysis retrace, no exporter, no
    trace — the zero-config cost is bookkeeping only."""
    default_registry().reset()
    engine, _, _, _ = dstpu.initialize(config=base_config(),
                                       model=SimpleModel())
    batch = (np.random.RandomState(0).randn(8, 8).astype(np.float32),
             np.zeros((8,), np.int32))
    for _ in range(3):
        engine.train_batch(batch)
    assert engine._trace_window is None
    assert engine._telemetry_exporters() == []
    snap = engine.telemetry_snapshot()
    assert snap["counters"]["train/steps"] == 3
    assert engine._tel_flops_per_step is None      # never priced
    assert "train/mfu" not in snap["gauges"]


def test_config_gates_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    c = DeepSpeedConfig({"train_batch_size": 4})
    assert not c.monitor_config.enabled and not c.profiling_config.trace_dir
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 4,
                         "profiling": {"trace_dir": "/tmp/x"}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 4,
                         "profiling": {"trace_dir": "/tmp/x",
                                       "trace_steps": [3, 3]}})


def test_trace_window_unit():
    tw = TraceWindow.from_config(type("P", (), {
        "trace_dir": "", "trace_steps": ()})())
    assert tw is None
    tw = TraceWindow("/tmp/nonexistent_ok", 2, 4)
    assert not tw.active and not tw.done
    tw.on_step_end(5)          # never started — must be a no-op
    assert not tw.done


# --------------------------------------------------------------- serving

def test_serving_metrics_snapshot_mixed_workload():
    """Mixed prompt/budget workload through the tiny CPU serving
    engine: TTFT and admission wait per request, tick latency + slot
    utilization per tick, page-pool occupancy high-water mark."""
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    import deepspeed_tpu.serving as serving
    cfg = GPT2Config(vocab_size=128, n_positions=64, n_embd=32,
                     n_layer=2, n_head=4, dtype=jnp.float32,
                     param_dtype=jnp.float32, scan_layers=True)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    eng = serving.build_engine(
        "gpt2", cfg, params,
        config={"serving": {"slots": 2, "page_size": 16,
                            "max_pages_per_slot": 3}})
    rs = np.random.RandomState(0)
    shapes = [(8, 4), (20, 3), (5, 4), (16, 2)]   # (prompt, max_new)
    reqs = [serving.Request(i, rs.randint(0, 128, size=(s,))
                            .astype(np.int32), max_new_tokens=n)
            for i, (s, n) in enumerate(shapes)]
    done = eng.serve(reqs)
    assert len(done) == 4
    snap = eng.metrics_snapshot()
    assert snap["ttft_s"]["count"] == 4
    assert snap["admission_wait_s"]["count"] == 4
    assert snap["ttft_s"]["p50"] >= 0 and snap["ttft_s"]["max"] > 0
    assert 0 < snap["page_pool"]["occupancy_hwm"] <= 1
    assert snap["page_pool"]["used_pages"] == 0    # all released
    assert snap["tick_latency_s"]["count"] == snap["ticks"] > 0
    assert 0 < snap["slot_utilization"]["max"] <= 1
    # decode tokens exclude each request's prefill-sampled first token
    assert snap["decode_tokens"] == sum(n for _, n in shapes) - len(shapes)
    assert snap["decode_tokens_per_sec"] > 0
    assert snap["queue_depth"] == 0 and snap["active_slots"] == 0
