"""1-bit optimizer tests — the reference's test_onebit.py role: warmup phase
matches Adam exactly; compressed phase keeps training and maintains error
feedback."""

import numpy as np
import jax
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.runtime.fp16.onebit import OnebitAdam, OnebitLamb
from deepspeed_tpu.ops.adam import FusedAdam
from tests.simple_model import SimpleModel, random_batch, base_config


def _params():
    return {"w": jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32),
            "b": jnp.zeros((16,), jnp.float32)}


def _grads():
    return {"w": jnp.asarray(np.random.RandomState(1).randn(8, 16), jnp.float32),
            "b": jnp.ones((16,), jnp.float32)}


def test_onebit_adam_warmup_matches_adam():
    p = _params()
    g = _grads()
    ob = OnebitAdam(lr=1e-2, freeze_step=100, weight_decay=0.0)
    ad = FusedAdam(lr=1e-2, adam_w_mode=False, bias_correction=False,
                   weight_decay=0.0)
    s_ob, s_ad = ob.init(p), ad.init(p)
    p_ob, p_ad = p, p
    for _ in range(3):
        p_ob, s_ob = ob.step(p_ob, g, s_ob)
        p_ad, s_ad = ad.step(p_ad, g, s_ad)
    for a, b in zip(jax.tree_util.tree_leaves(p_ob),
                    jax.tree_util.tree_leaves(p_ad)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_onebit_adam_compressed_phase():
    p = _params()
    g = _grads()
    ob = OnebitAdam(lr=1e-3, freeze_step=2)
    s = ob.init(p)
    for i in range(6):
        p, s = ob.step(p, g, s)
    # variance frozen after step 2, error feedback nonzero
    assert float(jnp.abs(s["worker_error"]["w"]).sum()) > 0
    assert np.isfinite(np.asarray(p["w"])).all()


def test_onebit_adam_variance_frozen():
    p, g = _params(), _grads()
    ob = OnebitAdam(lr=1e-3, freeze_step=1)
    s = ob.init(p)
    p, s = ob.step(p, g, s)       # step 1: warmup (count=1 <= freeze)
    v_after_freeze = np.asarray(s["exp_avg_sq"]["w"]).copy()
    p, s = ob.step(p, g, s)       # step 2: compressed
    np.testing.assert_array_equal(v_after_freeze, np.asarray(s["exp_avg_sq"]["w"]))


def test_onebit_lamb_trains_engine():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitLamb",
                        "params": {"lr": 1e-2, "freeze_step": 5}}
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(20):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_onebit_adam_engine_name():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 5}}
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert isinstance(engine.optimizer, OnebitAdam)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert np.isfinite(l1)


def test_onebit_adam_compressed_comm_multidevice():
    """The real 1-bit path: dp=4 mesh, grads stay local, momentum goes
    through the compressed collective (reference test_nccl_backend.py role
    but driven through the engine)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert engine._compressed_comm_active()
    batch = random_batch()
    losses = [float(engine.train_batch(batch)) for _ in range(12)]
    # trains through both phases (3 warmup + 9 compressed)
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)
    # error feedback is per-device state with a leading dp axis
    we = engine.state.opt_state["worker_error"]
    leaf = jax.tree_util.tree_leaves(we)[0]
    assert leaf.shape[0] == 4
    # params stayed identical across devices (replicated out-sharding)
    p = jax.tree_util.tree_leaves(engine.state.params)[0]
    assert np.isfinite(np.asarray(p)).all()


def test_onebit_adam_compressed_vs_exact_close():
    """Compressed training should roughly track exact-Adam training over a
    short horizon (error feedback keeps the trajectories close)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    batch = random_batch()

    def run(opt_cfg):
        cfg = base_config()
        cfg["optimizer"] = opt_cfg
        engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                           mesh=mesh)
        for _ in range(15):
            loss = engine.train_batch(batch)
        return float(loss)

    l_onebit = run({"type": "OneBitAdam",
                    "params": {"lr": 1e-2, "freeze_step": 5}})
    l_exact = run({"type": "Adam", "params": {"lr": 1e-2}})
    assert abs(l_onebit - l_exact) < 0.5 * max(abs(l_exact), 0.1) + 0.3, \
        (l_onebit, l_exact)


def test_onebit_lamb_compressed_comm_multidevice():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitLamb",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert engine._compressed_comm_active()
    batch = random_batch()
    losses = [float(engine.train_batch(batch)) for _ in range(10)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_onebit_grad_norm_approximation_bounded():
    """The compressed path reports pmean(local-shard norms) instead of the
    exact norm of the dp-mean gradient (engine.py: an exact norm would
    need an uncompressed collective). VERDICT r2 weak #6: bound the
    divergence. With identical shards, local == global gradients, so the
    approximation must match the exact norm; with heterogeneous shards it
    must stay within a loose factor (E[local norm] >= global norm, equal
    up to shard noise)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")

    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-3, "freeze_step": 100}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)

    def exact_norm(batch):
        # out-of-band exact norm of the FULL-batch (= dp-mean) gradient at
        # the engine's current params
        loss_fn = engine._resolve_loss_fn()
        grads = jax.grad(
            lambda p: loss_fn(p, batch, jax.random.PRNGKey(0), 1.0))(
                engine.state.params)
        return float(jnp.sqrt(sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree_util.tree_leaves(grads))))

    def step_metrics(batch):
        # the jitted step DONATES state — reassign to keep the engine live
        state, metrics = engine._jit_train_batch(
            engine.state, engine._globalize_batch(batch),
            jax.random.PRNGKey(1))
        engine.state = state
        return metrics

    # identical shards: every device sees the same 2-sample micro batch
    x, y = random_batch(batch_size=2)
    batch_same = (np.tile(x, (4, 1)), np.tile(y, 4))
    engine.train_batch(batch_same)      # compile + one step
    exact = exact_norm(batch_same)
    metrics = step_metrics(batch_same)
    np.testing.assert_allclose(float(metrics["grad_norm"]), exact,
                               rtol=0.05)

    # heterogeneous shards: approximation within a loose factor
    batch_mix = random_batch(batch_size=8, seed=3)
    exact = exact_norm(batch_mix)
    approx = float(step_metrics(batch_mix)["grad_norm"])
    assert exact / 3 < approx < exact * 3, (approx, exact)


def test_onebit_freeze_boundary_residuals_carry_over():
    """ISSUE 10 satellite: the warmup→compressed transition. Error
    feedback must be identically zero through warmup (momentum is exact
    there — nothing to compensate), turn on at the first compressed
    step, and the recorded residual must actually FEED the next step's
    compensation (pinned by a counterfactual: replaying the same step
    with the residuals zeroed changes the params). The loss trajectory
    crosses the boundary without a jump."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    # the hierarchical bucketed exchange (2x2 synthetic split) so the
    # carryover pin covers the per-bucket error lists too
    cfg["comm"] = {"hierarchy": {"slow_axis": 2, "compression": "always"}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    tm = jax.tree_util.tree_map

    def err_leaves():
        return [np.asarray(x) for x in jax.tree_util.tree_leaves(
            engine.state.opt_state["worker_error"])]

    losses = []
    for _ in range(3):                     # warmup: count 1..3 <= freeze
        losses.append(float(engine.train_batch(batch)))
        assert all((e == 0).all() for e in err_leaves()), \
            "error feedback must stay zero through warmup"
    losses.append(float(engine.train_batch(batch)))   # first compressed
    assert any((e != 0).any() for e in err_leaves()), \
        "first compressed step must record a residual"

    # counterfactual: replay the next step from the same state with the
    # residuals zeroed — if the residual carries over the transition,
    # the resulting params must differ
    saved = tm(lambda x: jnp.array(x), engine.state)
    rng = jax.random.PRNGKey(11)
    gbatch = engine._globalize_batch(batch)
    state_with, _ = engine._jit_train_batch(
        tm(lambda x: jnp.array(x), saved), gbatch, rng)
    zeroed = saved.replace(opt_state={
        **saved.opt_state,
        "worker_error": tm(jnp.zeros_like,
                           saved.opt_state["worker_error"]),
        "server_error": tm(jnp.zeros_like,
                           saved.opt_state["server_error"])})
    state_without, _ = engine._jit_train_batch(zeroed, gbatch, rng)
    diffs = [float(jnp.abs(a - b).max()) for a, b in zip(
        jax.tree_util.tree_leaves(state_with.params),
        jax.tree_util.tree_leaves(state_without.params))]
    assert max(diffs) > 0, "residuals did not carry over the transition"

    # the engine's own state was donated into the replay; restore and
    # finish the trajectory — no jump at or after the boundary
    engine.state = state_with
    for _ in range(4):
        losses.append(float(engine.train_batch(batch)))
    assert all(np.isfinite(losses))
    jumps = [losses[i + 1] - losses[i] for i in range(2, len(losses) - 1)]
    assert max(jumps) < 0.25, (losses, "loss jumped at the freeze boundary")
    assert losses[-1] < losses[0]


def test_onebit_adam_hierarchical_engine_multidevice():
    """Engine e2e over the link-aware hierarchical exchange (ISSUE 10,
    single-process synthetic slow axis 2x2): trains through both phases,
    publishes the bytes-on-wire model + counters, and records the
    comm_hierarchy_plan breadcrumb and onebit_freeze ring event."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    cfg["comm"] = {"hierarchy": {"slow_axis": 2, "compression": "always"}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert engine._compressed_comm_active()
    batch = random_batch()
    losses = [float(engine.train_batch(batch)) for _ in range(10)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)

    plan = engine.comm_hierarchy
    assert (plan.inter, plan.intra) == (2, 2)
    # slow-hop bytes must drop >=4x vs the fp32 hop post-freeze
    wire = engine._comm_wire_model
    assert wire["compressed"]["inter_uncompressed"] \
        >= 4 * wire["compressed"]["inter"], wire
    # warmup phase pays the full fp32 slow hop
    assert wire["warmup"]["inter"] == wire["warmup"]["inter_uncompressed"]
    ctr = engine.telemetry.snapshot("comm/")["counters"]
    assert ctr["comm/bytes_on_wire/inter"] > 0
    assert ctr["comm/bytes_on_wire/intra"] > 0
    assert ctr["comm/bytes_on_wire/inter_uncompressed"] \
        > ctr["comm/bytes_on_wire/inter"]
    kinds = [e["kind"] for e in engine.flight_recorder.events()]
    assert "comm_hierarchy_plan" in kinds
    assert "onebit_freeze" in kinds
    # error feedback is per-BUCKET list state with a leading dp axis
    we = engine.state.opt_state["worker_error"]
    assert isinstance(we, list)
    leaf = jax.tree_util.tree_leaves(we)[0]
    assert leaf.shape[0] == 4


def test_onebit_hierarchical_checkpoint_roundtrip(tmp_path):
    """ISSUE 10: the hierarchical path's per-bucket error LISTS must
    survive a checkpoint round trip (the serializer rebuilds containers
    as dicts and drops None entries — engine._restore_error_lists
    reassembles them), and the restored residuals must continue the
    trajectory bit-exactly."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    cfg["comm"] = {"hierarchy": {"slow_axis": 2, "compression": "always"}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    for _ in range(5):                      # through the freeze boundary
        engine.train_batch(batch)
    assert any(float(jnp.abs(x).max()) > 0 for x in
               jax.tree_util.tree_leaves(
                   engine.state.opt_state["worker_error"])), \
        "test needs nonzero residuals to prove the round trip"
    engine.save_checkpoint(str(tmp_path), tag="t0")
    l_ref = float(engine.train_batch(batch))
    engine.load_checkpoint(str(tmp_path), tag="t0")
    we = engine.state.opt_state["worker_error"]
    assert isinstance(we, list), type(we)   # digit-dict would break zip
    l_resumed = float(engine.train_batch(batch))
    assert l_resumed == l_ref, (l_resumed, l_ref)


def test_onebit_hierarchical_resume_after_policy_change(tmp_path):
    """Residual reconciliation on resume (ISSUE 10): a checkpoint
    written under one compression policy must load under another —
    residuals for now-uncompressed buckets drop, now-compressed buckets
    start from zero (warned, not a trace-time crash on a None
    operand)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")

    def build(policy):
        cfg = base_config()
        cfg["optimizer"] = {"type": "OneBitAdam",
                            "params": {"lr": 1e-2, "freeze_step": 3}}
        cfg["comm"] = {"hierarchy": {"slow_axis": 2,
                                     "compression": policy}}
        mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
        e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                      mesh=mesh)
        return e

    batch = random_batch()
    eng = build("never")
    for _ in range(5):
        eng.train_batch(batch)
    eng.save_checkpoint(str(tmp_path), tag="t0")

    eng2 = build("always")                      # never -> always
    eng2.load_checkpoint(str(tmp_path), tag="t0")
    we = eng2.state.opt_state["worker_error"]
    assert isinstance(we, list) and we[0] is not None
    assert float(jnp.abs(we[0]).max()) == 0     # fresh zero residuals
    assert np.isfinite(float(eng2.train_batch(batch)))

    eng3 = build("always")                      # and always -> never
    for _ in range(5):
        eng3.train_batch(batch)
    eng3.save_checkpoint(str(tmp_path), tag="t1")
    eng4 = build("never")
    eng4.load_checkpoint(str(tmp_path), tag="t1")
    assert eng4.state.opt_state["worker_error"][0] is None
    assert np.isfinite(float(eng4.train_batch(batch)))


def test_onebit_hierarchical_ckpt_resumes_on_flat_path(tmp_path):
    """The reverse flip: a hierarchical-path checkpoint resumed on the
    FLAT compressed exchange (hierarchy block removed / no slow axis at
    the new world). Residuals reset to per-leaf zero trees with a
    warning instead of a tree-structure trace crash."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    cfg["comm"] = {"hierarchy": {"slow_axis": 2, "compression": "always"}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    for _ in range(5):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path), tag="t0")

    cfg2 = base_config()
    cfg2["optimizer"] = {"type": "OneBitAdam",
                         "params": {"lr": 1e-2, "freeze_step": 3}}
    flat, _, _, _ = dstpu.initialize(config=cfg2, model=SimpleModel(),
                                     mesh=mesh)
    flat.load_checkpoint(str(tmp_path), tag="t0")
    we = flat.state.opt_state["worker_error"]
    assert not isinstance(we, (list, dict)) or "Dense_0" in we
    assert all(float(jnp.abs(x).max()) == 0
               for x in jax.tree_util.tree_leaves(we))
    assert np.isfinite(float(flat.train_batch(batch)))
