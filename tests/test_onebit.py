"""1-bit optimizer tests — the reference's test_onebit.py role: warmup phase
matches Adam exactly; compressed phase keeps training and maintains error
feedback."""

import numpy as np
import jax
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.runtime.fp16.onebit import OnebitAdam, OnebitLamb
from deepspeed_tpu.ops.adam import FusedAdam
from tests.simple_model import SimpleModel, random_batch, base_config


def _params():
    return {"w": jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32),
            "b": jnp.zeros((16,), jnp.float32)}


def _grads():
    return {"w": jnp.asarray(np.random.RandomState(1).randn(8, 16), jnp.float32),
            "b": jnp.ones((16,), jnp.float32)}


def test_onebit_adam_warmup_matches_adam():
    p = _params()
    g = _grads()
    ob = OnebitAdam(lr=1e-2, freeze_step=100, weight_decay=0.0)
    ad = FusedAdam(lr=1e-2, adam_w_mode=False, bias_correction=False,
                   weight_decay=0.0)
    s_ob, s_ad = ob.init(p), ad.init(p)
    p_ob, p_ad = p, p
    for _ in range(3):
        p_ob, s_ob = ob.step(p_ob, g, s_ob)
        p_ad, s_ad = ad.step(p_ad, g, s_ad)
    for a, b in zip(jax.tree_util.tree_leaves(p_ob),
                    jax.tree_util.tree_leaves(p_ad)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_onebit_adam_compressed_phase():
    p = _params()
    g = _grads()
    ob = OnebitAdam(lr=1e-3, freeze_step=2)
    s = ob.init(p)
    for i in range(6):
        p, s = ob.step(p, g, s)
    # variance frozen after step 2, error feedback nonzero
    assert float(jnp.abs(s["worker_error"]["w"]).sum()) > 0
    assert np.isfinite(np.asarray(p["w"])).all()


def test_onebit_adam_variance_frozen():
    p, g = _params(), _grads()
    ob = OnebitAdam(lr=1e-3, freeze_step=1)
    s = ob.init(p)
    p, s = ob.step(p, g, s)       # step 1: warmup (count=1 <= freeze)
    v_after_freeze = np.asarray(s["exp_avg_sq"]["w"]).copy()
    p, s = ob.step(p, g, s)       # step 2: compressed
    np.testing.assert_array_equal(v_after_freeze, np.asarray(s["exp_avg_sq"]["w"]))


def test_onebit_lamb_trains_engine():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitLamb",
                        "params": {"lr": 1e-2, "freeze_step": 5}}
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(20):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_onebit_adam_engine_name():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 5}}
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert isinstance(engine.optimizer, OnebitAdam)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert np.isfinite(l1)


def test_onebit_adam_compressed_comm_multidevice():
    """The real 1-bit path: dp=4 mesh, grads stay local, momentum goes
    through the compressed collective (reference test_nccl_backend.py role
    but driven through the engine)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert engine._compressed_comm_active()
    batch = random_batch()
    losses = [float(engine.train_batch(batch)) for _ in range(12)]
    # trains through both phases (3 warmup + 9 compressed)
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)
    # error feedback is per-device state with a leading dp axis
    we = engine.state.opt_state["worker_error"]
    leaf = jax.tree_util.tree_leaves(we)[0]
    assert leaf.shape[0] == 4
    # params stayed identical across devices (replicated out-sharding)
    p = jax.tree_util.tree_leaves(engine.state.params)[0]
    assert np.isfinite(np.asarray(p)).all()


def test_onebit_adam_compressed_vs_exact_close():
    """Compressed training should roughly track exact-Adam training over a
    short horizon (error feedback keeps the trajectories close)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    batch = random_batch()

    def run(opt_cfg):
        cfg = base_config()
        cfg["optimizer"] = opt_cfg
        engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                           mesh=mesh)
        for _ in range(15):
            loss = engine.train_batch(batch)
        return float(loss)

    l_onebit = run({"type": "OneBitAdam",
                    "params": {"lr": 1e-2, "freeze_step": 5}})
    l_exact = run({"type": "Adam", "params": {"lr": 1e-2}})
    assert abs(l_onebit - l_exact) < 0.5 * max(abs(l_exact), 0.1) + 0.3, \
        (l_onebit, l_exact)


def test_onebit_lamb_compressed_comm_multidevice():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["optimizer"] = {"type": "OneBitLamb",
                        "params": {"lr": 1e-2, "freeze_step": 3}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    assert engine._compressed_comm_active()
    batch = random_batch()
    losses = [float(engine.train_batch(batch)) for _ in range(10)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


def test_onebit_grad_norm_approximation_bounded():
    """The compressed path reports pmean(local-shard norms) instead of the
    exact norm of the dp-mean gradient (engine.py: an exact norm would
    need an uncompressed collective). VERDICT r2 weak #6: bound the
    divergence. With identical shards, local == global gradients, so the
    approximation must match the exact norm; with heterogeneous shards it
    must stay within a loose factor (E[local norm] >= global norm, equal
    up to shard noise)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")

    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-3, "freeze_step": 100}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)

    def exact_norm(batch):
        # out-of-band exact norm of the FULL-batch (= dp-mean) gradient at
        # the engine's current params
        loss_fn = engine._resolve_loss_fn()
        grads = jax.grad(
            lambda p: loss_fn(p, batch, jax.random.PRNGKey(0), 1.0))(
                engine.state.params)
        return float(jnp.sqrt(sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree_util.tree_leaves(grads))))

    def step_metrics(batch):
        # the jitted step DONATES state — reassign to keep the engine live
        state, metrics = engine._jit_train_batch(
            engine.state, engine._globalize_batch(batch),
            jax.random.PRNGKey(1))
        engine.state = state
        return metrics

    # identical shards: every device sees the same 2-sample micro batch
    x, y = random_batch(batch_size=2)
    batch_same = (np.tile(x, (4, 1)), np.tile(y, 4))
    engine.train_batch(batch_same)      # compile + one step
    exact = exact_norm(batch_same)
    metrics = step_metrics(batch_same)
    np.testing.assert_allclose(float(metrics["grad_norm"]), exact,
                               rtol=0.05)

    # heterogeneous shards: approximation within a loose factor
    batch_mix = random_batch(batch_size=8, seed=3)
    exact = exact_norm(batch_mix)
    approx = float(step_metrics(batch_mix)["grad_norm"])
    assert exact / 3 < approx < exact * 3, (approx, exact)
