"""Elastic preemption-tolerant training (ISSUE 7): async snapshots
through the swap tier, fault injection, elastic resume.

The contracts under test:

- **async snapshot**: begin() stages + submits on the write-behind aio
  handle and returns; finalize() (the next step boundary) is the drain
  fence + checksummed manifest + two-rename commit. A resumed engine
  continues the uninterrupted run's loss trajectory exactly.
- **elastic resume parity** (the acceptance criterion): train at dp=8,
  kill mid-run via the fault harness, resume the snapshot at dp=4 (and
  dp=2, slow-marked) — the HCN ladder re-solves micro/grad-accum so the
  effective batch is unchanged and the loss trajectory matches the
  uninterrupted run step-for-step.
- **fault injection**: kill-at-step, torn manifest, rotted shard
  checksum, crash-between-renames each auto-recover to the newest
  VALID snapshot and emit exactly one flight-recorder dump.
- **crash-between-renames in the blocking checkpoint path** (satellite:
  the hazard documented at checkpointing.py:318): the ``{tag}.old``
  fallback restores the previous save.
"""

import glob
import json
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
from deepspeed_tpu.runtime import checkpointing as ckpt
from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.runtime.elastic.snapshot import (
    AsyncSnapshotter, SnapshotCorrupt, SnapshotReader)
from deepspeed_tpu.telemetry import view
from deepspeed_tpu.telemetry.recorder import default_recorder
from tests.simple_model import SimpleModel, base_config, random_batch


def _dumps(dump_dir):
    return sorted(glob.glob(os.path.join(dump_dir, "flight_*.jsonl")))


def _restore(*engines):
    for e in engines:
        if e._preemption is not None:
            e._preemption.restore()


def _elastic_cfg(snap_path, dump_dir=None, interval=2, grace=20.0):
    cfg = {
        "steps_per_print": 1000,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        # HCN ladder: batch 24 factors as micro*gas*dp for dp in
        # {1,2,3,4,6,8,12} with micros [1,2,4] — dp=8 -> (1,3),
        # dp=4 -> (2,3), dp=2 -> (4,3); effective batch always 24
        "elasticity": {"enabled": True, "max_train_batch_size": 24,
                       "micro_batch_sizes": [1, 2, 4], "min_chips": 1,
                       "max_chips": 16, "version": 0.1},
        "snapshot": {"path": snap_path, "interval_steps": interval,
                     "grace_secs": grace},
    }
    if dump_dir is not None:
        cfg["monitor"] = {"enabled": False,
                          "watchdog": {"dump_dir": dump_dir,
                                       "min_samples": 4,
                                       "step_time_factor": 100.0}}
    return cfg


def _mesh(dp):
    return make_mesh(MeshConfig(data=dp), devices=jax.devices()[:dp])


def _elastic_batch(n=24, seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(n, 8).astype(np.float32),
            rs.randint(0, 4, (n,)).astype(np.int32))


# ---------------------------------------------------------- unit: snapshot

def test_snapshot_roundtrip_checksums_and_bf16(tmp_path):
    """Direct snapshotter round trip: mixed-dtype trees come back
    bit-exact through the raw-byte format, the manifest carries
    per-file crc32s, and the reader verifies them."""
    rs = np.random.RandomState(0)
    trees = {
        "model_states": {"params": {
            "w": jnp.asarray(rs.randn(8, 16), jnp.bfloat16),
            "b": jnp.asarray(rs.randn(16), jnp.float32)}},
        "optim_states": {
            "opt_state": {"m": {"w": jnp.asarray(rs.randn(8, 16))}},
            "scaler": {"loss_scale": jnp.float32(1.0)},
            "global_step": jnp.int32(7),
            "skipped_steps": jnp.int32(0)},
    }
    sp = AsyncSnapshotter(str(tmp_path), keep=2)
    sp.begin("t1", trees, extra={"global_steps": 7},
             meta={"dp_world_size": 1, "train_batch_size": 8})
    assert sp.in_flight
    final, stall = sp.finalize()
    assert not sp.in_flight and stall >= 0
    man = json.load(open(os.path.join(final, "manifest.json")))
    assert man["tag"] == "t1" and man["index_files"]
    reader = SnapshotReader(final)
    state, meta = reader.state_and_meta()
    reader.close()
    assert state["params"]["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(state["params"]["w"], np.float32),
        np.asarray(trees["model_states"]["params"]["w"], np.float32))
    np.testing.assert_array_equal(
        state["opt_state"]["m"]["w"],
        np.asarray(trees["optim_states"]["opt_state"]["m"]["w"]))
    assert int(state["global_step"]) == 7
    assert meta["extra"]["global_steps"] == 7
    assert meta["train_batch_size"] == 8
    assert ckpt.read_latest_tag(str(tmp_path)) == "t1"


def test_snapshot_reader_rejects_torn_and_rotted(tmp_path):
    trees = {"model_states": {"params": {
        "w": jnp.asarray(np.arange(64, dtype=np.float32))}},
        "optim_states": {"opt_state": {}, "scaler": {},
                         "global_step": jnp.int32(1),
                         "skipped_steps": jnp.int32(0)}}
    sp = AsyncSnapshotter(str(tmp_path))
    sp.begin("t", trees)
    final, _ = sp.finalize()
    SnapshotReader(final)                      # valid
    rotted = faults.rot_shard(final)
    with pytest.raises(SnapshotCorrupt):
        SnapshotReader(final)
    # un-rot, then tear the manifest instead
    faults.rot_shard(final)                    # XOR twice restores
    SnapshotReader(final)
    faults.tear_manifest(final)
    with pytest.raises(SnapshotCorrupt):
        SnapshotReader(final)
    assert rotted.endswith(".bin")


def test_snapshot_config_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    good = base_config()
    good["snapshot"] = {"path": "/tmp/x"}
    DeepSpeedConfig(good, world_size=1)
    for bad in ({"path": ""}, {"path": "/tmp/x", "interval_steps": 0},
                {"path": "/tmp/x", "keep": 0},
                {"path": "/tmp/x", "grace_secs": 0},
                {"path": "/tmp/x", "signals": ["SIGNOPE"]}):
        cfg = base_config()
        cfg["snapshot"] = bad
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig(cfg, world_size=1)
    # disabled block parses without a path
    cfg = base_config()
    cfg["snapshot"] = {"enabled": False}
    assert not DeepSpeedConfig(cfg, world_size=1).snapshot_config.enabled


# ------------------------------------------- engine: async snapshot cycle

def test_engine_periodic_async_snapshot_and_auto_resume(tmp_path):
    """Engine-level round trip: periodic async snapshots commit at the
    next step boundary, old generations prune to `keep`, and a fresh
    engine auto-resumes from the newest one and CONTINUES THE SAME LOSS
    TRAJECTORY as the uninterrupted run."""
    snap = str(tmp_path / "snaps")
    cfg = base_config(steps_per_print=1000)
    cfg["snapshot"] = {"path": snap, "interval_steps": 2, "keep": 2}
    batch = random_batch()

    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    base = e.telemetry.snapshot("ckpt/")["counters"]  # registry is
    ref = [float(e.train_batch(batch)) for _ in range(7)]  # process-wide
    # snapshots begin at steps 2/4/6 and commit at the NEXT boundary
    # (3/5/7) — all three committed; keep=2 pruned global_step2
    names = set(os.listdir(snap))
    assert "global_step4" in names and "global_step6" in names
    assert "global_step2" not in names       # pruned to keep=2
    assert ckpt.read_latest_tag(snap) == "global_step6"
    snapd = e.telemetry.snapshot("ckpt/")
    assert snapd["counters"]["ckpt/bytes_written"] \
        > base.get("ckpt/bytes_written", 0)
    assert snapd["counters"]["ckpt/snapshots"] \
        == base.get("ckpt/snapshots", 0) + 3
    assert "ckpt/stall_s" in snapd["histograms"]

    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    got = float(e2.train_batch(batch))        # auto-resume then step 7
    assert e2.global_steps == 7
    np.testing.assert_allclose(got, ref[6], rtol=1e-6)
    _restore(e, e2)


def test_engine_snapshot_from_parked_nvme_leaves(tmp_path):
    """The swap-tier composition: with params parked on NVMe
    (pipeline_write, pool smaller than the leaf count), snapshot
    leaves come off the swap FILES for the uncached leaves (FileLeaf
    markers — never re-serialized from the device) and the staging
    cache for the rest; the param swapper runs fsync-fenced, and
    resume restores the exact trajectory."""
    snap = str(tmp_path / "snaps")
    cfg = base_config(steps_per_print=1000)
    cfg["zero_optimization"] = {
        "stage": 3,
        # buffer_count=2 < SimpleModel's 4 leaves, so the write-behind
        # cache holds only the 2 most recent parks and the other 2
        # leaves MUST take the FileLeaf (read-the-swap-file) path
        "offload_param": {"device": "nvme",
                          "nvme_path": str(tmp_path / "nvme"),
                          "pipeline_read": True, "pipeline_write": True,
                          "buffer_count": 2, "fsync": True}}
    cfg["snapshot"] = {"path": snap, "interval_steps": 2}
    batch = random_batch()

    rec = default_recorder()
    rec.clear()
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    assert e._config.zero_config.offload_param.fsync
    ref = [float(e.train_batch(batch)) for _ in range(5)]
    assert e._params_parked and e._host_runner is None
    assert e._param_swapper.fsync
    begins = [ev for ev in rec.events() if ev["kind"] == "ckpt_begin"]
    assert begins
    assert any(ev.get("from_swapfiles", 0) > 0 for ev in begins), \
        "no snapshot leaf came off a swap file — FileLeaf path unused"
    # snapshot shards rode an aio write stream and committed
    assert ckpt.read_latest_tag(snap) == "global_step4"

    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    got = float(e2.train_batch(batch))
    assert e2.global_steps == 5
    np.testing.assert_allclose(got, ref[4], rtol=1e-5)
    _restore(e, e2)


def test_manual_fwd_bwd_step_path_snapshots_too(tmp_path):
    """The forward()/backward()/step() parity API must drive the
    elastic hook exactly like train_batch — snapshots begin/commit at
    its step boundaries and a preemption request is honored there (the
    gap a review caught: parking without _elastic_step left the
    feature silently dead on this path)."""
    snap = str(tmp_path / "snaps")
    cfg = base_config(steps_per_print=1000)
    cfg["snapshot"] = {"path": snap, "interval_steps": 2,
                       "grace_secs": 20.0}
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    batch = random_batch()
    for _ in range(5):
        loss = e.forward(batch)
        e.backward(loss)
        e.step()
    assert ckpt.read_latest_tag(snap) == "global_step4"
    e._preemption.request("manual")
    loss = e.forward(batch)
    e.backward(loss)
    e.step()
    assert e.preempted
    assert ckpt.read_latest_tag(snap) == "global_step6_final"
    _restore(e)


# --------------------------------------------------- faults: kill at step

def test_kill_at_step_final_snapshot_one_preempt_dump(tmp_path):
    """Fault scenario 1 (kill-at-step): SIGTERM lands mid-run, the
    engine takes a final snapshot inside the grace budget, marks itself
    preempted, and the watchdog writes EXACTLY ONE preempt dump whose
    timeline renders in the viewer."""
    snap = str(tmp_path / "snaps")
    dump = str(tmp_path / "flight")
    cfg = _elastic_cfg(snap, dump_dir=dump)
    batch = _elastic_batch()
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=_mesh(1))
    with faults.kill_at_step(3):
        losses = []
        for _ in range(6):
            losses.append(float(e.train_batch(batch)))
            if e.preempted:
                break
    assert e.preempted and len(losses) == 3
    assert ckpt.read_latest_tag(snap) == "global_step3_final"
    files = _dumps(dump)
    assert len(files) == 1 and "preempt" in files[0]
    header, events, _ = view.load_dump(files[0])
    assert header["rule"] == "preempt"
    assert header["detail"]["snapshotted"] is True
    kinds = {ev["kind"] for ev in events}
    assert {"ckpt_begin", "ckpt_commit", "preempt_signal"} <= kinds
    out = "\n".join(view.render(files[0]))
    assert "checkpoint / restore / preempt timeline" in out
    # a second train_batch after preemption must not re-snapshot
    float(e.train_batch(batch))
    assert _dumps(dump) == files
    _restore(e)


# ------------------------------- faults: corruption + recovery scenarios

def _run_and_snapshot(tmp_path, steps=5):
    """Common setup: a dp=1 elastic run of 5 steps leaves snapshots of
    steps 2 and 4 both COMMITTED (begin at the interval boundary,
    commit at the next step) and nothing in flight."""
    snap = str(tmp_path / "snaps")
    dump = str(tmp_path / "flight")
    cfg = _elastic_cfg(snap, dump_dir=dump)
    batch = _elastic_batch()
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=_mesh(1))
    ref = [float(e.train_batch(batch)) for _ in range(steps)]
    _restore(e)
    return snap, dump, cfg, batch, ref


def test_torn_manifest_falls_back_one_dump(tmp_path):
    """Fault scenario 2: the newest snapshot's manifest is torn — the
    resume falls back to the previous valid generation with exactly one
    flight-recorder dump."""
    snap, dump, cfg, batch, ref = _run_and_snapshot(tmp_path)
    faults.tear_manifest(os.path.join(snap, "global_step4"))
    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(1))
    got = float(e2.train_batch(batch))
    assert e2.global_steps == 3          # resumed from global_step2
    np.testing.assert_allclose(got, ref[2], rtol=1e-6)
    files = _dumps(dump)
    assert len(files) == 1 and "ckpt_corrupt" in files[0]
    _restore(e2)


def test_rotted_shard_falls_back_one_dump(tmp_path):
    """Fault scenario 3: a data shard of the newest snapshot rots — the
    manifest checksum catches it at load, recovery falls back, one
    dump."""
    snap, dump, cfg, batch, ref = _run_and_snapshot(tmp_path)
    faults.rot_shard(os.path.join(snap, "global_step4"))
    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(1))
    got = float(e2.train_batch(batch))
    assert e2.global_steps == 3
    np.testing.assert_allclose(got, ref[2], rtol=1e-6)
    files = _dumps(dump)
    assert len(files) == 1 and "ckpt_corrupt" in files[0]
    _restore(e2)


def test_snapshot_crash_between_renames_recovers_one_dump(tmp_path):
    """Fault scenario 4: the process dies between the commit's two
    renames — on disk: an orphaned ``.saving`` staging dir, no final.
    Recovery reports the interrupted commit ONCE, adopts the newest
    committed snapshot, and clears the orphan so a second restart is
    dump-free."""
    snap = str(tmp_path / "snaps")
    dump = str(tmp_path / "flight")
    cfg = _elastic_cfg(snap, dump_dir=dump)
    batch = _elastic_batch()
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=_mesh(1))
    ref = [float(e.train_batch(batch)) for _ in range(3)]
    with faults.crash_between_renames():
        with pytest.raises(faults.SimulatedCrash):
            for _ in range(2):           # step 4 commits snapshot of 4
                ref.append(float(e.train_batch(batch)))
    _restore(e)
    assert os.path.isdir(os.path.join(snap, "global_step4.saving"))
    assert not os.path.isdir(os.path.join(snap, "global_step4"))
    assert ckpt.read_latest_tag(snap) == "global_step2"

    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(1))
    got = float(e2.train_batch(batch))
    assert e2.global_steps == 3          # newest valid = global_step2
    np.testing.assert_allclose(got, ref[2], rtol=1e-6)
    files = _dumps(dump)
    assert len(files) == 1 and "ckpt_corrupt" in files[0]
    assert not os.path.isdir(os.path.join(snap, "global_step4.saving"))
    _restore(e2)
    # second restart: orphan cleared, nothing new to report
    e3, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(1))
    float(e3.train_batch(batch))
    assert _dumps(dump) == files
    _restore(e3)


def test_blocking_ckpt_crash_between_renames_old_fallback(tmp_path):
    """Satellite: the pre-existing recovery window in checkpointing.py
    (the comment at resolve_ckpt_dir documents it; nothing pinned it).
    A crash between save_checkpoint's two renames of a RE-SAVED tag
    leaves the only valid save at ``{tag}.old`` — load_checkpoint must
    find it instead of silently training from scratch."""

    class _State:
        def __init__(self, v):
            self.params = {"w": jnp.full((4, 4), v, jnp.float32)}
            self.opt_state = {}
            self.scaler = {"loss_scale": jnp.float32(1.0)}
            self.global_step = jnp.int32(int(v))
            self.skipped_steps = jnp.int32(0)

    ckpt.save_checkpoint(str(tmp_path), "t", _State(1.0),
                         {"global_steps": 1})
    with faults.crash_between_renames("ckpt_between_renames"):
        with pytest.raises(faults.SimulatedCrash):
            ckpt.save_checkpoint(str(tmp_path), "t", _State(2.0),
                                 {"global_steps": 2})
    # the crash window: final moved to .old, staging not yet swapped in
    assert not os.path.isdir(os.path.join(str(tmp_path), "t"))
    assert os.path.isdir(os.path.join(str(tmp_path), "t.old"))
    state, meta = ckpt.load_checkpoint(str(tmp_path))
    np.testing.assert_array_equal(np.asarray(state["params"]["w"]),
                                  np.full((4, 4), 1.0, np.float32))
    assert meta["global_steps"] == 1


# -------------------------------------------- elastic resume parity (e2e)

def _parity_run(tmp_path, resume_dp, kill_at=5, total=8):
    """Train dp=8, kill at `kill_at`, resume at `resume_dp`; return
    (reference_losses, interrupted_losses, resumed_losses)."""
    snap = str(tmp_path / "snaps")
    cfg = _elastic_cfg(snap, grace=30.0)
    batch = _elastic_batch()

    e0, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(8))
    assert (e0.train_micro_batch_size_per_gpu(),
            e0.gradient_accumulation_steps()) == (1, 3)
    ref = [float(e0.train_batch(batch)) for _ in range(total)]
    _restore(e0)
    import shutil
    shutil.rmtree(snap)

    e1, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(8))
    got = []
    with faults.kill_at_step(kill_at):
        for _ in range(total):
            got.append(float(e1.train_batch(batch)))
            if e1.preempted:
                break
    assert e1.preempted and len(got) == kill_at
    _restore(e1)

    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=_mesh(resume_dp))
    assert e2.train_batch_size() == 24   # HCN plan: effective batch kept
    rest = []
    while e2.global_steps < total:
        rest.append(float(e2.train_batch(batch)))
    assert e2.global_steps == total and len(rest) == total - kill_at
    _restore(e2)
    return ref, got, rest


def test_elastic_resume_parity_dp8_to_dp4(tmp_path):
    """THE acceptance criterion: dp=8 training killed mid-run resumes
    at dp=4 — micro goes 1→2 with gas 3 (same 24-sample effective
    batch, same micro partitioning), and the loss trajectory matches
    the uninterrupted dp=8 run step-for-step."""
    ref, got, rest = _parity_run(tmp_path, resume_dp=4)
    np.testing.assert_allclose(got, ref[:len(got)], rtol=1e-6)
    np.testing.assert_allclose(rest, ref[len(got):], rtol=2e-5)


@pytest.mark.slow
def test_elastic_resume_parity_dp8_to_dp2(tmp_path):
    """The dp=2 leg of the acceptance criterion (micro 1→4, gas 3)."""
    ref, got, rest = _parity_run(tmp_path, resume_dp=2)
    np.testing.assert_allclose(rest, ref[len(got):], rtol=2e-5)


@pytest.mark.slow
def test_elastic_resume_batch_mismatch_rejected(tmp_path):
    """Changing the elastic config between save and resume (different
    effective batch) must refuse the snapshot, not silently change the
    convergence behavior."""
    snap = str(tmp_path / "snaps")
    cfg = _elastic_cfg(snap, interval=1)
    batch = _elastic_batch()
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=_mesh(1))
    for _ in range(3):
        e.train_batch(batch)
    _restore(e)
    cfg2 = _elastic_cfg(snap, interval=1)
    cfg2["elasticity"]["max_train_batch_size"] = 12   # batch 24 -> 12
    e2, _, _, _ = dstpu.initialize(config=cfg2, model=SimpleModel(),
                                   mesh=_mesh(1))
    with pytest.raises(SnapshotCorrupt):
        e2.train_batch((_elastic_batch()[0][:12], _elastic_batch()[1][:12]))
    _restore(e2)


# ------------------------------------------------------------ view render

def test_view_renders_ckpt_timeline_synthetic(tmp_path):
    """The viewer's checkpoint timeline from a synthetic dump — no
    engine, no jax arrays, just the event schema."""
    path = str(tmp_path / "d.jsonl")
    evs = [
        {"kind": "dump_header", "rule": "preempt", "dump_id": 1,
         "source": "train", "ts": 10.0, "detail": {}, "n_events": 5},
        {"kind": "ckpt_begin", "ts": 10.0, "seq": 1, "step": 2,
         "tag": "global_step2", "files": 6, "bytes": 4096,
         "from_swapfiles": 2},
        {"kind": "ckpt_commit", "ts": 10.5, "seq": 2, "step": 3,
         "tag": "global_step2", "bytes": 4096, "wait_s": 0.001,
         "fsync": True},
        {"kind": "preempt_signal", "ts": 11.0, "seq": 3,
         "signal": "SIGTERM", "grace_s": 30.0},
        {"kind": "preempt", "ts": 11.2, "seq": 4, "step": 4,
         "snapshotted": True, "tag": "global_step4_final"},
        {"kind": "resume", "ts": 20.0, "seq": 5, "step": 4,
         "tag": "global_step4_final", "from_dp": 8, "to_dp": 4,
         "micro": 2, "grad_accum": 3, "fell_back": 1},
        {"kind": "ckpt_corrupt", "ts": 19.5, "seq": 6,
         "dir": "/x/global_step6", "reason": "torn manifest"},
    ]
    with open(path, "w") as fh:
        for ev in evs:
            fh.write(json.dumps(ev) + "\n")
    out = "\n".join(view.render(path))
    assert "checkpoint / restore / preempt timeline" in out
    # the table clips cell text at column width — match the prefixes
    assert "ckpt_comm" in out and "resume" in out
    assert "preempt_s" in out and "ckpt_corr" in out
    assert "dp 8" in out
