"""Link-aware two-level collective stream (ISSUE 16).

Numerics contract: with ``CollectiveMatmulConfig.hierarchy`` set, both
fused-collective ops must reproduce the flat single-ring schedule (and
the dense einsum it is pinned against) to fp32 partial-sum rounding —
the two-level lowering only reorders the partial sums, it never changes
what is summed. Same for the overlap-layer two-level gather/reduce
primitives vs their numpy references, and for the compressed slow hop
vs the flat 1-bit primitive when the split is degenerate (intra=1).
Also pins the `comm.hierarchy` x `stage3_prefetch` config composition
rules and the per-(axis, reason) fallback-warning latch.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as dstpu
from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.ops.pallas import fused_collective as fc
from deepspeed_tpu.parallel import compression as comp
from deepspeed_tpu.parallel import overlap as ov
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.parallel.mesh import MeshConfig, make_mesh, shard_map

SPLITS = [(2, 4), (4, 2)]


def _flat_mesh(n):
    devs = jax.devices()
    assert len(devs) >= n
    return Mesh(np.asarray(devs[:n]), ("data",))


def _split_mesh(ni, k):
    devs = jax.devices()
    assert len(devs) >= ni * k
    return Mesh(np.asarray(devs[:ni * k]).reshape(ni, k), ("di", "dt"))


def _hier_cfg(ni, k, backend="lax", tile_m=8):
    # axis_name is the axes tuple, mirroring how the engine passes
    # plan.axes — the hierarchical lowering routes every collective
    # through inter_axis/intra_axis and never uses the flat name
    return fc.CollectiveMatmulConfig(
        axis_name=("di", "dt"), axis_size=ni * k, backend=backend,
        tile_m=tile_m, min_shard_bytes=0, interpret=True,
        hierarchy=fc.RingHierarchy(inter_axis="di", intra_axis="dt",
                                   inter=ni, intra=k))


def _flat_cfg(n, tile_m=8):
    return fc.CollectiveMatmulConfig(
        axis_name="data", axis_size=n, backend="lax", tile_m=tile_m,
        min_shard_bytes=0, interpret=True)


# ---------------------------------------------------------------------------
# forward parity: hier all_gather_matmul / matmul_reduce_scatter
# ---------------------------------------------------------------------------

def _ag_inputs(dtype, transpose_w, M, K, N):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(M, N if transpose_w else K)
                    .astype(np.float32) * 0.1, dtype)
    w = jnp.asarray(rng.randn(K, N).astype(np.float32) * 0.1, dtype)
    ref = x.astype(jnp.float32) @ \
        (w.T if transpose_w else w).astype(jnp.float32)
    return x, w, np.asarray(ref)


def _run_hier_ag(ni, k, dtype, shard_dim, transpose_w, backend="lax",
                 M=32, K=48, N=64, tile_m=8):
    n = ni * k
    mesh = _split_mesh(ni, k)
    x, w, ref = _ag_inputs(dtype, transpose_w, M, K, N)
    cfg = _hier_cfg(ni, k, backend, tile_m)

    def f(x_l, w_l):
        return fc.all_gather_matmul(
            x_l, w_l, shard_dim=shard_dim, axis_name=("di", "dt"),
            axis_size=n, transpose_w=transpose_w, cfg=cfg,
            out_dtype=jnp.float32)

    wspec = P(("di", "dt"), None) if shard_dim == 0 \
        else P(None, ("di", "dt"))
    g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), wspec),
                          out_specs=P(), check_vma=False))
    return np.asarray(g(x, w)), ref


def _run_flat_ag(n, dtype, shard_dim, transpose_w, M=32, K=48, N=64):
    mesh = _flat_mesh(n)
    x, w, _ = _ag_inputs(dtype, transpose_w, M, K, N)
    cfg = _flat_cfg(n)

    def f(x_l, w_l):
        return fc.all_gather_matmul(
            x_l, w_l, shard_dim=shard_dim, axis_name="data", axis_size=n,
            transpose_w=transpose_w, cfg=cfg, out_dtype=jnp.float32)

    wspec = P("data", None) if shard_dim == 0 else P(None, "data")
    g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), wspec),
                          out_specs=P(), check_vma=False))
    return np.asarray(g(x, w))


@pytest.mark.parametrize("ni,k", SPLITS)
@pytest.mark.parametrize("shard_dim", [0, 1])
def test_hier_ag_matmul_matches_dense_and_flat(ni, k, shard_dim):
    out, ref = _run_hier_ag(ni, k, jnp.float32, shard_dim, False)
    flat = _run_flat_ag(ni * k, jnp.float32, shard_dim, False)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    np.testing.assert_allclose(out, flat, atol=2e-5)


@pytest.mark.parametrize("ni,k", SPLITS)
def test_hier_ag_matmul_transpose_w(ni, k):
    out, ref = _run_hier_ag(ni, k, jnp.float32, 1, True)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_hier_ag_matmul_bf16():
    out, ref = _run_hier_ag(2, 4, jnp.bfloat16, 0, False)
    np.testing.assert_allclose(out, ref, atol=5e-2)


def test_hier_ag_matmul_uneven_chunks():
    # K=56 over n=8 -> 7-wide shards; tile_m=7 exercises the divisor
    # clamp inside the per-block intra rings
    out, ref = _run_hier_ag(2, 4, jnp.float32, 0, False,
                            M=24, K=56, N=40, tile_m=7)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_hier_ag_matmul_fused_backend_routes_to_lax():
    # pallas remote DMA cannot address a two-named-axis env, so a
    # "fused" backend under a hierarchy must still lower (via the lax
    # intra ring) instead of crashing in dma_start
    out, ref = _run_hier_ag(2, 4, jnp.float32, 0, False, backend="fused")
    np.testing.assert_allclose(out, ref, atol=2e-5)


def _rs_inputs(dtype, M, K, N):
    rng = np.random.RandomState(1)
    lhs = jnp.asarray(rng.randn(M, K).astype(np.float32) * 0.1, dtype)
    rhs = jnp.asarray(rng.randn(M, N).astype(np.float32) * 0.1, dtype)
    return lhs, rhs


def _run_hier_rs(ni, k, dtype, shard_dim, backend="lax",
                 M=32, K=48, N=64):
    n = ni * k
    mesh = _split_mesh(ni, k)
    lhs, rhs = _rs_inputs(dtype, M, K, N)
    # identical local operands -> the SUM over the axis is n * dense
    ref = np.asarray(lhs.astype(jnp.float32).T
                     @ rhs.astype(jnp.float32)) * n
    cfg = _hier_cfg(ni, k, backend)

    def f(l, r):
        return fc.matmul_reduce_scatter(
            l, r, shard_dim=shard_dim, axis_name=("di", "dt"),
            axis_size=n, cfg=cfg)

    out_spec = P(("di", "dt"), None) if shard_dim == 0 \
        else P(None, ("di", "dt"))
    g = jax.jit(shard_map(f, mesh=mesh, in_specs=(P(), P()),
                          out_specs=out_spec, check_vma=False))
    return np.asarray(g(lhs, rhs)).astype(np.float32), ref


@pytest.mark.parametrize("ni,k", SPLITS)
@pytest.mark.parametrize("shard_dim", [0, 1])
def test_hier_mm_rs_matches_dense(ni, k, shard_dim):
    out, ref = _run_hier_rs(ni, k, jnp.float32, shard_dim)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_hier_mm_rs_bf16():
    out, ref = _run_hier_rs(2, 4, jnp.bfloat16, 0, M=24, K=32, N=16)
    np.testing.assert_allclose(out, ref, atol=5e-2)


# ---------------------------------------------------------------------------
# custom-VJP parity vs dense autodiff (the prefetch grad contract)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ni,k", SPLITS)
@pytest.mark.parametrize("shard_dim", [0, 1])
def test_hier_collective_matmul_vjp_matches_dense(ni, k, shard_dim):
    n, M, K, N = ni * k, 16, 32, 24
    mesh = _split_mesh(ni, k)
    rng = np.random.RandomState(2)
    x = rng.randn(n * M, K).astype(np.float32) * 0.1
    w = rng.randn(K, N).astype(np.float32) * 0.1
    cfg = _hier_cfg(ni, k)

    def local_loss(x_l, w_l):
        y = fc.collective_matmul(x_l, w_l, shard_dim=shard_dim,
                                 axis_name=("di", "dt"), axis_size=n,
                                 cfg=cfg)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def f(x_l, w_l):
        loss = local_loss(x_l, w_l)
        gx, gw = jax.grad(local_loss, argnums=(0, 1))(x_l, w_l)
        return jax.lax.psum(loss, ("di", "dt")), gx, gw

    wspec = P(("di", "dt"), None) if shard_dim == 0 \
        else P(None, ("di", "dt"))
    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=(P(("di", "dt"), None), wspec),
                          out_specs=(P(), P(("di", "dt"), None), wspec),
                          check_vma=False))
    loss, gx, gw = g(jnp.asarray(x), jnp.asarray(w))

    def ref_loss(x_r, w_r):
        return jnp.sum((x_r @ w_r) ** 2)

    rl = ref_loss(jnp.asarray(x), jnp.asarray(w))
    rgx, rgw = jax.grad(ref_loss, argnums=(0, 1))(
        jnp.asarray(x), jnp.asarray(w))
    # dW comes back as the SUM over the whole split axis — the
    # two-level reduce-scatter must land the same total as the flat ring
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rgx),
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rgw),
                               atol=2e-4, rtol=1e-4)


def test_hier_collective_matmul_vjp_bf16():
    n, M, K, N = 8, 16, 32, 24
    mesh = _split_mesh(2, 4)
    rng = np.random.RandomState(5)
    x = (rng.randn(n * M, K) * 0.1).astype(np.float32)
    w = (rng.randn(K, N) * 0.1).astype(np.float32)
    cfg = _hier_cfg(2, 4)

    def local_loss(x_l, w_l):
        y = fc.collective_matmul(x_l, w_l, shard_dim=0,
                                 axis_name=("di", "dt"), axis_size=n,
                                 cfg=cfg)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    def f(x_l, w_l):
        return jax.grad(local_loss, argnums=1)(x_l, w_l)

    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=(P(("di", "dt"), None),
                                    P(("di", "dt"), None)),
                          out_specs=P(("di", "dt"), None),
                          check_vma=False))
    gw = g(jnp.asarray(x, jnp.bfloat16), jnp.asarray(w, jnp.bfloat16))
    assert gw.dtype == jnp.bfloat16
    rgw = jax.grad(lambda wr: jnp.sum((
        jnp.asarray(x, jnp.bfloat16).astype(jnp.float32)
        @ wr.astype(jnp.float32)) ** 2))(jnp.asarray(w, jnp.bfloat16))
    np.testing.assert_allclose(np.asarray(gw, np.float32),
                               np.asarray(rgw, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_hier_world_mismatch_asserts():
    # hierarchy inter*intra must equal axis_size — a split that does not
    # cover the axis would silently drop shards
    cfg = fc.CollectiveMatmulConfig(
        axis_name=("di", "dt"), axis_size=8, backend="lax",
        min_shard_bytes=0, interpret=True,
        hierarchy=fc.RingHierarchy("di", "dt", 2, 2))
    mesh = _split_mesh(2, 4)

    def f(x_l, w_l):
        return fc.all_gather_matmul(
            x_l, w_l, shard_dim=0, axis_name=("di", "dt"), axis_size=8,
            cfg=cfg, out_dtype=jnp.float32)

    g = shard_map(f, mesh=mesh,
                  in_specs=(P(), P(("di", "dt"), None)),
                  out_specs=P(), check_vma=False)
    with pytest.raises(AssertionError):
        jax.jit(g)(jnp.zeros((16, 48)), jnp.zeros((48, 32)))


# ---------------------------------------------------------------------------
# overlap-layer two-level primitives
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("ni,k", SPLITS + [(8, 1), (1, 8)])
def test_two_level_all_gather_natural_order(ni, k):
    n, c = ni * k, 6
    mesh = _split_mesh(ni, k)
    data = np.arange(n * c, dtype=np.float32).reshape(n, c)
    plan = ov.HierarchyPlan("di", "dt", ni, k)

    def f(sh):
        return ov.two_level_all_gather(sh[0], plan)

    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=P(("di", "dt"), None),
                          out_specs=P(), check_vma=False))
    # every device must reassemble the full stack in natural data order
    np.testing.assert_array_equal(np.asarray(g(data)), data)


@pytest.mark.parametrize("ni,k", SPLITS)
def test_two_level_reduce_scatter_sum_matches_numpy(ni, k):
    n, c = ni * k, 5
    mesh = _split_mesh(ni, k)
    rng = np.random.RandomState(3)
    pieces = rng.randn(n, n, c).astype(np.float32)
    plan = ov.HierarchyPlan("di", "dt", ni, k)

    def f(p):
        return ov.two_level_reduce_scatter_sum(p[0], plan)[None]

    g = jax.jit(shard_map(f, mesh=mesh,
                          in_specs=P(("di", "dt"), None, None),
                          out_specs=P(("di", "dt"), None),
                          check_vma=False))
    np.testing.assert_allclose(np.asarray(g(pieces)),
                               pieces.sum(axis=0), rtol=1e-6, atol=1e-6)


def test_two_level_compressed_degenerate_matches_flat_primitive():
    """intra=1 collapses the two-level schedule to exactly the flat
    1-bit exchange: same piece order, same padding, same axis — the
    outputs and carried errors must be bit-identical."""
    n, c = 8, 16
    rng = np.random.RandomState(4)
    pieces = rng.randn(n, n, c).astype(np.float32)
    plan = ov.HierarchyPlan("di", "dt", 8, 1, compression="always")
    assert ov.two_level_error_numel(c, plan) == n * c
    err = np.zeros((n, n * c), np.float32)

    mesh = _split_mesh(8, 1)

    def f(p, e):
        out, ne = ov.two_level_reduce_scatter_compressed(p[0], e[0], plan)
        return out[None], ne[None]

    g = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P(("di", "dt"), None, None), P(("di", "dt"), None)),
        out_specs=(P(("di", "dt"), None), P(("di", "dt"), None)),
        check_vma=False))
    out_h, err_h = g(pieces, err)

    flat = _flat_mesh(n)

    def ff(p, e):
        out, ne = comp.compressed_reduce_scatter_sum(
            p[0].reshape(-1), e[0], "data")
        return out[None], ne[None]

    gf = jax.jit(shard_map(
        ff, mesh=flat,
        in_specs=(P("data", None, None), P("data", None)),
        out_specs=(P("data", None), P("data", None)),
        check_vma=False))
    out_f, err_f = gf(pieces, err)

    np.testing.assert_array_equal(np.asarray(out_h), np.asarray(out_f))
    np.testing.assert_array_equal(np.asarray(err_h), np.asarray(err_f))
    assert float(np.abs(np.asarray(err_h)).sum()) > 0


def test_two_level_compressed_error_feedback_converges():
    """Worker-error feedback: re-applying the compressed reduce on the
    SAME pieces with the carried residual must beat round 1 on average —
    the residual re-enters the next round, so the running mean of the
    outputs approaches the exact sum."""
    ni, k = 2, 4
    n, c, rounds = ni * k, 16, 8
    rng = np.random.RandomState(6)
    pieces = rng.randn(n, n, c).astype(np.float32)
    plan = ov.HierarchyPlan("di", "dt", ni, k, compression="always")
    err = np.zeros((n, ov.two_level_error_numel(c, plan)), np.float32)
    mesh = _split_mesh(ni, k)

    def f(p, e):
        out, ne = ov.two_level_reduce_scatter_compressed(p[0], e[0], plan)
        return out[None], ne[None]

    g = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P(("di", "dt"), None, None), P(("di", "dt"), None)),
        out_specs=(P(("di", "dt"), None), P(("di", "dt"), None)),
        check_vma=False))
    exact = pieces.sum(axis=0)
    outs = []
    e = jnp.asarray(err)
    for _ in range(rounds):
        out, e = g(pieces, e)
        outs.append(np.asarray(out))
    scale = np.linalg.norm(exact)
    first_err = np.linalg.norm(outs[0] - exact) / scale
    avg_err = np.linalg.norm(np.mean(outs, axis=0) - exact) / scale
    assert np.isfinite(first_err) and first_err > 0
    assert avg_err < first_err * 0.7, (avg_err, first_err)


# ---------------------------------------------------------------------------
# config composition + fallback latch
# ---------------------------------------------------------------------------

def _cfg_dict(gather, hierarchy=True, prefetch=True):
    d = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_prefetch": prefetch,
                              "stage3_prefetch_gather": gather},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    }
    if hierarchy:
        d["comm"] = {"hierarchy": {"slow_axis": 2,
                                   "compression": "always"}}
    return d


def test_hierarchy_prefetch_gather_fused_rejected():
    # "fused" hands the gather schedule to XLA, which cannot honor the
    # two-level link split — must fail loudly at config time
    with pytest.raises(DeepSpeedConfigError, match="fused"):
        DeepSpeedConfig(_cfg_dict("fused"), world_size=8)


@pytest.mark.parametrize("gather", ["ring", "fused_matmul"])
def test_hierarchy_prefetch_explicit_gathers_accepted(gather):
    cfg = DeepSpeedConfig(_cfg_dict(gather), world_size=8)
    assert cfg.comm_config.hierarchy.enabled
    assert cfg.zero_config.stage3_prefetch_gather == gather


def test_hierarchy_off_or_no_prefetch_allows_fused():
    DeepSpeedConfig(_cfg_dict("fused", hierarchy=False), world_size=8)
    DeepSpeedConfig(_cfg_dict("fused", prefetch=False), world_size=8)


def test_fallback_latch_once_per_axis_reason():
    topo.reset_fallback_latch()
    try:
        assert topo.latch_fallback("auto", "single process")
        # same (axis, reason) pair: latched, warn only once
        assert not topo.latch_fallback("auto", "single process")
        # distinct reason or axis latches independently
        assert topo.latch_fallback("auto", "axis size 1")
        assert topo.latch_fallback(3, "single process")
        assert not topo.latch_fallback(3, "single process")
        topo.reset_fallback_latch()
        assert topo.latch_fallback("auto", "single process")
    finally:
        topo.reset_fallback_latch()


# ---------------------------------------------------------------------------
# engine-level trajectory parity (single process, synthetic split)
# ---------------------------------------------------------------------------

def _gpt2_tiny():
    return GPT2Config(vocab_size=512, n_positions=64, n_embd=64,
                      n_layer=2, n_head=2, dtype=jnp.float32,
                      param_dtype=jnp.float32, scan_layers=True)


def _make_engine(hier, gather="ring", cm=None):
    cfg = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_prefetch": True,
                              "stage3_prefetch_gather": gather,
                              "stage3_param_persistence_threshold": 0,
                              **({"collective_matmul": cm} if cm else {})},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }
    if hier is not None:
        cfg["comm"] = {"hierarchy": hier}
    mesh = make_mesh(MeshConfig(data=8), devices=jax.devices()[:8])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model_fn(),
                                       mesh=mesh)
    return engine


def model_fn():
    return GPT2LMHeadModel(_gpt2_tiny())


def _batch():
    return {"input_ids": np.random.RandomState(0).randint(
        0, 512, (8, 64)).astype(np.int32)}


def test_engine_hier_exact_matches_flat():
    """comm.hierarchy with compression 'never' is a pure reschedule of
    the stage-3 stream — the training trajectory must match the flat
    engine to fp32 reduction-order noise."""
    batch = _batch()
    eng_h = _make_engine({"slow_axis": 2, "compression": "never"})
    l_h = [float(eng_h.train_batch(batch)) for _ in range(3)]
    eng_f = _make_engine(None)
    l_f = [float(eng_f.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(l_h, l_f, rtol=2e-5, atol=1e-5)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(eng_h.state.params),
            jax.tree_util.tree_leaves_with_path(eng_f.state.params)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-5, atol=1e-5, err_msg=jax.tree_util.keystr(pa))


def test_engine_hier_compressed_wire_reduction():
    """The acceptance bar of ISSUE 16 as a pinned test: the compressed
    slow hop must cut modeled inter-host bytes by >= 2x vs the flat-ring
    baseline on a 2x4 synthetic split, while training stays finite and
    the error residuals ride the optimizer state."""
    batch = _batch()
    eng = _make_engine({"slow_axis": 2, "compression": "always"})
    losses = [float(eng.train_batch(batch)) for _ in range(2)]
    assert np.isfinite(losses).all()
    assert losses[1] < losses[0]
    assert any(key.startswith("pf_") for key in eng.state.opt_state)
    wire = eng._pf_wire_model
    assert 0 < wire["inter"] < wire["inter_uncompressed"]
    assert wire["inter_uncompressed"] / wire["inter"] >= 2.0, wire
    counters = eng.telemetry.snapshot("comm/")["counters"]
    assert counters["comm/bytes_on_wire/inter"] > 0
    assert counters["comm/bytes_on_wire/inter_uncompressed"] \
        > counters["comm/bytes_on_wire/inter"]


@pytest.mark.slow
def test_engine_hier_fused_matmul_exact_matches_flat():
    batch = _batch()
    cm = {"backend": "lax", "min_shard_bytes": 0}
    eng_h = _make_engine({"slow_axis": 2, "compression": "never"},
                         gather="fused_matmul", cm=cm)
    l_h = [float(eng_h.train_batch(batch)) for _ in range(3)]
    eng_f = _make_engine(None, gather="fused_matmul", cm=cm)
    l_f = [float(eng_f.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(l_h, l_f, rtol=2e-5, atol=1e-5)
