"""Fused decode-kernel parity tests (ops/pallas/decode.py) vs plain-XLA
references, in interpret mode. The e2e serving path (prompt fill through
the general path + fused single-token decode) is covered by
tests/test_gpt2_inference.py; these pin each kernel's math in isolation.

Reference role: the reference validates its fused inference CUDA kernels
against torch baselines the same way
(tests/unit/test_cuda_forward.py methodology)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.pallas.decode import (
    matvec_int8, ln_qkv_int8, kv_quant_int8,
    decode_attention_int8, out_ffn_int8)


def _ln_ref(x, w, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / np.sqrt(var + eps) * w + b


@pytest.fixture
def rs():
    return np.random.RandomState(0)


def test_matvec_int8_matches_xla(rs):
    B, E, N = 2, 256, 512
    x = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.3
    wq = jnp.asarray(rs.randint(-127, 128, (E, N)), jnp.int8)
    b = jnp.asarray(rs.randn(N), jnp.float32) * 0.01
    s = 0.002
    ref = x @ (wq.astype(jnp.float32) * s) + b
    got = matvec_int8(x, wq, s, b)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_matvec_int8_gelu(rs):
    B, E, N = 1, 128, 256
    x = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.3
    wq = jnp.asarray(rs.randint(-127, 128, (E, N)), jnp.int8)
    b = jnp.zeros((N,), jnp.float32)
    s = 0.001
    ref = jax.nn.gelu((x @ (wq.astype(jnp.float32) * s) + b),
                      approximate=True)
    got = matvec_int8(x, wq, s, b, act="gelu_tanh")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ln_qkv_int8_matches_xla(rs):
    B, E = 2, 256
    x = jnp.asarray(rs.randn(B, E), jnp.float32)
    lw = jnp.asarray(1.0 + 0.1 * rs.randn(E), jnp.float32)
    lb = jnp.asarray(0.1 * rs.randn(E), jnp.float32)
    wq = jnp.asarray(rs.randint(-127, 128, (E, 3 * E)), jnp.int8)
    b = jnp.asarray(rs.randn(3 * E), jnp.float32) * 0.01
    s = 0.001
    u = _ln_ref(np.asarray(x), np.asarray(lw), np.asarray(lb))
    ref = u @ (np.asarray(wq, np.float32) * s) + np.asarray(b)
    got = ln_qkv_int8(x, lw, lb, wq, s, b)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_kv_quant_int8_roundtrip(rs):
    B, H, D = 2, 4, 64
    k = jnp.asarray(rs.randn(B, H, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, D), jnp.float32) * 3.0
    kq, ks, vq, vs = kv_quant_int8(k, v)
    assert kq.dtype == jnp.int8 and ks.shape == (B, H, 1)
    k_rt = np.asarray(kq, np.float32) * np.asarray(ks)
    v_rt = np.asarray(vq, np.float32) * np.asarray(vs)
    # symmetric per-head absmax quant: error bounded by scale/2
    assert np.max(np.abs(k_rt - np.asarray(k))) <= np.max(np.asarray(ks))
    assert np.max(np.abs(v_rt - np.asarray(v))) <= np.max(np.asarray(vs))


def test_decode_attention_int8_matches_xla(rs):
    B, H, D, L, pos = 2, 4, 64, 256, 150
    q = jnp.asarray(rs.randn(B, H, 1, D), jnp.float32) * 0.3
    kc = jnp.asarray(rs.randint(-127, 128, (B, H, L, D)), jnp.int8)
    vc = jnp.asarray(rs.randint(-127, 128, (B, H, L, D)), jnp.int8)
    ks = jnp.asarray(np.abs(rs.randn(B, H, L)), jnp.float32) * 0.01 + 1e-3
    vs = jnp.asarray(np.abs(rs.randn(B, H, L)), jnp.float32) * 0.01 + 1e-3
    dn_qk = (((3,), (3,)), ((0, 1), (0, 1)))
    scores = jax.lax.dot_general(q, kc.astype(q.dtype), dn_qk)
    scores = scores * ks[:, :, None, :] * (1.0 / np.sqrt(D))
    vis = jnp.arange(L)[None, None, None, :] <= pos
    scores = jnp.where(vis, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1) * vs[:, :, None, :]
    ref = jax.lax.dot_general(p.astype(q.dtype), vc.astype(q.dtype),
                              (((3,), (2,)), ((0, 1), (0, 1))))
    # block_l below L exercises the online-softmax carry across blocks
    # (round-4 regression: a missing m_ref writeback only showed multi-block)
    got = decode_attention_int8(q, kc, ks, vc, vs, pos, block_l=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_decode_attention_pos_zero(rs):
    """First decode step: only position 0 visible -> output == v[0]·vs."""
    B, H, D, L = 1, 2, 64, 128
    q = jnp.asarray(rs.randn(B, H, 1, D), jnp.float32)
    kc = jnp.asarray(rs.randint(-127, 128, (B, H, L, D)), jnp.int8)
    vc = jnp.asarray(rs.randint(-127, 128, (B, H, L, D)), jnp.int8)
    ks = jnp.ones((B, H, L), jnp.float32)
    vs = jnp.full((B, H, L), 0.5, jnp.float32)
    got = decode_attention_int8(q, kc, ks, vc, vs, 0, block_l=64)
    ref = vc[:, :, 0].astype(jnp.float32) * 0.5
    np.testing.assert_allclose(np.asarray(got[:, :, 0]), np.asarray(ref),
                               rtol=1e-6)


def test_out_ffn_int8_matches_xla(rs):
    B, E, F = 1, 256, 512
    ctx = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.3
    x = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.3
    wp = jnp.asarray(rs.randint(-127, 128, (E, E)), jnp.int8)
    w1 = jnp.asarray(rs.randint(-127, 128, (E, F)), jnp.int8)
    w2 = jnp.asarray(rs.randint(-127, 128, (F, E)), jnp.int8)
    bp = jnp.asarray(rs.randn(E), jnp.float32) * 0.01
    b1 = jnp.asarray(rs.randn(F), jnp.float32) * 0.01
    b2 = jnp.asarray(rs.randn(E), jnp.float32) * 0.01
    lw = jnp.asarray(1.0 + 0.1 * rs.randn(E), jnp.float32)
    lb = jnp.asarray(0.1 * rs.randn(E), jnp.float32)
    sp, s1, s2 = 0.002, 0.001, 0.0015
    x1 = np.asarray(x) + (np.asarray(ctx)
                          @ (np.asarray(wp, np.float32) * sp)
                          + np.asarray(bp))
    u = _ln_ref(x1, np.asarray(lw), np.asarray(lb))
    h = np.asarray(jax.nn.gelu(
        jnp.asarray(u @ (np.asarray(w1, np.float32) * s1) + np.asarray(b1)),
        approximate=True))
    ref = x1 + h @ (np.asarray(w2, np.float32) * s2) + np.asarray(b2)
    got = out_ffn_int8(ctx, x, wp, sp, bp, lw, lb, w1, s1, b1, w2, s2, b2,
                       block_f=256)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_decode_attention_fp_stacked_multiblock(rs):
    """Full-precision stacked cache variant with block_l below L — pins
    the cross-block online-softmax carry (alpha rescale + m writeback)
    of the shared kernel body on its quantized=False operand layout, and
    the layer block-index maps."""
    from deepspeed_tpu.ops.pallas.decode import decode_attention_fp_stacked
    Lyr, B, H, D, L, pos, layer = 3, 2, 4, 64, 256, 150, 1
    q = jnp.asarray(rs.randn(B, H, 1, D), jnp.float32) * 0.3
    kc = jnp.asarray(rs.randn(Lyr, B, H, L, D), jnp.float32)
    vc = jnp.asarray(rs.randn(Lyr, B, H, L, D), jnp.float32)
    dn_qk = (((3,), (3,)), ((0, 1), (0, 1)))
    scores = jax.lax.dot_general(q, kc[layer], dn_qk) * (1.0 / np.sqrt(D))
    vis = jnp.arange(L)[None, None, None, :] <= pos
    scores = jnp.where(vis, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    ref = jax.lax.dot_general(p, vc[layer],
                              (((3,), (2,)), ((0, 1), (0, 1))))
    got = decode_attention_fp_stacked(q, kc, vc, pos, layer, block_l=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_rms_qkv_stacked_matches_xla(rs):
    """norm='rms' mode: RMSNorm + bias-free packed projection over an
    int8 stack — pins the LLaMA qkv kernel math."""
    from deepspeed_tpu.ops.pallas.decode import ln_qkv_int8_stacked
    Lyr, B, E, N, layer = 3, 2, 128, 256, 1
    x = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.5
    lw = jnp.asarray(1.0 + 0.1 * rs.randn(Lyr, E), jnp.float32)
    wq = jnp.asarray(rs.randint(-127, 128, (Lyr, E, N)), jnp.int8)
    s = jnp.full((Lyr,), 0.002, jnp.float32)
    xf = np.asarray(x)
    u = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-5) \
        * np.asarray(lw[layer])
    ref = u @ (np.asarray(wq[layer], np.float32) * 0.002)
    got = ln_qkv_int8_stacked(x, lw, None, wq, s, None, layer,
                              norm="rms")
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4,
                               atol=2e-4)


def test_swiglu_out_ffn_stacked_matches_xla(rs):
    """norm='rms' + act='swiglu': o_proj + residual + RMSNorm + gated
    FFN + residual, bias-free — pins the LLaMA ffn kernel math."""
    from deepspeed_tpu.ops.pallas.decode import out_ffn_int8_stacked
    Lyr, B, E, F, layer = 2, 2, 128, 256, 1
    ctx = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.3
    x = jnp.asarray(rs.randn(B, E), jnp.float32) * 0.3
    wo = jnp.asarray(rs.randint(-127, 128, (Lyr, E, E)), jnp.int8)
    wg = jnp.asarray(rs.randint(-127, 128, (Lyr, E, F)), jnp.int8)
    wu = jnp.asarray(rs.randint(-127, 128, (Lyr, E, F)), jnp.int8)
    wd = jnp.asarray(rs.randint(-127, 128, (Lyr, F, E)), jnp.int8)
    nw = jnp.asarray(1.0 + 0.1 * rs.randn(Lyr, E), jnp.float32)
    so, sg, su, sd = (jnp.full((Lyr,), v, jnp.float32)
                      for v in (0.002, 0.001, 0.0015, 0.001))
    x1 = np.asarray(x) + np.asarray(ctx) @ (
        np.asarray(wo[layer], np.float32) * 0.002)
    u = x1 / np.sqrt((x1 ** 2).mean(-1, keepdims=True) + 1e-5) \
        * np.asarray(nw[layer])
    g = u @ (np.asarray(wg[layer], np.float32) * 0.001)
    up = u @ (np.asarray(wu[layer], np.float32) * 0.0015)
    h = np.asarray(jax.nn.silu(jnp.asarray(g))) * up
    ref = x1 + h @ (np.asarray(wd[layer], np.float32) * 0.001)
    got = out_ffn_int8_stacked(
        ctx, x, wo, so, None, nw, None, wg, sg, None, wd, sd, None,
        layer, act="swiglu", norm="rms", w1b_stack=wu, s1b=su,
        block_f=128)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=3e-4,
                               atol=3e-4)


def test_decode_attention_stacked_gqa_rows(rs):
    """R > 1 grouped-query rows: the R = H/Hkv query heads sharing each
    KV head ride the row axis; the cache is read once. Must equal the
    per-row XLA reference (multi-block path via block_l < L)."""
    from deepspeed_tpu.ops.pallas.decode import (
        decode_attention_int8_stacked)
    Lyr, B, Hkv, R, D, L, pos, layer = 2, 2, 2, 4, 64, 256, 130, 1
    q = jnp.asarray(rs.randn(B, Hkv, R, D), jnp.float32) * 0.3
    kc = jnp.asarray(rs.randint(-127, 128, (Lyr, B, Hkv, L, D)),
                     jnp.int8)
    vc = jnp.asarray(rs.randint(-127, 128, (Lyr, B, Hkv, L, D)),
                     jnp.int8)
    ks = jnp.asarray(np.abs(rs.randn(Lyr, B, Hkv, L)),
                     jnp.float32) * 0.01 + 1e-3
    vs = jnp.asarray(np.abs(rs.randn(Lyr, B, Hkv, L)),
                     jnp.float32) * 0.01 + 1e-3
    dn_qk = (((3,), (3,)), ((0, 1), (0, 1)))
    scores = jax.lax.dot_general(q, kc[layer].astype(q.dtype), dn_qk)
    scores = scores * ks[layer][:, :, None, :] * (1.0 / np.sqrt(D))
    vis = jnp.arange(L)[None, None, None, :] <= pos
    scores = jnp.where(vis, scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1) * vs[layer][:, :, None, :]
    ref = jax.lax.dot_general(p.astype(q.dtype),
                              vc[layer].astype(q.dtype),
                              (((3,), (2,)), ((0, 1), (0, 1))))
    got = decode_attention_int8_stacked(
        q, kc, ks.reshape(Lyr, B, Hkv, 1, L), vc,
        vs.reshape(Lyr, B, Hkv, 1, L), pos, layer, block_l=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
