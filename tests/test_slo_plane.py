"""Windowed per-role SLO plane (ISSUE 19): rolling windows, burn-rate
math, the ``slo/*`` gauge contract, and the two autoscaling consumers
(ReplicaPool ``scale_signal="slo"``, the supervisor's role ladder).

The consumer tests are THE acceptance pin: a role-scale recommendation
driven purely from exported ``slo/*`` gauges — decode scale-up under a
saturated decode window, no-op under a balanced one — with no access
to the plane object itself.
"""

import types

import pytest

from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.slo import (DEFAULT_TARGETS, SLO_FAMILIES,
                                         SloPlane, SloWindow,
                                         roles_signal, slo_metric_names)

# ----------------------------------------------------------- windows


def test_window_evicts_whole_buckets_past_horizon():
    w = SloWindow(window_s=10.0, n_buckets=5)       # 2 s buckets
    for i in range(5):
        w.observe(float(i), now=100.0 + 2.0 * i)    # one per bucket
    assert sorted(w.samples(now=109.0)) == [0.0, 1.0, 2.0, 3.0, 4.0]
    # 4 s later the two oldest buckets fall off WHOLE
    assert sorted(w.samples(now=113.0)) == [2.0, 3.0, 4.0]
    # far future: empty, nothing lingers
    assert w.samples(now=1000.0) == []
    assert w.total == 5                             # lifetime count kept


def test_window_caps_samples_per_bucket():
    w = SloWindow(window_s=10.0, n_buckets=5, per_bucket_cap=16)
    for _ in range(1000):
        w.observe(1.0, now=100.0)
    assert len(w.samples(now=100.0)) == 16


# --------------------------------------------------------- burn rate


def test_burn_rate_is_violation_fraction_over_budget():
    p = SloPlane(targets={"tick_s": 0.1}, budget=0.1, min_samples=1)
    # 3 of 10 over target -> 30% violations / 10% budget = 3.0
    for i in range(10):
        p.observe("decode", "tick_s", 0.2 if i < 3 else 0.05, now=100.0)
    s = p.stats("decode", "tick_s", now=100.0)
    assert s["samples"] == 10
    assert s["burn_rate"] == pytest.approx(3.0)
    assert s["p50"] == 0.05


def test_stats_none_until_samples_and_windows_age_out():
    p = SloPlane(window_s=10.0, min_samples=1)
    assert p.stats("decode", "tick_s", now=100.0) is None
    p.observe("decode", "tick_s", 0.5, now=100.0)
    assert p.stats("decode", "tick_s", now=100.0)["samples"] == 1
    # the windowed plane FORGETS — the lifetime-histogram failure mode
    # this module exists to fix
    assert p.stats("decode", "tick_s", now=200.0) is None


def test_feed_counted_dedupes_by_count_cursor_and_source():
    p = SloPlane(min_samples=1)
    vals = [0.2, 0.3]
    p.feed_counted("prefill", "ttft_s", vals, 2, now=100.0)
    p.feed_counted("prefill", "ttft_s", vals, 2, now=100.0)  # re-poll
    assert p.stats("prefill", "ttft_s", now=100.0)["samples"] == 2
    # a third observation feeds ONLY the new tail
    p.feed_counted("prefill", "ttft_s", vals + [0.4], 3, now=100.0)
    assert p.stats("prefill", "ttft_s", now=100.0)["samples"] == 3
    # two histograms feeding ONE window keep independent cursors
    p.feed_counted("prefill", "transport_s", [0.01], 1, now=100.0,
                   source="a:encode")
    p.feed_counted("prefill", "transport_s", [0.02], 1, now=100.0,
                   source="b:collective")
    assert p.stats("prefill", "transport_s",
                   now=100.0)["samples"] == 2


# ------------------------------------------------------ gauge export


def test_export_writes_only_fed_families():
    p = SloPlane(min_samples=1)
    for _ in range(4):
        p.observe("decode", "tick_s", 0.05, now=100.0)
    reg = MetricsRegistry()
    p.export(reg, now=100.0)
    assert reg.peek_gauge("slo/window_s") == p.window_s
    assert reg.peek_gauge("slo/decode/tick_s/samples") == 4
    # an unfed family exports NOTHING (no phantom zeros)
    assert reg.peek_gauge("slo/prefill/ttft_s/samples") is None
    exported = {n for n in slo_metric_names()
                if reg.peek_gauge(n) is not None}
    assert exported == {"slo/window_s", "slo/decode/tick_s/p50",
                        "slo/decode/tick_s/p99",
                        "slo/decode/tick_s/burn_rate",
                        "slo/decode/tick_s/samples"}


def _saturate(reg, role, metric, burn, samples=32):
    reg.gauge(f"slo/{role}/{metric}/burn_rate").set(burn)
    reg.gauge(f"slo/{role}/{metric}/samples").set(samples)


def test_roles_signal_pinned_decisions():
    """THE acceptance decisions, purely from gauges: saturated decode
    -> decode up; balanced -> hold everywhere; slack everywhere ->
    down; thin samples -> hold regardless of burn."""
    reg = MetricsRegistry()
    _saturate(reg, "decode", "tick_s", burn=5.0)
    _saturate(reg, "prefill", "ttft_s", burn=0.8)
    assert roles_signal(reg) == {"decode": "up", "prefill": "hold"}
    # balanced: burns inside the hysteresis band on both roles
    reg2 = MetricsRegistry()
    _saturate(reg2, "decode", "tick_s", burn=1.0)
    _saturate(reg2, "prefill", "ttft_s", burn=1.0)
    assert roles_signal(reg2) == {"decode": "hold", "prefill": "hold"}
    # slack
    reg3 = MetricsRegistry()
    _saturate(reg3, "decode", "tick_s", burn=0.0)
    assert roles_signal(reg3)["decode"] == "down"
    # thin window: a single hot sample must NOT scale anything
    reg4 = MetricsRegistry()
    _saturate(reg4, "decode", "tick_s", burn=99.0, samples=2)
    assert roles_signal(reg4) == {"decode": "hold", "prefill": "hold"}
    # the worst family of a role decides: one hot metric beats two calm
    reg5 = MetricsRegistry()
    _saturate(reg5, "prefill", "ttft_s", burn=0.0)
    _saturate(reg5, "prefill", "queue_wait_s", burn=4.0)
    assert roles_signal(reg5)["prefill"] == "up"


def test_metric_names_cover_every_family():
    names = set(slo_metric_names())
    for role, metric in SLO_FAMILIES:
        for stat in ("p50", "p99", "burn_rate", "samples"):
            assert f"slo/{role}/{metric}/{stat}" in names
    assert "slo/window_s" in names
    assert all(m in DEFAULT_TARGETS for _r, m in SLO_FAMILIES)


# ------------------------------------------------------------- config


def test_slo_config_defaults_and_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfigError,
                                             SloConfig)
    c = SloConfig({})
    assert c.enabled and c.window_s == 30.0 and c.budget == 0.1
    assert c.down_burn < c.up_burn
    p = SloPlane.from_config(c)
    assert p is not None and p.window_s == 30.0
    assert SloPlane.from_config(
        SloConfig({"slo": {"enabled": False}})) is None
    assert SloPlane.from_config(None) is None
    c2 = SloConfig({"slo": {"window_s": 5.0,
                            "targets": {"tick_s": 0.5}}})
    assert SloPlane.from_config(c2).targets["tick_s"] == 0.5
    for bad in ({"window_s": 0}, {"budget": 0}, {"budget": 2},
                {"up_burn": 1.0, "down_burn": 1.0},
                {"targets": {"tick_s": -1}}):
        with pytest.raises(DeepSpeedConfigError):
            SloConfig({"slo": bad})


# ------------------------------------------- consumers: replica pool


def _fake_batcher(_rid):
    slot = types.SimpleNamespace(active=False)
    elastic = types.SimpleNamespace(
        request_preemption=lambda source=None: None,
        last_snapshot_dir=None)
    return types.SimpleNamespace(
        watchdog=None, metrics=MetricsRegistry(), queue=[],
        slots=[slot, slot], elastic=elastic, preempted=False,
        step=lambda now=None: [])


def _mk_pool(reg, **kw):
    from deepspeed_tpu.serving.replica_pool import ReplicaPool
    kw.setdefault("n_replicas", 1)
    kw.setdefault("max_replicas", 3)
    return ReplicaPool(_fake_batcher, scale_signal="slo",
                       slo_registry=reg, **kw)


def test_pool_scales_up_on_decode_burn_from_gauges_only():
    reg = MetricsRegistry()
    _saturate(reg, "decode", "tick_s", burn=5.0)
    pool = _mk_pool(reg)
    assert len(pool.replicas) == 1
    pool._autoscale()
    assert len(pool.replicas) == 2
    assert pool.stats["scale_ups"] == 1
    ev = [e for e in pool.recorder.events()
          if e.get("kind") == "replica_scale"]
    assert ev and ev[-1]["reason"] == "slo_burn:decode"
    # capped at max_replicas
    pool._autoscale()
    pool._autoscale()
    assert len(pool.replicas) == 3
    pool._autoscale()
    assert len(pool.replicas) == 3


def test_pool_holds_under_balanced_gauges():
    reg = MetricsRegistry()
    _saturate(reg, "decode", "tick_s", burn=1.0)
    _saturate(reg, "prefill", "ttft_s", burn=1.0)
    pool = _mk_pool(reg, n_replicas=2)
    for _ in range(100):
        pool._autoscale()
    assert len(pool.replicas) == 2        # no-op, both directions
    assert pool.stats["scale_ups"] == 0
    assert pool.stats["scale_downs"] == 0


def test_pool_scale_down_needs_sustained_slack():
    reg = MetricsRegistry()
    _saturate(reg, "decode", "tick_s", burn=0.0)
    pool = _mk_pool(reg, n_replicas=2, scale_down_idle_rounds=5)
    for _ in range(4):
        pool._autoscale()
    assert len(pool.replicas) == 2        # patience not yet spent
    pool._autoscale()
    # the 5th consecutive "down" round drains the least-loaded replica
    assert pool._draining or len(pool.replicas) == 1


def test_pool_watchdog_signal_ignores_slo_gauges():
    from deepspeed_tpu.serving.replica_pool import ReplicaPool
    reg = MetricsRegistry()
    _saturate(reg, "decode", "tick_s", burn=99.0)
    pool = ReplicaPool(_fake_batcher, n_replicas=1, max_replicas=3,
                       scale_signal="watchdog", slo_registry=reg)
    pool._autoscale()
    assert len(pool.replicas) == 1


def test_pool_slo_recommendation_is_inspectable():
    reg = MetricsRegistry()
    _saturate(reg, "prefill", "ttft_s", burn=3.0)
    pool = _mk_pool(reg)
    assert pool.slo_recommendation()["prefill"] == "up"


# --------------------------------------------- consumer: supervisor


def _mk_supervisor(tmp_path, roles, registry=None):
    from deepspeed_tpu.runtime.elastic.supervisor import Supervisor
    return Supervisor(["true"], world=3, roles=roles,
                      heartbeat_dir=str(tmp_path / "hb"),
                      log_dir=str(tmp_path / "logs"),
                      registry=registry if registry is not None
                      else MetricsRegistry())


def test_roles_for_world_prefer_biases_only_fill_ranks(tmp_path):
    sup = _mk_supervisor(tmp_path, {0: "prefill", 1: "decode"})
    assert sup.roles_for_world(4) == {0: "prefill", 1: "decode",
                                      2: "decode", 3: "decode"}
    # prefer overrides the FILL only; configured ranks keep their role
    assert sup.roles_for_world(4, prefer="prefill") == {
        0: "prefill", 1: "decode", 2: "prefill", 3: "prefill"}
    assert sup.roles_for_world(2, prefer="prefill") == {
        0: "prefill", 1: "decode"}


def test_supervisor_roles_preference_reads_slo_gauges(tmp_path):
    reg = MetricsRegistry()
    sup = _mk_supervisor(tmp_path, {0: "prefill", 1: "decode"},
                         registry=reg)
    assert sup.roles_preference() is None            # no gauges: no bias
    _saturate(reg, "decode", "tick_s", burn=5.0)
    assert sup.roles_preference() == "decode"
    ladder = sup.roles_for_world(4, prefer=sup.roles_preference())
    assert ladder == {0: "prefill", 1: "decode", 2: "decode",
                      3: "decode"}
    # a hot rank-0 role cannot re-role rank 0 — it biases the fill
    reg2 = MetricsRegistry()
    sup2 = _mk_supervisor(tmp_path, {0: "prefill", 1: "decode"},
                          registry=reg2)
    _saturate(reg2, "prefill", "ttft_s", burn=5.0)
    _saturate(reg2, "decode", "tick_s", burn=5.0)
    assert sup2.roles_preference() == "decode"


def test_training_supervisor_has_no_role_preference(tmp_path):
    sup = _mk_supervisor(tmp_path, None)
    assert sup.roles_for_world(4) is None
    assert sup.roles_preference() is None
