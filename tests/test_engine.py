"""Engine end-to-end tests — the role of the reference's test_fp16.py /
simple-model training tests: loss decreases, GAS paths agree, fp16 scaler
behaves, checkpoint roundtrips."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as dstpu
from tests.simple_model import (SimpleModel, random_batch, random_dataset,
                                base_config, token_batch)


def one_device_mesh():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    return make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def make_engine(config=None, model=None, **kw):
    model = model or SimpleModel()
    kw.setdefault("mesh", one_device_mesh())
    engine, _, _, _ = dstpu.initialize(config=config or base_config(),
                                       model=model, **kw)
    return engine


def test_train_batch_loss_decreases():
    engine = make_engine()
    batch = random_batch(batch_size=8)
    first = float(engine.train_batch(batch))
    for _ in range(30):
        last = float(engine.train_batch(batch))
    assert last < first, f"loss did not decrease: {first} -> {last}"


def test_forward_backward_step_equals_train_batch():
    cfg = base_config(train_batch_size=8, gradient_accumulation_steps=2)
    e1 = make_engine(cfg)
    e2 = make_engine(cfg)
    x, y = random_batch(batch_size=8)

    # path A: fused train_batch over the full batch
    lossA = e1.train_batch((x, y))

    # path B: forward/backward per micro batch + step
    for i in range(2):
        mb = (x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
        loss = e2.forward(mb)
        e2.backward(loss)
    e2.step()

    pa = jax.tree_util.tree_leaves(e1.state.params)
    pb = jax.tree_util.tree_leaves(e2.state.params)
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    assert e1.global_steps == e2.global_steps == 1


def test_gradient_accumulation_boundary():
    cfg = base_config(train_batch_size=8, gradient_accumulation_steps=2,
                      train_micro_batch_size_per_gpu=4)
    engine = make_engine(cfg)
    mb = random_batch(batch_size=4)
    assert engine.is_gradient_accumulation_boundary() is False
    loss = engine.forward(mb)
    engine.backward(loss)
    engine.step()  # not a boundary: no optimizer step yet
    assert engine.global_steps == 0
    loss = engine.forward(mb)
    engine.backward(loss)
    engine.step()
    assert engine.global_steps == 1


def test_train_batch_with_data_iter():
    cfg = base_config(train_batch_size=8, gradient_accumulation_steps=2,
                      train_micro_batch_size_per_gpu=4)
    engine = make_engine(cfg)
    data = random_dataset(n=32)
    loader = engine.deepspeed_io(data)
    it = iter(dstpu.runtime.dataloader.RepeatingLoader(loader))
    loss = engine.train_batch(data_iter=it)
    assert np.isfinite(float(loss))
    assert engine.global_steps == 1


def test_lr_schedule_applied():
    cfg = base_config()
    cfg["scheduler"] = {"type": "WarmupLR",
                        "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 0.1,
                                   "warmup_num_steps": 10, "warmup_type": "linear"}}
    engine = make_engine(cfg)
    batch = random_batch()
    engine.train_batch(batch)
    lr1 = engine.get_lr()[0]
    for _ in range(5):
        engine.train_batch(batch)
    lr2 = engine.get_lr()[0]
    assert lr2 > lr1


def test_gradient_clipping_reduces_norm():
    cfg = base_config(gradient_clipping=1e-4)
    engine = make_engine(cfg)
    batch = random_batch()
    engine.train_batch(batch)
    # with aggressive clipping, params barely move
    engine2 = make_engine(base_config())
    engine2.train_batch(batch)
    assert float(engine.get_global_grad_norm()) == pytest.approx(
        float(engine2.get_global_grad_norm()), rel=1e-4)


def test_fp16_dynamic_loss_scale_starts_high():
    cfg = base_config()
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 16}
    engine = make_engine(cfg)
    batch = random_batch()
    engine.train_batch(batch)
    assert engine.loss_scale in (2.0 ** 16, 2.0 ** 17)


def test_fp16_overflow_skips_step():
    cfg = base_config()
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}
    engine = make_engine(cfg)
    x, y = random_batch()
    x_bad = x.copy()
    x_bad[0, 0] = np.inf
    engine.train_batch((x, y))
    params_before = jax.device_get(engine.state.params)
    scale_before = engine.loss_scale
    engine.train_batch((x_bad, y))
    params_after = jax.device_get(engine.state.params)
    # step skipped: params unchanged, scale halved
    for a, b in zip(jax.tree_util.tree_leaves(params_before),
                    jax.tree_util.tree_leaves(params_after)):
        np.testing.assert_array_equal(a, b)
    assert engine.loss_scale == scale_before / 2


def test_bf16_training():
    cfg = base_config()
    cfg["bf16"] = {"enabled": True}
    engine = make_engine(cfg)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(20):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_checkpoint_roundtrip(tmp_path):
    engine = make_engine()
    batch = random_batch()
    for _ in range(3):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path), client_state={"note": "hi"})

    engine2 = make_engine()
    engine2.train_batch(batch)  # init state differently
    tag, client = engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == 3
    assert client.get("note") == "hi"
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(engine.state.params)),
                    jax.tree_util.tree_leaves(jax.device_get(engine2.state.params))):
        np.testing.assert_array_equal(a, b)
    # resumed training continues identically
    la = float(engine.train_batch(batch))
    lb = float(engine2.train_batch(batch))
    assert la == pytest.approx(lb, rel=1e-5)


def test_gpt2_tiny_trains():
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel, gpt2_tiny
    cfg = base_config(train_batch_size=4)
    cfg["optimizer"]["params"]["lr"] = 1e-3
    model = GPT2LMHeadModel(gpt2_tiny(dtype=jnp.float32))
    engine = make_engine(cfg, model=model)
    batch = token_batch(batch_size=4, seq=16, vocab=512)
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_lamb_optimizer():
    cfg = base_config()
    cfg["optimizer"] = {"type": "Lamb", "params": {"lr": 1e-2}}
    engine = make_engine(cfg)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(20):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_sgd_optimizer():
    cfg = base_config()
    cfg["optimizer"] = {"type": "SGD", "params": {"lr": 1e-2, "momentum": 0.9}}
    engine = make_engine(cfg)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(20):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_tensorboard_monitor_writes_scalars(tmp_path):
    """Monitor subsystem: scalar stream lands in TB event files (or the
    JSONL fallback) under output_path/job_name (reference engine.py:162,
    1095-1105)."""
    import os
    cfg = base_config()
    cfg["tensorboard"] = {"enabled": True,
                          "output_path": str(tmp_path),
                          "job_name": "job1"}
    engine = make_engine(cfg)
    batch = random_batch()
    for _ in range(3):
        engine.train_batch(batch)
    log_dir = os.path.join(str(tmp_path), "job1")
    assert os.path.isdir(log_dir) and os.listdir(log_dir)
    assert len(engine.scalar_history) == 3
    assert {"loss", "lr", "loss_scale", "grad_norm"} <= \
        set(engine.scalar_history[0][1].keys())


def test_flops_profiler_detailed_breakdown():
    """detailed mode emits the per-module table (reference
    print_model_profile role)."""
    from deepspeed_tpu.profiling.flops_profiler import (
        module_breakdown, get_model_profile)
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel
    import numpy as np
    model = GPT2LMHeadModel(gpt2_tiny())
    table = module_breakdown(model, np.zeros((1, 8), np.int32), depth=2)
    assert "GPT2LMHeadModel" in table and "flops" in table
    flops, macs, n_params = get_model_profile(model, (1, 8))
    assert flops > 0 and n_params > 0


def test_wall_clock_breakdown_fused_path():
    """wall_clock_breakdown instruments the real train_batch (reference
    engine.py:1028-1047): per-phase fwd/bwd/step timers populate, and the
    instrumented step matches the fused step numerically."""
    cfg = base_config(train_batch_size=8, gradient_accumulation_steps=2)
    cfg["wall_clock_breakdown"] = True
    e_inst = make_engine(cfg)
    e_fused = make_engine(base_config(train_batch_size=8,
                                      gradient_accumulation_steps=2))
    batch = random_batch(batch_size=8)
    for _ in range(3):
        l_inst = float(e_inst.train_batch(batch))
        l_fused = float(e_fused.train_batch(batch))
    assert l_inst == pytest.approx(l_fused, rel=1e-4)
    times = e_inst.wall_clock_times()
    # 'fence' is the measured per-phase readback cost (a full round trip
    # on tunneled backends) that the phase numbers are reported NET of
    assert set(times) == {"forward", "backward", "step", "fence"}
    assert times["forward"] > 0 and times["step"] > 0
    # uninstrumented engine reports no phase timers
    assert e_fused.wall_clock_times() == {}


class _FakeMpu:
    def __init__(self, mp):
        self._mp = mp

    def get_model_parallel_world_size(self):
        return self._mp


def test_mpu_adopted_into_mesh():
    """initialize(mpu=...) maps the client TP object onto the mesh 'model'
    axis (reference engine.py:636-641 adopts mpu groups) instead of
    silently ignoring it."""
    if len(jax.devices()) < 2:
        pytest.skip("need 2 devices")
    from deepspeed_tpu.models.sharding import gpt2_tp_specs
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel
    model = GPT2LMHeadModel(gpt2_tiny(dtype=jnp.float32))
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model,
                                       mpu=_FakeMpu(2))
    assert dict(engine.mesh.shape)["model"] == 2
    batch = {"input_ids": np.random.RandomState(0)
             .randint(0, 512, (8, 32)).astype(np.int32)}
    assert np.isfinite(float(engine.train_batch(batch)))


def test_mpu_mesh_mismatch_raises():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    with pytest.raises(ValueError, match="model_parallel_world_size"):
        dstpu.initialize(config=base_config(), model=SimpleModel(),
                         mesh=mesh, mpu=_FakeMpu(2))


def test_mpu_without_interface_raises():
    with pytest.raises(ValueError, match="get_model_parallel_world_size"):
        dstpu.initialize(config=base_config(), model=SimpleModel(),
                         mpu=object())
