"""ZeRO-Offload tests — native CPU Adam numerics, NVMe swapper roundtrip,
engine offload training parity with the in-device optimizer (reference
test_cpu_adam.py / test_aio.py roles)."""

import numpy as np
import jax
import pytest

import deepspeed_tpu as dstpu
from tests.simple_model import SimpleModel, random_batch, base_config


def one_device_mesh():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    return make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def has_native():
    try:
        from deepspeed_tpu.ops.native import cpu_adam
        cpu_adam.load()
        return True
    except Exception:
        return False


def test_native_cpu_adam_matches_jax_adam():
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.ops.native import cpu_adam
    from deepspeed_tpu.ops.adam import FusedAdam
    import jax.numpy as jnp

    lib = cpu_adam.load()
    rng = np.random.RandomState(0)
    p = rng.randn(1000).astype(np.float32)
    g = rng.randn(1000).astype(np.float32)
    m = np.zeros(1000, np.float32)
    v = np.zeros(1000, np.float32)
    p_native = p.copy()
    for step in range(1, 4):
        lib.adam_step(p_native, g, m, v, step, 1e-2, 0.9, 0.999, 1e-8,
                      0.01, True)

    opt = FusedAdam(lr=1e-2, weight_decay=0.01, adam_w_mode=True)
    params = {"w": jnp.asarray(p)}
    state = opt.init(params)
    for _ in range(3):
        params, state = opt.step(params, {"w": jnp.asarray(g)}, state)
    np.testing.assert_allclose(p_native, np.asarray(params["w"]),
                               rtol=2e-5, atol=2e-6)


def test_native_adam_step_ex_matches_plain():
    """The single-pass _ex kernel (wire-dtype grads + folded scale + bf16
    out copy) must match scale-then-step with the plain kernel."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    import ml_dtypes
    from deepspeed_tpu.ops.native import cpu_adam
    lib = cpu_adam.load()
    rng = np.random.RandomState(7)
    n = 4097
    p0 = rng.randn(n).astype(np.float32)
    g = (rng.randn(n) * 3).astype(np.float32)
    scale = 0.37

    p_ref, m_ref, v_ref = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    for step in range(1, 4):
        lib.adam_step(p_ref, np.ascontiguousarray(g * scale), m_ref, v_ref,
                      step, 1e-2, 0.9, 0.999, 1e-8, 0.01, True)

    # fp32 grads through _ex
    p1, m1, v1 = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    out_bf16 = np.empty(n, np.uint16)
    for step in range(1, 4):
        lib.adam_step_ex(p1, g, m1, v1, step, 1e-2, 0.9, 0.999, 1e-8,
                         0.01, True, grad_scale=scale, params_bf16=out_bf16)
    np.testing.assert_allclose(p1, p_ref, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(m1, m_ref, rtol=1e-6, atol=1e-7)
    # the bf16 out copy is the rounded updated params
    np.testing.assert_allclose(out_bf16.view(ml_dtypes.bfloat16)
                               .astype(np.float32), p1, rtol=8e-3, atol=1e-5)

    # bf16 grads through _ex: matches stepping on widened bf16 grads
    g_bf16 = g.astype(ml_dtypes.bfloat16)
    p2, m2, v2 = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    p3, m3, v3 = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    for step in range(1, 4):
        lib.adam_step_ex(p2, g_bf16, m2, v2, step, 1e-2, 0.9, 0.999, 1e-8,
                         0.01, True, grad_scale=scale)
        lib.adam_step(p3, np.ascontiguousarray(
            g_bf16.astype(np.float32) * scale), m3, v3,
            step, 1e-2, 0.9, 0.999, 1e-8, 0.01, True)
    np.testing.assert_allclose(p2, p3, rtol=1e-6, atol=1e-7)


def test_native_lamb_step_ex_matches_plain():
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.ops.native import cpu_adam
    lib = cpu_adam.load()
    rng = np.random.RandomState(8)
    n = 1031
    p0 = rng.randn(n).astype(np.float32)
    g = rng.randn(n).astype(np.float32)
    scale = 2.5

    p_ref, m_ref, v_ref = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    p_ex, m_ex, v_ex = p0.copy(), np.zeros(n, np.float32), np.zeros(n, np.float32)
    for step in range(1, 4):
        lib.lamb_step(p_ref, np.ascontiguousarray(g * scale), m_ref, v_ref,
                      step, 1e-2, 0.9, 0.999, 1e-8, 0.01, 10.0, 0.01)
        lib.lamb_step_ex(p_ex, g, m_ex, v_ex, step, 1e-2, 0.9, 0.999, 1e-8,
                         0.01, 10.0, 0.01, grad_scale=scale)
    np.testing.assert_allclose(p_ex, p_ref, rtol=1e-6, atol=1e-7)


def test_offload_streamed_matches_unstreamed():
    """HostOffloadOptimizer.step_streamed (pipelined d2h/step/h2d) must be
    numerically identical to the batch `step` path."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    import jax.numpy as jnp
    from deepspeed_tpu.ops.adam import FusedAdam
    from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
    from deepspeed_tpu.config.config import ZeroOffloadConfig

    rng = np.random.RandomState(9)
    params = {"a": jnp.asarray(rng.randn(64, 32).astype(np.float32)),
              "b": jnp.asarray(rng.randn(33).astype(np.float32))}
    grads = {"a": jnp.asarray(rng.randn(64, 32).astype(np.float32)),
             "b": jnp.asarray(rng.randn(33).astype(np.float32))}
    off_cfg = ZeroOffloadConfig({"device": "cpu"})

    r1 = HostOffloadOptimizer(params, FusedAdam(lr=1e-2), off_cfg)
    r2 = HostOffloadOptimizer(params, FusedAdam(lr=1e-2), off_cfg)
    scale = 0.5
    for _ in range(3):
        leaves = [np.ascontiguousarray(np.asarray(g, np.float32) * scale)
                  for g in jax.tree_util.tree_leaves(grads)]
        r1.step(leaves, 1e-2)
        r2.step_streamed(jax.tree_util.tree_leaves(grads), 1e-2,
                         grad_scale=scale)
    for x, y in zip(r1.master, r2.master):
        np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)


def test_offload_runner_selection():
    """device=cpu defaults to the device-streamed tier (state in
    pinned_host, update on device); stream='host' forces the numpy/SIMD
    runner; NVMe state always uses the host runner (the swapper)."""
    from deepspeed_tpu.runtime.zero.offload import HostOffloadOptimizer
    from deepspeed_tpu.runtime.zero.offload_stream import (
        StreamedOffloadOptimizer)

    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}}
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=one_device_mesh())
    e.train_batch(random_batch())
    assert isinstance(e._host_runner, StreamedOffloadOptimizer)
    assert e.state.opt_state == {}  # still zero HBM-resident opt state

    cfg2 = base_config()
    cfg2["zero_optimization"] = {
        "stage": 2, "offload_optimizer": {"device": "cpu", "stream": "host"}}
    e2, _, _, _ = dstpu.initialize(config=cfg2, model=SimpleModel(),
                                   mesh=one_device_mesh())
    e2.train_batch(random_batch())
    assert isinstance(e2._host_runner, HostOffloadOptimizer)


def test_offload_streamed_matches_host_runner():
    """The device-streamed tier and the numpy/SIMD host runner implement
    the same optimizer: training curves must agree."""
    def run(stream):
        cfg = base_config()
        cfg["zero_optimization"] = {
            "stage": 2,
            "offload_optimizer": {"device": "cpu", "stream": stream}}
        e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                      mesh=one_device_mesh())
        batch = random_batch()
        return [float(e.train_batch(batch)) for _ in range(5)]

    host = run("host")
    dev = run("device")
    np.testing.assert_allclose(dev, host, rtol=2e-3)


def test_streamed_offload_state_rests_in_pinned_host():
    """The streamed runner's master/m/v must actually live in the
    pinned_host memory space (the whole point: zero HBM-resident state)."""
    cfg = base_config()
    cfg["zero_optimization"] = {
        "stage": 2,
        "offload_optimizer": {"device": "cpu", "stream": "device"}}
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=one_device_mesh())
    e.train_batch(random_batch())
    r = e._host_runner
    # intended placements always carry the backend's host memory space
    # (pinned_host on TPU; the collapsed unpinned_host kind on XLA CPU,
    # which only names that one space)
    assert r.host_memory_kind is not None
    for u in r.units:
        assert r._host_sh(u).memory_kind == r.host_memory_kind
    # realized placements: XLA CPU collapses memory spaces (host == device
    # memory), so the runtime kind is only meaningful on accelerators
    from deepspeed_tpu.utils.platform import is_tpu_backend
    if is_tpu_backend():
        for arr in (*r.master, *r.m, *r.v):
            assert arr.sharding.memory_kind == "pinned_host"
        for leaf in jax.tree_util.tree_leaves(e.state.params):
            assert leaf.sharding.memory_kind == "device"


def test_streamed_offload_unit_split_matches_whole():
    """Leaves above the unit budget stream as chunks along dim0 (the HBM
    bound for scan-stacked 2 GB leaves); chunked and unsplit streaming
    must produce identical updates, params, and checkpoints."""
    import jax.numpy as jnp
    from deepspeed_tpu.ops.adam import FusedAdam
    from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
    from deepspeed_tpu.runtime.zero.offload_stream import (
        StreamedOffloadOptimizer)

    mesh = one_device_mesh()
    rng = np.random.RandomState(3)
    params = {"big": jnp.asarray(rng.randn(8, 16, 12).astype(np.float32)),
              "small": jnp.asarray(rng.randn(17).astype(np.float32))}
    part = ZeroPartitioner(mesh, stage=2)

    def mk(unit_bytes):
        return StreamedOffloadOptimizer(
            params, FusedAdam(lr=1e-2, weight_decay=0.01), mesh, part,
            unit_bytes=unit_bytes)

    r_whole = mk(1 << 30)
    r_split = mk(8 * 16 * 12)     # ~1/4 of the big leaf per unit
    assert len(r_split.units) > len(r_whole.units)

    for step in range(3):
        grads = [rng.randn(*p.shape).astype(np.float32)
                 for p in (params["big"], params["small"])]
        # step() donates gradient buffers — each runner gets its own copies
        pw = r_whole.step([jnp.asarray(g) for g in grads], 1e-2,
                          grad_scale=0.5, out_dtype=jnp.float32)
        ps = r_split.step([jnp.asarray(g) for g in grads], 1e-2,
                          grad_scale=0.5, out_dtype=jnp.float32)
        for a, b in zip(pw, ps):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
    # checkpoint surfaces reassemble split leaves
    sd_w, sd_s = r_whole.state_dict(), r_split.state_dict()
    for ka in ("exp_avg", "exp_avg_sq"):
        for x, y in zip(jax.tree_util.tree_leaves(sd_w[ka]),
                        jax.tree_util.tree_leaves(sd_s[ka])):
            np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)
    # load back into a fresh split runner and keep stepping identically
    r2 = mk(8 * 16 * 12)
    r2.load_state_dict(sd_s)
    g2 = [rng.randn(*p.shape).astype(np.float32)
          for p in (params["big"], params["small"])]
    r_split.step([jnp.asarray(g) for g in g2], 1e-2, out_dtype=jnp.float32)
    # r2's master restarted from init params; only moments were loaded —
    # compare moment trees instead of params
    r2.step([jnp.asarray(g) for g in g2], 1e-2, out_dtype=jnp.float32)
    for x, y in zip(jax.tree_util.tree_leaves(r_split.state_dict()["exp_avg"]),
                    jax.tree_util.tree_leaves(r2.state_dict()["exp_avg"])):
        np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)


def test_param_swapper_roundtrip(tmp_path):
    """PartitionedParamSwapper: leaves rest on disk, stream back to the
    device bit-exactly, staging stays bounded at 2 buffers."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper

    mesh = None
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    sh = NamedSharding(mesh, P())
    rng = np.random.RandomState(0)
    import ml_dtypes
    leaves = [jnp.asarray(rng.randn(64, 32).astype(np.float32),
                          jnp.bfloat16),
              jnp.asarray(rng.randn(1000).astype(np.float32)),
              jnp.asarray(rng.randint(-5, 5, (7,)).astype(np.int32))]
    sw = PartitionedParamSwapper(str(tmp_path))
    sw.write_all(leaves)
    import glob
    assert len(glob.glob(str(tmp_path) + "/param_swap_*/param_*.swp")) == 3
    got = sw.swap_in_device([sh] * 3)
    for a, b in zip(leaves, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # round-trip updated values through swap_out/swap_in
    upd = [jnp.asarray(np.asarray(g, np.float32) * 2 + 1, g.dtype)
           for g in got]
    sw.swap_out_device(upd)
    again = sw.swap_in_device([sh] * 3)
    for a, b in zip(upd, again):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert sum(1 for b in sw._staging if b is not None) <= 2
    sw.release()


def test_param_offload_nvme_training(tmp_path):
    """VERDICT r3 missing #1: offload_param device=nvme actually rests
    params on disk — swap files exist, device params are freed between
    steps (parked), and the loss trajectory matches the no-offload run."""
    def run(cfg_extra):
        cfg = base_config()
        cfg["zero_optimization"] = {"stage": 2, **cfg_extra}
        e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                      mesh=one_device_mesh())
        batch = random_batch()
        losses = [float(e.train_batch(batch)) for _ in range(5)]
        return e, losses

    _, base = run({})
    e, got = run({"offload_param": {"device": "nvme",
                                    "nvme_path": str(tmp_path)},
                  "offload_optimizer": {"device": "cpu"}})
    np.testing.assert_allclose(got, base, rtol=2e-3)

    # params rest on NVMe between steps: files exist and the device
    # arrays are parked (deleted)
    import glob
    files = glob.glob(str(tmp_path) + "/param_swap_*/param_*.swp")
    assert files, "no param swap files written"
    assert e._params_parked
    for leaf in jax.tree_util.tree_leaves(e.state.params):
        assert leaf.is_deleted()
    # eval and checkpoint transparently restore residency
    x, _ = random_batch()
    out = e.eval_batch(x)
    assert np.all(np.isfinite(np.asarray(out, np.float32)))
    e.save_checkpoint(str(tmp_path / "ck"))
    l2 = float(e.train_batch(random_batch()))
    assert np.isfinite(l2)


def test_param_offload_nvme_checkpoint_load_not_stale(tmp_path):
    """Loading a checkpoint while params are parked must NOT let the next
    step swap the pre-load disk copies back in (the swap files are
    re-written from the loaded weights); a fresh engine restoring before
    any train_batch still gets the NVMe tier."""
    cfg = base_config()
    cfg["zero_optimization"] = {
        "stage": 2,
        "offload_param": {"device": "nvme", "nvme_path": str(tmp_path)},
        "offload_optimizer": {"device": "cpu"}}
    e, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                  mesh=one_device_mesh())
    batch = random_batch()
    for _ in range(3):
        e.train_batch(batch)
    e.save_checkpoint(str(tmp_path / "ck"), tag="t")
    ref = [float(e.train_batch(batch)) for _ in range(3)]

    # same engine: drift past the checkpoint, then load it back while
    # parked — continued training must reproduce ref, not the drifted run
    e.load_checkpoint(str(tmp_path / "ck"), tag="t")
    got = [float(e.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(got, ref, rtol=2e-3)

    # fresh engine, restore-before-first-step: tier stays active
    e2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                   mesh=one_device_mesh())
    e2.load_checkpoint(str(tmp_path / "ck"), tag="t")
    assert e2._param_swapper is not None
    got2 = [float(e2.train_batch(batch)) for _ in range(3)]
    np.testing.assert_allclose(got2, ref, rtol=2e-3)
    assert e2._params_parked


def test_aio_roundtrip(tmp_path):
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    h = AsyncIOHandle(block_size=4096, thread_count=2)
    data = np.random.RandomState(0).randn(32768).astype(np.float32)
    path = str(tmp_path / "t.bin")
    assert h.sync_pwrite(data, path) == 1
    out = np.empty_like(data)
    assert h.sync_pread(out, path) == 1
    np.testing.assert_array_equal(data, out)


@pytest.mark.parametrize("backend", ["threads", "io_uring", "auto"])
def test_aio_backends_roundtrip(tmp_path, backend):
    """Both backends (kernel ring + thread pool) move the same bytes; the
    reference only had the libaio path (deepspeed_aio_common.cpp)."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    try:
        h = AsyncIOHandle(block_size=8192, queue_depth=8, thread_count=2,
                          backend=backend)
    except OSError:
        assert backend == "io_uring"
        pytest.skip("kernel without io_uring")
    assert h.backend in ("threads", "io_uring")
    if backend != "auto":
        assert h.backend == backend
    data = np.random.RandomState(2).randn(100000).astype(np.float32)
    path = str(tmp_path / "t.bin")
    fd = h.open(path, True)
    h.async_pwrite(data, fd)
    assert h.wait() == 1
    h.close(fd)
    out = np.empty_like(data)
    fd = h.open(path, False)
    h.async_pread(out, fd)
    assert h.wait() == 1
    h.close(fd)
    np.testing.assert_array_equal(data, out)


def test_aio_many_small_requests(tmp_path):
    """Queue-depth pressure: many outstanding requests on one handle all
    complete and are counted per user request."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    h = AsyncIOHandle(block_size=1024, queue_depth=4, thread_count=2)
    rng = np.random.RandomState(3)
    chunks = [rng.randn(1000 + i).astype(np.float32) for i in range(32)]
    path = str(tmp_path / "many.bin")
    fd = h.open(path, True)
    off = 0
    for c in chunks:
        h.async_pwrite(c, fd, offset=off)
        off += c.nbytes
    assert h.wait() == len(chunks)
    h.close(fd)
    outs = [np.empty_like(c) for c in chunks]
    fd = h.open(path, False)
    off = 0
    for o in outs:
        h.async_pread(o, fd, offset=off)
        off += o.nbytes
    assert h.wait() == len(chunks)
    h.close(fd)
    for c, o in zip(chunks, outs):
        np.testing.assert_array_equal(c, o)


def test_tensor_swapper(tmp_path):
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.runtime.swap_tensor import TensorSwapper
    sw = TensorSwapper(str(tmp_path))
    x = np.random.RandomState(1).randn(4096).astype(np.float32)
    sw.swap_out("a", x)
    out = np.empty_like(x)
    sw.swap_in("a", out)
    np.testing.assert_array_equal(x, out)
    # prefetch path
    buf = np.empty_like(x)
    sw.prefetch("a", buf)
    got = sw.swap_in("a", buf)
    np.testing.assert_array_equal(x, got)
    sw.release()


def test_offload_cpu_training_matches_device():
    cfg_dev = base_config()
    cfg_off = base_config()
    cfg_off["zero_optimization"] = {"stage": 2,
                                    "offload_optimizer": {"device": "cpu"}}
    e_dev, _, _, _ = dstpu.initialize(config=cfg_dev, model=SimpleModel(),
                                      mesh=one_device_mesh())
    e_off, _, _, _ = dstpu.initialize(config=cfg_off, model=SimpleModel(),
                                      mesh=one_device_mesh())
    batch = random_batch()
    for _ in range(5):
        l_dev = float(e_dev.train_batch(batch))
        l_off = float(e_off.train_batch(batch))
    assert l_off == pytest.approx(l_dev, rel=1e-3)
    assert e_off._host_runner is not None
    assert e_off.state.opt_state == {}  # no optimizer state in HBM


def test_offload_overlap_comm_matches_fused_accumulation():
    """overlap_comm offload (per-micro streamed accumulation) must match the
    device-fused gas scan numerically."""
    cfg_fused = base_config()
    cfg_fused["train_batch_size"] = 8
    cfg_fused["gradient_accumulation_steps"] = 4
    cfg_fused["zero_optimization"] = {
        "stage": 2, "offload_optimizer": {"device": "cpu"}}
    cfg_ovl = {**cfg_fused,
               "zero_optimization": {"stage": 2, "overlap_comm": True,
                                     "offload_optimizer": {"device": "cpu"}}}
    e_fused, _, _, _ = dstpu.initialize(config=cfg_fused, model=SimpleModel(),
                                        mesh=one_device_mesh())
    e_ovl, _, _, _ = dstpu.initialize(config=cfg_ovl, model=SimpleModel(),
                                      mesh=one_device_mesh())
    batch = random_batch(batch_size=8)
    for _ in range(3):
        l_fused = float(e_fused.train_batch(batch))
        l_ovl = float(e_ovl.train_batch(batch))
    assert l_ovl == pytest.approx(l_fused, rel=2e-3)


def test_offload_nvme_training(tmp_path):
    if not has_native():
        pytest.skip("no C++ toolchain")
    cfg = base_config()
    cfg["zero_optimization"] = {
        "stage": 2,
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=one_device_mesh())
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0
    # moments actually live on disk
    import glob
    files = glob.glob(str(tmp_path) + "/optimizer_swap_*/**/*.swp",
                      recursive=True)
    assert files, "no NVMe swap files written"


def test_offload_checkpoint_roundtrip(tmp_path):
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=one_device_mesh())
    batch = random_batch()
    for _ in range(3):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path / "ck"))

    engine2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                        mesh=one_device_mesh())
    engine2.train_batch(batch)
    engine2.load_checkpoint(str(tmp_path / "ck"))
    la = float(engine.train_batch(batch))
    lb = float(engine2.train_batch(batch))
    assert la == pytest.approx(lb, rel=1e-4)


def test_offload_fp16_unscales_gradients():
    """fp16 loss scaling + offload: host Adam must see unscaled grads —
    training should match the pure-device fp16 path closely."""
    cfg_dev = base_config()
    cfg_dev["fp16"] = {"enabled": True, "initial_scale_power": 8}
    cfg_off = base_config()
    cfg_off["fp16"] = {"enabled": True, "initial_scale_power": 8}
    cfg_off["zero_optimization"] = {"stage": 2,
                                    "offload_optimizer": {"device": "cpu"}}
    e_dev, _, _, _ = dstpu.initialize(config=cfg_dev, model=SimpleModel(),
                                      mesh=one_device_mesh())
    e_off, _, _, _ = dstpu.initialize(config=cfg_off, model=SimpleModel(),
                                      mesh=one_device_mesh())
    batch = random_batch()
    for _ in range(5):
        l_dev = float(e_dev.train_batch(batch))
        l_off = float(e_off.train_batch(batch))
    # a 256x-scaled update would diverge instantly; equality to the device
    # fp16 path proves the unscale happened
    assert l_off == pytest.approx(l_dev, rel=2e-2)


def test_offload_fp16_overflow_skips_step():
    cfg = base_config()
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=one_device_mesh())
    x, y = random_batch()
    engine.train_batch((x, y))
    params_before = jax.device_get(engine.state.params)
    scale_before = float(jax.device_get(engine.state.scaler["loss_scale"]))
    x_bad = x.copy()
    x_bad[0, 0] = np.inf
    engine.train_batch((x_bad, y))
    params_after = jax.device_get(engine.state.params)
    scale_after = float(jax.device_get(engine.state.scaler["loss_scale"]))
    assert scale_after < scale_before
    leaves_b = jax.tree_util.tree_leaves(params_before)
    leaves_a = jax.tree_util.tree_leaves(params_after)
    for b, a in zip(leaves_b, leaves_a):
        np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


def test_offload_rejects_non_adam_optimizer():
    cfg = base_config()
    cfg["optimizer"] = {"type": "SGD", "params": {"lr": 1e-2}}
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}}
    # rejection now happens at construction, not at the first step
    with pytest.raises(ValueError, match="Adam"):
        dstpu.initialize(config=cfg, model=SimpleModel(),
                         mesh=one_device_mesh())


def test_swapper_prefetch_no_fd_leak(tmp_path):
    if not has_native():
        pytest.skip("no C++ toolchain")
    import resource
    from deepspeed_tpu.runtime.swap_tensor import TensorSwapper
    sw = TensorSwapper(str(tmp_path))
    a = np.arange(256, dtype=np.float32)
    sw.swap_out("x", a)
    out = np.zeros_like(a)
    soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    for _ in range(min(soft + 64, 4096)):
        sw.prefetch("x", out)
        sw.swap_in("x", out)
    np.testing.assert_array_equal(out, a)
    sw.release()


def test_swapper_prefetch_error_attribution(tmp_path):
    """A failed prefetch raises at its drain point; sync ops sharing the
    handle neither absorb that error nor deliver garbage silently."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.runtime.swap_tensor import TensorSwapper
    sw = TensorSwapper(str(tmp_path))
    a = np.arange(64, dtype=np.float32)
    sw.swap_out("good", a)
    # hand-craft a truncated swap file
    with open(sw._path("bad"), "wb") as f:
        f.write(b"xyz")
    out = np.zeros_like(a)
    sw.prefetch("bad", out)
    # the next op drains the pending prefetch and must surface ITS failure
    with pytest.raises(IOError):
        sw.swap_out("good", a)
    # handle recovered: clean sync ops still work
    sw.swap_in("good", out)
    np.testing.assert_array_equal(out, a)
    sw.release()


def test_aio_split_transfer_counts_one_error(tmp_path):
    """One failed user transfer = ONE reported error, even when submit_split
    fanned it into many pieces across the worker pool."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    h = AsyncIOHandle(block_size=4096, queue_depth=4, thread_count=4)
    short = tmp_path / "short.bin"
    short.write_bytes(b"\0" * 4096)
    buf = np.zeros(1 << 20, np.uint8)  # 1 MiB read from a 4 KiB file
    fd = h.open(short, False)
    h.async_pread(buf, fd, 0)
    with pytest.raises(IOError, match=r"\b1 async IO request"):
        h.wait()
    h.close(fd)


def test_param_offload_host_trains():
    """offload_param: params rest in pinned_host memory between steps and
    stream to HBM inside the step (the TPU form of the reference's
    ZeRO-3/Infinity param tier, partitioned_param_swapper.py:36)."""
    import jax
    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config

    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 3,
                                "offload_param": {"device": "cpu"}}
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    from deepspeed_tpu.utils.platform import is_tpu_backend
    # on non-TPU backends the tier downgrades to default memory (the CPU
    # PJRT backend cannot execute cross-memory-space programs)
    assert engine._param_offload_host == is_tpu_backend()
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0
    if is_tpu_backend():
        leaf = jax.tree_util.tree_leaves(engine.state.params)[0]
        assert leaf.sharding.memory_kind == "pinned_host"
    # eval path streams too
    out = engine.eval_batch(batch)
    assert np.isfinite(np.asarray(out)).all()


def test_param_offload_multidevice_zero3():
    import jax
    import pytest
    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 3,
                                "offload_param": {"device": "cpu"}}
    mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(8):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


# -- native kernel extensions ------------------------------------------------

def _native():
    import pytest
    try:
        from deepspeed_tpu.ops.native import cpu_adam
        return cpu_adam.load()
    except Exception as e:
        pytest.skip(f"native lib unavailable: {e}")


def test_native_multi_tensor_adam_matches_single():
    import numpy as np
    lib = _native()
    rng = np.random.RandomState(0)
    shapes = [(1000,), (33,), (257,)]
    ps = [rng.randn(*s).astype(np.float32) for s in shapes]
    gs = [rng.randn(*s).astype(np.float32) for s in shapes]
    ms = [np.zeros(s, np.float32) for s in shapes]
    vs = [np.zeros(s, np.float32) for s in shapes]
    ps2 = [p.copy() for p in ps]
    ms2 = [m.copy() for m in ms]
    vs2 = [v.copy() for v in vs]
    args = dict(step=3, lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-8,
                weight_decay=0.01, adamw_mode=True)
    for p, g, m, v in zip(ps, gs, ms, vs):
        lib.adam_step(p, g, m, v, **args)
    lib.adam_step_multi(ps2, gs, ms2, vs2, **args)
    for a, b in zip(ps, ps2):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_native_lamb_matches_jit_lamb():
    import numpy as np
    import jax.numpy as jnp
    from deepspeed_tpu.ops.lamb import FusedLamb
    lib = _native()
    rng = np.random.RandomState(1)
    p = rng.randn(512).astype(np.float32)
    g = rng.randn(512).astype(np.float32)
    opt = FusedLamb(lr=1e-2, weight_decay=0.01)
    state = opt.init({"w": jnp.asarray(p)})
    jp, jstate = {"w": jnp.asarray(p)}, state
    np_p, np_m, np_v = p.copy(), np.zeros(512, np.float32), \
        np.zeros(512, np.float32)
    for step in range(1, 4):
        jp, jstate = opt.step(jp, {"w": jnp.asarray(g)}, jstate)
        lib.lamb_step(np_p, g, np_m, np_v, step, 1e-2, 0.9, 0.999, 1e-8,
                      0.01, 10.0, 0.01)
    np.testing.assert_allclose(np_p, np.asarray(jp["w"]), rtol=2e-5,
                               atol=2e-5)


def test_native_bf16_conversions_roundtrip():
    import numpy as np
    import jax.numpy as jnp
    lib = _native()
    x = np.random.RandomState(2).randn(4096).astype(np.float32)
    bf = lib.fp32_to_bf16(x)
    # match jax's RNE fp32->bf16
    ref = np.asarray(jnp.asarray(x).astype(jnp.bfloat16)).view(np.uint16)
    np.testing.assert_array_equal(bf, ref)
    back = lib.bf16_to_fp32(bf)
    np.testing.assert_array_equal(
        back, np.asarray(jnp.asarray(x).astype(jnp.bfloat16)
                         .astype(jnp.float32)))


def test_native_bf16_conversion_preserves_nan_inf():
    """NaNs must survive fp32→bf16 staging (they drive overflow-skip); the
    RNE rounding add must not carry a high-mantissa NaN into ±0/Inf."""
    import numpy as np
    lib = _native()
    specials = np.array([0x7FFFFFFF, 0xFFFFFFFF, 0x7F800001, 0x7FC00000,
                         0xFF800001], np.uint32).view(np.float32)
    x = np.concatenate([specials, [np.inf, -np.inf, 0.0, -0.0]]).astype(
        np.float32)
    bf = lib.fp32_to_bf16(x)
    back = lib.bf16_to_fp32(bf)
    assert np.isnan(back[:5]).all(), back[:5]
    assert back[5] == np.inf and back[6] == -np.inf
    assert back[7] == 0.0 and back[8] == 0.0


def test_native_l2_norm():
    import numpy as np
    lib = _native()
    x = np.random.RandomState(3).randn(10000).astype(np.float32)
    np.testing.assert_allclose(lib.l2_norm(x), np.linalg.norm(x), rtol=1e-6)


def test_lamb_offload_trains():
    """LAMB under the host-offload tier (TPU-side extension of the
    reference's Adam-only offload)."""
    import numpy as np
    import jax
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config
    cfg = base_config()
    cfg["optimizer"] = {"type": "Lamb", "params": {"lr": 1e-2}}
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}}
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_aio_split_large_transfer_roundtrip(tmp_path):
    """Large transfers fan across the worker pool; data must round-trip
    bit-exact through the split path."""
    import numpy as np
    import pytest
    try:
        from deepspeed_tpu.ops.native.aio import AsyncIOHandle
        h = AsyncIOHandle(block_size=4096, queue_depth=4, thread_count=4)
    except Exception as e:
        pytest.skip(f"aio unavailable: {e}")
    data = np.random.RandomState(0).randint(0, 255, 1 << 20) \
        .astype(np.uint8).view(np.float32) if False else \
        np.random.RandomState(0).randn(1 << 18).astype(np.float32)
    path = str(tmp_path / "big.swp")
    h.sync_pwrite(data, path)
    out = np.empty_like(data)
    h.sync_pread(out, path)
    np.testing.assert_array_equal(out, data)
    # async path too
    fd = h.open(path, False)
    out2 = np.empty_like(data)
    h.async_pread(out2, fd)
    h.wait()
    h.close(fd)
    np.testing.assert_array_equal(out2, data)


def test_optimizer_swapper_uses_contiguous_arena(tmp_path):
    """The swap staging buffers come from the ContiguousMemoryAllocator
    (reference stage3.py:1073 backs partitions with the arena): steady-state
    double-buffering reuses the same arena instead of allocating fresh host
    buffers every step."""
    if not has_native():
        pytest.skip("no C++ toolchain")
    from deepspeed_tpu.runtime.swap_tensor.swapper import OptimizerStateSwapper
    sw = OptimizerStateSwapper(str(tmp_path))
    a = np.arange(64, dtype=np.float32).reshape(8, 8)
    for leaf in ("l0", "l1"):
        sw.init_state(leaf, a.shape)
    sw.prefetch("l0")
    for step in range(4):
        for i, leaf in enumerate(("l0", "l1")):
            m, v = sw.fetch(leaf)
            sw.prefetch(("l0", "l1")[(i + 1) % 2])
            m += 1.0
            v += 2.0
            sw.store(leaf, m, v)
    m, v = sw.fetch("l0")
    np.testing.assert_array_equal(m, np.full((8, 8), 4.0, np.float32))
    np.testing.assert_array_equal(v, np.full((8, 8), 8.0, np.float32))
    arena = sw._arena.arena
    assert arena is not None and arena.size == 4 * 64
    # steady state never outgrew the arena: no numpy fallback, and at most
    # the double-buffered pairs were ever live at once
    assert arena.max_allocated <= arena.size
    assert sw._arena._live <= 4
    sw.release()


import pytest as _pytest


@_pytest.mark.parametrize("overlap,gas", [(False, 1), (True, 4)])
def test_offload_wall_clock_breakdown(overlap, gas):
    """wall_clock_breakdown must not silently no-op for offload engines
    (r3 review finding), on BOTH the fused-accumulation and the
    overlap_comm per-micro paths: 'backward' (device compute incl.
    overlapped transfers) and 'step' (host SIMD+push) timers populate."""
    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["gradient_accumulation_steps"] = gas
    cfg["wall_clock_breakdown"] = True
    cfg["zero_optimization"] = {"stage": 2, "overlap_comm": overlap,
                                "offload_optimizer": {"device": "cpu"}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=one_device_mesh())
    batch = random_batch(batch_size=8)
    for _ in range(2):
        engine.train_batch(batch)
    times = engine.wall_clock_times()
    assert times.get("backward", 0) > 0
    assert times.get("step", 0) > 0
    assert "forward" not in times   # offload reports fwd+bwd fused
