"""Bucketed gradient-sync scheduler benchmark — step time with the
overlap_comm scheduler on vs. off (ISSUE 1 acceptance: >1-device mesh,
CPU device emulation acceptable).

Three engine variants over the same model/batch:

  fused_gspmd   overlap_comm=False — the monolithic implicit psum exchange
  overlap_ring  overlap_comm=True, overlap_reduce="ring"  — per-bucket
                ppermute ring reduce-scatter + all-gather
  overlap_fused overlap_comm=True, overlap_reduce="fused" — per-bucket psum

On the CPU-emulated mesh the collectives are memcpy-bound, so the numbers
calibrate plumbing overhead (bucket pack/unpack, ring hop count), not real
ICI overlap — run on a TPU slice for the actual overlap win. Prints one
JSON object.

Run directly: python tests/perf/overlap_bench.py [hidden] [depth] [bucket_elems]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def main(hidden=512, depth=4, bucket_elems=131_072):
    import numpy as np
    import jax
    if "xla_force_host_platform_device_count" in \
            os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    import flax.linen as nn
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel import overlap
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(depth):
                x = nn.relu(nn.Dense(hidden)(x))
            return nn.Dense(4)(x)

    n = len(jax.devices())
    rng = np.random.RandomState(0)
    batch = (rng.randn(8 * n, 64).astype(np.float32),
             rng.randint(0, 4, size=(8 * n,)).astype(np.int32))

    def build(overlap_on, mode):
        cfg = {
            "train_batch_size": 8 * n,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10**9,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {
                "stage": 2, "overlap_comm": overlap_on,
                "reduce_bucket_size": bucket_elems,
                "overlap_reduce": mode},
        }
        mesh = make_mesh(MeshConfig(data=n), devices=jax.devices())
        engine, _, _, _ = dstpu.initialize(config=cfg, model=MLP(), mesh=mesh)
        return engine

    def time_steps(engine, steps=10):
        engine.train_batch(batch)                       # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        return (time.perf_counter() - t0) / steps * 1e3, float(loss)

    variants = {"fused_gspmd": (False, "ring"),
                "overlap_ring": (True, "ring"),
                "overlap_fused": (True, "fused")}
    result = {"devices": n, "hidden": hidden, "depth": depth,
              "bucket_elems": bucket_elems, "step_ms": {}, "loss": {}}
    numel = None
    for name, (on, mode) in variants.items():
        engine = build(on, mode)
        if on:
            assert engine._overlap_comm_active(), \
                "overlap scheduler did not activate on this mesh"
        ms, loss = time_steps(engine)
        if numel is None:              # state materializes on first step
            leaves = jax.tree_util.tree_leaves(engine.state.params)
            numel = int(sum(l.size for l in leaves))
            result["param_numel"] = numel
            result["buckets"] = len(overlap.plan_buckets(
                [l.shape for l in leaves], bucket_elems, n))
        result["step_ms"][name] = round(ms, 3)
        result["loss"][name] = round(loss, 6)
    base = result["step_ms"]["fused_gspmd"]
    result["overlap_speedup_ring"] = round(
        base / result["step_ms"]["overlap_ring"], 3)
    result["overlap_speedup_fused"] = round(
        base / result["step_ms"]["overlap_fused"], 3)
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # re-exec with the multi-device CPU env (XLA_FLAGS is read at
        # interpreter start)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        os.execve(sys.executable, [sys.executable, __file__] + sys.argv[1:],
                  env)
    main(*(int(a) for a in sys.argv[1:]))
