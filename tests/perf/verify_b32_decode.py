"""Round-5: batched decode through the fused loop on the real chip.

Parity at b=32 (small model) + GPT-2-large b32/ctx512 int8-KV tok/s via
the bench difference method.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import (
    generate, convert_gpt2_params)
import deepspeed_tpu.models.gpt2_inference as gi


def parity():
    ctx = 256
    cfg = GPT2Config(vocab_size=512, n_positions=ctx, n_embd=256,
                     n_layer=3, n_head=4, dtype=jnp.bfloat16,
                     param_dtype=jnp.bfloat16, scan_layers=True)
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 512, size=(32, 40)).astype(np.int32)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(5), prompt[:, :8])["params"]
    sparams = convert_gpt2_params(params, cfg)
    assert gi._supports_fast_decode(cfg, 32, 0, 1, 8, 1)
    kw = dict(max_new_tokens=8, max_out_tokens=ctx, scan_decode=True,
              quantize_bits=0, kv_cache_bits=8)
    t_fast = generate(cfg, sparams, prompt, **kw)
    orig = gi._supports_fast_decode
    gi._supports_fast_decode = lambda *a: False
    try:
        t_ref = generate(cfg, sparams, prompt, **kw)
    finally:
        gi._supports_fast_decode = orig
    fast, ref = np.asarray(t_fast), np.asarray(t_ref)
    same = (fast == ref).mean()
    print(f"b32 parity (0,8): {same * 100:.1f}% tokens equal")
    assert same == 1.0


def perf():
    ctx = 512
    cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                     n_layer=36, n_head=20, dtype=jnp.bfloat16,
                     param_dtype=jnp.bfloat16, scan_layers=True)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 50304, size=(32, ctx - 80)).astype(np.int32)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), prompt[:, :8])["params"]

    def run(new):
        toks = generate(cfg, params, prompt, max_new_tokens=new,
                        max_out_tokens=ctx, scan_decode=True,
                        kv_cache_bits=8)
        return float(jax.device_get(toks[0, -1]))

    run(4)
    run(68)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run(4)
        t_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(68)
        t_l = time.perf_counter() - t0
        best = min(best, t_l - t_s)
    print(f"gpt2_large b32/ctx512 int8kv fused: "
          f"{32 * 64 / best:.1f} tok/s ({best * 1000 / 64:.2f} ms/tick)")


if __name__ == "__main__":
    print("devices:", jax.devices())
    parity()
    perf()
    print("OK")
