"""Forward-pass component profile at the 774M headline shapes (bs8,
seq1024, E=1280, H=20, L=36): where do the forward milliseconds go vs
each component's roofline?

The r4 phase breakdown put forward at 167 ms against a ~72 ms matmul+
attention roofline (43% util) while backward ran at 58% — this harness
times each forward component in isolation (difference-method windows;
the tunnel fence is ~100 ms and must amortize) and prints a JSON line
per component with achieved TFLOP/s and % of the 197 TF v5e peak.

Run: python -m tests.perf.fwd_profile
"""

import json
import time

import numpy as np


def timed(fn, *args, iters=30, reps=3):
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    # data-dependent fence: device_get of a freshly computed scalar
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jax.device_get(leaf.reshape(-1)[0]).astype(np.float32))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        leaf = jax.tree_util.tree_leaves(out)[0]
        float(jax.device_get(leaf.reshape(-1)[0]).astype(np.float32))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main():
    import jax
    import jax.numpy as jnp
    from bench import peak_flops, _enable_compile_cache

    _enable_compile_cache()
    dev = jax.devices()[0]
    peak = peak_flops(dev)
    B, S, E, H, L = 8, 1024, 1280, 20, 36
    D = E // H
    M = B * S
    key = jax.random.PRNGKey(0)
    results = {}

    def report(name, dt, flops):
        tf = flops / dt / 1e12
        results[name] = {"ms": round(dt * 1000, 3),
                         "tflops": round(tf, 1),
                         "pct_peak": round(100 * tf * 1e12 / peak, 1)}

    x = jax.random.normal(key, (M, E), jnp.bfloat16)
    for name, n in (("matmul_qkv_3840", 3 * E), ("matmul_fc_5120", 4 * E),
                    ("matmul_proj_1280", E)):
        w32 = jax.random.normal(key, (E, n), jnp.float32) * 0.02
        wbf = w32.astype(jnp.bfloat16)
        f_bf = jax.jit(lambda a, w: a @ w)
        f_cast = jax.jit(lambda a, w: a @ w.astype(jnp.bfloat16))
        flops = 2 * M * E * n
        report(name + "_bf16w", timed(f_bf, x, wbf), flops)
        report(name + "_fp32w_cast", timed(f_cast, x, w32), flops)

    # flash attention fwd (causal): 4*S*E flops/token
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (B, H, S, D), jnp.bfloat16) * 0.3
               for i in range(3))
    fa = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    # causal: half the S^2 work counts as "useful" in the 12LSE accounting
    report("flash_attn_fwd", timed(fa, q, k, v), 2 * 2 * B * S * S * E / 2)

    # one transformer block fwd (no remat wrapper)
    from deepspeed_tpu.models.gpt2 import GPT2Config, Block
    cfg = GPT2Config(vocab_size=50304, n_positions=S, n_embd=E, n_layer=L,
                     n_head=H, dtype=jnp.bfloat16, scan_layers=False,
                     remat=False)
    blk = Block(cfg)
    xb = jax.random.normal(key, (B, S, E), jnp.bfloat16)
    pb = jax.jit(blk.init)(key, xb)["params"]
    bf = jax.jit(lambda p, a: blk.apply({"params": p}, a))
    blk_flops = 2 * M * (12 * E * E) + 2 * 2 * B * S * S * E / 2
    report("block_fwd_fp32w", timed(bf, pb, xb), blk_flops)
    pb16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a, pb)
    cfg16 = GPT2Config(vocab_size=50304, n_positions=S, n_embd=E, n_layer=L,
                       n_head=H, dtype=jnp.bfloat16,
                       param_dtype=jnp.bfloat16, scan_layers=False,
                       remat=False)
    blk16 = Block(cfg16)
    bf16 = jax.jit(lambda p, a: blk16.apply({"params": p}, a))
    report("block_fwd_bf16w", timed(bf16, pb16, xb), blk_flops)

    # full-model forward + chunked loss, headline config (remat ON —
    # jax.checkpoint also runs in the primal, its policy should not
    # change pure-forward time) and OFF
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, 50304, (B, S)), jnp.int32)
    model_flops = 2 * M * (L * 12 * E * E + 50304 * E) \
        + 2 * 2 * B * S * S * E * L / 2
    for tag, remat in (("remat_lean", True), ("noremat", False)):
        mcfg = GPT2Config(vocab_size=50304, n_positions=S, n_embd=E,
                          n_layer=L, n_head=H, dtype=jnp.bfloat16,
                          scan_layers=True, remat=remat,
                          remat_policy="dots_flash_fc_lean" if remat
                          else None, loss_chunk=1024)
        model = GPT2LMHeadModel(mcfg)
        pm = jax.jit(model.init)(key, ids[:, :8])["params"]
        lf = jax.jit(lambda p, i: model.apply({"params": p}, i, labels=i))
        report(f"model_fwd_loss_{tag}", timed(lf, pm, ids, iters=10),
               model_flops)
        del pm, lf
        jax.clear_caches()

    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
