"""Round-5 verify: LLaMA fused serving on the real chip.

1. Mosaic lowering + parity for the RMS/SwiGLU/GQA kernel modes
   (tiny model, fp and int8).
2. GQA flash forward on-chip (Hkv-aware index maps must lower).
3. llama_7b b1/ctx2048 int8 decode tok/s (bench difference method) +
   the honest roofline note: 6.7 GB of int8 weights per token bounds
   b1 at ~120 tok/s on an 819 GB/s chip.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import (llama_tiny, llama_7b,
                                        LlamaForCausalLM, llama_generate)
from deepspeed_tpu.models.llama_inference import (
    convert_llama_serving_params, quantize_llama_serving_params,
    llama_fast_generate, random_int8_serving_params)


def parity():
    cfg = llama_tiny(hidden_size=128, intermediate_size=256, n_layers=3,
                     n_heads=4, n_kv_heads=2, max_seq_len=192,
                     dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    rs = np.random.RandomState(11)
    prompt = rs.randint(0, 512, size=(2, 40)).astype(np.int32)
    params = jax.jit(LlamaForCausalLM(cfg).init)(
        jax.random.PRNGKey(7), prompt[:, :8])["params"]
    ref = llama_generate(cfg, params, prompt, max_new_tokens=8,
                         max_out_tokens=cfg.max_seq_len)
    sparams = convert_llama_serving_params(params, cfg)
    fp = llama_fast_generate(cfg, sparams, prompt, max_new_tokens=8,
                             max_out_tokens=cfg.max_seq_len)
    same = (np.asarray(fp) == np.asarray(ref)).mean()
    print(f"llama fp fast vs flax: {same * 100:.1f}% tokens equal")
    assert same == 1.0, (np.asarray(fp), np.asarray(ref))
    q = llama_fast_generate(cfg, quantize_llama_serving_params(sparams),
                            prompt, max_new_tokens=8,
                            max_out_tokens=cfg.max_seq_len,
                            kv_cache_bits=8)
    same_q = (np.asarray(q) == np.asarray(fp)).mean()
    print(f"llama int8 fast vs fp fast: {same_q * 100:.1f}% tokens equal")
    assert same_q > 0.8


def gqa_flash_chip():
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    from deepspeed_tpu.ops.attention import reference_attention
    B, H, Hkv, S, D = 2, 8, 2, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, Hkv, S, D), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    err = float(jnp.mean(jnp.abs(out.astype(jnp.float32)
                                 - ref.astype(jnp.float32))))
    print(f"GQA flash on-chip mean abs err vs reference: {err:.5f}")
    assert err < 0.01

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32) ** 2)
    g = jax.jit(jax.grad(loss, argnums=(1,)))(q, k, v)[0]
    assert g.shape == (B, Hkv, S, D)
    print("GQA flash backward on-chip OK (reduced dk shape)")


def perf7b(bs=1, ctx=2048):
    cfg = llama_7b(dtype=jnp.bfloat16, param_dtype=jnp.bfloat16,
                   max_seq_len=ctx)
    print(f"llama_7b: {cfg.num_params() / 1e9:.2f}B params")
    sparams = random_int8_serving_params(cfg)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, cfg.vocab_size,
                        size=(bs, ctx - 80)).astype(np.int32)

    def run(new):
        toks = llama_fast_generate(cfg, sparams, prompt,
                                   max_new_tokens=new,
                                   max_out_tokens=ctx, kv_cache_bits=8)
        return float(jax.device_get(toks[0, -1]))

    run(4)
    run(68)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter(); run(4)
        ts = time.perf_counter() - t0
        t0 = time.perf_counter(); run(68)
        tl = time.perf_counter() - t0
        best = min(best, tl - ts)
    tps = bs * 64 / best
    print(f"llama7b b{bs}/ctx{ctx} int8: {tps:.1f} tok/s "
          f"({best * 1000 / 64:.2f} ms/tick)")
    return tps


if __name__ == "__main__":
    print("devices:", jax.devices())
    parity()
    gqa_flash_chip()
    perf7b(1)
    perf7b(8)
    print("OK")
