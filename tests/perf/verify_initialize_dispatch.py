"""Drive dstpu.initialize() -> InfinityEngine dispatch end-to-end."""
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=64, n_layer=4,
                 n_head=2, dtype=jnp.float32, param_dtype=jnp.float32,
                 scan_layers=True)
import tempfile
tmp = tempfile.mkdtemp()
engine, opt, loader, sched = dstpu.initialize(
    config={
        "train_batch_size": 2,
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "nvme", "nvme_path": tmp,
                              "stream_segments": 2},
            "offload_optimizer": {"device": "cpu"}},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
    },
    model=GPT2LMHeadModel(cfg))
from deepspeed_tpu.runtime.zero.infinity import InfinityEngine
assert isinstance(engine, InfinityEngine), type(engine)
assert engine.params_on_disk_bytes() > 0
rng = np.random.RandomState(0)
batch = {"input_ids": rng.randint(0, 512, (2, 32)).astype(np.int32)}
losses = [engine.train_batch(batch) for _ in range(4)]
print("losses:", [round(l, 4) for l in losses])
assert losses[-1] < losses[0]
print("initialize() -> InfinityEngine dispatch OK")
