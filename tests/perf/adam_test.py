"""CPU-Adam throughput harness — reference tests/perf/adam_test.py.

Run directly: python tests/perf/adam_test.py [numel]
Reports native SIMD cpu_adam steps/sec vs the numpy fallback.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

import numpy as np


def main(numel=4_000_000, steps=10):
    from deepspeed_tpu.ops.native import cpu_adam
    p = np.random.randn(numel).astype(np.float32)
    g = np.random.randn(numel).astype(np.float32)
    m = np.zeros(numel, np.float32)
    v = np.zeros(numel, np.float32)

    lib = cpu_adam.load()
    lib.adam_step(p, g, m, v, 1, 1e-3, 0.9, 0.999, 1e-8, 0.0, True, True)
    t0 = time.perf_counter()
    for i in range(steps):
        lib.adam_step(p, g, m, v, i + 2, 1e-3, 0.9, 0.999, 1e-8, 0.0,
                      True, True)
    dt = (time.perf_counter() - t0) / steps
    print(f"native cpu_adam: {numel/dt/1e9:.2f} Gparam/s "
          f"({dt*1e3:.2f} ms for {numel/1e6:.0f}M params)")


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
