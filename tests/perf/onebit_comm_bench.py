"""Hierarchical link-aware 1-bit gradient exchange benchmark (ISSUE 10
acceptance: inter-host bytes-on-wire drop >= 4x post-freeze, step time
vs the flat allreduce recorded).

Three OneBitAdam engine variants over the same MLP/batch on one mesh,
data axis split 2 x (n/2) by the synthetic slow-axis override:

  flat        no ``comm.hierarchy`` block — the pre-existing single-link
              compressed allreduce: EVERY hop pays the sign-pack
  hier_1bit   hierarchy on, compression "always" — fast-axis ring hops
              uncompressed, only the slow-axis hop carries sign bits
  hier_exact  hierarchy on, compression "never" — the exact two-level
              mean through the same bucket stream (the numeric floor
              and the fair step-time baseline for the compression cost)

The headline is ``bytes_reduction``: modeled post-freeze slow-hop bytes
of the fp32 exchange over the sign-packed exchange (the trace-time cost
model behind the ``comm/bytes_on_wire/*`` counters — exact, because the
bucket plan and policy are static). Step times ride along; on this
CPU-emulated mesh every "link" is a memcpy and the virtual devices
timeshare the host cores, so compression can only ADD pack/unpack
compute here — the wire-byte ledger is the portable result, the
step-time ratio is harness calibration (run on a real multi-host slice
for wall-clock wins; the slow axis then comes from process boundaries,
not the override). Prints one JSON object.

Run directly: python tests/perf/onebit_comm_bench.py [hidden] [layers]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def _build_engine(n, hidden, layers, comm=None, freeze=5,
                  bucket_elems=65536):
    import jax
    import flax.linen as nn
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    class CommMLP(nn.Module):
        """Several equal Dense blocks: enough parameter volume for a
        multi-bucket plan (one SimpleModel bucket would make the
        per-bucket policy trivial). tanh, NOT relu: a relu unit dead
        through the whole warmup leaves its variance frozen at exactly
        0, and the first post-freeze gradient there divides by eps —
        every 1-bit variant walks off on that, hierarchy or not."""
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(hidden)(x)
            for _ in range(layers - 1):
                x = nn.tanh(x)
                x = nn.Dense(hidden)(x)
            return nn.Dense(16)(x)

    cfg = {
        "train_batch_size": 8 * n,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10**9,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": 1e-4, "freeze_step": freeze}},
        # small buckets so the tree splits into several (the per-bucket
        # policy and cost model see a real plan, not one blob)
        "zero_optimization": {"stage": 0,
                              "reduce_bucket_size": bucket_elems},
    }
    if comm is not None:
        cfg["comm"] = comm
    mesh = make_mesh(MeshConfig(data=n), devices=jax.devices())
    engine, _, _, _ = dstpu.initialize(config=cfg, model=CommMLP(),
                                       mesh=mesh)
    return engine


def run_onebit_comm_bench(hidden=512, layers=4, steps=10, freeze=5):
    import numpy as np
    import jax

    n = len(jax.devices())
    assert n >= 4 and n % 2 == 0, f"need an even mesh >= 4, got {n}"
    rng = np.random.RandomState(0)
    # a learnable task (labels from a fixed linear teacher) with several
    # samples per device: random labels + 1-sample-per-device local
    # grads leave the compressed momentum nothing but noise to follow
    # and every variant diverges — that would measure the toy problem
    xs = rng.randn(8 * n, 64).astype(np.float32)
    teacher = rng.randn(64, 16).astype(np.float32)
    batch = (xs, np.argmax(xs @ teacher, axis=1).astype(np.int32))

    variants = {
        "flat": None,
        "hier_1bit": {"hierarchy": {"slow_axis": 2,
                                    "compression": "always"}},
        "hier_exact": {"hierarchy": {"slow_axis": 2,
                                     "compression": "never"}},
    }
    result = {"devices": n, "split": f"2x{n // 2} (synthetic slow axis)",
              "hidden": hidden, "layers": layers,
              "step_time_s": {}, "final_loss": {}}
    wire = None
    for name, comm in variants.items():
        engine = _build_engine(n, hidden, layers, comm=comm,
                               freeze=freeze)
        # through the freeze into compressed steady state + compile both
        # phase programs before the clock starts
        for _ in range(freeze + 2):
            loss = engine.train_batch(batch)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch)
        jax.block_until_ready(loss)
        result["step_time_s"][name] = round(
            (time.perf_counter() - t0) / steps, 6)
        result["final_loss"][name] = round(float(loss), 6)
        if name == "hier_1bit":
            wire = dict(engine._comm_wire_model)
            result["counters"] = {
                k: int(v) for k, v in engine.telemetry.snapshot(
                    "comm/")["counters"].items()}
        del engine
        jax.clear_caches()

    # the headline: post-freeze slow-hop fp32 bytes over sign-packed
    # bytes — per step per device, from the static cost model
    comp = wire["compressed"]
    result["bytes_per_step"] = wire
    result["bytes_reduction"] = round(
        comp["inter_uncompressed"] / comp["inter"], 3)
    result["hier_vs_flat_step_time"] = round(
        result["step_time_s"]["flat"]
        / result["step_time_s"]["hier_1bit"], 3)
    return result


def main(hidden=512, layers=4):
    import jax
    if "xla_force_host_platform_device_count" in \
            os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_onebit_comm_bench(hidden=hidden, layers=layers),
                     indent=2))


if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # re-exec with the multi-device CPU env (XLA_FLAGS is read at
        # interpreter start)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        os.execve(sys.executable, [sys.executable, __file__] + sys.argv[1:],
                  env)
    main(*(int(a) for a in sys.argv[1:]))
