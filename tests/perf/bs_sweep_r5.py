"""Grouped-row block-sparse sweep: S=16384 BigBird across block sizes."""
import time
import numpy as np
import jax
import jax.numpy as jnp
from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

S, B, H, D = int(__import__("os").environ.get("BS_S", 16384)), 1, 16, 64
rng = jax.random.PRNGKey(0)
q, k, v = (jax.random.normal(jax.random.fold_in(rng, i),
                             (B, H, S, D), jnp.bfloat16) * 0.3
           for i in range(3))

def timed(fn):
    g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
    r = g(q, k, v)
    float(jax.device_get(r[0].astype(jnp.float32).sum()))
    t0 = time.perf_counter()
    for _ in range(5):
        r = g(q, k, v)
    float(jax.device_get(r[0].astype(jnp.float32).sum()))
    return (time.perf_counter() - t0) / 5

dn = timed(lambda a, b, c: jnp.sum(flash_attention(
    a, b, c, causal=False).astype(jnp.float32) ** 2))
print(f"dense flash: {dn * 1000:.2f} ms")
for block in (128, 256, 512):
    cfg = BigBirdSparsityConfig(num_heads=1, block=block,
                                num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    np.random.seed(0)
    layout = cfg.make_layout(S)
    sp = timed(lambda a, b, c: jnp.sum(blocksparse_attention(
        a, b, c, layout, block).astype(jnp.float32) ** 2))
    print(f"block {block}: density {float(layout[0].mean()):.3f} "
          f"sparse {sp * 1000:.2f} ms speedup {dn / sp:.2f}x")
