"""Device-trace the b32/ctx512 int8-KV fused decode tick to find where
the 17 ms goes (roofline says ~4-6)."""
import glob
import gzip
import json
import collections

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import generate

ctx = 512
cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                 n_layer=36, n_head=20, dtype=jnp.bfloat16,
                 param_dtype=jnp.bfloat16, scan_layers=True)
rng = np.random.RandomState(0)
prompt = rng.randint(0, 50304, size=(32, ctx - 80)).astype(np.int32)
params = jax.jit(GPT2LMHeadModel(cfg).init)(
    jax.random.PRNGKey(0), prompt[:, :8])["params"]


def run(new):
    toks = generate(cfg, params, prompt, max_new_tokens=new,
                    max_out_tokens=ctx, scan_decode=True, kv_cache_bits=8)
    return float(jax.device_get(toks[0, -1]))


run(4)
run(36)                                  # compile
d = "/tmp/b32trace"
with jax.profiler.trace(d):
    run(36)

agg = collections.Counter()
for f in glob.glob(d + "/**/*.trace.json.gz", recursive=True):
    ev = json.loads(gzip.open(f).read())["traceEvents"]
    for e in ev:
        if e.get("ph") == "X" and "dur" in e:
            pid_name = e.get("pid")
            agg[e["name"]] += e["dur"]
total = sum(agg.values())
print(f"total device us: {total}  (~{total / 35 / 1000:.2f} ms/tick over 35 ticks)")
for name, us in agg.most_common(25):
    print(f"{us / 35:10.1f} us/tick  {name[:110]}")

print("\n--- device ops only ---")
skip = ("$", "jit_", "while", "copy-start", "copy-done")
dev = [(n, us) for n, us in agg.items()
       if not any(n.startswith(s) or s in n for s in ("$",))
       and not n.startswith(("jit_", "while", "copy-start"))
       and "py" not in n[:2]]
dev.sort(key=lambda t: -t[1])
tot = 0.0
for name, us in dev[:40]:
    tot += us
    print(f"{us / 35:10.1f} us/tick  {name[:110]}")
print(f"listed sum: {tot / 35 / 1000:.2f} ms/tick")
