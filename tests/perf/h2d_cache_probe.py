import gc
import numpy as np
import jax
import jax.numpy as jnp

def rss():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024

print("start", rss())
base = np.empty(56 << 20, np.uint8)         # reused staging buffer
for i in range(6):
    base[:4] = i
    view = base.view(np.float32)
    x = jax.device_put(view)                 # h2d from the same buffer
    x.block_until_ready()
    x.delete()
    del x
    gc.collect()
    print(f"iter {i} (reused buf): rss={rss():.0f}", flush=True)
for i in range(6):
    fresh = np.random.RandomState(i).randint(0, 255, 56 << 20) \
        .astype(np.uint8).view(np.float32)
    x = jax.device_put(fresh)
    x.block_until_ready()
    x.delete()
    del x, fresh
    gc.collect()
    print(f"iter {i} (fresh buf): rss={rss():.0f}", flush=True)
