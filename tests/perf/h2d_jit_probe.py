import gc
import numpy as np
import jax
import jax.numpy as jnp

def rss():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024

ident = jax.jit(lambda a: a + 0)
print("start", rss())
for i in range(6):
    fresh = np.random.RandomState(i).randint(0, 255, 56 << 20) \
        .astype(np.uint8).view(np.float32)
    x = ident(fresh)
    x.block_until_ready()
    x.delete()
    del x, fresh
    gc.collect()
    print(f"iter {i} (jit arg): rss={rss():.0f}", flush=True)
