"""Decode-latency ablation (b1, GPT-2 large, ctx 2048): where do the
~9 ms/token go? Times the full scan decode, then variants with pieces
removed, using the two-window difference method (the readback fence is a
~100 ms tunnel RTT and must cancel).

Run: python -m tests.perf.decode_ablate
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_inference import (
        generate, convert_gpt2_params, quantize_gpt2_inference_params)

    ctx = 2048
    cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                     n_layer=36, n_head=20, dtype=jnp.bfloat16,
                     param_dtype=jnp.bfloat16, scan_layers=True)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, 50304, size=(1, ctx - 200)).astype(np.int32)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), prompt[:, :8])["params"]
    iparams = convert_gpt2_params(params, cfg)
    qparams = quantize_gpt2_inference_params(iparams)

    def tok_ms(**kw):
        p = qparams if kw.get("quantize_bits") else iparams

        def run(new):
            toks = generate(cfg, p, prompt, max_new_tokens=new,
                            max_out_tokens=ctx, **kw)
            return float(jax.device_get(toks[0, -1]))
        run(4)
        run(132)
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            run(4)
            t_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            run(132)
            t_l = time.perf_counter() - t0
            best = min(best, (t_l - t_s) / 128)
        return best * 1000

    out = {"scan_bf16": round(tok_ms(scan_decode=True), 2),
           "steploop_bf16": round(tok_ms(scan_decode=False), 2),
           "scan_int8w": round(tok_ms(scan_decode=True, quantize_bits=8), 2),
           "scan_int8w_int8kv": round(
               tok_ms(scan_decode=True, quantize_bits=8, kv_cache_bits=8), 2)}
    out["tok_per_s_best"] = round(1000 / min(out.values()), 1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
