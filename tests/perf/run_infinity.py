"""ZeRO-Infinity scale driver: train an N-billion-param GPT-2 on the one
16 GB chip with segment-streamed params + pinned_host master/moments +
NVMe at-rest files.

Usage: python tests/perf/run_infinity.py [preset] [steps]
presets: 1b (shakeout), 6b (the scale proof)
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config
from deepspeed_tpu.runtime.zero.infinity import InfinityEngine


def rss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024
    return 0.0


from deepspeed_tpu.runtime.zero.infinity import gpt2_client_init \
    as numpy_init  # noqa: E402

PRESETS = {
    "1b": dict(n_embd=2048, n_layer=20, n_head=16, segments=4, batch=4,
               seq=1024),
    "6b": dict(n_embd=4096, n_layer=30, n_head=32, segments=6, batch=4,
               seq=1024),
    # ~7.9B: 79 GB of pinned state (fp32 master + bf16 m + fp32 v)
    "8b": dict(n_embd=4096, n_layer=40, n_head=32, segments=5,
               batch=4, seq=1024, tiled=True),
    # ~9.4B: ~94 GB of pinned state
    "9b": dict(n_embd=4608, n_layer=36, n_head=36, segments=6, batch=4,
               seq=1024, tiled=True),
}


def tiled_init(cfg, seed=0):
    """Canonical copy lives in bench.py (tiled_gpt2_init)."""
    import sys
    sys.path.insert(0, "/root/repo")
    from bench import tiled_gpt2_init
    return tiled_gpt2_init(cfg, seed)


def main():
    preset = sys.argv[1] if len(sys.argv) > 1 else "1b"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    p = PRESETS[preset]
    cfg = GPT2Config(vocab_size=50304, n_positions=p["seq"],
                     n_embd=p["n_embd"], n_layer=p["n_layer"],
                     n_head=p["n_head"], dtype=jnp.bfloat16,
                     param_dtype=jnp.bfloat16, scan_layers=True,
                     remat=True, loss_chunk=2048)
    if p.get("segments") is None:
        p["segments"] = next(s for s in (6, 5, 4, 3, 2)
                             if cfg.n_layer % s == 0)
    nb = cfg.num_params() / 1e9
    print(f"model: {nb:.3f}B params; preset {preset}", flush=True)
    t0 = time.time()
    params = (tiled_init(cfg) if p.get("tiled") else numpy_init(cfg))
    print(f"init: {time.time() - t0:.1f}s rss={rss_mb():.0f}MB",
          flush=True)

    nvme_dir = "/root/nvme_infinity"
    os.makedirs(nvme_dir, exist_ok=True)
    t0 = time.time()
    eng = InfinityEngine(cfg, params, segments=p["segments"],
                         nvme_path=nvme_dir, lr=1e-4)
    del params
    print(f"engine init (incl NVMe write + pinned placement): "
          f"{time.time() - t0:.1f}s rss={rss_mb():.0f}MB", flush=True)
    print(f"params_on_disk_mb: {eng.params_on_disk_bytes() / 2**20:.1f}",
          flush=True)

    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(
        0, 50304, size=(p["batch"], p["seq"])).astype(np.int32)}
    losses = []
    rss_track = []
    for i in range(steps):
        t0 = time.time()
        loss = eng.train_batch(batch)
        dt = time.time() - t0
        losses.append(loss)
        rss_track.append(round(rss_mb(), 1))
        print(f"step {i}: loss={loss:.4f} {dt:.1f}s rss={rss_track[-1]}MB",
              flush=True)
    print(f"losses: {losses}")
    print(f"rss_track: {rss_track}")
    print("OK" if losses[-1] < losses[0] else "LOSS NOT FALLING")


if __name__ == "__main__":
    main()
