"""Dump the optimized HLO of the b32 fast_scan to identify the per-tick
copy.60/copy.64 and add_add_fusion.2 ops the trace surfaced."""
import re

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import (
    generate, convert_gpt2_params, _fast_decode_scan_fn)

ctx = 512
cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                 n_layer=36, n_head=20, dtype=jnp.bfloat16,
                 param_dtype=jnp.bfloat16, scan_layers=True)
rng = np.random.RandomState(0)
prompt = rng.randint(0, 50304, size=(32, ctx - 80)).astype(np.int32)
params = jax.jit(GPT2LMHeadModel(cfg).init)(
    jax.random.PRNGKey(0), prompt[:, :8])["params"]
iparams = convert_gpt2_params(params, cfg)

model_p = {"wte": iparams["wte"], "wpe": iparams["wpe"],
           "ln_f": iparams["ln_f"]}
blk = iparams["h"]["blk"]
B, H, D, Lyr = 32, 20, 64, 36
kc = jnp.zeros((Lyr, B, H, ctx, D), jnp.int8)
ks = jnp.zeros((Lyr, B, H, ctx), jnp.float32)
vc = jnp.zeros((Lyr, B, H, ctx), jnp.float32)  # placeholder fix below
vc = jnp.zeros((Lyr, B, H, ctx, D), jnp.int8)
vs = jnp.zeros((Lyr, B, H, ctx), jnp.float32)
fast = _fast_decode_scan_fn(cfg, ctx, weights_q8=False, cache_q8=True)
first = jnp.zeros((B,), jnp.int32)
rngs = jax.random.split(jax.random.PRNGKey(0), 35)
lowered = fast.lower(model_p, blk, (kc, ks, vc, vs), first, 35,
                     jnp.asarray(400, jnp.int32), rngs,
                     jnp.float32(0.0))
txt = lowered.compile().as_text()
with open("/tmp/b32_fastscan_hlo.txt", "w") as f:
    f.write(txt)
print("bytes:", len(txt))
for pat in (r".*copy\.6[04].*", r".*add_add_fusion\.2\b.*",
            r".*fusion\.11[89].*", r".*convolution_add_fusion\.4.*"):
    for m in re.findall(pat, txt):
        print(m.strip()[:240])
    print("---")
