"""(a) Re-measure the BERT headline twice to pin the r3->r4 swing.
(b) Instrument the small NVMe-park case RSS over 12 steps to classify
the r4 197.7 MB growth (leak vs warm-up plateau)."""
import time
import numpy as np
import jax
import jax.numpy as jnp
import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
import sys
sys.path.insert(0, "/root/repo")
import bench

dev = jax.devices()[0]
for i in range(2):
    sps = bench.bench_bert(dstpu, make_mesh, MeshConfig, dev)
    print(f"bert run {i}: {sps} samples/s", flush=True)
    jax.clear_caches()

# small nvme-park case, 12 steps with per-step RSS
import tempfile
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

def rss_mb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024

tmp = tempfile.mkdtemp(prefix="dstpu_nvme_rss_")
cfg_m = GPT2Config(vocab_size=8192, n_positions=256, n_embd=512,
                   n_layer=8, n_head=8, dtype=jnp.bfloat16,
                   scan_layers=True)
engine, _, _, _ = dstpu.initialize(
    config={
        "train_batch_size": 4,
        "zero_optimization": {
            "stage": 2,
            "offload_param": {"device": "nvme", "nvme_path": tmp},
            "offload_optimizer": {"device": "cpu"}},
        "bf16": {"enabled": True},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "steps_per_print": 1000,
    },
    model=GPT2LMHeadModel(cfg_m),
    mesh=make_mesh(MeshConfig(data=1), devices=[dev]))
rng = np.random.RandomState(0)
batch = {"input_ids": rng.randint(0, 8192, size=(4, 256))
         .astype(np.int32)}
track = []
for i in range(12):
    engine.train_batch(batch)
    track.append(round(rss_mb(), 1))
    print(f"step {i}: rss={track[-1]}", flush=True)
print("rss deltas:", [round(b - a, 1) for a, b in zip(track, track[1:])])
