"""Device-trace the b1/ctx2048 bf16 fused decode tick."""
import glob, gzip, json, collections, shutil
import numpy as np
import jax
import jax.numpy as jnp
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import generate

ctx = 2048
cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                 n_layer=36, n_head=20, dtype=jnp.bfloat16,
                 param_dtype=jnp.bfloat16, scan_layers=True)
rng = np.random.RandomState(0)
prompt = rng.randint(0, 50304, size=(1, ctx - 80)).astype(np.int32)
params = jax.jit(GPT2LMHeadModel(cfg).init)(
    jax.random.PRNGKey(0), prompt[:, :8])["params"]

def run(new):
    toks = generate(cfg, params, prompt, max_new_tokens=new,
                    max_out_tokens=ctx, scan_decode=True)
    return float(jax.device_get(toks[0, -1]))

run(4); run(36)
d = "/tmp/b1trace"
shutil.rmtree(d, ignore_errors=True)
with jax.profiler.trace(d):
    run(36)

agg = collections.Counter()
for f in glob.glob(d + "/**/*.trace.json.gz", recursive=True):
    for e in json.loads(gzip.open(f).read())["traceEvents"]:
        if e.get("ph") == "X" and "dur" in e and not e["name"].startswith(
                ("$", "jit_", "while", "np.", "PjitF", "Device")):
            agg[e["name"]] += e["dur"]
for name, us in agg.most_common(22):
    print(f"{us / 35:9.1f} us/tick  {name[:100]}")
