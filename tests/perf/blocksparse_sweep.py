"""Block-sparse vs dense flash at S=4096: block-size sweep.

The r3 streaming kernel is DMA-issue-bound (~0.7M tile issues/s); bigger
layout blocks cut the issue count quadratically per coverage while the
per-issue bytes grow linearly — the lever the VERDICT asks to try before
conceding a density crossover.

Run: python -m tests.perf.blocksparse_sweep
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
    from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    H, D = 16, 64
    for S, B in ((4096, 4), (16384, 1)):
        rng = jax.random.PRNGKey(0)
        q, k, v = (jax.random.normal(jax.random.fold_in(rng, i),
                                     (B, H, S, D), jnp.bfloat16) * 0.3
                   for i in range(3))

        def timed(fn):
            g = jax.jit(jax.grad(fn, argnums=(0, 1, 2)))
            r = g(q, k, v)
            float(jax.device_get(r[0].astype(jnp.float32).sum()))
            best = 1e9
            for _ in range(2):
                t0 = time.perf_counter()
                for _ in range(5):
                    r = g(q, k, v)
                float(jax.device_get(r[0].astype(jnp.float32).sum()))
                best = min(best, (time.perf_counter() - t0) / 5)
            return best * 1000

        dn = timed(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=False).astype(jnp.float32) ** 2))
        print(json.dumps({"S": S, "dense_flash_ms": round(dn, 2)}))

        for block in (128, 256, 512):
            if S % block:
                continue
            cfg = BigBirdSparsityConfig(
                num_heads=1, block=block, num_random_blocks=1,
                num_sliding_window_blocks=3, num_global_blocks=1)
            np.random.seed(0)
            try:
                layout = cfg.make_layout(S)
            except Exception as e:
                print(json.dumps({"S": S, "block": block,
                                  "error": str(e)[:120]}))
                continue
            density = float(layout[0].mean())

            def sp(qq, kk, vv, layout=layout, block=block):
                return jnp.sum(blocksparse_attention(
                    qq, kk, vv, layout, block).astype(jnp.float32) ** 2)

            try:
                ms = timed(sp)
            except Exception as e:
                print(json.dumps({"S": S, "block": block,
                                  "error": str(e)[:120]}))
                continue
            print(json.dumps({
                "S": S, "block": block, "density": round(density, 3),
                "sparse_ms": round(ms, 2),
                "speedup_vs_dense": round(dn / ms, 2)}))


if __name__ == "__main__":
    main()
