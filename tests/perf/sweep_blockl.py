"""block_l sweep for the b1 bf16 fused decode."""
import time
import numpy as np
import jax
import jax.numpy as jnp
import deepspeed_tpu.ops.pallas.decode as dk
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import generate, _STEP_CACHE

ctx = 2048
cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                 n_layer=36, n_head=20, dtype=jnp.bfloat16,
                 param_dtype=jnp.bfloat16, scan_layers=True)
rng = np.random.RandomState(0)
prompt = rng.randint(0, 50304, size=(1, ctx - 80)).astype(np.int32)
params = jax.jit(GPT2LMHeadModel(cfg).init)(
    jax.random.PRNGKey(0), prompt[:, :8])["params"]

orig = dk._pick_block_l
for blk in (512, 1024, 2048):
    dk._pick_block_l = lambda L, H, D, it, **kw: min(blk, L)
    _STEP_CACHE.clear()
    jax.clear_caches()

    def run(new):
        toks = generate(cfg, params, prompt, max_new_tokens=new,
                        max_out_tokens=ctx, scan_decode=True)
        return float(jax.device_get(toks[0, -1]))

    run(4); run(68)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter(); run(4); ts = time.perf_counter() - t0
        t0 = time.perf_counter(); run(68); tl = time.perf_counter() - t0
        best = min(best, tl - ts)
    print(f"block_l={blk}: {64 / best:.1f} tok/s")
dk._pick_block_l = orig
