"""Device-trace the headline 774M ZeRO-3 fused train step and aggregate
per-op device time — hunting the backward's gap to peak (r4: bwd 309 ms
= 65% of step at ~46% of peak vs fwd's ~60%)."""
import collections
import glob
import gzip
import json
import shutil

import numpy as np
import jax
import jax.numpy as jnp
import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

dev = jax.devices()[0]
mesh = make_mesh(MeshConfig(data=1), devices=[dev])
seq, batch_size = 1024, 8
model_cfg = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=1280,
                       n_layer=36, n_head=20, dtype=jnp.bfloat16,
                       scan_layers=True, remat=True,
                       remat_policy="dots_flash_fc_lean", loss_chunk=1024)
cfg = {
    "train_batch_size": batch_size,
    "zero_optimization": {"stage": 3},
    "bf16": {"enabled": True},
    "data_types": {"grad_dtype": "bf16"},
    "gradient_clipping": 1.0,
    "optimizer": {"type": "AdamW",
                  "params": {"lr": 1e-4, "weight_decay": 0.01,
                             "moment_dtype": "bf16"}},
    "steps_per_print": 1000,
}
engine, _, _, _ = dstpu.initialize(config=cfg,
                                   model=GPT2LMHeadModel(model_cfg),
                                   mesh=mesh)
rng = np.random.RandomState(0)
batch = {"input_ids": rng.randint(0, 50304, size=(batch_size, seq))
         .astype(np.int32)}
for _ in range(2):
    loss = engine.train_batch(batch)
float(jax.device_get(loss))

d = "/tmp/bwdtrace"
shutil.rmtree(d, ignore_errors=True)
N = 3
with jax.profiler.trace(d):
    for _ in range(N):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))

agg = collections.Counter()
cnt = collections.Counter()
for f in glob.glob(d + "/**/*.trace.json.gz", recursive=True):
    for e in json.loads(gzip.open(f).read())["traceEvents"]:
        if e.get("ph") == "X" and "dur" in e and not e["name"].startswith(
                ("$", "jit_", "while", "np.", "PjitF", "Device", "copy-")):
            agg[e["name"]] += e["dur"]
            cnt[e["name"]] += 1
total = sum(agg.values())
print(f"device total {total / N / 1000:.1f} ms/step over {N} steps")
for name, us in agg.most_common(30):
    print(f"{us / N / 1000:8.2f} ms/step x{cnt[name] // N:4d}  {name[:95]}")
