"""1-bit compressed allreduce benchmark — reference tests/onebit/
test_nccl_perf.py role, on a forced multi-device CPU mesh (or a real TPU
slice when available).

Run directly: python tests/perf/compression_bench.py [numel]
"""

import functools
import os
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def main(numel=8_388_608):
    import numpy as np
    import jax
    if "xla_force_host_platform_device_count" in \
            os.environ.get("XLA_FLAGS", ""):
        # XLA_FLAGS must be set at process start; the platform switch must
        # happen through jax.config BEFORE first device use
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from deepspeed_tpu.parallel import compression as comp
    from deepspeed_tpu.parallel.mesh import shard_map

    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    buf = jnp.zeros((n, numel), jnp.float32) + 0.01
    we = jnp.zeros((n, numel), jnp.float32)
    se = jnp.zeros((n, numel // n), jnp.float32)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P("data"),) * 3,
                       out_specs=(P("data"),) * 3)
    def run(b, w, s):
        o, w2, s2 = comp.compressed_allreduce(b[0], w[0], s[0], "data")
        return o[None], w2[None], s2[None]

    o, we, se = run(buf, we, se)
    jax.block_until_ready(o)
    t0 = time.perf_counter()
    for _ in range(10):
        o, we, se = run(buf, we, se)
    jax.block_until_ready(o)
    dt = (time.perf_counter() - t0) / 10
    print(f"1-bit allreduce {numel/1e6:.0f}M floats on {n} devices: "
          f"{dt*1e3:.1f} ms ({numel*4/dt/1e9:.2f} GB/s equivalent dense)")


if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # re-exec with the multi-device CPU env (XLA_FLAGS is read at
        # interpreter start)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        os.execve(sys.executable, [sys.executable, __file__] + sys.argv[1:],
                  env)
    main(*(int(a) for a in sys.argv[1:]))
