"""Link-aware ZeRO-3 prefetch stream benchmark (ISSUE 16 acceptance:
modeled inter-host bytes drop >= 2x with the compressed slow hop on a
2 x (n/2) synthetic split).

Three stage-3 prefetch engine variants over the same tiny GPT-2 and
batch on one mesh, data axis split by the synthetic slow-axis override:

  flat        no ``comm.hierarchy`` block — the pre-ISSUE-16 stream:
              flat single-ring gathers and reduce-scatters, every hop
              pays the full fp32 payload on whatever link it crosses
  hier_exact  hierarchy on, compression "never" — every gather and
              grad leg rescheduled two-level (ONE inter hop per chunk),
              numerically a pure partial-sum reorder (the trajectory
              parity floor and the fair step-time baseline)
  hier_comp   hierarchy on, compression "always" — the grad
              reduce-scatter legs additionally carry error-compensated
              sign bits across the slow hop

The headline is ``inter_bytes_reduction``: modeled slow-hop bytes of
the FLAT single-ring schedule over the two-level compressed schedule
(``inter_uncompressed / inter`` from the trace-time cost model behind
the ``comm/bytes_on_wire/*`` counters — exact, because the prefetch
plan and per-leg policy are static; NOTE the denominator semantics
differ from onebit_comm's, see docs/observability.md). Step times ride
along; on this CPU-emulated mesh every "link" is a memcpy, so the
wire-byte ledger is the portable result and the step-time ratio is
harness calibration (real multi-host slices derive the split from
process boundaries — that path is pinned by tests/
test_multiprocess_dist.py::test_stage3_prefetch_hierarchy_two_processes).
Prints one JSON object.

Run directly: python tests/perf/zero3_hier_bench.py [n_embd] [n_layer]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def _build_engine(n, n_embd, n_layer, comm=None):
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    model = GPT2LMHeadModel(GPT2Config(
        vocab_size=512, n_positions=64, n_embd=n_embd, n_layer=n_layer,
        n_head=2, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=True))
    cfg = {
        "train_batch_size": n,
        "steps_per_print": 10**9,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        # persistence 0: every leaf rides the gather stream, so the
        # cost model covers the whole parameter volume
        "zero_optimization": {"stage": 3, "stage3_prefetch": True,
                              "stage3_prefetch_gather": "ring",
                              "stage3_param_persistence_threshold": 0},
    }
    if comm is not None:
        cfg["comm"] = comm
    mesh = make_mesh(MeshConfig(data=n), devices=jax.devices())
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model,
                                       mesh=mesh)
    return engine


def run_zero3_hier_bench(n_embd=128, n_layer=4, steps=8):
    import numpy as np
    import jax

    n = len(jax.devices())
    assert n >= 4 and n % 2 == 0, f"need an even mesh >= 4, got {n}"
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 512, (n, 64)).astype(np.int32)}

    variants = {
        "flat": None,
        "hier_exact": {"hierarchy": {"slow_axis": 2,
                                     "compression": "never"}},
        "hier_comp": {"hierarchy": {"slow_axis": 2,
                                    "compression": "always"}},
    }
    result = {"devices": n, "split": f"2x{n // 2} (synthetic slow axis)",
              "n_embd": n_embd, "n_layer": n_layer,
              "step_time_s": {}, "final_loss": {}, "wire_model": {}}
    for name, comm in variants.items():
        engine = _build_engine(n, n_embd, n_layer, comm=comm)
        for _ in range(2):   # compile + settle before the clock
            loss = engine.train_batch(batch)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch)
        jax.block_until_ready(loss)
        result["step_time_s"][name] = round(
            (time.perf_counter() - t0) / steps, 6)
        result["final_loss"][name] = round(float(loss), 6)
        wire = getattr(engine, "_pf_wire_model", None)
        if wire is not None:
            result["wire_model"][name] = {k: int(v)
                                          for k, v in wire.items()}
        if name == "hier_comp":
            result["counters"] = {
                k: int(v) for k, v in engine.telemetry.snapshot(
                    "comm/")["counters"].items()}
        del engine
        jax.clear_caches()

    # the headline: FLAT single-ring slow-hop bytes over the two-level
    # compressed schedule's — per step per device, static cost model
    comp = result["wire_model"]["hier_comp"]
    result["inter_bytes_reduction"] = round(
        comp["inter_uncompressed"] / comp["inter"], 3)
    # schedule-only share of the win (no compression), for calibration
    exact = result["wire_model"]["hier_exact"]
    result["inter_bytes_reduction_exact"] = round(
        exact["inter_uncompressed"] / exact["inter"], 3)
    result["hier_vs_flat_step_time"] = round(
        result["step_time_s"]["flat"]
        / result["step_time_s"]["hier_comp"], 3)
    return result


def main(n_embd=128, n_layer=4):
    import jax
    if "xla_force_host_platform_device_count" in \
            os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_zero3_hier_bench(n_embd=n_embd,
                                          n_layer=n_layer), indent=2))


if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # re-exec with the multi-device CPU env (XLA_FLAGS is read at
        # interpreter start)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        os.execve(sys.executable, [sys.executable, __file__] + sys.argv[1:],
                  env)
    main(*(int(a) for a in sys.argv[1:]))
