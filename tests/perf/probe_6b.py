"""Probe remote-host pinned_host capacity, tunnel h2d BW, disk speed."""
import time
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec
from jax.sharding import Mesh

dev = jax.devices()[0]
print("mems:", [m.kind for m in dev.addressable_memories()])
mesh = Mesh(np.array([dev]), ("d",))
host_sh = NamedSharding(mesh, PartitionSpec(), memory_kind="pinned_host")
dev_sh = NamedSharding(mesh, PartitionSpec(), memory_kind="device")

# pinned_host capacity: allocate 4 GB chunks up to 120 GB
held = []
try:
    for i in range(30):
        a = jax.jit(lambda: jnp.zeros((1 << 30,), jnp.float32),
                    out_shardings=host_sh)()
        a.block_until_ready()
        held.append(a)
        print(f"pinned_host alloc: {(i + 1) * 4} GB ok", flush=True)
except Exception as e:
    print("pinned_host cap hit:", str(e)[:160])
for a in held:
    a.delete()
held = None

# tunnel h2d: device_put 1 GB from local numpy
x = np.ones((1 << 28,), np.float32)  # 1 GB
t0 = time.perf_counter()
d = jax.device_put(x, dev_sh)
d.block_until_ready()
t1 = time.perf_counter()
print(f"client->device 1GB: {1.0 / (t1 - t0):.2f} GB/s")
# d2h
t0 = time.perf_counter()
_ = np.asarray(d)
print(f"device->client 1GB: {1.0 / (time.perf_counter() - t0):.2f} GB/s")
d.delete()

# pinned_host <-> device DMA (remote-host link)
h = jax.jit(lambda: jnp.zeros((1 << 28,), jnp.float32),
            out_shardings=host_sh)()
h.block_until_ready()
mv = jax.jit(lambda a: a + 1.0, out_shardings=dev_sh)
r = mv(h); r.block_until_ready()
t0 = time.perf_counter()
r2 = mv(h); r2.block_until_ready()
print(f"pinned_host->HBM 1GB (jit add): {1.0 / (time.perf_counter() - t0):.2f} GB/s")
