"""ZeRO-3 layer-wise parameter-gather prefetch benchmark — step time
across gather modes (ISSUE 3 acceptance: prefetch on >= off; ISSUE 8
acceptance: ``fused_matmul`` >= 1.1x over ring-mode prefetch with equal
losses, exposure breakdown recorded).

Three engine variants over the same GPT-2 model/batch:

  fused_gspmd   stage 3, stage3_prefetch=False — every per-layer gather
                implicit (a sharding constraint), XLA schedules freely
  ring          stage3_prefetch=True, gather="ring" — the explicit
                double-buffered per-layer packed gather pipeline
                (parallel/prefetch.py)
  fused_matmul  gather="fused_matmul" (ISSUE 8) — the layer's dominant
                projection weights skip the packed full-param buffer
                and stream chunk-by-chunk through the tile-granular
                fused all-gather+matmul / matmul+reduce-scatter path
                (ops/pallas/fused_collective.py; the lax decomposed
                ring on this CPU harness, the pallas kernels on TPU)

Exposure breakdown (gather-wait vs compute): with T_comm the timing of
a standalone comm-only program replaying ring mode's per-step
collective stream (per layer: forward gather + backward re-gather +
grad reduce-scatter of the packed sharded-leaf buffer), and the
fused_gspmd step as the compute proxy (XLA's own schedule of the
IDENTICAL computation — the floor the explicit pipelines chase; a
replicated-params engine is NOT usable as the proxy here because its
whole-gradient allreduce dwarfs the sharded exchanges),

  exposed(mode) = step(mode) - step(fused_gspmd)    # comm NOT hidden
  hidden(mode)  = T_comm - exposed(mode)            # comm overlapped

both clamped at 0 and recorded as ``comm/zero3_prefetch_<mode>/
{exposed,hidden}_s`` counters in the telemetry registry (ISSUE 8
satellite). On the CPU-emulated mesh the collectives are memcpy-bound
and the 8 virtual devices timeshare the host cores, so the numbers
calibrate plumbing overhead + copy elision (fused_matmul's win here is
skipping the pack/moveaxis/unpack of the packed buffer and never
materializing full weights or weight grads), not real ICI overlap —
run on a TPU slice for the true overlap win. Prints one JSON object.

Run directly: python tests/perf/prefetch_bench.py [n_embd] [n_layer]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def _build_engine(model_cfg, n, batch_size, gather, threshold=0):
    import jax
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = {
        "train_batch_size": batch_size,
        "gradient_accumulation_steps": 1,
        "steps_per_print": 10**9,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "zero_optimization": {
            "stage": 3,
            "stage3_prefetch": gather is not None,
            "stage3_prefetch_gather": gather or "ring",
            "collective_matmul": {"backend": "auto"},
            "stage3_param_persistence_threshold": threshold},
    }
    mesh = make_mesh(MeshConfig(data=n), devices=jax.devices())
    engine, _, _, _ = dstpu.initialize(
        config=cfg, model=GPT2LMHeadModel(model_cfg), mesh=mesh)
    return engine


def _time_comm_stream(engine, steps):
    """Standalone comm-only program: ring mode's per-step collective
    volume over the engine's ACTUAL sharded layer stack (per layer:
    2 packed gathers + 1 packed reduce-scatter), timed under the same
    virtual-device contention as the engines."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec
    from deepspeed_tpu.parallel import mesh as mesh_lib
    from deepspeed_tpu.parallel import overlap as overlap_lib

    mesh = engine.mesh
    axis = mesh_lib.DATA_AXIS
    n = mesh_lib.mesh_axis_size(mesh, axis)
    subtree = engine.module.prefetch_layer_subtree
    params = engine.state.params[subtree]
    spec_tree = engine.zero.param_specs(engine.state.params)[subtree]
    plan = engine.zero.explicit_shard_plan(params, specs=spec_tree)
    leaves = jax.tree_util.tree_leaves(params)
    spec_leaves = jax.tree_util.tree_leaves(
        spec_tree, is_leaf=lambda x: isinstance(x, PartitionSpec))
    sharded = [l for l, e in zip(leaves, plan) if e is not None]
    sharded_specs = [s for s, e in zip(spec_leaves, plan)
                     if e is not None]
    if not sharded:
        return 0.0
    L = sharded[0].shape[0]

    def comm_only(*stacks):
        total = jnp.float32(0.0)
        for l in range(L):
            flat = jnp.concatenate(
                [s[l].reshape(-1) for s in stacks]) if len(stacks) > 1 \
                else stacks[0][l].reshape(-1)
            g1 = overlap_lib.ring_all_gather(flat, axis, n)     # forward
            rs = overlap_lib.ring_reduce_scatter(g1, axis, n)   # grad RS
            # backward re-gather: data-depends on the RS so XLA cannot
            # CSE it with g1 (two identical pure gathers would collapse
            # into one and undercount the stream by a third)
            g2 = overlap_lib.ring_all_gather(flat + 0.0 * rs, axis, n)
            total = total + g2[0] + rs[0]
        return total

    # shard_map with the resting specs hands each device its local shard
    fn = jax.jit(mesh_lib.shard_map(
        comm_only, mesh=mesh,
        in_specs=tuple(sharded_specs),
        out_specs=PartitionSpec(), check_vma=False))
    fn(*sharded)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*sharded)
    jax.block_until_ready(out)  # sync-ok: bench timing fence
    return (time.perf_counter() - t0) / steps * 1e3


def run_prefetch_bench(n_embd=512, n_layer=8, seq=64, vocab=2048,
                       steps=6, batch_per_dev=1):
    import numpy as np
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config
    from deepspeed_tpu.telemetry.registry import (
        default_registry, record_comm_exposure)

    n = len(jax.devices())
    bs = batch_per_dev * n
    model_cfg = GPT2Config(vocab_size=vocab, n_positions=seq,
                           n_embd=n_embd, n_layer=n_layer,
                           n_head=max(2, n_embd // 64),
                           dtype=jnp.float32, param_dtype=jnp.float32,
                           scan_layers=True)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, vocab, size=(bs, seq))
             .astype(np.int32)}

    def time_steps(engine):
        engine.train_batch(batch)                       # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)  # sync-ok: bench fence
        return (time.perf_counter() - t0) / steps * 1e3, float(loss)

    result = {"devices": n, "n_embd": n_embd, "n_layer": n_layer,
              "seq": seq, "batch_per_dev": batch_per_dev,
              "step_ms": {}, "loss": {}}
    comm_stream_ms = None
    variants = (("fused_gspmd", None, 0),
                ("ring", "ring", 0),
                ("fused_matmul", "fused_matmul", 0))
    for name, gather, threshold in variants:
        engine = _build_engine(model_cfg, n, bs, gather, threshold)
        if gather is not None and threshold == 0:
            assert engine._prefetch_active(), \
                "prefetch pipeline did not activate on this mesh"
        ms, loss = time_steps(engine)
        if name == "fused_matmul":
            stats = engine.prefetch_live_param_stats()
            result["live_param_bytes"] = stats["live_param_bytes"]
            result["fused_leaves_per_layer"] = \
                stats["fused_leaves_per_layer"]
            result["fused_stream_bytes"] = stats["fused_stream_bytes"]
        if name == "ring":
            stats = engine.prefetch_live_param_stats()
            result["per_layer_gather_bytes"] = \
                stats["per_layer_gather_bytes"]
            comm_stream_ms = _time_comm_stream(engine, steps)
        result["step_ms"][name] = round(ms, 3)
        result["loss"][name] = round(loss, 6)
        del engine
        jax.clear_caches()

    result["prefetch_speedup"] = round(
        result["step_ms"]["fused_gspmd"] / result["step_ms"]["ring"], 3)
    result["fused_vs_ring"] = round(
        result["step_ms"]["ring"] / result["step_ms"]["fused_matmul"], 3)
    # gather-wait vs compute decomposition (see module docstring) —
    # recorded as per-site telemetry counters and echoed in the JSON
    compute_ms = result["step_ms"]["fused_gspmd"]
    result["exposure"] = {"comm_stream_ms": round(comm_stream_ms or 0.0, 3),
                          "compute_proxy_ms": compute_ms}
    for mode in ("ring", "fused_matmul"):
        exposed = max(0.0, result["step_ms"][mode] - compute_ms)
        hidden = max(0.0, (comm_stream_ms or 0.0) - exposed)
        record_comm_exposure(f"zero3_prefetch_{mode}",
                             exposed / 1e3, hidden / 1e3)
        result["exposure"][mode] = {"exposed_comm_ms": round(exposed, 3),
                                    "hidden_comm_ms": round(hidden, 3)}
    result["telemetry_counters"] = {
        k: round(v, 6) for k, v in
        default_registry().snapshot(prefix="comm/")["counters"].items()}
    return result


def main(n_embd=512, n_layer=8):
    import jax
    if "xla_force_host_platform_device_count" in \
            os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_prefetch_bench(n_embd=n_embd, n_layer=n_layer),
                     indent=2))


if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # re-exec with the multi-device CPU env (XLA_FLAGS is read at
        # interpreter start)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        os.execve(sys.executable, [sys.executable, __file__] + sys.argv[1:],
                  env)
    main(*(int(a) for a in sys.argv[1:]))
