"""ZeRO-3 layer-wise parameter-gather prefetch benchmark — step time
with ``stage3_prefetch`` on vs. off (ISSUE 3 acceptance: prefetch on
>= off, measured on a >1-device mesh; CPU device emulation acceptable
as the step-time proxy for the single-chip bench harness).

Two engine variants over the same GPT-2 model/batch:

  fused_gspmd  stage 3, stage3_prefetch=False — every per-layer gather
               implicit (a sharding constraint), XLA schedules freely
  prefetch     stage 3, stage3_prefetch=True  — the explicit
               double-buffered per-layer gather pipeline
               (parallel/prefetch.py), backward re-gather interleaved
               with the per-layer grad reduce-scatter

On the CPU-emulated mesh the collectives are memcpy-bound, so the
numbers calibrate plumbing overhead (per-layer pack/unpack, ring hop
count, the one redundant edge gather per scan), not real ICI overlap —
run on a TPU slice for the actual overlap win. Prints one JSON object.

Run directly: python tests/perf/prefetch_bench.py [n_embd] [n_layer]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def run_prefetch_bench(n_embd=256, n_layer=8, seq=128, vocab=2048,
                       steps=8, mode="ring"):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    n = len(jax.devices())
    model_cfg = GPT2Config(vocab_size=vocab, n_positions=seq, n_embd=n_embd,
                           n_layer=n_layer, n_head=max(2, n_embd // 64),
                           dtype=jnp.float32, param_dtype=jnp.float32,
                           scan_layers=True)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, vocab, size=(2 * n, seq))
             .astype(np.int32)}

    def build(prefetch_on):
        cfg = {
            "train_batch_size": 2 * n,
            "gradient_accumulation_steps": 1,
            "steps_per_print": 10**9,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {
                "stage": 3, "stage3_prefetch": prefetch_on,
                "stage3_prefetch_gather": mode,
                "stage3_param_persistence_threshold": 0},
        }
        mesh = make_mesh(MeshConfig(data=n), devices=jax.devices())
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=GPT2LMHeadModel(model_cfg), mesh=mesh)
        return engine

    def time_steps(engine):
        engine.train_batch(batch)                       # compile + warm
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch)
        jax.block_until_ready(engine.state.params)
        return (time.perf_counter() - t0) / steps * 1e3, float(loss)

    result = {"devices": n, "n_embd": n_embd, "n_layer": n_layer,
              "seq": seq, "gather_mode": mode, "step_ms": {}, "loss": {}}
    for name, on in (("fused_gspmd", False), ("prefetch", True)):
        engine = build(on)
        if on:
            assert engine._prefetch_active(), \
                "prefetch pipeline did not activate on this mesh"
        ms, loss = time_steps(engine)
        if on:
            stats = engine.prefetch_live_param_stats()
            result["live_param_bytes"] = stats["live_param_bytes"]
            result["per_layer_gather_bytes"] = \
                stats["per_layer_gather_bytes"]
        result["step_ms"][name] = round(ms, 3)
        result["loss"][name] = round(loss, 6)
        del engine
        jax.clear_caches()
    result["prefetch_speedup"] = round(
        result["step_ms"]["fused_gspmd"] / result["step_ms"]["prefetch"], 3)
    return result


def main(n_embd=256, n_layer=8):
    import jax
    if "xla_force_host_platform_device_count" in \
            os.environ.get("XLA_FLAGS", ""):
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(run_prefetch_bench(n_embd=n_embd, n_layer=n_layer),
                     indent=2))


if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        # re-exec with the multi-device CPU env (XLA_FLAGS is read at
        # interpreter start)
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8")
        env.setdefault("JAX_PLATFORMS", "cpu")
        os.execve(sys.executable, [sys.executable, __file__] + sys.argv[1:],
                  env)
    main(*(int(a) for a in sys.argv[1:]))
