import re
import numpy as np
import jax
import jax.numpy as jnp
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import (
    convert_gpt2_params, _fast_decode_scan_fn)

ctx = 2048
cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                 n_layer=36, n_head=20, dtype=jnp.bfloat16,
                 param_dtype=jnp.bfloat16, scan_layers=True)
prompt = np.zeros((1, 8), np.int32)
params = jax.eval_shape(
    lambda k: GPT2LMHeadModel(cfg).init(k, prompt),
    jax.random.PRNGKey(0))["params"]
params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)
iparams = convert_gpt2_params(params, cfg)
model_p = {"wte": iparams["wte"], "wpe": iparams["wpe"],
           "ln_f": iparams["ln_f"]}
blk = iparams["h"]["blk"]
B, H, D, Lyr = 1, 20, 64, 36
kc = jnp.zeros((Lyr, B, H, ctx, D), jnp.bfloat16)
vc = jnp.zeros((Lyr, B, H, ctx, D), jnp.bfloat16)
fast = _fast_decode_scan_fn(cfg, ctx, weights_q8=False, cache_q8=False)
lowered = fast.lower(model_p, blk, (kc, vc), jnp.zeros((B,), jnp.int32),
                     35, jnp.asarray(400, jnp.int32),
                     jax.random.split(jax.random.PRNGKey(0), 35),
                     jnp.float32(0.0))
txt = lowered.compile().as_text()
open("/tmp/b1_hlo.txt", "w").write(txt)
for pat in (r"%fusion\.1(19|20|21) = [^)]*", r"%copy\.(8|9|19|20) = [^)]*"):
    for m in re.findall("(" + pat + ")", txt):
        print(m[0][:220]); print()
