"""Headline-MFU experiment harness: one 774M config per invocation.

Usage: python tests/perf/mfu_sweep.py [bs] [policy] [loss_chunk] [flags...]
Flags: param_bf16 (store params in bf16; fp32 master lives in the
optimizer), gas2 (gradient accumulation 2).
Prints one JSON line with step time + MFU so sweeps are scriptable.
"""

import json
import sys
import time

import numpy as np


def main():
    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    policy = sys.argv[2] if len(sys.argv) > 2 else "dots_flash_fc_lean"
    loss_chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    flags = set(sys.argv[4:])

    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from bench import model_flops_per_token, peak_flops, _enable_compile_cache
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    _enable_compile_cache()
    dev = jax.devices()[0]
    mesh = make_mesh(MeshConfig(data=1), devices=[dev])
    seq = 1024
    model_cfg = GPT2Config(
        vocab_size=50304, n_positions=seq, n_embd=1280, n_layer=36,
        n_head=20, dtype=jnp.bfloat16,
        param_dtype=jnp.bfloat16 if "param_bf16" in flags else jnp.float32,
        scan_layers=True, remat=True,
        remat_policy=None if policy == "none" else policy,
        scan_unroll=4 if "unroll4" in flags else (2 if "unroll2" in flags else 1),
        loss_chunk=loss_chunk)
    cfg = {
        "train_batch_size": bs,
        "gradient_accumulation_steps": 2 if "gas2" in flags else 1,
        "zero_optimization": {"stage": 3},
        "bf16": {"enabled": True},
        "data_types": {"grad_dtype": "bf16"},
        "gradient_clipping": 1.0,
        "optimizer": {"type": "AdamW",
                      "params": {"lr": 1e-4, "weight_decay": 0.01,
                                 "moment_dtype": "bf16"}},
        "steps_per_print": 1000,
    }
    engine, _, _, _ = dstpu.initialize(
        config=cfg, model=GPT2LMHeadModel(model_cfg), mesh=mesh)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 50304, size=(bs, seq))
             .astype(np.int32)}
    t0 = time.perf_counter()
    for _ in range(2):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))
    compile_s = time.perf_counter() - t0
    iters = 12
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = engine.train_batch(batch)
        float(jax.device_get(loss))
        best = min(best, (time.perf_counter() - t0) / iters)
    flops = model_flops_per_token(model_cfg) * bs * seq
    mfu = flops / best / peak_flops(dev)
    print(json.dumps({
        "bs": bs, "policy": policy, "loss_chunk": loss_chunk,
        "flags": sorted(flags), "step_ms": round(best * 1000, 2),
        "mfu_pct": round(mfu * 100, 2), "compile_s": round(compile_s, 1),
        "loss": float(jax.device_get(loss))}))


if __name__ == "__main__":
    main()
