"""b1/ctx2048 int8+int8kv fused decode tok/s (headline int8 case)."""
import time
import numpy as np
import jax
import jax.numpy as jnp
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import (
    generate, convert_gpt2_params, quantize_gpt2_inference_params)

ctx = 2048
cfg = GPT2Config(vocab_size=50304, n_positions=ctx, n_embd=1280,
                 n_layer=36, n_head=20, dtype=jnp.bfloat16,
                 param_dtype=jnp.bfloat16, scan_layers=True)
rng = np.random.RandomState(0)
prompt = rng.randint(0, 50304, size=(1, ctx - 80)).astype(np.int32)
params = jax.jit(GPT2LMHeadModel(cfg).init)(
    jax.random.PRNGKey(0), prompt[:, :8])["params"]
qparams = quantize_gpt2_inference_params(convert_gpt2_params(params, cfg))

def run(new):
    toks = generate(cfg, qparams, prompt, max_new_tokens=new,
                    max_out_tokens=ctx, scan_decode=True,
                    quantize_bits=8, kv_cache_bits=8)
    return float(jax.device_get(toks[0, -1]))

run(4); run(68)
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter(); run(4); ts = time.perf_counter() - t0
    t0 = time.perf_counter(); run(68); tl = time.perf_counter() - t0
    best = min(best, tl - ts)
print(f"b1/ctx2048 int8: {64 / best:.1f} tok/s ({best * 1000 / 64:.2f} ms/tok)")
