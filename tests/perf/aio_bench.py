"""Async-IO throughput harness — the role of the reference's aio perf suite
(csrc/aio/py_test/: ds_aio_basic.py sweep of block size / queue depth /
submit mode against libaio).

Measures MB/s for write + read of a tensor-sized file through each backend
(io_uring ring vs pread/pwrite thread pool) across queue depths and block
sizes. Run directly for the sweep table, or import `quick_throughput` for
the single-point number bench.py reports.

Usage: python tests/perf/aio_bench.py [--mb 512] [--dir /tmp]
"""

import argparse
import json
import os
import tempfile
import time

import numpy as np


def _run_case(handle, arr, path, write_first=True):
    """One write+read pass; returns (write_mbps, read_mbps). The file's
    pages are dropped from the page cache between write and read (fsync
    makes them clean, fadvise evicts) so read_mbps measures the device,
    not memcpy out of cache."""
    nbytes = arr.nbytes
    fd = handle.open(path, True)
    t0 = time.perf_counter()
    handle.async_pwrite(arr, fd)
    handle.wait()
    os.fsync(fd)
    wt = time.perf_counter() - t0
    os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
    handle.close(fd)

    out = np.empty_like(arr)
    fd = handle.open(path, False)
    t0 = time.perf_counter()
    handle.async_pread(out, fd)
    handle.wait()
    rt = time.perf_counter() - t0
    handle.close(fd)
    assert np.array_equal(arr, out), "aio roundtrip corrupted data"
    return nbytes / wt / 2**20, nbytes / rt / 2**20


def quick_throughput(mb=256, directory=None, queue_depth=32,
                     block_size=1 << 20, trials=3):
    """Pinned-methodology MB/s point for bench.py.

    Round-3 postmortem: a single write+read pass is measuring LUCK on a
    virtualized disk — the guest-side fadvise(DONTNEED) drops the guest
    page cache but cannot touch the virtio host's cache, so one-shot read
    numbers swing 20x (43.9 vs 950 MB/s across r3 runs) with host-cache
    state. Two pinned numbers instead:

    - ``read_mbps`` / ``write_mbps``: MEDIAN of ``trials`` passes — the
      steady-state tier. This is the number the swap tier actually sees:
      ZeRO-Infinity re-reads the same optimizer-state files every step,
      so steady-state (host-cache-assisted) behavior is the
      representative regime, not an anomaly.
    - ``first_read_mbps``: the cold first pass, reported separately (the
      restart/first-touch case).
    - ``o_direct``: the same point through the O_DIRECT alignment layer
      (ISSUE 20) — no page cache in the path at all, so first ≈ steady
      by construction and the numbers are device truth on both legs.

    All knob values ride along so the number is reproducible. Returns
    None if the native lib is unavailable.
    """
    try:
        from deepspeed_tpu.ops.native.aio import (
            AsyncIOHandle, aligned_empty, o_direct_fallback_latched)
        handle = AsyncIOHandle(block_size=block_size, queue_depth=queue_depth,
                               thread_count=4)
    except Exception:
        return None
    arr = np.random.randint(0, 255, size=mb << 20, dtype=np.uint8)
    path = tempfile.mktemp(dir=directory, suffix=".aio")
    try:
        ws, rs = [], []
        for _ in range(trials):
            w, r = _run_case(handle, arr, path)
            ws.append(w)
            rs.append(r)
        dws, drs = [], []
        dhandle = AsyncIOHandle(block_size=block_size,
                                queue_depth=queue_depth,
                                thread_count=4, o_direct=True)
        darr = aligned_empty(arr.nbytes)    # page-aligned: zero-copy leg
        darr[:] = arr
        for _ in range(trials):
            w, r = _run_case(dhandle, darr, path)
            dws.append(w)
            drs.append(r)
        return {"backend": handle.backend,
                "write_mbps": round(float(np.median(ws)), 1),
                "read_mbps": round(float(np.median(rs)), 1),
                "first_read_mbps": round(rs[0], 1),
                "o_direct": {
                    "write_mbps": round(float(np.median(dws)), 1),
                    "read_mbps": round(float(np.median(drs)), 1),
                    "first_read_mbps": round(drs[0], 1),
                    "fallback_latched": o_direct_fallback_latched(),
                },
                "mb": mb, "trials": trials,
                "queue_depth": queue_depth,
                "block_kb": block_size >> 10,
                "cache_note": "guest page cache dropped (fsync+fadvise) "
                              "each pass; virtio host cache uncontrollable "
                              "from the guest — median == steady-state "
                              "(the swap tier's every-step re-read regime); "
                              "the o_direct point bypasses the guest cache "
                              "entirely (honest first-touch == steady)"}
    finally:
        if os.path.exists(path):
            os.unlink(path)


def sweep(mb, directory):
    from deepspeed_tpu.ops.native.aio import AsyncIOHandle
    arr = np.random.randint(0, 255, size=mb << 20, dtype=np.uint8)
    rows = []
    for backend in ("io_uring", "threads"):
        for queue_depth in (4, 16, 64):
            for block_kb in (256, 1024, 4096):
                try:
                    handle = AsyncIOHandle(block_size=block_kb << 10,
                                           queue_depth=queue_depth,
                                           thread_count=4, backend=backend)
                except OSError:
                    continue  # io_uring unsupported here
                path = tempfile.mktemp(dir=directory, suffix=".aio")
                try:
                    w, r = _run_case(handle, arr, path)
                finally:
                    if os.path.exists(path):
                        os.unlink(path)
                rows.append({"backend": backend, "queue_depth": queue_depth,
                             "block_kb": block_kb, "write_mbps": round(w, 1),
                             "read_mbps": round(r, 1)})
                print(json.dumps(rows[-1]))
    best = max(rows, key=lambda x: x["read_mbps"])
    print(json.dumps({"best": best, "mb": mb}))
    return rows


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=512)
    ap.add_argument("--dir", default=None)
    args = ap.parse_args()
    sweep(args.mb, args.dir)
