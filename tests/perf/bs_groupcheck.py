"""Measure grouped-row block-sparse vs dense flash on the real chip."""
import sys
sys.path.insert(0, "/root/repo")
import bench
import jax.numpy as jnp
out = bench.bench_sparse_attention(jnp)
import json
print(json.dumps(out, indent=1))
