"""Continuous batching vs static-batch serving throughput.

Workload: N requests with MIXED prompt lengths and mixed decode budgets,
arriving on a Poisson clock (exponential interarrivals at a rate that
keeps the queue saturated — the benchmark measures throughput, not an
idle arrival tail). Both systems serve the identical request trace:

- **continuous** (deepspeed_tpu/serving): slot scheduler + paged KV
  cache; a request admits the moment a slot and pages free up, so the
  chip never decodes padding for a finished request.
- **static baseline** (`models/gpt2_inference.generate`): requests gang
  into batches of ``slots`` in arrival order; every gang pads its
  prompts to the longest member and decodes the gang-max new-token
  budget before ANY member of the next gang starts — the cost model of
  the one-static-batch-per-call path. (Its outputs for the shorter
  members would additionally be wrong — right-padded prompts shift
  logits, the static path has no left-pad masking — so the baseline is
  charged only for its TIME, which is generous to it.)

Speedup = continuous requests/sec over static requests/sec; the mixed
decode budgets are where static batching bleeds (every short request
pays the gang's longest budget).

Run: ``python tests/perf/serving_bench.py`` (CPU ok; prints JSON).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def _workload(rs, n_requests, prompt_lens, new_tokens, rate):
    """Poisson arrival trace over mixed lengths/budgets."""
    lens = rs.choice(prompt_lens, size=n_requests)
    news = rs.choice(new_tokens, size=n_requests)
    arrivals = np.cumsum(rs.exponential(1.0 / rate, size=n_requests))
    arrivals -= arrivals[0]            # first request is already queued
    return lens, news, arrivals


def run_serving_bench(n_requests=32, slots=4, seed=0,
                      prompt_lens=(8, 16, 32, 48),
                      new_tokens=(2, 4, 8, 96), rate=400.0,
                      page_size=32, max_pages_per_slot=5,
                      kv_cache_bits=0, model_cfg=None, params=None,
                      warm=True):
    """Returns {continuous: {...}, static: {...}, speedup_requests_per_sec}.

    ``model_cfg``/``params`` default to a small fp32 GPT-2 sized for CPU
    runs; pass a real config + converted params to measure on-chip."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_inference import generate
    import deepspeed_tpu.serving as serving

    rs = np.random.RandomState(seed)
    if model_cfg is None:
        # big enough that per-step MODEL compute (not interpret-mode /
        # dispatch constants) is what both systems spend their time on —
        # the regime the comparison is about
        model_cfg = GPT2Config(
            vocab_size=2048, n_positions=512, n_embd=256, n_layer=6,
            n_head=8, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True)
    if params is None:
        params = jax.jit(GPT2LMHeadModel(model_cfg).init)(
            jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]

    lens, news, arrivals = _workload(rs, n_requests, prompt_lens,
                                     new_tokens, rate)
    prompts = [rs.randint(0, model_cfg.vocab_size,
                          size=(s,)).astype(np.int32) for s in lens]
    total_new = int(news.sum())

    def make_requests():
        return [serving.Request(i, prompts[i], max_new_tokens=int(news[i]),
                                arrival_time=float(arrivals[i]))
                for i in range(n_requests)]

    # ONE adapter for every window: compiled tick/prefill programs live
    # on the adapter, so fresh engines per window (clean scheduler/pool
    # state) still replay warm executables — a long-lived server's
    # steady state, which is what the benchmark measures
    shared = serving.build_engine(
        "gpt2", model_cfg, params,
        config={"serving": {"slots": slots, "page_size": page_size,
                            "max_pages_per_slot": max_pages_per_slot,
                            "kv_cache_bits": kv_cache_bits}})

    # watchdog rides the measured engine (ISSUE 6): TTFT-blowup /
    # pool-exhaustion trips surface in the snapshot next to the TTFT
    # percentiles, so the bench record says whether the run was clean.
    # One dump SUBDIR per window: each window's Watchdog restarts its
    # dump_id at 1, so a shared dir would overwrite an earlier window's
    # incident with a later one's
    import tempfile
    from deepspeed_tpu.telemetry.anomaly import Watchdog
    wd_dump_dir = tempfile.mkdtemp(prefix="dstpu_flight_serving_")
    wd_window = [0]

    def run_continuous():
        wd_window[0] += 1
        eng = serving.ContinuousBatcher(
            shared.adapter,
            watchdog=Watchdog(
                os.path.join(wd_dump_dir, f"window{wd_window[0]}"),
                source="serving"))
        t0 = time.monotonic()
        res = eng.serve(make_requests(), respect_arrival_times=True)
        dt = time.monotonic() - t0
        assert len(res) == n_requests
        return dt, eng.stats, eng.metrics_snapshot()

    # one cache length for every static gang → one compiled decode_scan
    max_out = int(np.max(lens)) + int(news.max())
    max_out = min(model_cfg.n_positions, -(-max_out // 64) * 64)

    def run_static():
        # gangs in arrival order; a gang launches once its LAST member
        # has arrived (static batching gathers a full batch first)
        order = np.argsort(arrivals, kind="stable")
        t0 = time.monotonic()
        for g in range(0, n_requests, slots):
            gang = order[g:g + slots]
            gate = float(arrivals[gang].max())
            while time.monotonic() - t0 < gate:
                time.sleep(min(gate - (time.monotonic() - t0), 0.02))
            S = int(max(lens[i] for i in gang))
            batch = np.zeros((len(gang), S), np.int32)
            for row, i in enumerate(gang):
                batch[row, :lens[i]] = prompts[i]      # right-pad: the
                # static path's only option — and part of why it loses
            steps = int(max(news[i] for i in gang))
            toks = generate(model_cfg, params, batch, max_new_tokens=steps,
                            max_out_tokens=max_out)
            float(jax.device_get(toks[0, -1]))         # fence

        return time.monotonic() - t0

    if warm:
        # compile both systems outside the timed windows
        run_continuous()
        run_static()
    # best of three INTERLEAVED window pairs: the CPU/tunnel shows ±15%
    # run-to-run noise and the comparison should report the scheduler,
    # not which system a descheduling blip landed on (same rule as
    # bench.py's 3-window MFU)
    dt_c, stats, telemetry = run_continuous()
    dt_s = run_static()
    for _ in range(2):
        dt_c2, stats2, telemetry2 = run_continuous()
        if dt_c2 < dt_c:
            dt_c, stats, telemetry = dt_c2, stats2, telemetry2
        dt_s = min(dt_s, run_static())

    out = {
        "workload": {
            "n_requests": n_requests, "slots": slots,
            "prompt_lens": list(map(int, prompt_lens)),
            "new_tokens": list(map(int, new_tokens)),
            "total_decode_tokens": total_new,
            "poisson_rate_per_s": rate,
        },
        "continuous": {
            "requests_per_sec": round(n_requests / dt_c, 2),
            "decode_tokens_per_sec": round(total_new / dt_c, 1),
            "wall_s": round(dt_c, 3),
            "tick_dispatches": stats["ticks"],
            "tick_steps": stats["tick_steps"],
            "mean_slot_occupancy": round(
                stats["decode_tokens"] / max(stats["tick_steps"], 1), 2),
            # the serving engine's own metrics (TTFT, admission wait,
            # tick latency, page-pool occupancy HWM — the winning
            # window's snapshot)
            "telemetry": telemetry,
        },
        "static": {
            "requests_per_sec": round(n_requests / dt_s, 2),
            "decode_tokens_per_sec": round(total_new / dt_s, 1),
            "wall_s": round(dt_s, 3),
        },
        "speedup_requests_per_sec": round(dt_s / dt_c, 2),
    }
    return out


if __name__ == "__main__":
    print(json.dumps(run_serving_bench(), indent=1))
