"""Continuous batching vs static-batch serving throughput.

Workload: N requests with MIXED prompt lengths and mixed decode budgets,
arriving on a Poisson clock (exponential interarrivals at a rate that
keeps the queue saturated — the benchmark measures throughput, not an
idle arrival tail). Both systems serve the identical request trace:

- **continuous** (deepspeed_tpu/serving): slot scheduler + paged KV
  cache; a request admits the moment a slot and pages free up, so the
  chip never decodes padding for a finished request.
- **static baseline** (`models/gpt2_inference.generate`): requests gang
  into batches of ``slots`` in arrival order; every gang pads its
  prompts to the longest member and decodes the gang-max new-token
  budget before ANY member of the next gang starts — the cost model of
  the one-static-batch-per-call path. (Its outputs for the shorter
  members would additionally be wrong — right-padded prompts shift
  logits, the static path has no left-pad masking — so the baseline is
  charged only for its TIME, which is generous to it.)

Speedup = continuous requests/sec over static requests/sec; the mixed
decode budgets are where static batching bleeds (every short request
pays the gang's longest budget).

Run: ``python tests/perf/serving_bench.py`` (CPU ok; prints JSON).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))


def _workload(rs, n_requests, prompt_lens, new_tokens, rate):
    """Poisson arrival trace over mixed lengths/budgets."""
    lens = rs.choice(prompt_lens, size=n_requests)
    news = rs.choice(new_tokens, size=n_requests)
    arrivals = np.cumsum(rs.exponential(1.0 / rate, size=n_requests))
    arrivals -= arrivals[0]            # first request is already queued
    return lens, news, arrivals


def run_serving_bench(n_requests=32, slots=4, seed=0,
                      prompt_lens=(8, 16, 32, 48),
                      new_tokens=(2, 4, 8, 96), rate=400.0,
                      page_size=32, max_pages_per_slot=5,
                      kv_cache_bits=0, model_cfg=None, params=None,
                      warm=True):
    """Returns {continuous: {...}, static: {...}, speedup_requests_per_sec}.

    ``model_cfg``/``params`` default to a small fp32 GPT-2 sized for CPU
    runs; pass a real config + converted params to measure on-chip."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_inference import generate
    import deepspeed_tpu.serving as serving

    rs = np.random.RandomState(seed)
    if model_cfg is None:
        # big enough that per-step MODEL compute (not interpret-mode /
        # dispatch constants) is what both systems spend their time on —
        # the regime the comparison is about
        model_cfg = GPT2Config(
            vocab_size=2048, n_positions=512, n_embd=256, n_layer=6,
            n_head=8, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True)
    if params is None:
        params = jax.jit(GPT2LMHeadModel(model_cfg).init)(
            jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]

    lens, news, arrivals = _workload(rs, n_requests, prompt_lens,
                                     new_tokens, rate)
    prompts = [rs.randint(0, model_cfg.vocab_size,
                          size=(s,)).astype(np.int32) for s in lens]
    total_new = int(news.sum())

    def make_requests():
        return [serving.Request(i, prompts[i], max_new_tokens=int(news[i]),
                                arrival_time=float(arrivals[i]))
                for i in range(n_requests)]

    # ONE adapter for every window: compiled tick/prefill programs live
    # on the adapter, so fresh engines per window (clean scheduler/pool
    # state) still replay warm executables — a long-lived server's
    # steady state, which is what the benchmark measures
    shared = serving.build_engine(
        "gpt2", model_cfg, params,
        config={"serving": {"slots": slots, "page_size": page_size,
                            "max_pages_per_slot": max_pages_per_slot,
                            "kv_cache_bits": kv_cache_bits}})

    # watchdog rides the measured engine (ISSUE 6): TTFT-blowup /
    # pool-exhaustion trips surface in the snapshot next to the TTFT
    # percentiles, so the bench record says whether the run was clean.
    # One dump SUBDIR per window: each window's Watchdog restarts its
    # dump_id at 1, so a shared dir would overwrite an earlier window's
    # incident with a later one's
    import tempfile
    from deepspeed_tpu.telemetry.anomaly import Watchdog
    wd_dump_dir = tempfile.mkdtemp(prefix="dstpu_flight_serving_")
    wd_window = [0]

    def run_continuous():
        wd_window[0] += 1
        eng = serving.ContinuousBatcher(
            shared.adapter,
            watchdog=Watchdog(
                os.path.join(wd_dump_dir, f"window{wd_window[0]}"),
                source="serving"))
        t0 = time.monotonic()
        res = eng.serve(make_requests(), respect_arrival_times=True)
        dt = time.monotonic() - t0
        assert len(res) == n_requests
        return dt, eng.stats, eng.metrics_snapshot()

    # one cache length for every static gang → one compiled decode_scan
    max_out = int(np.max(lens)) + int(news.max())
    max_out = min(model_cfg.n_positions, -(-max_out // 64) * 64)

    def run_static():
        # gangs in arrival order; a gang launches once its LAST member
        # has arrived (static batching gathers a full batch first)
        order = np.argsort(arrivals, kind="stable")
        t0 = time.monotonic()
        for g in range(0, n_requests, slots):
            gang = order[g:g + slots]
            gate = float(arrivals[gang].max())
            while time.monotonic() - t0 < gate:
                time.sleep(min(gate - (time.monotonic() - t0), 0.02))
            S = int(max(lens[i] for i in gang))
            batch = np.zeros((len(gang), S), np.int32)
            for row, i in enumerate(gang):
                batch[row, :lens[i]] = prompts[i]      # right-pad: the
                # static path's only option — and part of why it loses
            steps = int(max(news[i] for i in gang))
            toks = generate(model_cfg, params, batch, max_new_tokens=steps,
                            max_out_tokens=max_out)
            float(jax.device_get(toks[0, -1]))         # fence

        return time.monotonic() - t0

    if warm:
        # compile both systems outside the timed windows
        run_continuous()
        run_static()
    # best of three INTERLEAVED window pairs: the CPU/tunnel shows ±15%
    # run-to-run noise and the comparison should report the scheduler,
    # not which system a descheduling blip landed on (same rule as
    # bench.py's 3-window MFU)
    dt_c, stats, telemetry = run_continuous()
    dt_s = run_static()
    for _ in range(2):
        dt_c2, stats2, telemetry2 = run_continuous()
        if dt_c2 < dt_c:
            dt_c, stats, telemetry = dt_c2, stats2, telemetry2
        dt_s = min(dt_s, run_static())

    out = {
        "workload": {
            "n_requests": n_requests, "slots": slots,
            "prompt_lens": list(map(int, prompt_lens)),
            "new_tokens": list(map(int, new_tokens)),
            "total_decode_tokens": total_new,
            "poisson_rate_per_s": rate,
        },
        "continuous": {
            "requests_per_sec": round(n_requests / dt_c, 2),
            "decode_tokens_per_sec": round(total_new / dt_c, 1),
            "wall_s": round(dt_c, 3),
            "tick_dispatches": stats["ticks"],
            "tick_steps": stats["tick_steps"],
            "mean_slot_occupancy": round(
                stats["decode_tokens"] / max(stats["tick_steps"], 1), 2),
            # the serving engine's own metrics (TTFT, admission wait,
            # tick latency, page-pool occupancy HWM — the winning
            # window's snapshot)
            "telemetry": telemetry,
        },
        "static": {
            "requests_per_sec": round(n_requests / dt_s, 2),
            "decode_tokens_per_sec": round(total_new / dt_s, 1),
            "wall_s": round(dt_s, 3),
        },
        "speedup_requests_per_sec": round(dt_s / dt_c, 2),
    }
    return out


def run_hot_prefix_bench(n_requests=16, slots=2, seed=0, sys_prompt_len=150,
                         unique_len=6, max_new=8, page_size=16,
                         max_pages_per_slot=16, model_cfg=None,
                         params=None):
    """Hot-prefix workload (ISSUE 9 satellite): N requests sharing an
    S-token system prompt (each with a short unique user suffix), served
    with the prefix cache OFF then ON. Records token-level
    prefix-hit-rate, pages-saved, and admission-to-first-token latency
    (TTFT — prefill is the dominant admission cost, and a prefix hit
    skips the shared span's compute entirely)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    import deepspeed_tpu.serving as serving

    rs = np.random.RandomState(seed)
    if model_cfg is None:
        model_cfg = GPT2Config(
            vocab_size=2048, n_positions=512, n_embd=256, n_layer=6,
            n_head=8, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True)
    if params is None:
        params = jax.jit(GPT2LMHeadModel(model_cfg).init)(
            jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    sys_prompt = rs.randint(0, model_cfg.vocab_size,
                            size=(sys_prompt_len,)).astype(np.int32)
    prompts = [np.concatenate([sys_prompt, rs.randint(
        0, model_cfg.vocab_size, size=(unique_len,)).astype(np.int32)])
        for _ in range(n_requests)]

    def make_requests():
        return [serving.Request(i, prompts[i], max_new_tokens=max_new)
                for i in range(n_requests)]

    def run(prefix_on):
        sv = {"slots": slots, "page_size": page_size,
              "max_pages_per_slot": max_pages_per_slot}
        if prefix_on:
            sv["prefix_cache"] = {}
        eng = serving.build_engine("gpt2", model_cfg, params,
                                   config={"serving": sv})
        # warm the compiled programs: the SECOND identical-prompt
        # request drives the prefix-hit path (COW copy + suffix
        # prefill), so the measured window replays warm executables
        eng_warm = serving.ContinuousBatcher(eng.adapter,
                                             prefix_cache=prefix_on)
        eng_warm.serve([serving.Request("w", prompts[0],
                                        max_new_tokens=max_new)])
        if prefix_on:
            eng_warm.serve([serving.Request("w2", prompts[1],
                                            max_new_tokens=max_new)])
        eng = serving.ContinuousBatcher(eng.adapter,
                                        prefix_cache=prefix_on)
        t0 = time.monotonic()
        res = eng.serve(make_requests())
        dt = time.monotonic() - t0
        assert len(res) == n_requests
        snap = eng.metrics_snapshot()
        return dt, res, snap

    dt_off, res_off, snap_off = run(False)
    dt_on, res_on, snap_on = run(True)
    # prefix sharing must not change outputs
    mismatches = sum(
        res_on[i].tokens().tolist() != res_off[i].tokens().tolist()
        for i in range(n_requests))
    return {
        "workload": {
            "n_requests": n_requests, "slots": slots,
            "sys_prompt_len": sys_prompt_len, "unique_len": unique_len,
            "max_new_tokens": max_new, "page_size": page_size,
        },
        "prefix_hit_rate": round(
            snap_on["prefix_cache"]["hit_rate"], 4),
        "pages_saved": snap_on["prefix_cache"]["pages_saved"],
        "cow_hits": snap_on["prefix_cache"].get("cow_hits", 0),
        "evictions": snap_on["prefix_cache"].get("evictions", 0),
        "token_mismatches": mismatches,
        # admission-to-first-token latency: the prefill skip is the win
        "ttft_p50_s_off": snap_off["ttft_s"].get("p50"),
        "ttft_p50_s_on": snap_on["ttft_s"].get("p50"),
        "ttft_p99_s_off": snap_off["ttft_s"].get("p99"),
        "ttft_p99_s_on": snap_on["ttft_s"].get("p99"),
        "wall_s_off": round(dt_off, 3),
        "wall_s_on": round(dt_on, 3),
        "wall_speedup": round(dt_off / dt_on, 2) if dt_on > 0 else None,
    }


def run_spec_decode_bench(seed=0, prompt_len=32, max_new=96,
                          spec_tokens=3, page_size=16,
                          max_pages_per_slot=16, kv_cache_bits=0,
                          model_cfg=None, params=None, best_of=3):
    """Speculative-decode b1 throughput: ONE greedy request decoded by
    the plain engine vs the speculative engine (n-gram self-drafting, no
    second checkpoint). Outputs are asserted token-for-token identical;
    speedup = plain wall / spec wall. The n-gram drafter wins on
    repetitive continuations — greedy decode of a small model settles
    into loops, the same regime the multi-step tick's EOS cap already
    exploits — and the verify dispatch prices K tokens at ~one tick of
    host/dispatch overhead."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    import deepspeed_tpu.serving as serving

    rs = np.random.RandomState(seed)
    if model_cfg is None:
        model_cfg = GPT2Config(
            vocab_size=2048, n_positions=512, n_embd=256, n_layer=6,
            n_head=8, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True)
    if params is None:
        params = jax.jit(GPT2LMHeadModel(model_cfg).init)(
            jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    prompt = rs.randint(0, model_cfg.vocab_size,
                        size=(prompt_len,)).astype(np.int32)
    sv = {"slots": 1, "page_size": page_size,
          "max_pages_per_slot": max_pages_per_slot,
          "kv_cache_bits": kv_cache_bits}
    plain_proto = serving.build_engine("gpt2", model_cfg, params,
                                       config={"serving": sv})
    spec_proto = serving.build_engine(
        "gpt2", model_cfg, params,
        config={"serving": {**sv,
                            "speculative": {"tokens": spec_tokens}}})

    def run(proto, spec_on):
        from deepspeed_tpu.serving.drafter import NGramDrafter
        drafter = NGramDrafter(1) if spec_on else None
        eng = serving.ContinuousBatcher(proto.adapter, drafter=drafter,
                                        spec_tokens=spec_tokens)
        t0 = time.monotonic()
        res = eng.serve([serving.Request(0, prompt,
                                         max_new_tokens=max_new)])
        return time.monotonic() - t0, res[0].tokens(), \
            eng.metrics_snapshot()

    run(plain_proto, False)        # compile warmup
    run(spec_proto, True)
    dt_p, toks_p, _ = run(plain_proto, False)
    dt_s, toks_s, snap = run(spec_proto, True)
    for _ in range(best_of - 1):   # interleaved best-of windows (±15%
        dt_p = min(dt_p, run(plain_proto, False)[0])     # box noise)
        dt_s2, toks_s2, snap2 = run(spec_proto, True)
        if dt_s2 < dt_s:
            dt_s, snap = dt_s2, snap2
    identical = toks_p.tolist() == toks_s.tolist()
    return {
        "workload": {"prompt_len": prompt_len, "max_new": max_new,
                     "spec_tokens": spec_tokens, "b": 1,
                     "kv_cache_bits": kv_cache_bits},
        "tokens_identical": identical,
        "tok_per_s_plain": round(max_new / dt_p, 1),
        "tok_per_s_spec": round(max_new / dt_s, 1),
        "spec_decode_speedup": round(dt_p / dt_s, 2),
        "accept_rate": round(snap["speculative"]["accept_rate"], 3),
        "verify_rounds": snap["speculative"]["rounds"],
        "wall_s_plain": round(dt_p, 3),
        "wall_s_spec": round(dt_s, 3),
    }


def run_disagg_bench(n_requests=32, slots=4, seed=0,
                     prompt_lens=(8, 16, 32, 48),
                     new_tokens=(2, 4, 8, 96), rate=400.0,
                     page_size=32, max_pages_per_slot=5,
                     prefill_replicas=1, decode_replicas=1,
                     pool_factor=1, model_cfg=None, params=None,
                     warm=True, best_of=3):
    """Disaggregated prefill/decode vs the colocated engine
    (ISSUE 14): the SAME deterministic mixed-traffic workload (seeded
    lengths/budgets/arrivals — BENCH_r08's serving trace) served by

    - the colocated ``ContinuousBatcher`` (prefill competes with
      decode for slot residency: an arriving prompt waits for a long
      request to FINISH before it can prefill — the TTFT p99 vs p50
      head-of-line gap), and
    - a ``DisaggRouter`` over prefill-role + decode-role engines:
      every arrival prefills the moment a prefill slot frees (they
      free at handoff), so TTFT stops depending on decode residency.

    Every engine gets the SAME fully-provisioned pool
    (``pool_factor`` x slots x max_pages_per_slot + trash) so the
    comparison isolates the ROLE SPLIT, not pool size — this jax CPU
    backend implements no buffer donation, so every donated
    prefill/tick COPIES its pool and per-op cost grows linearly with
    num_blocks (a proxy artifact a real chip does not have; keep
    pool_factor=1 here). The disaggregation memory trade (KV of
    requests queued behind a decode slot) is carried OUTSIDE the pools
    by the in-flight packets, bounded by the router's
    ``max_inflight_pages``. Greedy outputs are asserted
    token-for-token identical across the handoff, and the leak fence
    (every pool drains to num_blocks - 1 after a sweep) must hold
    across every handoff the run performed."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    import deepspeed_tpu.serving as serving
    from deepspeed_tpu.serving.engine import ContinuousBatcher
    from deepspeed_tpu.serving.router import DisaggRouter

    rs = np.random.RandomState(seed)
    if model_cfg is None:
        model_cfg = GPT2Config(
            vocab_size=2048, n_positions=512, n_embd=256, n_layer=6,
            n_head=8, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True)
    if params is None:
        params = jax.jit(GPT2LMHeadModel(model_cfg).init)(
            jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    lens, news, arrivals = _workload(rs, n_requests, prompt_lens,
                                     new_tokens, rate)
    prompts = [rs.randint(0, model_cfg.vocab_size,
                          size=(s,)).astype(np.int32) for s in lens]
    total_new = int(news.sum())
    num_blocks = slots * max_pages_per_slot * pool_factor + 1

    def make_requests():
        return [serving.Request(i, prompts[i],
                                max_new_tokens=int(news[i]),
                                arrival_time=float(arrivals[i]))
                for i in range(n_requests)]

    # ONE adapter for every engine in every window (colocated AND both
    # roles): the compiled prefill/tick programs are shared, so each
    # window replays warm executables — the long-lived-server steady
    # state, and the disagg engines pay zero extra compile
    shared = serving.build_engine(
        "gpt2", model_cfg, params,
        config={"serving": {"slots": slots, "page_size": page_size,
                            "max_pages_per_slot": max_pages_per_slot,
                            "num_blocks": num_blocks}})
    adapter = shared.adapter

    def run_colocated():
        eng = ContinuousBatcher(adapter)
        t0 = time.monotonic()
        res = eng.serve(make_requests(), respect_arrival_times=True)
        dt = time.monotonic() - t0
        assert len(res) == n_requests
        return dt, res, eng.metrics_snapshot()

    def run_disagg():
        router = DisaggRouter(
            [ContinuousBatcher(adapter, role="prefill",
                               prefix_cache=True)
             for _ in range(prefill_replicas)],
            [ContinuousBatcher(adapter, role="decode",
                               prefix_cache=True)
             for _ in range(decode_replicas)])
        t0 = time.monotonic()
        res = router.run(make_requests(), respect_arrival_times=True)
        dt = time.monotonic() - t0
        assert len(res) == n_requests and not router.lost
        snap = router.metrics_snapshot()
        # leak fence: after the drained workload + a prefix sweep,
        # every engine's pool must hold its full allocatable count
        leak_ok = True
        for cb in router.prefill_engines + router.decode_engines:
            cb.cache.sweep_prefix_cache()
            leak_ok &= cb.cache.free_pages == cb.cache.num_blocks - 1
        return dt, res, snap, leak_ok

    if warm:
        run_colocated()
        run_disagg()
    dt_c, res_c, snap_c = run_colocated()
    dt_d, res_d, snap_d, leak_ok = run_disagg()
    # greedy outputs must be token-for-token identical across the
    # handoff — compared on the first measured pair
    mismatches = sum(
        res_d[i].tokens().tolist() != res_c[i].tokens().tolist()
        for i in range(n_requests))
    for _ in range(best_of - 1):   # interleaved best-of windows (±15%
        dt_c2, _res, snap_c2 = run_colocated()      # box noise)
        if dt_c2 < dt_c:
            dt_c, snap_c = dt_c2, snap_c2
        dt_d2, _res, snap_d2, leak2 = run_disagg()
        leak_ok &= leak2
        if dt_d2 < dt_d:
            dt_d, snap_d = dt_d2, snap_d2

    def bd(b):
        return {k: {kk: round(vv, 4) for kk, vv in v.items()
                    if isinstance(vv, float)}
                for k, v in b.items()}

    ttft_c = snap_c["ttft_s"]
    ttft_d = snap_d["ttft_s"]
    return {
        "workload": {
            "n_requests": n_requests, "slots": slots,
            "prompt_lens": list(map(int, prompt_lens)),
            "new_tokens": list(map(int, new_tokens)),
            "total_decode_tokens": total_new,
            "poisson_rate_per_s": rate, "seed": seed,
            "prefill_replicas": prefill_replicas,
            "decode_replicas": decode_replicas,
            "pool_blocks_per_engine": num_blocks,
        },
        "colocated": {
            "ttft_p50_s": ttft_c.get("p50"),
            "ttft_p99_s": ttft_c.get("p99"),
            "decode_tokens_per_sec": round(total_new / dt_c, 1),
            "wall_s": round(dt_c, 3),
            "ttft_breakdown": bd(snap_c["ttft_breakdown"]),
        },
        "disagg": {
            "ttft_p50_s": ttft_d.get("p50"),
            "ttft_p99_s": ttft_d.get("p99"),
            "decode_tokens_per_sec": round(total_new / dt_d, 1),
            "wall_s": round(dt_d, 3),
            "handoffs": snap_d["handoffs"],
            "handoff_requeues": snap_d["handoff_requeues"],
            "decode_blocked": snap_d["decode_blocked"],
            "prefix_routed": snap_d["prefix_routed"],
            "ttft_breakdown": bd(snap_d["ttft_breakdown"]),
        },
        # the gated headline (lower is better) + its attribution
        "ttft_p99_s_disagg": ttft_d.get("p99"),
        "ttft_p99_s_colocated": ttft_c.get("p99"),
        "disagg_ttft_p99_speedup": round(
            ttft_c.get("p99") / max(ttft_d.get("p99"), 1e-9), 2)
        if ttft_c.get("p99") else None,
        "decode_tok_s_ratio": round(
            (total_new / dt_d) / (total_new / dt_c), 3),
        "token_mismatches": mismatches,
        "leak_fence_ok": bool(leak_ok),
    }


def run_serving_elastic_bench(n_requests=16, slots=2, seed=0,
                              prompt_lens=(8, 16, 24),
                              max_new=24, rate=400.0, page_size=16,
                              max_pages_per_slot=8, model_cfg=None,
                              params=None):
    """Elastic-serving workload (ISSUE 11): a Poisson request trace
    served by a ReplicaPool that takes ONE injected hard replica kill
    and ONE graceful SIGTERM-style drain mid-flight, recovering both
    from committed elastic snapshots. Reports the recovered-request
    fraction (must be 1.0), the committed-token-loss count vs an
    uninterrupted reference (must be 0 — greedy replay regenerates the
    identical stream), and the mean per-recovery restore latency; a
    second mini-experiment measures TTFT p99 under a burst overload
    with autoscaling on vs off (watchdog-trip scale-up, 1 -> up to 3
    replicas)."""
    import tempfile
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    import deepspeed_tpu.serving as serving
    from deepspeed_tpu.serving.elastic import ElasticServingController
    from deepspeed_tpu.serving.replica_pool import ReplicaPool
    from deepspeed_tpu.telemetry.anomaly import Watchdog
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    rs = np.random.RandomState(seed)
    if model_cfg is None:
        # smaller than the throughput bench's sizing: this section
        # measures recovery plumbing, not model compute
        model_cfg = GPT2Config(
            vocab_size=512, n_positions=256, n_embd=128, n_layer=3,
            n_head=4, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True)
    if params is None:
        params = jax.jit(GPT2LMHeadModel(model_cfg).init)(
            jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    lens, news, arrivals = _workload(
        rs, n_requests, prompt_lens, [max_new], rate)
    prompts = [rs.randint(0, model_cfg.vocab_size,
                          size=(s,)).astype(np.int32) for s in lens]

    def make_requests():
        return [serving.Request(i, prompts[i],
                                max_new_tokens=int(news[i]))
                for i in range(n_requests)]

    proto = serving.build_engine(
        "gpt2", model_cfg, params,
        config={"serving": {"slots": slots, "page_size": page_size,
                            "max_pages_per_slot": max_pages_per_slot}})

    # uninterrupted greedy reference — the token-loss baseline
    ref_eng = serving.ContinuousBatcher(proto.adapter)
    ref = {rid: r.tokens().tolist()
           for rid, r in ref_eng.serve(make_requests()).items()}

    root = tempfile.mkdtemp(prefix="dstpu_serving_elastic_")
    wd_dir = os.path.join(root, "flight")

    def factory_for(registry, interval_ticks=2, wd_kw=None):
        def factory(rid):
            cb = serving.ContinuousBatcher(
                proto.adapter, registry=registry,
                watchdog=Watchdog(os.path.join(wd_dir, f"r{rid}"),
                                  source=f"serving_r{rid}",
                                  registry=registry,
                                  **(wd_kw or {})))
            cb.attach_elastic(ElasticServingController(
                cb, os.path.join(root, f"replica_{rid}"),
                grace_secs=30.0, interval_ticks=interval_ticks,
                fsync=False, install_signals=False))
            return cb
        return factory

    # --- fault leg: 3 replicas, one kill + one graceful drain -------
    # the Poisson trace is honored: requests become dispatchable at
    # their arrival times while the pool steps (rate is rescaled so
    # arrivals actually spread across the run instead of landing at
    # t=0 on this CPU proxy)
    reg = MetricsRegistry()
    pool = ReplicaPool(factory_for(reg), n_replicas=3, min_replicas=1,
                       max_replicas=3, scale_signal="none")
    todo = sorted(make_requests(), key=lambda r: r.arrival_time)
    for req, t_arr in zip(todo, arrivals * (rate / 25.0)):
        req.arrival_time = float(t_arr)
    t0 = time.monotonic()
    rounds = 0
    killed = drained = False
    while (todo or pool.pending) and rounds < 3000:
        now = time.monotonic() - t0
        while todo and todo[0].arrival_time <= now:
            pool.submit(todo.pop(0))
        if not pool.pending:
            time.sleep(0.002)      # waiting on arrivals, not a round
            continue
        pool.step()
        rounds += 1
        if rounds == 3 and pool.replicas:
            killed = True
            pool.kill_replica(next(iter(pool.replicas)), reason="bench")
        if rounds == 6 and len(pool.replicas) > 1:
            drained = True
            pool.preempt_replica(list(pool.replicas)[-1],
                                 source="bench_drain")
    wall = time.monotonic() - t0
    done = pool.done
    token_loss = sum(
        done[i].tokens().tolist() != ref[i]
        for i in range(n_requests) if i in done)
    missing = n_requests - len(done)
    st = pool.snapshot_stats()
    n_recoveries = st["kills"] + st["preempts"]
    # pool-level aggregation (ISSUE 12): merged-reservoir TTFT
    # percentiles + per-replica utilization — the document a
    # disaggregated router would schedule on
    pool_telemetry = pool.metrics_snapshot()
    pool.close()

    # --- autoscale leg: burst overload, watchdog signal on vs off ---
    def ttft_burst(signal):
        reg2 = MetricsRegistry()
        # a hair-trigger TTFT rule so queue buildup trips fast on the
        # CPU proxy (pool_exhausted trips fire regardless)
        p = ReplicaPool(
            factory_for(reg2, interval_ticks=0,
                        wd_kw=dict(ttft_factor=1.5, ttft_min_s=0.01,
                                   min_samples=4)),
            n_replicas=1, min_replicas=1, max_replicas=3,
            scale_signal=signal, scale_down_idle_rounds=10**9)
        burst = [serving.Request(f"b{i}", prompts[i % n_requests],
                                 max_new_tokens=max_new)
                 for i in range(2 * n_requests)]
        p.run(burst)
        snap = reg2.snapshot()
        ttft = snap["histograms"].get("serving/ttft_s", {})
        out = {"ttft_p50_s": ttft.get("p50"),
               "ttft_p99_s": ttft.get("p99"),
               "replicas_final": len(p.replicas),
               "scale_ups": p.stats["scale_ups"]}
        p.close()
        return out

    fixed = ttft_burst("none")
    auto = ttft_burst("watchdog")

    return {
        "workload": {"n_requests": n_requests, "slots": slots,
                     "replicas": 3, "max_new_tokens": max_new,
                     "prompt_lens": list(map(int, prompt_lens))},
        "faults_injected": int(killed) + int(drained),
        "recovered_fraction": round(len(done) / n_requests, 4),
        "committed_token_loss": int(token_loss) + int(missing),
        "requests_lost": len(pool.lost),
        "restore_latency_s": round(
            st["restore_s_total"] / max(n_recoveries, 1), 4),
        "recovered_direct": st["recovered_direct"],
        "recovered_requeued": st["recovered_requeued"],
        "resubmitted_fresh": st["resubmitted_fresh"],
        "wall_s": round(wall, 3),
        "ttft_p99_s_fixed": fixed["ttft_p99_s"],
        "ttft_p99_s_autoscale": auto["ttft_p99_s"],
        "autoscale": {"fixed": fixed, "watchdog": auto},
        "pool_telemetry": pool_telemetry,
    }


def run_disagg_xproc_bench(n_requests=32, max_new=6, timeout=420,
                           world=2, slots=2, tick_cap=0,
                           addressing="targeted"):
    """``transport: "process"`` over ``world`` REAL ranked OS
    processes (ISSUE 17/18): rank 0 = router + prefill engine
    (``PrefillNode``), every other rank one decode engine
    (``DecodeNode``), KV pages crossing as versioned wire frames —
    the header leg on the gloo fence, dst-addressed payloads
    point-to-point (``addressing: "targeted"``). Reuses the PR-10
    ``spawn_workers`` harness and tests/xproc_serving_worker.py — the
    same module the acceptance tests and the supervisor SIGKILL fault
    leg run — on the tiny deterministic model, so the section prices
    the TRANSPORT (frame encode → collective hop → decode → scatter →
    adopt), not a big model's compute.

    Headline: ``ttft_p99_s_disagg_xproc`` (TTFT is observed on the
    PREFILL engine at first-token delivery, so the cross-process
    placement can only show up in it through admission/handoff
    stalls); the decode ranks' ``transport_s`` summaries attribute
    the wire/move segment inside the breakdown, and the byte counters
    are re-derived on both sides of the boundary (``sent == recv``
    pins the codec). Greedy parity vs an in-process colocated run of
    the identical trace is asserted, as is the leak fence on EVERY
    pool.

    ISSUE 18 honesty additions: ``slot_util`` per role (busy/capacity
    decode ticks — idle ticks count in the denominator, so a
    queue-wait-bound TTFT tail shows as low utilization on the
    default 2-slot geometry instead of hiding behind the breakdown)
    and ``decode_tok_s_aggregate`` (the scale-out headline's
    numerator: each rank's slot occupancy × one saturated rank's
    decode rate calibrated on the quiet in-process reference run —
    occupancy is deterministic, so the projection sidesteps the
    one-core harness box where every per-rank clock prices
    time-slicing instead of capacity; see the inline comment at the
    computation)."""
    import pathlib
    import tempfile
    from tests.test_multiprocess_dist import spawn_workers
    from tests.xproc_serving_worker import (build_model, build_requests,
                                            serving_config)

    tmp = pathlib.Path(tempfile.mkdtemp(prefix="dstpu_xproc_bench_"))
    outs = spawn_workers(
        world,
        "import sys\n"
        "from tests.xproc_serving_worker import main\n"
        "main(['worker'] + sys.argv[1:])\n",
        tmp, script_args=(tmp / "out", n_requests, max_new, -1, slots,
                          0, addressing, tick_cap),
        timeout=timeout)
    met, res = {}, {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("MET "):
                doc = json.loads(line[4:])
                met[doc["rank"]] = doc
            elif line.startswith("RES "):
                _tag, rid, blob = line.split(" ", 2)
                res[int(rid)] = json.loads(blob)
    m0 = met[0]
    dmets = [met[r] for r in range(1, world)]

    # in-process colocated reference over the IDENTICAL trace: greedy
    # parity across the process boundary is the bench's correctness
    # fence, same as the acceptance test's
    import deepspeed_tpu.serving as serving
    sv = {k: v for k, v in serving_config(slots)["serving"].items()
          if k != "disaggregation"}
    cfg, params = build_model()
    eng = serving.build_engine("gpt2", cfg, params,
                               config={"serving": sv})
    ref = eng.serve(build_requests(n_requests, max_new))
    mismatches = sum(
        res[rid]["tokens"] != ref[rid].tokens().tolist()
        for rid in ref)

    sent = int(m0["counters"].get("router/handoff_bytes_sent", 0))
    recv = sum(int(m["counters"].get("router/handoff_bytes_recv", 0))
               for m in dmets)
    wasted = sum(int(m["stats"].get("wasted_bytes", 0))
                 for m in [m0] + dmets)
    payload = sum(int(m["absorbed_pages"]) for m in dmets) \
        * int(m0["page_nbytes"])
    fences = [f for m in [m0] + dmets for f in m["leak_fence"]]

    def pct(h):
        return {k: (round(h[k], 6) if isinstance(h.get(k), float)
                    else h.get(k))
                for k in ("count", "mean", "p50", "p99", "max")}

    def merged_pct(mets, key):
        # decode ranks each carry their own registry: merge the
        # samples' summaries coarsely (count-weighted mean, max of
        # tails) — good enough for a breakdown row
        hs = [m[key] for m in mets if m.get(key, {}).get("count")]
        if not hs:
            return {"count": 0}
        n = sum(h["count"] for h in hs)
        return {"count": n,
                "mean": round(sum(h["mean"] * h["count"]
                                  for h in hs) / n, 6),
                "p50": round(max(h["p50"] for h in hs), 6),
                "p99": round(max(h["p99"] for h in hs), 6),
                "max": round(max(h["max"] for h in hs), 6)}

    # scale-out numerator: on the one-core harness box every rank
    # time-slices the same CPU, so ANY per-rank clock — wall, process
    # CPU (bills XLA pool-thread spin), even the scheduler thread's
    # own CPU (XLA:CPU result sync busy-waits, so it stretches with
    # the peers' contention) — prices the box's interleaving, not
    # rank capacity. The honest per-rank observable is the
    # DETERMINISTIC slot occupancy each rank sustained; the quiet
    # in-process reference run above calibrates one saturated rank's
    # decode rate, and each rank's projected rate is occupancy × that
    # rate (decode steps are batch-padded to the slot count, so
    # per-tick cost is occupancy-independent). The calibration
    # constant cancels in the scale-out RATIO the gate pins — the
    # ratio is purely the balancer's occupancy split.
    tl = eng.metrics.histogram("serving/tick_latency_s").summary()
    su = eng.metrics.histogram("serving/slot_utilization").summary()
    tick_wall = float(tl.get("count", 0) or 0) * float(
        tl.get("mean", 0.0) or 0.0)
    sat_tok_s = (eng.stats["decode_tokens"]
                 / tick_wall / max(float(su.get("mean") or 0.0), 1e-9)
                 ) if tick_wall > 0 else 0.0
    tok_s = [round(float(m["slot_util"]) * sat_tok_s, 3)
             for m in dmets]

    ttft = m0["ttft_s"]
    return {
        "workload": {"world": world, "n_requests": n_requests,
                     "max_new_tokens": max_new, "slots": slots,
                     "transport": "process",
                     "addressing": addressing},
        "handoffs": m0["stats"]["handoffs"],
        "handoff_bytes_sent": sent,
        "handoff_bytes_recv": recv,
        "handoff_wasted_bytes": wasted,
        "kv_payload_bytes": payload,
        "wire_overhead_bytes": sent - payload,
        "payload_bytes_per_handoff": round(
            (payload + wasted) / max(m0["stats"]["handoffs"], 1), 1),
        "bytes_counters_equal": sent == recv,
        "ttft_p50_s": ttft.get("p50"),
        "ttft_breakdown": {
            "queue_wait_s": pct(m0["ttft_queue_wait_s"]),
            "prefill_s": pct(m0["ttft_prefill_s"]),
            # the wire/move segments (ISSUE 18 split): encode on the
            # router rank, collective on every rank, land on decode
            "transport_s": merged_pct(dmets, "transport_s"),
            "transport_encode_s": pct(m0["transport_encode_s"]),
            "transport_collective_s": merged_pct(
                [m0] + dmets, "transport_collective_s"),
            "transport_decode_s": merged_pct(dmets,
                                             "transport_decode_s"),
        },
        "slot_util": {
            "prefill": round(float(m0["slot_util"]), 4),
            "decode_per_rank": [round(float(m["slot_util"]), 4)
                                for m in dmets],
        },
        "decode_tok_s_per_rank": tok_s,
        "decode_tok_s_aggregate": round(sum(tok_s), 3),
        "decode_tok_s_calibration": round(sat_tok_s, 3),
        "delivered_per_rank": [m["stats"]["delivered"] for m in dmets],
        "ttft_p99_s_disagg_xproc": ttft.get("p99"),
        "token_mismatches": mismatches,
        "leak_fence_ok": all(f["free"] == f["want"] for f in fences),
    }


def run_disagg_scaleout_bench(n_requests=16, max_new=24, timeout=420):
    """ISSUE 18 scale-out headline: the SAME deterministic trace over
    world=2 (1 decode rank) and world=3 (2 decode ranks, LPT-balanced
    targeted transport). ``decode_scaleout_tok_s_ratio`` = world-3
    aggregate decode tok/s over world-2's, computed with ONE shared
    calibration so it reduces to the deterministic occupancy ratio —
    ≥ ~2× when the balancer keeps both ranks at the single-rank
    occupancy, gated ≥ 1.6× —
    with token parity and the leak fence asserted on every leg, and
    the per-handoff payload wire cost reported for both worlds (the
    targeted transport keeps it world-independent).

    Geometry note: both legs run the SAME saturation geometry —
    longer streams (``max_new=24``) than the TTFT leg's 6 and
    ``decode_tick_cap=1`` so each stream stays slot-resident across
    ~24 router sweeps instead of 6. At the TTFT leg's geometry the
    prefill rank's arrival rate sustains only ~1.6 concurrent decode
    streams, which one world-2 rank absorbs whole while two world-3
    ranks split it and idle half their slots; the longer residency
    lifts steady-state concurrency past 2 slots x 2 ranks so BOTH
    world-3 ranks hold near-single-rank occupancy (the reported
    ``slot_util`` is the honesty check). Per-rank rates are projected
    as occupancy × the calibrated saturated single-rank rate (decode
    steps are batch-padded, so per-tick cost is
    occupancy-independent): on the one-core harness box every direct
    per-rank clock prices the ranks' time-slicing of the shared core,
    while a real deployment runs one host per rank — and the
    calibration constant cancels in the gated ratio, which is exactly
    the occupancy the balancer + targeted transport sustained."""
    w2 = run_disagg_xproc_bench(n_requests, max_new, timeout, world=2,
                                tick_cap=1)
    w3 = run_disagg_xproc_bench(n_requests, max_new, timeout, world=3,
                                tick_cap=1)
    # the gated ratio divides out ONE shared calibration: it is the
    # pure occupancy ratio Σ util_w3 / Σ util_w2, so per-leg
    # calibration drift (box noise in each leg's quiet reference run)
    # cannot leak into the gate — the legs' absolute tok_s figures
    # keep their own calibration and are reported for scale only
    u2 = sum(w2["slot_util"]["decode_per_rank"])
    u3 = sum(w3["slot_util"]["decode_per_rank"])
    ratio = round(u3 / u2, 3) if u2 else 0.0
    return {
        "xproc_w2": w2,
        "xproc_w3": w3,
        "decode_scaleout_tok_s_ratio": ratio,
        "wire_cost_ratio_w3_over_w2": round(
            w3["payload_bytes_per_handoff"]
            / max(w2["payload_bytes_per_handoff"], 1e-9), 4),
        "token_parity_ok": w2["token_mismatches"] == 0
        and w3["token_mismatches"] == 0,
        "leak_fence_ok": w2["leak_fence_ok"] and w3["leak_fence_ok"],
    }


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="poisson",
                    choices=["poisson", "hot_prefix", "spec_decode",
                             "elastic", "disagg", "disagg_xproc",
                             "disagg_scaleout"])
    args = ap.parse_args()
    fn = {"poisson": run_serving_bench,
          "hot_prefix": run_hot_prefix_bench,
          "spec_decode": run_spec_decode_bench,
          "elastic": run_serving_elastic_bench,
          "disagg": run_disagg_bench,
          "disagg_xproc": run_disagg_xproc_bench,
          "disagg_scaleout": run_disagg_scaleout_bench}[args.mode]
    print(json.dumps(fn(), indent=1))
