"""Does the axon client retain d2h results per device buffer?"""
import gc
import numpy as np
import jax
import jax.numpy as jnp

def rss():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024

make = jax.jit(lambda k: jax.random.normal(k, (14 << 20,), jnp.float32))
print("start", rss())
for i in range(6):
    x = make(jax.random.PRNGKey(i))          # fresh 56 MB device buffer
    a = np.asarray(x)                         # d2h
    del a
    x.delete()
    del x
    gc.collect()
    print(f"iter {i}: rss={rss():.0f}", flush=True)
jax.clear_caches()
gc.collect()
print("after clear_caches:", f"{rss():.0f}")
