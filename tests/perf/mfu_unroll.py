"""Headline 774M ZeRO-3 step time vs scan_unroll."""
import sys
import time
import numpy as np
import jax
import jax.numpy as jnp
import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

unroll = int(sys.argv[1]) if len(sys.argv) > 1 else 1
dev = jax.devices()[0]
mesh = make_mesh(MeshConfig(data=1), devices=[dev])
import os as _os2
SEQ = int(_os2.environ.get("SEQ", 1024))
BS = 8192 // SEQ
model_cfg = GPT2Config(vocab_size=50304, n_positions=SEQ, n_embd=1280,
                       n_layer=36, n_head=20, dtype=jnp.bfloat16,
                       scan_layers=True, remat=True,
                       remat_policy=__import__("os").environ.get("RP", "dots_flash_fc_lean"),
                       loss_chunk=int(__import__("os").environ.get("LC", 1024)), scan_unroll=unroll)
cfg = {
    "train_batch_size": BS,
    "zero_optimization": {"stage": 3},
    "bf16": {"enabled": True},
    "data_types": {"grad_dtype": "bf16"},
    "gradient_clipping": 1.0,
    "optimizer": {"type": "AdamW",
                  "params": {"lr": 1e-4, "weight_decay": 0.01,
                             "moment_dtype": "bf16"}},
    "steps_per_print": 1000,
}
import os as _os
if _os.environ.get("FBQ"):
    import functools as _ft
    import importlib
    _fa = importlib.import_module(
        "deepspeed_tpu.ops.pallas.flash_attention")
    _orig = _fa.flash_attention
    _fa.flash_attention = _ft.partial(
        _orig, block_q=int(_os.environ["FBQ"]),
        block_k=int(_os.environ["FBK"]))
engine, _, _, _ = dstpu.initialize(config=cfg,
                                   model=GPT2LMHeadModel(model_cfg),
                                   mesh=mesh)
rng = np.random.RandomState(0)
batch = {"input_ids": rng.randint(0, 50304, size=(BS, SEQ))
         .astype(np.int32)}
for _ in range(2):
    loss = engine.train_batch(batch)
float(jax.device_get(loss))
iters = 30
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = engine.train_batch(batch)
    float(jax.device_get(loss))
    best = min(best, (time.perf_counter() - t0) / iters)
t0 = time.perf_counter()
int(jax.device_get(engine.state.global_step))
fence = time.perf_counter() - t0
dt = best - fence / iters
from bench import model_flops_per_token, peak_flops
mfu = model_flops_per_token(model_cfg) * 8192 / dt / peak_flops(dev)
print(f"unroll={unroll}: step {dt * 1000:.1f} ms  MFU {mfu * 100:.2f}%")
