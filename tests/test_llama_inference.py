"""LLaMA fused serving (models/llama_inference.py): packed-stack
conversion, the RMS/SwiGLU/GQA kernel modes, and the fast decode loop
vs the flax llama_generate path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.llama import (llama_tiny, LlamaForCausalLM,
                                        llama_generate)
from deepspeed_tpu.models.llama_inference import (
    convert_llama_serving_params, quantize_llama_serving_params,
    llama_fast_generate, _supports_fast_decode)


def _cfg(**over):
    # packed widths lane-aligned: (H + 2*Hkv)*D = 256, H*D = 128, F = 256
    return llama_tiny(hidden_size=128, intermediate_size=256,
                      n_layers=3, n_heads=4, n_kv_heads=2,
                      max_seq_len=192, **over)


def _setup():
    cfg = _cfg()
    rs = np.random.RandomState(11)
    prompt = rs.randint(0, 512, size=(2, 40)).astype(np.int32)
    params = jax.jit(LlamaForCausalLM(cfg).init)(
        jax.random.PRNGKey(7), prompt[:, :8])["params"]
    return cfg, params, prompt


def test_supports_gate():
    cfg = _cfg()
    assert _supports_fast_decode(cfg, 2, 0, 0)
    assert _supports_fast_decode(cfg, 2, 8, 8)
    assert not _supports_fast_decode(cfg, 128, 8, 8)   # B cap


@pytest.mark.parametrize("kv_bits", [0, 8])
def test_fast_generate_matches_flax(kv_bits):
    """Full-precision packed fast loop must reproduce the flax serving
    path's greedy tokens exactly — RMS qkv kernel, GQA grouped-row
    attention kernel (R = H/Hkv = 2), SwiGLU ffn kernel, RoPE offsets.
    kv_bits=8 additionally exercises the int8 GQA cache (prompt fills
    codes+scales; rows append through kv_quant)."""
    cfg, params, prompt = _setup()
    ref = llama_generate(cfg, params, prompt, max_new_tokens=8,
                         max_out_tokens=cfg.max_seq_len)
    sparams = convert_llama_serving_params(params, cfg)
    got = llama_fast_generate(cfg, sparams, prompt, max_new_tokens=8,
                              max_out_tokens=cfg.max_seq_len,
                              kv_cache_bits=kv_bits)
    ref_n, got_n = np.asarray(ref), np.asarray(got)
    if kv_bits == 0:
        np.testing.assert_array_equal(got_n, ref_n)
    else:
        # int8 KV perturbs scores ~0.4% — token-for-token equality is
        # not the contract (same as the GPT-2 int8-KV test); the
        # sequences must still be near-identical on a random tiny model
        same = (got_n == ref_n).mean()
        assert same > 0.85, (same, got_n, ref_n)


def test_fast_generate_int8_weights_close_to_fp():
    """int8 packed weights: greedy generation must track the fp path
    (quantization noise can flip late tokens on a random model, so the
    contract is high overlap, not equality)."""
    cfg, params, prompt = _setup()
    sparams = convert_llama_serving_params(params, cfg)
    fp = llama_fast_generate(cfg, sparams, prompt, max_new_tokens=8,
                             max_out_tokens=cfg.max_seq_len)
    qparams = quantize_llama_serving_params(sparams)
    assert qparams["blk"]["qkv_w"]["kernel_q"].dtype == jnp.int8
    q = llama_fast_generate(cfg, qparams, prompt, max_new_tokens=8,
                            max_out_tokens=cfg.max_seq_len,
                            kv_cache_bits=8)
    same = (np.asarray(q) == np.asarray(fp)).mean()
    assert same > 0.8, (same, np.asarray(q), np.asarray(fp))
    assert np.isfinite(np.asarray(q, np.float64)).all()


def test_fast_generate_sampled_deterministic():
    cfg, params, prompt = _setup()
    sparams = convert_llama_serving_params(params, cfg)
    kw = dict(max_new_tokens=6, max_out_tokens=cfg.max_seq_len,
              temperature=0.7, rng=jax.random.PRNGKey(3))
    a = llama_fast_generate(cfg, sparams, prompt, **kw)
    b = llama_fast_generate(cfg, sparams, prompt, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fast_generate_rejects_unsupported_config():
    """Outside the fused envelope the loop must raise the gate's clean
    error, not an opaque kernel assert (B cap here)."""
    cfg, params, _ = _setup()
    sparams = convert_llama_serving_params(params, cfg)
    big_prompt = np.zeros((128, 8), np.int32)   # B=128 > the 64 cap
    with pytest.raises(ValueError, match="fast-decode envelope"):
        llama_fast_generate(cfg, sparams, big_prompt, max_new_tokens=4,
                            max_out_tokens=cfg.max_seq_len)
