"""Module injection tests — the reference checks injection by numerics
(fused layer output vs the HF layer it replaced); same here, against real
transformers FlaxBert modules. Plus KV-cache decode parity and TP-sharded
inference on the virtual mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

transformers = pytest.importorskip("transformers")
from transformers import BertConfig as HFBertConfig  # noqa: E402
from transformers.models.bert.modeling_flax_bert import (  # noqa: E402
    FlaxBertModel)

from deepspeed_tpu.module_inject import (  # noqa: E402
    HFBertLayerPolicy, MegatronLayerPolicy, DSTransformerLayerPolicy,
    inject_layer_params, revert_layer_params, replace_transformer_layer,
    quantize_transformer_layer, convert_hf_bert)
from deepspeed_tpu.ops.transformer import (  # noqa: E402
    DeepSpeedTransformerConfig, DeepSpeedTransformerLayer)
from deepspeed_tpu.ops.transformer.inference import (  # noqa: E402
    DeepSpeedInferenceConfig, DeepSpeedTransformerInference,
    inference_tp_specs)


def _hf_model(n_layers=2):
    cfg = HFBertConfig(vocab_size=256, hidden_size=32, num_hidden_layers=n_layers,
                       num_attention_heads=2, intermediate_size=64,
                       max_position_embeddings=64,
                       hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0)
    model = FlaxBertModel(cfg, seed=0)
    return cfg, model


def test_injected_layer_matches_hf_layer():
    """Fused layer with injected params reproduces the HF layer output —
    the core correctness property of replace_transformer_layer."""
    hf_cfg, hf_model = _hf_model(n_layers=1)
    layer_params = jax.tree.map(
        jnp.asarray, hf_model.params["encoder"]["layer"]["0"])
    fused_params = inject_layer_params(HFBertLayerPolicy(), layer_params)

    ds_cfg = DeepSpeedTransformerConfig(
        hidden_size=32, intermediate_size=64, heads=2, num_hidden_layers=1,
        pre_layer_norm=False, layer_norm_eps=hf_cfg.layer_norm_eps,
        dtype=jnp.float32)
    layer = DeepSpeedTransformerLayer(ds_cfg)

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 10, 32), jnp.float32)
    out_fused = layer.apply({"params": fused_params}, x)

    # run the HF model's encoder layer directly via its module class
    from transformers.models.bert.modeling_flax_bert import FlaxBertLayer
    hf_layer = FlaxBertLayer(hf_cfg, dtype=jnp.float32)
    out_hf = hf_layer.apply(
        {"params": layer_params}, x, None, None,
        deterministic=True)[0]
    np.testing.assert_allclose(np.asarray(out_fused), np.asarray(out_hf),
                               rtol=2e-4, atol=2e-4)


def test_whole_model_conversion_matches_hf():
    """convert_hf_bert: full backbone parity (sequence + pooled) vs
    FlaxBertModel on padded batches."""
    hf_cfg, hf_model = _hf_model(n_layers=2)
    ids = np.random.RandomState(0).randint(0, 256, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[:, -3:] = 0
    types = np.zeros((2, 12), np.int32)
    hf_out = hf_model(input_ids=ids, attention_mask=mask,
                      token_type_ids=types)

    from deepspeed_tpu.models.bert import BertModel
    cfg, params = convert_hf_bert(
        jax.tree.map(jnp.asarray, hf_model.params), hf_cfg)
    model = BertModel(cfg)
    seq, pooled = model.apply({"params": params}, jnp.asarray(ids),
                              jnp.asarray(mask), jnp.asarray(types))
    # valid positions match (HF attends pad queries to valid keys; we mask
    # pad queries into their own segment, so compare non-pad rows)
    np.testing.assert_allclose(np.asarray(seq[:, :9]),
                               np.asarray(hf_out.last_hidden_state[:, :9]),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pooled),
                               np.asarray(hf_out.pooler_output),
                               rtol=2e-4, atol=2e-4)


def test_revert_roundtrip():
    hf_cfg, hf_model = _hf_model(n_layers=1)
    layer_params = jax.tree.map(
        jnp.asarray, hf_model.params["encoder"]["layer"]["0"])
    fused = inject_layer_params(HFBertLayerPolicy(), layer_params)
    back = revert_layer_params(fused, HFBertLayerPolicy())
    flat_a = jax.tree_util.tree_leaves(layer_params)
    flat_b = jax.tree_util.tree_leaves(back)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_megatron_policy_layout():
    """Megatron-style subtree injects into the fused names with pre-LN."""
    E, F = 16, 32
    rs = np.random.RandomState(1)
    layer = {
        "input_layernorm": {"scale": jnp.ones(E), "bias": jnp.zeros(E)},
        "attention": {
            "query_key_value": {"kernel": jnp.asarray(rs.randn(E, 3 * E),
                                                      jnp.float32),
                                "bias": jnp.zeros(3 * E)},
            "dense": {"kernel": jnp.asarray(rs.randn(E, E), jnp.float32),
                      "bias": jnp.zeros(E)},
        },
        "post_attention_layernorm": {"scale": jnp.ones(E),
                                     "bias": jnp.zeros(E)},
        "mlp": {
            "dense_h_to_4h": {"kernel": jnp.asarray(rs.randn(E, F),
                                                    jnp.float32),
                              "bias": jnp.zeros(F)},
            "dense_4h_to_h": {"kernel": jnp.asarray(rs.randn(F, E),
                                                    jnp.float32),
                              "bias": jnp.zeros(E)},
        },
    }
    cfg, layers = replace_transformer_layer(
        MegatronLayerPolicy, [layer], training=True)
    assert cfg.pre_layer_norm is True
    assert cfg.hidden_size == E and cfg.intermediate_size == F
    fused = layers[0]
    ds_layer = DeepSpeedTransformerLayer(
        DeepSpeedTransformerConfig(hidden_size=E, intermediate_size=F,
                                   heads=2, num_hidden_layers=1,
                                   pre_layer_norm=True, dtype=jnp.float32))
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, E), jnp.float32)
    out = ds_layer.apply({"params": fused}, x)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()


def test_quantize_on_injection():
    hf_cfg, hf_model = _hf_model(n_layers=1)
    layer_params = jax.tree.map(
        jnp.asarray, hf_model.params["encoder"]["layer"]["0"])
    fused = inject_layer_params(HFBertLayerPolicy(), layer_params)
    q = quantize_transformer_layer(fused, bits=8, groups=4)
    w, wq = fused["inter_w"]["kernel"], q["inter_w"]["kernel"]
    assert wq.dtype == w.dtype
    err = np.abs(np.asarray(w) - np.asarray(wq)).max()
    assert 0 < err < np.abs(np.asarray(w)).max() / 50  # int8-level error
    # biases and layernorms untouched
    np.testing.assert_array_equal(np.asarray(fused["attn_nw"]["scale"]),
                                  np.asarray(q["attn_nw"]["scale"]))


def test_inference_layer_encoder_matches_training_layer():
    """Inference layer == training layer numerics in encoder mode (the
    DSTransformerLayerPolicy train→infer path)."""
    cfg_t = DeepSpeedTransformerConfig(hidden_size=32, intermediate_size=64,
                                       heads=2, num_hidden_layers=1,
                                       pre_layer_norm=False,
                                       dtype=jnp.float32)
    train_layer = DeepSpeedTransformerLayer(cfg_t)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32), jnp.float32)
    params = train_layer.init(jax.random.PRNGKey(1), x)["params"]
    fused = inject_layer_params(
        DSTransformerLayerPolicy(pre_layer_norm=False), params)
    cfg_i = DeepSpeedInferenceConfig(hidden_size=32, intermediate_size=64,
                                     heads=2, pre_layer_norm=False,
                                     triangular_masking=False,
                                     dtype=jnp.float32)
    infer_layer = DeepSpeedTransformerInference(cfg_i)
    out_i = infer_layer.apply({"params": fused}, x)
    out_t = train_layer.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_i), np.asarray(out_t),
                               rtol=1e-5, atol=1e-5)


def test_decode_with_cache_matches_full_context():
    """Incremental decode through the KV cache == one full causal pass."""
    cfg = DeepSpeedInferenceConfig(hidden_size=32, intermediate_size=64,
                                   heads=2, pre_layer_norm=True,
                                   triangular_masking=True, max_out_tokens=16,
                                   dtype=jnp.float32)
    layer = DeepSpeedTransformerInference(cfg)
    B, S, E = 2, 10, 32
    x = jax.random.normal(jax.random.PRNGKey(0), (B, S, E), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]

    # full causal pass, no cache
    full = layer.apply({"params": params}, x)

    # prompt pass (first 6) then token-by-token decode
    prompt, rest = x[:, :6], x[:, 6:]
    out_p, vars_ = layer.apply({"params": params}, prompt, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(full[:, :6]),
                               rtol=1e-5, atol=1e-5)
    cache = vars_["cache"]
    outs = [out_p]
    for t in range(rest.shape[1]):
        step = rest[:, t:t + 1]
        out_t, vars_ = layer.apply({"params": params, "cache": cache}, step,
                                   mutable=["cache"])
        cache = vars_["cache"]
        outs.append(out_t)
    decoded = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(decoded), np.asarray(full),
                               rtol=1e-4, atol=1e-4)


def test_decode_past_cache_poisons_with_nan():
    """Overflowing max_out_tokens must be loud (NaN), not silently stale."""
    cfg = DeepSpeedInferenceConfig(hidden_size=16, intermediate_size=32,
                                   heads=2, triangular_masking=True,
                                   max_out_tokens=4, dtype=jnp.float32)
    layer = DeepSpeedTransformerInference(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 16), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    cache = None
    for t in range(6):
        variables = {"params": params}
        if cache is not None:
            variables["cache"] = cache
        out, vars_ = layer.apply(variables, x, mutable=["cache"])
        cache = vars_["cache"]
        if t < 4:
            assert np.isfinite(np.asarray(out)).all(), t
        else:
            assert np.isnan(np.asarray(out)).any(), t


def test_tp_sharded_inference_matches_single_device(devices8):
    """mp_size=8 TP sharding over the model axis reproduces single-device
    outputs (module_inject's mp_size path)."""
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    cfg = DeepSpeedInferenceConfig(hidden_size=32, intermediate_size=64,
                                   heads=8, pre_layer_norm=False,
                                   triangular_masking=False, mp_size=8,
                                   dtype=jnp.float32)
    layer = DeepSpeedTransformerInference(cfg)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    expected = layer.apply({"params": params}, x)

    mesh = Mesh(np.array(devices8).reshape(8), ("model",))
    specs = inference_tp_specs(params)
    sharded = jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)
    x_sh = jax.device_put(x, NamedSharding(mesh, P()))
    with mesh:
        out = jax.jit(lambda pp, xx: layer.apply({"params": pp}, xx))(
            sharded, x_sh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)
