"""Bucketed gradient-sync scheduler tests (parallel/overlap.py).

The numerics contract: the bucket stream's ring reduce-scatter + all-gather
(and the per-bucket fused psum) must reproduce the monolithic psum exchange
at fp32 rounding tolerance across bucket layouts — including the uneven
last bucket and the single-bucket degenerate case — and the engine's
overlap_comm train path must match the fused GSPMD train path step for
step."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel import overlap
from deepspeed_tpu.parallel.mesh import shard_map, make_mesh, MeshConfig
from tests.simple_model import SimpleModel, random_batch, base_config

N = 8


def _mesh():
    devs = jax.devices()
    assert len(devs) >= N
    return Mesh(np.asarray(devs[:N]), ("data",))


# ---------------------------------------------------------------------------
# bucket planning
# ---------------------------------------------------------------------------

def test_plan_buckets_greedy_packing():
    shapes = [(128,), (16,), (8, 8), (4,)]          # 128, 16, 64, 4 elems
    buckets = overlap.plan_buckets(shapes, bucket_elems=100, axis_size=N)
    assert [b.leaf_ids for b in buckets] == [(0,), (1, 2, 3)]
    assert buckets[0].numel == 128 and buckets[0].padded == 128
    # 84 elems → padded up to the next multiple of the axis size
    assert buckets[1].numel == 84 and buckets[1].padded == 88


def test_plan_buckets_oversized_leaf_gets_own_bucket():
    buckets = overlap.plan_buckets([(10,), (1000,), (10,)], 64, 4)
    assert [b.leaf_ids for b in buckets] == [(0,), (1,), (2,)]


def test_plan_buckets_single_bucket_degenerate():
    buckets = overlap.plan_buckets([(3,), (5,), (7,)], 10**9, 4)
    assert len(buckets) == 1
    assert buckets[0].numel == 15 and buckets[0].padded == 16


def test_plan_buckets_scalar_leaves():
    buckets = overlap.plan_buckets([(), ()], 10, 4)
    assert len(buckets) == 1 and buckets[0].numel == 2


# ---------------------------------------------------------------------------
# ring collectives vs psum
# ---------------------------------------------------------------------------

def _stacked(shape, seed=0):
    """Per-device distinct local buffers, stacked on the data axis."""
    return jnp.asarray(
        np.random.RandomState(seed).randn(N, *shape).astype(np.float32))


def test_ring_reduce_scatter_matches_sum():
    mesh = _mesh()
    L = N * 6
    bufs = _stacked((L,))

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def run(b):
        return overlap.ring_reduce_scatter(b.reshape(-1), "data", N) \
            .reshape(1, -1)

    out = np.asarray(run(bufs)).reshape(-1)          # chunk i from device i
    np.testing.assert_allclose(out, np.asarray(bufs).sum(0), rtol=1e-5,
                               atol=1e-6)


def test_ring_all_gather_roundtrip():
    mesh = _mesh()
    full = np.random.RandomState(1).randn(N * 5).astype(np.float32)
    shards = jnp.asarray(full.reshape(N, 5))         # device i owns chunk i

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"))
    def run(s):
        return overlap.ring_all_gather(s.reshape(-1), "data", N) \
            .reshape(1, -1)

    out = np.asarray(run(shards))                    # [N, N*5]: per-device copy
    for row in out:
        np.testing.assert_array_equal(row, full)


def test_ring_scan_path_matches_unrolled(monkeypatch):
    """Force the scan (large-mesh) lowering and pin it to the unrolled one."""
    mesh = _mesh()
    bufs = _stacked((N * 4,), seed=2)

    def run_once():
        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"))
        def run(b):
            s = overlap.ring_reduce_scatter(b.reshape(-1), "data", N)
            return overlap.ring_all_gather(s, "data", N).reshape(1, -1)
        return np.asarray(run(bufs))

    unrolled = run_once()
    monkeypatch.setattr(overlap, "_ring_hops", lambda fn, n, **kw: False)
    scanned = run_once()
    np.testing.assert_allclose(scanned, unrolled, rtol=1e-6)


# ---------------------------------------------------------------------------
# bucketed tree sync vs monolithic psum
# ---------------------------------------------------------------------------

def _grad_tree(seed=0):
    """Varied shapes/dtypes; sizes chosen so small bucket budgets produce
    several buckets with an uneven (padded) last one."""
    r = np.random.RandomState(seed)
    return {
        "w1": jnp.asarray(r.randn(N, 16, 8), jnp.float32),
        "b1": jnp.asarray(r.randn(N, 8), jnp.float32),
        "w2": jnp.asarray(r.randn(N, 8, 5), jnp.bfloat16),
        "scalar": jnp.asarray(r.randn(N), jnp.float32),
    }


def _reference_mean(tree):
    return {k: np.asarray(v, np.float32).mean(0) for k, v in tree.items()}


@pytest.mark.parametrize("mode", ["ring", "fused"])
@pytest.mark.parametrize("bucket_elems", [1, 50, 10**9])
def test_bucketed_allreduce_matches_psum(mode, bucket_elems):
    """bucket_elems=1 → one bucket per leaf; 50 → multi-leaf buckets with
    an uneven tail; 1e9 → single-bucket degenerate. All must agree with
    the monolithic mean."""
    mesh = _mesh()
    tree = _grad_tree()
    specs = {k: P("data") for k in tree}

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=specs)
    def run(t):
        local = jax.tree_util.tree_map(lambda x: x[0], t)
        red = overlap.bucketed_allreduce(local, "data", N, bucket_elems,
                                         mode=mode, mean=True)
        return jax.tree_util.tree_map(lambda x: x[None], red)

    out = run(tree)
    want = _reference_mean(tree)
    for k in tree:
        got = np.asarray(out[k], np.float32)
        assert out[k].dtype == tree[k].dtype        # dtype round-trips
        tol = 2e-2 if tree[k].dtype == jnp.bfloat16 else 1e-5
        for dev in range(N):                        # identical on every device
            np.testing.assert_allclose(got[dev], want[k], rtol=tol, atol=tol)


def test_bucketed_allreduce_sum_and_single_device():
    mesh = _mesh()
    tree = {"w": jnp.asarray(np.ones((N, 4), np.float32))}

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=({"w": P("data")},),
                       out_specs={"w": P("data")})
    def run(t):
        local = jax.tree_util.tree_map(lambda x: x[0], t)
        red = overlap.bucketed_allreduce(local, "data", N, 16, mean=False)
        return jax.tree_util.tree_map(lambda x: x[None], red)

    np.testing.assert_array_equal(np.asarray(run(tree)["w"]),
                                  np.full((N, 4), N, np.float32))
    # n=1 passthrough never touches the axis
    t = {"w": jnp.ones((3,))}
    assert overlap.bucketed_allreduce(t, "data", 1, 16) is t


def test_bucketed_allreduce_rejects_bad_mode():
    with pytest.raises(ValueError):
        overlap.bucketed_allreduce({"w": jnp.ones(3)}, "data", 2, 8,
                                   mode="tree")


def test_bucketed_reduce_scatter_shards():
    mesh = _mesh()
    tree = _grad_tree(seed=3)
    specs = {k: P("data") for k in tree}

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(specs,),
                       out_specs=P("data"))
    def run(t):
        local = jax.tree_util.tree_map(lambda x: x[0], t)
        shards, _ = overlap.bucketed_reduce_scatter(local, "data", N, 50)
        return jnp.concatenate(shards)[None]

    leaves = jax.tree_util.tree_leaves(
        {k: jnp.asarray(v[0]) for k, v in tree.items()})
    buckets = overlap.plan_buckets([l.shape for l in leaves], 50, N)
    out = np.asarray(run(tree))                      # [N, sum(padded)/N]
    # reassembling the per-device chunks bucket by bucket gives the mean
    flat_mean = np.concatenate(
        [np.asarray(l, np.float32) for l in jax.tree_util.tree_leaves(
            {k: np.asarray(v, np.float32).mean(0) for k, v in tree.items()})],
        axis=None)
    off_out, off_ref = 0, 0
    for b in buckets:
        per_dev = b.padded // N
        chunk = out[:, off_out:off_out + per_dev].reshape(-1)[:b.numel]
        np.testing.assert_allclose(
            chunk, flat_mean[off_ref:off_ref + b.numel], rtol=1e-5, atol=1e-6)
        off_out += per_dev
        off_ref += b.numel


def test_bucketed_compressed_allreduce_runs_and_converges_direction():
    """The 1-bit bucket stream: error states align with the bucket plan and
    the first-pass result preserves the sign structure of the true mean
    (exactness is the compression suite's job; here we pin the plumbing)."""
    mesh = _mesh()
    r = np.random.RandomState(4)
    tree = {"a": jnp.asarray(r.randn(N, 10, 10), jnp.float32),
            "b": jnp.asarray(r.randn(N, 96), jnp.float32),
            "c": jnp.asarray(r.randn(N, 60), jnp.float32)}
    wes, ses = overlap.compressed_error_states(
        {k: jnp.zeros(v.shape[1:]) for k, v in tree.items()},
        N, bucket_elems=100)
    assert len(wes) == len(ses) == 3                 # whole-leaf buckets

    specs = {k: P("data") for k in tree}

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(specs, [P()] * 3, [P()] * 3),
        out_specs=(specs, [P()] * 3, [P()] * 3),
        check_vma=False)
    def run(t, wes, ses):
        local = jax.tree_util.tree_map(lambda x: x[0], t)
        red, we2, se2 = overlap.bucketed_compressed_allreduce(
            local, wes, ses, "data", N, 100)
        return jax.tree_util.tree_map(lambda x: x[None], red), we2, se2

    red, we2, se2 = run(tree, wes, ses)
    for a, b in zip(we2, wes):
        assert a.shape == b.shape
    got = np.asarray(red["a"][0])
    want = np.asarray(tree["a"], np.float32).mean(0)
    assert np.isfinite(got).all()
    # 1-bit first pass: magnitudes are quantized but signs track the mean
    # (a mean of N gaussians sits near zero, so agreement is well below
    # 1.0 — error feedback recovers the residual over steps; chance = 0.5)
    agree = (np.sign(got) == np.sign(want)).mean()
    assert agree > 0.7, agree


# ---------------------------------------------------------------------------
# engine integration: overlap_comm train path == fused GSPMD path
# ---------------------------------------------------------------------------

def _train(overlap_on, stage, mode="ring", bucket=100, steps=3,
           optimizer=None, data=N):
    cfg = base_config()
    if optimizer is not None:
        cfg["optimizer"] = optimizer
    cfg["zero_optimization"] = {
        "stage": stage, "overlap_comm": overlap_on,
        "reduce_bucket_size": bucket, "overlap_reduce": mode}
    mesh = make_mesh(MeshConfig(data=data), devices=jax.devices()[:data])
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    losses = [float(engine.train_batch(random_batch())) for _ in range(steps)]
    return engine, losses, jax.tree_util.tree_map(np.asarray,
                                                  engine.state.params)


_BASELINE = {}


def _fused_baseline(stage):
    """One fused-path run per stage, shared across the parametrized overlap
    cases (each build jit-compiles a full train step — worth caching)."""
    if stage not in _BASELINE:
        eng, losses, params = _train(False, stage)
        assert not eng._overlap_comm_active()
        _BASELINE[stage] = (losses, params)
    return _BASELINE[stage]


@pytest.mark.parametrize("stage,mode", [(1, "ring"), (2, "ring"),
                                        (2, "fused")])
def test_engine_overlap_matches_fused_path(stage, mode):
    """bucket=100 elems forces multiple buckets over SimpleModel's leaves
    (128/16/64/4), including a padded uneven tail."""
    loss_b, params_b = _fused_baseline(stage)
    eng_o, loss_o, params_o = _train(True, stage, mode)
    assert eng_o._overlap_comm_active()
    np.testing.assert_allclose(loss_o, loss_b, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(params_o),
                    jax.tree_util.tree_leaves(params_b)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_engine_overlap_gating():
    # single-device data axis → nothing to overlap
    eng, _, _ = _train(True, 2, data=1)
    assert not eng._overlap_comm_active()
    # LAMB's per-tensor trust ratio is not elementwise → fused fallback
    eng, losses, _ = _train(True, 2, optimizer={
        "type": "Lamb", "params": {"lr": 1e-2}})
    assert not eng._overlap_comm_active()
    assert np.isfinite(losses).all()
    # stage 3 shards params at rest → fused fallback
    eng, _, _ = _train(True, 3)
    assert not eng._overlap_comm_active()


def test_overlap_config_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage": 2, "overlap_comm": True,
                              "overlap_reduce": "fused",
                              "reduce_bucket_size": 1024}}, world_size=1)
    assert cfg.zero_config.overlap_comm
    assert cfg.zero_config.overlap_reduce == "fused"
    assert "overlap_reduce" in cfg.zero_config.repr_dict()
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {"overlap_reduce": "tree"}},
                        world_size=1)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {"overlap_comm": True,
                                               "reduce_bucket_size": 0}},
                        world_size=1)
    # parity configs (knob unused) keep accepting any value
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "zero_optimization": {"reduce_bucket_size": 0}},
                          world_size=1)
    assert cfg.zero_config.reduce_bucket_size == 0
    # with optimizer offload, overlap_comm means d2h grad streaming and
    # never reads the bucket size — also accepted
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "zero_optimization": {
                               "overlap_comm": True,
                               "reduce_bucket_size": 0,
                               "offload_optimizer": {"device": "cpu"}}},
                          world_size=1)
    assert cfg.zero_config.overlap_comm
