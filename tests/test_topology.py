"""Topology tests — mirrors the reference's pure-python test_topology.py."""

import pytest

from deepspeed_tpu.parallel.topology import (
    ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
    PipelineParallelGrid, _prime_factors)


def test_topology_2d():
    topo = ProcessTopology(axes=["row", "col"], dims=[2, 2])
    assert topo.world_size() == 4
    assert topo.get_rank(row=0, col=0) == 0
    assert topo.get_rank(row=0, col=1) == 1
    assert topo.get_rank(row=1, col=0) == 2
    assert topo.get_rank(row=1, col=1) == 3
    assert topo.get_axis_comm_lists("row") == [[0, 2], [1, 3]]
    assert topo.get_axis_comm_lists("col") == [[0, 1], [2, 3]]


def test_topology_dims():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=3, num_dp=4)
    assert topo.get_dim("pipe") == 2
    assert topo.get_dim("data") == 4
    assert topo.get_dim("model") == 3
    assert topo.world_size() == 24


def test_topology_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    ranks = topo.filter_match(pipe=0, data=1)
    assert len(ranks) == 2
    for r in ranks:
        coord = topo.get_coord(r)
        assert coord.pipe == 0 and coord.data == 1


def test_topology_coord_roundtrip():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    for rank in range(topo.world_size()):
        coord = topo.get_coord(rank)
        assert topo.get_rank(**coord._asdict()) == rank


def test_topology_invalid_rank():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    with pytest.raises(ValueError):
        topo.get_coord(99)


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    # omits data/pipe by default, leaving the model coordinate
    assert topo.get_rank_repr(rank=0) == "model_00"


def test_prime_factors():
    assert _prime_factors(12) == [2, 2, 3]
    assert _prime_factors(7) == [7]
    assert _prime_factors(1) == []


def test_grid_accessors():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    grid = PipelineParallelGrid(topology=topo, global_rank=5)
    assert grid.pipe_parallel_size == 2
    assert grid.data_parallel_size == 2
    assert grid.model_parallel_size == 2
    coord = topo.get_coord(5)
    assert grid.get_stage_id() == coord.pipe
    assert grid.get_data_parallel_rank() == coord.data
    assert grid.get_model_parallel_rank() == coord.model


def test_grid_p2p_pairs():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    grid = PipelineParallelGrid(topology=topo)
    assert len(grid.p2p_matrix) == 4
    for src, dst in grid.p2p_matrix:
        c_src, c_dst = topo.get_coord(src), topo.get_coord(dst)
        assert c_dst.pipe == (c_src.pipe + 1) % 2
        assert c_dst.data == c_src.data


def test_grid_stage_to_global():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    grid = PipelineParallelGrid(topology=topo, global_rank=0)
    other = grid.stage_to_global(stage_id=1)
    assert topo.get_coord(other).pipe == 1
    assert topo.get_coord(other).data == topo.get_coord(0).data


# ---------------------------------------------------------------------------
# data-axis hierarchy derivation (ISSUE 10) — the fast sibling of the
# slow multi-process test (test_multiprocess_dist.py): the split logic
# is pure over the mesh's device grid, so process-boundary rules pin
# here without forking processes.
# ---------------------------------------------------------------------------

class _FakeDev:
    def __init__(self, process_index):
        self.process_index = process_index


class _FakeMesh:
    def __init__(self, procs, axis="data"):
        import numpy as np
        self.axis_names = (axis,)
        self.devices = np.asarray([_FakeDev(p) for p in procs],
                                  dtype=object)
        self.shape = {axis: len(procs)}


def test_derive_hierarchy_from_process_boundaries():
    from deepspeed_tpu.parallel.topology import derive_data_hierarchy
    hier, reason = derive_data_hierarchy(_FakeMesh([0, 0, 0, 0,
                                                    1, 1, 1, 1]))
    assert reason == "" and (hier.inter, hier.intra) == (2, 4)
    assert hier.source == "process"


def test_derive_hierarchy_single_process_is_none():
    from deepspeed_tpu.parallel.topology import derive_data_hierarchy
    hier, reason = derive_data_hierarchy(_FakeMesh([0, 0, 0, 0]))
    assert hier is None and "single process" in reason


def test_derive_hierarchy_rejects_interleaved_processes():
    from deepspeed_tpu.parallel.topology import derive_data_hierarchy
    hier, reason = derive_data_hierarchy(_FakeMesh([0, 1, 0, 1]))
    assert hier is None and "not contiguous" in reason


def test_derive_hierarchy_rejects_uneven_blocks():
    from deepspeed_tpu.parallel.topology import derive_data_hierarchy
    hier, reason = derive_data_hierarchy(_FakeMesh([0, 0, 0, 1]))
    assert hier is None and "uneven" in reason


def test_derive_hierarchy_override_wins():
    from deepspeed_tpu.parallel.topology import derive_data_hierarchy
    # synthetic split on a single process (the single-process testing
    # override) — and a non-dividing override is rejected with a reason
    hier, reason = derive_data_hierarchy(_FakeMesh([0] * 8), slow_axis=2)
    assert (hier.inter, hier.intra, hier.source) == (2, 4, "override")
    hier, reason = derive_data_hierarchy(_FakeMesh([0] * 8), slow_axis=3)
    assert hier is None and "does not divide" in reason


def test_derive_hierarchy_trivial_axis_is_none():
    from deepspeed_tpu.parallel.topology import derive_data_hierarchy
    hier, reason = derive_data_hierarchy(_FakeMesh([0]))
    assert hier is None and "nothing to split" in reason
