"""GPT-2 serving-path tests: training→inference param injection, KV-cache
decode correctness (the reference's inference-kernel equivalence tests,
transformer_inference vs the training model)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2Config, \
    GPT2LMHeadModel
from deepspeed_tpu.models.gpt2_inference import (
    GPT2InferenceModel,
    convert_gpt2_params,
    generate,
    quantize_gpt2_inference_params,
)


def _setup(scan=True):
    cfg = gpt2_tiny(dtype=jnp.float32, scan_layers=scan)
    model = GPT2LMHeadModel(cfg)
    ids = np.random.RandomState(0).randint(0, 512, (2, 12)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    return cfg, model, params, ids


def test_injected_prompt_logits_match_training_model():
    cfg, model, params, ids = _setup()
    ref = model.apply({"params": params}, ids)
    iparams = convert_gpt2_params(params, cfg)
    inf = GPT2InferenceModel(cfg, max_out_tokens=32)
    got, _ = inf.apply({"params": iparams}, ids, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_injected_logits_match_unrolled_layout():
    cfg, model, params, ids = _setup(scan=False)
    ref = model.apply({"params": params}, ids)
    iparams = convert_gpt2_params(params, cfg)
    inf = GPT2InferenceModel(cfg, max_out_tokens=32)
    got, _ = inf.apply({"params": iparams}, ids, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_greedy_cache_decode_equals_full_reforward():
    """The KV-cache incremental decode must reproduce greedy generation done
    the slow way (full forward per emitted token on the training model)."""
    cfg, model, params, ids = _setup()
    steps = 8

    # slow path: re-run the full training model each step
    slow = jnp.asarray(ids)
    for _ in range(steps):
        logits = model.apply({"params": params}, slow)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        slow = jnp.concatenate([slow, nxt[:, None]], axis=1)

    fast = generate(cfg, params, ids, max_new_tokens=steps, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_generate_sampling_shape_and_determinism():
    cfg, _, params, ids = _setup()
    out1 = generate(cfg, params, ids, max_new_tokens=5, temperature=0.8,
                    rng=jax.random.PRNGKey(3))
    out2 = generate(cfg, params, ids, max_new_tokens=5, temperature=0.8,
                    rng=jax.random.PRNGKey(3))
    assert out1.shape == (2, 17)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert (np.asarray(out1[:, :12]) == ids).all()


def test_untied_embeddings_served_correctly():
    cfg = gpt2_tiny(dtype=jnp.float32, tie_word_embeddings=False)
    model = GPT2LMHeadModel(cfg)
    ids = np.random.RandomState(1).randint(0, 512, (2, 10)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    ref = model.apply({"params": params}, ids)
    iparams = convert_gpt2_params(params, cfg)
    got, _ = GPT2InferenceModel(cfg, max_out_tokens=32).apply(
        {"params": iparams}, ids, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_generate_rejects_overlong_request():
    import pytest
    cfg, _, params, ids = _setup()   # n_positions = 128, prompt 12
    with pytest.raises(AssertionError):
        generate(cfg, params, ids, max_new_tokens=120)


def test_int8_storage_serving():
    """int8 weight storage: params shrink to int8 codes, logits stay close
    to the fp path, generation runs (reference quantized inference)."""
    cfg, model, params, ids = _setup()
    ref = model.apply({"params": params}, ids)
    iparams = convert_gpt2_params(params, cfg)
    qparams = quantize_gpt2_inference_params(iparams, groups=4)
    blk = qparams["h"]["blk"]
    assert blk["attn_qkvw"]["kernel_q"].dtype == jnp.int8
    assert "kernel" not in blk["attn_qkvw"]

    inf = GPT2InferenceModel(cfg, max_out_tokens=32, quantize_bits=8,
                             quantize_groups=4)
    got, _ = inf.apply({"params": qparams}, ids, mutable=["cache"])
    ref_n = np.asarray(ref, np.float32)
    got_n = np.asarray(got, np.float32)
    # int8 weights shift logits but must stay within quantization noise
    err = np.abs(got_n - ref_n).mean() / (np.abs(ref_n).mean() + 1e-9)
    assert err < 0.12, err

    out = generate(cfg, qparams, ids, max_new_tokens=4, quantize_bits=8,
                   quantize_groups=4)
    assert out.shape == (2, 16)
    assert np.isfinite(np.asarray(out, np.float64)).all()


def test_step_loop_decode_matches_scan_decode():
    """The per-token decode_step path (streaming / big-batch callers,
    scan_decode=False) must produce exactly the scan-compiled path's
    greedy tokens — guards the offset/cache-donation math now that the
    scan path is the default everywhere else."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_inference import generate
    cfg = GPT2Config(vocab_size=512, n_positions=96, n_embd=64, n_layer=2,
                     n_head=2, dtype=jnp.float32)
    ids = np.random.RandomState(0).randint(0, 512, (2, 40)).astype(np.int32)
    params = GPT2LMHeadModel(cfg).init(jax.random.PRNGKey(0), ids)["params"]
    scan = generate(cfg, params, ids, max_new_tokens=12, scan_decode=True)
    loop = generate(cfg, params, ids, max_new_tokens=12, scan_decode=False)
    np.testing.assert_array_equal(np.asarray(scan), np.asarray(loop))


@pytest.mark.slow
def test_moe_gpt2_serves_through_inference_stack():
    """MoE GPT-2 decode: the fused inference layer routes each token
    through the expert bank. Exact equality with training-model
    re-forward holds iff expert capacity never binds (capacity_factor >=
    num_experts here guarantees it): under binding capacity the training
    model's own outputs are routed-length-dependent, so there is no
    single re-forward to match (see DeepSpeedInferenceConfig's capacity
    note)."""
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=32, n_layer=2,
                     n_head=2, dtype=jnp.float32, moe_experts=4, moe_k=1,
                     moe_capacity_factor=4.0, scan_layers=True)
    model = GPT2LMHeadModel(cfg)
    ids = np.random.RandomState(0).randint(0, 256, (2, 10)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    steps = 6
    slow = jnp.asarray(ids)
    for _ in range(steps):
        logits = model.apply({"params": params}, slow)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        slow = jnp.concatenate([slow, nxt[:, None]], axis=1)

    fast = generate(cfg, params, ids, max_new_tokens=steps, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_int8_kv_cache_decode():
    """kv_cache_bits=8: cached K/V live as int8 codes + per-token-per-head
    scales (2x cache memory vs bf16). Quantization perturbs scores, so
    the guarantee is LOGIT closeness (per-head symmetric int8 on K/V is a
    ~0.4% relative error), not token-for-token equality; the cache
    variables really are int8."""
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2_inference import (
        GPT2InferenceModel, convert_gpt2_params)
    cfg, model, params, ids = _setup()
    iparams = convert_gpt2_params(params, cfg)

    fp_m = GPT2InferenceModel(cfg, max_out_tokens=32)
    q8_m = GPT2InferenceModel(cfg, max_out_tokens=32, kv_cache_bits=8)
    fp_logits, _ = fp_m.apply({"params": iparams}, jnp.asarray(ids),
                              mutable=["cache"])
    q8_logits, vs = q8_m.apply({"params": iparams}, jnp.asarray(ids),
                               mutable=["cache"])
    err = np.max(np.abs(np.asarray(fp_logits, np.float32)
                        - np.asarray(q8_logits, np.float32)))
    spread = np.max(np.abs(np.asarray(fp_logits, np.float32)))
    assert err < 0.05 * spread, (err, spread)

    leaves = jax.tree_util.tree_leaves_with_path(vs["cache"])
    dtypes = {"/".join(str(getattr(k, "key", k)) for k in p): x.dtype
              for p, x in leaves}
    assert any(str(d) == "int8" for d in dtypes.values()), dtypes

    # decode runs end-to-end and emits the right shape
    out = generate(cfg, params, ids, max_new_tokens=6, temperature=0.0,
                   kv_cache_bits=8)
    assert out.shape == (ids.shape[0], ids.shape[1] + 6)


def test_kv_cache_bits_validation():
    import pytest
    from deepspeed_tpu.ops.transformer.inference import (
        DeepSpeedInferenceConfig)
    with pytest.raises(ValueError, match="kv_cache_bits"):
        DeepSpeedInferenceConfig(hidden_size=32, heads=2, kv_cache_bits=4)


@pytest.mark.slow
def test_tp_sharded_decode_matches_single_device(devices8):
    """mp_size serving (reference module_inject's mp_size sharding): a
    model-axis-sharded generate must produce the single-device tokens
    exactly (greedy, fp32). Covers the bf16/fp32 GSPMD path AND the
    int8-weights path (whose fused single-chip kernels must gate
    themselves off under mp_size > 1)."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=128,
                     n_layer=2, n_head=4, dtype=jnp.float32,
                     param_dtype=jnp.float32, scan_layers=True)
    rs = np.random.RandomState(3)
    prompt = rs.randint(0, 512, size=(2, 20)).astype(np.int32)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), prompt[:, :8])["params"]
    mesh = make_mesh(MeshConfig(model=2, data=1), devices=devices8[:2])

    t_single = generate(cfg, params, prompt, max_new_tokens=6,
                        max_out_tokens=128)
    t_tp = generate(cfg, params, prompt, max_new_tokens=6,
                    max_out_tokens=128, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(t_single), np.asarray(t_tp))

    qparams = quantize_gpt2_inference_params(
        convert_gpt2_params(params, cfg))
    t_q = generate(cfg, qparams, prompt, max_new_tokens=6,
                   max_out_tokens=128, quantize_bits=8, kv_cache_bits=8)
    t_q_tp = generate(cfg, qparams, prompt, max_new_tokens=6,
                      max_out_tokens=128, quantize_bits=8,
                      kv_cache_bits=8, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(t_q), np.asarray(t_q_tp))


@pytest.mark.slow
def test_fast_decode_scan_matches_flax_path():
    """The stacked-weight manual serving loop (_fast_decode_scan_fn —
    kernels index whole weight/cache stacks via scalar-prefetch, caches
    update one row in place) must produce EXACTLY the flax nn.scan
    path's tokens, greedy and sampled, across prompts and batch>1. The
    flax path slices every stacked array per layer per tick (~60% of the
    decode token in copies — device trace r4c), which is why the manual
    loop exists."""
    _parity_case(quantize_bits=8, kv_cache_bits=8)              # greedy
    _parity_case(quantize_bits=8, kv_cache_bits=8,              # sampled
                 temperature=0.8, rng=jax.random.PRNGKey(11))


def _parity_case(quantize_bits, kv_cache_bits, **gen_kw):
    """Fused fast-decode loop vs the flax path for one storage combo
    (optional generate kwargs, e.g. temperature/rng for sampled mode)."""
    import deepspeed_tpu.models.gpt2_inference as gi
    ctx = 192
    cfg = GPT2Config(vocab_size=512, n_positions=ctx, n_embd=256,
                     n_layer=3, n_head=4, dtype=jnp.float32,
                     param_dtype=jnp.float32, scan_layers=True)
    rs = np.random.RandomState(13)
    prompt = rs.randint(0, 512, size=(2, 40)).astype(np.int32)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(5), prompt[:, :8])["params"]
    sparams = convert_gpt2_params(params, cfg)
    if quantize_bits == 8:
        sparams = quantize_gpt2_inference_params(sparams)
    assert gi._supports_fast_decode(cfg, 2, quantize_bits, 1,
                                    kv_cache_bits, 1)
    kw = dict(max_new_tokens=8, max_out_tokens=ctx, scan_decode=True,
              quantize_bits=quantize_bits, kv_cache_bits=kv_cache_bits,
              **gen_kw)
    t_fast = generate(cfg, sparams, prompt, **kw)
    orig = gi._supports_fast_decode
    gi._supports_fast_decode = lambda *a: False
    try:
        t_ref = generate(cfg, sparams, prompt, **kw)
    finally:
        gi._supports_fast_decode = orig
    np.testing.assert_array_equal(np.asarray(t_fast), np.asarray(t_ref))


def test_fast_decode_bf16_weights_bf16_cache_parity():
    """Plain full-precision serving must take the fused loop too — the
    reference's inference kernels are fp16-first, quantization optional
    (csrc/transformer/inference/csrc/pt_binding.cpp)."""
    _parity_case(quantize_bits=0, kv_cache_bits=0)


def test_fast_decode_bf16_weights_int8_cache_parity():
    _parity_case(quantize_bits=0, kv_cache_bits=8)


def test_fast_decode_int8_weights_bf16_cache_parity():
    _parity_case(quantize_bits=8, kv_cache_bits=0)
