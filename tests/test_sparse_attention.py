"""Sparse-attention tests — the reference's test_sparse_attention.py role:
layout generators produce the documented patterns; sparse attention matches
dense attention when the layout is dense, and masks correctly otherwise."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    DenseSparsityConfig, FixedSparsityConfig, VariableSparsityConfig,
    BigBirdSparsityConfig, BSLongformerSparsityConfig, SparseSelfAttention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import sparse_attention
from deepspeed_tpu.ops.attention import reference_attention


def test_dense_layout_all_ones():
    layout = DenseSparsityConfig(num_heads=2, block=16).make_layout(64)
    assert layout.shape == (2, 4, 4)
    assert layout.sum() == 2 * 16


def test_fixed_layout_local_blocks():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    layout = cfg.make_layout(128)  # 8 blocks
    # diagonal (self) blocks always attended
    for i in range(8):
        assert layout[0, i, i] == 1
    # local windows of 2: block 0 attends block 1
    assert layout[0, 0, 1] == 1
    # global column: last block of each window attended by all rows
    assert layout[0, :, 1].all()


def test_fixed_unidirectional_causal():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(128)
    assert np.array_equal(layout[0], np.tril(layout[0]))


def test_fixed_bad_args():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=1, num_local_blocks=4, num_global_blocks=3)
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=1, attention="unidirectional",
                            horizontal_global_attention=True)


def test_seq_len_not_divisible_raises():
    cfg = DenseSparsityConfig(num_heads=1, block=16)
    with pytest.raises(ValueError):
        cfg.make_layout(100)


def test_variable_layout_globals():
    cfg = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                 local_window_blocks=[2],
                                 global_block_indices=[0])
    layout = cfg.make_layout(128)
    assert layout[0, :, 0].all()  # global column 0
    assert layout[0].sum() >= 8   # randoms + locals present


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    layout = cfg.make_layout(128)
    # global first/last row+col
    assert layout[0, 0, :].all() and layout[0, :, 0].all()
    assert layout[0, -1, :].all() and layout[0, :, -1].all()
    # sliding window around diagonal
    for i in range(1, 7):
        assert layout[0, i, i - 1] and layout[0, i, i] and layout[0, i, i + 1]


def test_bslongformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    layout = cfg.make_layout(128)
    assert layout[0, 0, :].all() and layout[0, :, 0].all()
    assert layout[0, 3, 2] and layout[0, 3, 3] and layout[0, 3, 4]
    assert not layout[0, 3, 6]


def test_different_layout_per_head_propagation():
    cfg = BigBirdSparsityConfig(num_heads=4, block=16,
                                different_layout_per_head=False)
    layout = cfg.make_layout(128)
    for h in range(1, 4):
        assert np.array_equal(layout[h], layout[0])


def test_sparse_attention_dense_layout_matches_reference():
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 2, 64, 16))
               for i in range(3))
    layout = DenseSparsityConfig(num_heads=2, block=16).make_layout(64)
    out = sparse_attention(q, k, v, layout, block=16)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_sparse_attention_blocks_masked():
    """keys outside the layout must not influence the output."""
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 1, 64, 8))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 1, 64, 8))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (1, 1, 64, 8))
    # only diagonal blocks allowed
    layout = np.zeros((1, 4, 4), np.int64)
    for i in range(4):
        layout[0, i, i] = 1
    out = sparse_attention(q, k, v, layout, block=16)
    # perturb keys/values in off-diagonal region for row block 0
    k2 = k.at[:, :, 16:, :].set(999.0)
    v2 = v.at[:, :, 16:, :].set(999.0)
    out2 = sparse_attention(q, k2, v2, layout, block=16)
    np.testing.assert_allclose(np.asarray(out[:, :, :16]),
                               np.asarray(out2[:, :, :16]), rtol=1e-5)


def test_sparse_self_attention_module():
    mod = SparseSelfAttention(
        FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2))
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (2, 2, 64, 16))
    out = mod(q, q, q)
    assert out.shape == q.shape
    assert np.isfinite(np.asarray(out)).all()
    assert 64 in mod._layout_cache
