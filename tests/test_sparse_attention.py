"""Sparse-attention tests — the reference's test_sparse_attention.py role:
layout generators produce the documented patterns; sparse attention matches
dense attention when the layout is dense, and masks correctly otherwise."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    DenseSparsityConfig, FixedSparsityConfig, VariableSparsityConfig,
    BigBirdSparsityConfig, BSLongformerSparsityConfig, SparseSelfAttention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import sparse_attention
from deepspeed_tpu.ops.attention import reference_attention


def test_dense_layout_all_ones():
    layout = DenseSparsityConfig(num_heads=2, block=16).make_layout(64)
    assert layout.shape == (2, 4, 4)
    assert layout.sum() == 2 * 16


def test_fixed_layout_local_blocks():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    layout = cfg.make_layout(128)  # 8 blocks
    # diagonal (self) blocks always attended
    for i in range(8):
        assert layout[0, i, i] == 1
    # local windows of 2: block 0 attends block 1
    assert layout[0, 0, 1] == 1
    # global column: last block of each window attended by all rows
    assert layout[0, :, 1].all()


def test_fixed_unidirectional_causal():
    cfg = FixedSparsityConfig(num_heads=1, block=16, num_local_blocks=4,
                              attention="unidirectional")
    layout = cfg.make_layout(128)
    assert np.array_equal(layout[0], np.tril(layout[0]))


def test_fixed_bad_args():
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=1, num_local_blocks=4, num_global_blocks=3)
    with pytest.raises(ValueError):
        FixedSparsityConfig(num_heads=1, attention="unidirectional",
                            horizontal_global_attention=True)


def test_seq_len_not_divisible_raises():
    cfg = DenseSparsityConfig(num_heads=1, block=16)
    with pytest.raises(ValueError):
        cfg.make_layout(100)


def test_variable_layout_globals():
    cfg = VariableSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                 local_window_blocks=[2],
                                 global_block_indices=[0])
    layout = cfg.make_layout(128)
    assert layout[0, :, 0].all()  # global column 0
    assert layout[0].sum() >= 8   # randoms + locals present


def test_bigbird_layout():
    cfg = BigBirdSparsityConfig(num_heads=1, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    layout = cfg.make_layout(128)
    # global first/last row+col
    assert layout[0, 0, :].all() and layout[0, :, 0].all()
    assert layout[0, -1, :].all() and layout[0, :, -1].all()
    # sliding window around diagonal
    for i in range(1, 7):
        assert layout[0, i, i - 1] and layout[0, i, i] and layout[0, i, i + 1]


def test_bslongformer_layout():
    cfg = BSLongformerSparsityConfig(num_heads=1, block=16,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    layout = cfg.make_layout(128)
    assert layout[0, 0, :].all() and layout[0, :, 0].all()
    assert layout[0, 3, 2] and layout[0, 3, 3] and layout[0, 3, 4]
    assert not layout[0, 3, 6]


def test_different_layout_per_head_propagation():
    cfg = BigBirdSparsityConfig(num_heads=4, block=16,
                                different_layout_per_head=False)
    layout = cfg.make_layout(128)
    for h in range(1, 4):
        assert np.array_equal(layout[h], layout[0])


def test_sparse_attention_dense_layout_matches_reference():
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (2, 2, 64, 16))
               for i in range(3))
    layout = DenseSparsityConfig(num_heads=2, block=16).make_layout(64)
    out = sparse_attention(q, k, v, layout, block=16)
    ref = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_sparse_attention_blocks_masked():
    """keys outside the layout must not influence the output."""
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 1, 64, 8))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 1, 64, 8))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (1, 1, 64, 8))
    # only diagonal blocks allowed
    layout = np.zeros((1, 4, 4), np.int64)
    for i in range(4):
        layout[0, i, i] = 1
    out = sparse_attention(q, k, v, layout, block=16)
    # perturb keys/values in off-diagonal region for row block 0
    k2 = k.at[:, :, 16:, :].set(999.0)
    v2 = v.at[:, :, 16:, :].set(999.0)
    out2 = sparse_attention(q, k2, v2, layout, block=16)
    np.testing.assert_allclose(np.asarray(out[:, :, :16]),
                               np.asarray(out2[:, :, :16]), rtol=1e-5)


def test_sparse_self_attention_module():
    mod = SparseSelfAttention(
        FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2))
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (2, 2, 64, 16))
    out = mod(q, q, q)
    assert out.shape == q.shape
    assert np.isfinite(np.asarray(out)).all()
    assert 64 in mod._layout_cache


# -- integration utils (reference sparse_attention_utils.py role) -----------

def test_bert_sparse_config_swap_forward():
    """Config-level sparse swap: a BERT encoder with a sparsity_config runs
    block-sparse attention end to end (reference
    replace_model_self_attention_with_sparse_self_attention)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.bert import bert_tiny, BertModel
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        SparseAttentionUtils,
    )
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

    dense_cfg = bert_tiny(max_position_embeddings=128)
    sparse_cfg = SparseAttentionUtils.sparse_config_for(
        dense_cfg, FixedSparsityConfig(num_heads=2, block=16,
                                       num_local_blocks=2,
                                       num_global_blocks=1))
    assert sparse_cfg.sparsity_config is not None

    ids = np.random.RandomState(0).randint(0, 512, (2, 64)).astype(np.int32)
    model = BertModel(sparse_cfg)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    seq_out, pooled = model.apply({"params": params}, ids)
    assert seq_out.shape == (2, 64, 64)
    assert np.isfinite(np.asarray(seq_out, np.float32)).all()

    # dense model with the same params differs (sparse layout masks scores)
    dense_out, _ = BertModel(dense_cfg).apply({"params": params}, ids)
    assert not np.allclose(np.asarray(seq_out, np.float32),
                           np.asarray(dense_out, np.float32), atol=1e-3)


def test_pad_unpad_to_block_size():
    import numpy as np
    import jax.numpy as jnp
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        SparseAttentionUtils,
    )
    ids = jnp.ones((2, 50), jnp.int32)
    mask = jnp.ones((2, 50), jnp.int32)
    pad_len, pids, pmask, ptok, ppos, pemb = \
        SparseAttentionUtils.pad_to_block_size(
            16, input_ids=ids, attention_mask=mask, pad_token_id=7)
    assert pad_len == 14
    assert pids.shape == (2, 64) and int(pids[0, -1]) == 7
    assert pmask.shape == (2, 64) and int(pmask[0, -1]) == 0
    out = jnp.zeros((2, 64, 8))
    assert SparseAttentionUtils.unpad_sequence_output(pad_len, out).shape \
        == (2, 50, 8)
    # already aligned → no-op
    pad_len, pids, *_ = SparseAttentionUtils.pad_to_block_size(
        16, input_ids=jnp.ones((2, 64), jnp.int32))
    assert pad_len == 0 and pids.shape == (2, 64)


def test_extend_position_embedding():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.bert import bert_tiny, BertModel
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        SparseAttentionUtils,
    )
    cfg = bert_tiny(max_position_embeddings=64)
    ids = np.zeros((1, 16), np.int32)
    params = BertModel(cfg).init(jax.random.PRNGKey(0), ids)["params"]
    ext = SparseAttentionUtils.extend_position_embedding(params, 150)
    tbl = ext["embeddings"]["position_embeddings"]
    assert tbl.shape[0] == 150
    orig = params["embeddings"]["position_embeddings"]
    np.testing.assert_array_equal(np.asarray(tbl[:64]), np.asarray(orig))
    np.testing.assert_array_equal(np.asarray(tbl[64:128]), np.asarray(orig))
    # other leaves untouched
    np.testing.assert_array_equal(
        np.asarray(ext["embeddings"]["word_embeddings"]),
        np.asarray(params["embeddings"]["word_embeddings"]))


def test_bert_sparse_self_attention_module():
    import numpy as np
    import jax
    from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import (
        BertSparseSelfAttention,
    )
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig
    mod = BertSparseSelfAttention(
        hidden_size=64, num_attention_heads=2,
        sparsity_config=FixedSparsityConfig(num_heads=2, block=16,
                                            num_local_blocks=2))
    x = np.random.RandomState(0).randn(2, 64, 64).astype(np.float32)
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    out = mod.apply({"params": params}, x)
    assert out.shape == (2, 64, 64)
    assert np.isfinite(np.asarray(out, np.float32)).all()


# ---- Pallas kernel (interpret mode) vs dense fallback: fwd AND grads ----

def _kernel_vs_dense(layout_cfg_block, seq, heads=2, batch=2, d=16, seed=0):
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        sparse_attention)
    layout, block = layout_cfg_block
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
    k = jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)
    v = jnp.asarray(rng.randn(batch, heads, seq, d), jnp.float32)

    def loss_kernel(q, k, v):
        o = sparse_attention(q, k, v, layout, block, use_kernel=True)
        return jnp.sum(jnp.sin(o))

    def loss_dense(q, k, v):
        o = sparse_attention(q, k, v, layout, block, use_kernel=False)
        return jnp.sum(jnp.sin(o))

    v1, g1 = jax.value_and_grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    v2, g2 = jax.value_and_grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(v1, v2, rtol=2e-5, atol=2e-5)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_blocksparse_kernel_grads_fixed_layout():
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        FixedSparsityConfig)
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2,
                              num_global_blocks=1)
    _kernel_vs_dense((cfg.make_layout(64), 16), 64)


def test_blocksparse_kernel_grads_bigbird_layout():
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        BigBirdSparsityConfig)
    cfg = BigBirdSparsityConfig(num_heads=2, block=16, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    _kernel_vs_dense((cfg.make_layout(96), 16), 96)


def test_blocksparse_kernel_grads_empty_rows():
    """A layout with an all-zero block row (no keys allowed) must produce
    zero output and zero grads for those rows, not NaN/Inf."""
    layout = np.zeros((1, 4, 4), np.int64)
    layout[0, 0, 0] = 1
    layout[0, 2, :3] = 1   # row 1 and 3 fully masked
    _kernel_vs_dense((layout, 16), 64, heads=1)


def test_blocksparse_kernel_under_jit_and_training_step():
    """jax.grad through the kernel inside a jitted update step — the
    reference's 'used under autograd for training' property."""
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        sparse_attention)
    from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
        FixedSparsityConfig)
    cfg = FixedSparsityConfig(num_heads=2, block=16, num_local_blocks=2)
    layout = cfg.make_layout(64)
    rng = np.random.RandomState(1)
    w = jnp.asarray(rng.randn(16, 16) * 0.2, jnp.float32)
    x = jnp.asarray(rng.randn(2, 2, 64, 16), jnp.float32)

    @jax.jit
    def step(w):
        def loss(w):
            qkv = x @ w
            o = sparse_attention(qkv, qkv, qkv, layout, 16, use_kernel=True)
            return jnp.mean(o ** 2)
        l, g = jax.value_and_grad(loss)(w)
        return l, w - 0.1 * g

    l0, w = step(w)
    for _ in range(4):
        l1, w = step(w)
    assert np.isfinite(float(l1)) and float(l1) < float(l0)


def test_bigbird_16k_kernel_long_sequence():
    """The streaming kernel handles S=16k in-kernel (the old whole-row
    variant refused past S*D=256k — VERDICT r2 weak #2): verify sampled
    q-block rows against a numpy reference restricted to active blocks."""
    import numpy as np
    from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention

    S, D, block = 16384, 16, 64
    nb = S // block
    cfg = BigBirdSparsityConfig(num_heads=1, block=block, num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    np.random.seed(0)
    layout = cfg.make_layout(S)
    rng = np.random.RandomState(1)
    q = rng.randn(1, 1, S, D).astype(np.float32) * 0.3
    k = rng.randn(1, 1, S, D).astype(np.float32) * 0.3
    v = rng.randn(1, 1, S, D).astype(np.float32) * 0.3

    out = np.asarray(blocksparse_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), layout, block,
        interpret=True))
    assert out.shape == (1, 1, S, D)
    assert np.isfinite(out).all()

    scale = 1.0 / np.sqrt(D)
    for r in (0, 7, nb // 2, nb - 1):      # sampled q-block rows
        cols = np.nonzero(layout[0, r])[0]
        ks = np.concatenate([k[0, 0, c * block:(c + 1) * block] for c in cols])
        vs = np.concatenate([v[0, 0, c * block:(c + 1) * block] for c in cols])
        qs = q[0, 0, r * block:(r + 1) * block]
        s = (qs @ ks.T) * scale
        p = np.exp(s - s.max(axis=1, keepdims=True))
        p /= p.sum(axis=1, keepdims=True)
        ref = p @ vs
        np.testing.assert_allclose(out[0, 0, r * block:(r + 1) * block],
                                   ref, rtol=2e-4, atol=2e-5)


def test_blocksparse_grad_long_sequence():
    """Gradients flow through the streaming kernels at a length the old
    kernel refused (S*D > 256k)."""
    from deepspeed_tpu.ops.pallas.blocksparse import blocksparse_attention

    S, D, block = 8192, 64, 64
    cfg = BSLongformerSparsityConfig(num_heads=1, block=block,
                                     num_sliding_window_blocks=3,
                                     global_block_indices=[0])
    layout = cfg.make_layout(S)
    rng = jax.random.PRNGKey(2)
    q, k, v = (jax.random.normal(jax.random.fold_in(rng, i), (1, 1, S, D),
                                 jnp.float32) * 0.2 for i in range(3))

    def loss(q, k, v):
        return jnp.sum(blocksparse_attention(q, k, v, layout, block,
                                             interpret=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for gi in g:
        arr = np.asarray(gi)
        assert np.isfinite(arr).all()
        assert np.abs(arr).max() > 0


def test_kernel_crossover_predicate():
    """Auto mode must reject the kernel for near-dense layouts (the
    issue-bound kernel loses to the masked-dense path there) and keep it
    for genuinely sparse ones — the v4 crossover calibration."""
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        _kernel_beats_dense)
    S, block = 4096, 128
    nb = S // block
    sparse_layout = np.zeros((1, nb, nb), np.int64)
    for i in range(nb):
        sparse_layout[0, i, max(0, i - 1):i + 2] = 1   # ~3-wide window
    assert _kernel_beats_dense(sparse_layout, block, S)
    dense_layout = np.ones((1, nb, nb), np.int64)
    assert not _kernel_beats_dense(dense_layout, block, S)
    # the 16k BigBird regime (density ~0.06) must stay on the kernel
    from deepspeed_tpu.ops.sparse_attention import BigBirdSparsityConfig
    cfg = BigBirdSparsityConfig(num_heads=1, block=128,
                                num_random_blocks=1,
                                num_sliding_window_blocks=3,
                                num_global_blocks=1)
    np.random.seed(0)
    assert _kernel_beats_dense(cfg.make_layout(16384), 128, 16384)
