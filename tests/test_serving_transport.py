"""Cross-process KV page-handoff transport (ISSUE 17).

Fast tier:

- wire-codec goldens (``test_wire_*``, pure numpy — the subset
  ci/serving_gate.sh runs): byte-exact round-trips for fp and int8
  pool layouts, the versioned-header guard (an unknown version raises
  LOUD instead of silently corrupting old packets/snapshots),
  crc/truncation rejection, forward-compatible extra header keys, and
  the receiver-side packet-size cost model;
- ``test_golden_*``: REAL :class:`HandoffPacket`\\ s extracted from a
  live prefill engine (fp32 + prefix-shared pages, and an int8
  quantized pool) survive encode→decode bytes-exactly.

Slow tier (2 REAL OS processes over the PR-10 ``spawn_workers``
harness / the PR-15 ``Supervisor``; fast single-process loopback
siblings live in tests/test_serving_disagg.py):

- the acceptance leg: prefill-role rank 0 hands off to decode-role
  rank 1, >= 32 cross-process handoffs token-identical to the
  colocated greedy run, leak fence clean on BOTH pools, and the
  ``router/handoff_bytes_{sent,recv}`` counters agreeing across the
  process boundary (recv is recomputed from decoded content — the
  canonical-encoding cost model);
- the fault leg: SIGKILL of the decode-role process mid-stream → the
  supervisor detects it (role-stamped incident), respawns the world,
  and every request finishes token-lossless with exactly one latched
  rank_dead dump and zero orphaned trace_ids across per-role dumps.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

from deepspeed_tpu.serving.transport import (
    FRAME_BASE_NBYTES, WIRE_MAGIC, WIRE_VERSION, WireFormatError,
    _HEAD, decode_frame, decode_frames, encode_frame, frame_nbytes,
    payload_nbytes)

# ------------------------------------------------------- codec goldens


def _mk_comps():
    rs = np.random.RandomState(7)
    return [rs.randn(2, 3, 8, 4).astype(np.float32),
            rs.randint(-128, 128, (2, 3, 8, 4)).astype(np.int8),
            rs.randn(2, 3).astype(np.float16)]


def test_wire_roundtrip_bytes_exact():
    """encode(decode(b)) == b — the canonical-encoding property every
    golden and the receiver-side cost model ride on."""
    doc = {"rid": 3, "prompt": [1, 2, 3], "generated": [9],
           "pos": 4, "last_tok": 9, "n_data_pages": 1,
           "t_sent": 123.25, "trace_id": "abc"}
    buf = encode_frame("packet", doc, _mk_comps(), src=0, dst=1)
    frame, end = decode_frame(buf)
    assert end == len(buf)
    assert frame["kind"] == "packet"
    assert frame["src"] == 0 and frame["dst"] == 1
    assert frame["doc"] == doc
    for a, b in zip(frame["comps"], _mk_comps()):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    re_encoded = encode_frame(frame["kind"], frame["doc"],
                              frame["comps"], frame["src"],
                              frame["dst"])
    assert re_encoded == buf
    assert frame_nbytes(frame) == len(buf)


def test_wire_int8_pool_layout_roundtrip():
    """The quantized pool shape — int8 code blocks + float scale
    rows — survives bytes-exactly (dtype/shape carried per component,
    payloads raw)."""
    rs = np.random.RandomState(3)
    comps = [rs.randint(-128, 128, (2, 6, 8, 16)).astype(np.int8),
             rs.randn(2, 6, 8, 1).astype(np.float32)]
    buf = encode_frame("packet", {"n_data_pages": 6}, comps)
    frame, _ = decode_frame(buf)
    assert [c.dtype.str for c in frame["comps"]] == ["|i1", "<f4"]
    for a, b in zip(frame["comps"], comps):
        np.testing.assert_array_equal(a, b)
    assert encode_frame(frame["kind"], frame["doc"], frame["comps"],
                        frame["src"], frame["dst"]) == buf
    assert payload_nbytes(frame["comps"]) == sum(c.nbytes for c in comps)


def test_wire_unknown_version_raises_loud():
    """The versioned-header contract: a field addition bumps
    WIRE_VERSION and an old reader REFUSES — no silent corruption of
    old packets or serving snapshots."""
    buf = bytearray(encode_frame("done", {"rid": 1}))
    head = _HEAD.unpack_from(buf, 0)
    _HEAD.pack_into(buf, 0, head[0], WIRE_VERSION + 1, head[2], head[3])
    with pytest.raises(WireFormatError, match="version"):
        decode_frame(bytes(buf))
    bad_magic = b"XXXX" + bytes(buf)[4:]
    with pytest.raises(WireFormatError, match="magic"):
        decode_frame(bad_magic)


def test_wire_crc_and_truncation_rejected():
    buf = encode_frame("packet", {"n_data_pages": 1},
                       [np.arange(64, dtype=np.float32)])
    # flip one payload byte -> component crc mismatch
    corrupt = bytearray(buf)
    corrupt[-1] ^= 0xFF
    with pytest.raises(WireFormatError, match="crc"):
        decode_frame(bytes(corrupt))
    # flip one header byte -> header crc mismatch
    corrupt = bytearray(buf)
    corrupt[FRAME_BASE_NBYTES + 2] ^= 0xFF
    with pytest.raises(WireFormatError, match="crc"):
        decode_frame(bytes(corrupt))
    for cut in (3, FRAME_BASE_NBYTES + 4, len(buf) - 8):
        with pytest.raises(WireFormatError, match="truncated"):
            decode_frame(buf[:cut])


def test_wire_forward_compat_extra_header_keys_ignored():
    """A SAME-version reader tolerates forward extensions: unknown
    header keys decode cleanly and are dropped."""
    import zlib
    header = json.dumps(
        {"v": WIRE_VERSION, "kind": "done", "src": 2, "dst": 0,
         "doc": {"rid": 5}, "comps": [], "future_field": [1, 2]},
        sort_keys=True, separators=(",", ":")).encode()
    buf = _HEAD.pack(WIRE_MAGIC, WIRE_VERSION, len(header),
                     zlib.crc32(header) & 0xFFFFFFFF) + header
    frame, end = decode_frame(buf)
    assert end == len(buf)
    assert frame["kind"] == "done" and frame["doc"] == {"rid": 5}
    assert frame["src"] == 2 and frame["comps"] == ()


def test_wire_multiframe_buffer_and_kinds():
    """Frames are self-delimiting: an exchange buffer concatenating a
    packet, a done and a nack decodes back into exactly those three."""
    frames_in = [
        encode_frame("packet", {"rid": 0, "n_data_pages": 2},
                     [np.ones((2, 2, 4), np.float32)], src=0, dst=1),
        encode_frame("done", {"rid": 1, "tokens": [1, 2, 3],
                              "finish_reason": "length"}, src=1, dst=0),
        encode_frame("nack", {"rid": 2, "error": "boom"}, src=1, dst=0),
    ]
    out = decode_frames(b"".join(frames_in))
    assert [f["kind"] for f in out] == ["packet", "done", "nack"]
    assert sum(frame_nbytes(f) for f in out) == \
        sum(len(b) for b in frames_in)


# ----------------------- ISSUE 18: addressed frames + LPT balancing
# (model-free — these ride ci/serving_gate.sh next to the codec
# goldens: LoopbackEndpoint wire routing/waste accounting and the
# PrefillNode placement policy over stub engines, no jax model build)


def _loopback_world3(addressing):
    from deepspeed_tpu.serving.transport import LoopbackFabric, MV_LEN
    fab = LoopbackFabric(3, addressing=addressing)
    mv = np.zeros(MV_LEN, np.float32)
    return fab, [fab.endpoint(r) for r in range(3)], mv


def test_addressed_frame_targeted_reaches_only_its_destination():
    """Targeted addressing golden: a dst=1 frame lands on rank 1 only,
    a dst=-1 frame lands everywhere, and no rank counts a single
    wasted byte — the wire-cost property the slow 3-process pin
    asserts from real counters."""
    fab, (e0, e1, e2), mv = _loopback_world3("targeted")
    pkt = encode_frame("packet", {"rid": 1, "n_data_pages": 1},
                       [np.arange(8, dtype=np.float32)], src=0, dst=1)
    bc = encode_frame("done", {"rid": 9}, src=0, dst=-1)
    e0.exchange([(1, pkt), (-1, bc)], mv)
    f1, _ = e1.exchange([], mv)
    f2, _ = e2.exchange([], mv)
    assert [f["kind"] for f in f1] == ["packet", "done"]
    assert [f["kind"] for f in f2] == ["done"]   # broadcast only
    assert e1.take_wasted() == 0 and e2.take_wasted() == 0


def test_addressed_frame_broadcast_counts_unaddressed_bytes_wasted():
    """Broadcast addressing copies the dst=1 frame to rank 2 as well;
    rank 2 filters it and books EXACTLY the frame's canonical wire
    size as wasted — the counter the targeted mode drives to ~0."""
    fab, (e0, e1, e2), mv = _loopback_world3("broadcast")
    pkt = encode_frame("packet", {"rid": 1, "n_data_pages": 1},
                       [np.arange(8, dtype=np.float32)], src=0, dst=1)
    e0.exchange([(1, pkt)], mv)
    f1, _ = e1.exchange([], mv)
    f2, _ = e2.exchange([], mv)
    assert [f["kind"] for f in f1] == ["packet"] and f2 == []
    assert e1.take_wasted() == 0
    assert e2.take_wasted() == len(pkt)
    assert e2.take_wasted() == 0    # drained


class _StubCache:
    def pages_needed(self, n):
        return max((int(n) + 7) // 8, 1)


class _StubPrefillEngine:
    role = "prefill"
    replica_id = "stub0"

    def __init__(self):
        from deepspeed_tpu.telemetry.registry import MetricsRegistry
        self.queue = []
        self.slots = []
        self.cache = _StubCache()
        self.metrics = MetricsRegistry()


def _mk_balancer(world=3, **pkw):
    from deepspeed_tpu.serving.transport import (LoopbackFabric,
                                                 PrefillNode)
    fab = LoopbackFabric(world)
    return PrefillNode([_StubPrefillEngine()], fab.endpoint(0), **pkw)


def _mk_packet(rid, n_pages=2, remaining=8):
    from deepspeed_tpu.serving.router import HandoffPacket
    doc = {"rid": rid, "generated": [], "max_new_tokens": remaining,
           "n_data_pages": n_pages, "trace_id": f"t{rid}"}
    return HandoffPacket(doc, [np.zeros((n_pages, 4), np.float32)], None)


def test_balancer_lpt_picks_least_loaded_rank():
    """The placement policy, white-box: with rank 1 reporting heavy
    remaining work and rank 2 idle, every packet goes to rank 2 until
    the packets themselves level the load estimate."""
    from deepspeed_tpu.serving.transport import (MV_LEN, MV_REMAINING)
    pnode = _mk_balancer()
    mat = np.zeros((3, MV_LEN), np.float32)
    mat[1, MV_REMAINING] = 100.0
    pnode._packets.extend(
        [_mk_packet(0, remaining=8), _mk_packet(1, remaining=6)])
    out = []
    pnode._sweep_and_send(mat, out)
    assert [dst for dst, _buf in out] == [2, 2]
    assert not pnode._packets
    assert pnode._sent_pages == {1: 0, 2: 4}
    # longest-remaining packet was placed FIRST (LPT order)
    frames = decode_frames(b"".join(buf for _dst, buf in out))
    assert [f["doc"]["rid"] for f in frames] == [0, 1]


def test_balancer_spreads_when_loads_level():
    """Equal reported load: LPT alternates because each placement adds
    the packet's own remaining estimate to its target's load."""
    from deepspeed_tpu.serving.transport import MV_LEN
    pnode = _mk_balancer()
    mat = np.zeros((3, MV_LEN), np.float32)
    pnode._packets.extend([_mk_packet(i, remaining=8) for i in range(4)])
    out = []
    pnode._sweep_and_send(mat, out)
    dsts = [dst for dst, _buf in out]
    assert sorted(dsts) == [1, 1, 2, 2], dsts


def test_balancer_per_rank_cap_holds_and_latches_per_episode():
    """No eligible rank → the packet stays queued at the router and
    each refusing rank latches ONE decode_blocked; acknowledged
    absorption (MV_ABSORBED_PAGES catching up) re-opens the rank and
    drains the held packet."""
    from deepspeed_tpu.serving.transport import (MV_ABSORBED_PAGES,
                                                 MV_LEN)
    pnode = _mk_balancer(max_inflight_pages_per_rank=4)
    mat = np.zeros((3, MV_LEN), np.float32)
    pnode._packets.extend([_mk_packet(i, n_pages=2) for i in range(3)])
    out = []
    pnode._sweep_and_send(mat, out)
    # all three fit under the 4-page cap (ranks end at 4 and 2 pages)
    assert len(out) == 3 and not pnode._packets
    # now both ranks carry 2-4 unacknowledged pages; a 4-page packet
    # fits nowhere (unabsorbed + 4 > 4 and unabsorbed != 0)
    pnode._packets.append(_mk_packet(9, n_pages=4))
    out2 = []
    pnode._sweep_and_send(mat, out2)
    assert out2 == [] and len(pnode._packets) == 1
    assert pnode.stats["decode_blocked"] == 2      # one latch per rank
    pnode._sweep_and_send(mat, out2)               # same episode:
    assert pnode.stats["decode_blocked"] == 2      # no re-count
    # rank 2 acknowledges everything -> unabsorbed 0 -> oversized
    # packet allowed (the cap is backpressure, not a validator)
    mat[2, MV_ABSORBED_PAGES] = pnode._sent_pages[2]
    pnode._sweep_and_send(mat, out2)
    assert [dst for dst, _buf in out2] == [2] and not pnode._packets


def test_balancer_uncapped_default_without_aggregate_bound():
    """No aggregate bound, no per-rank override -> no per-rank cap
    (None); with an aggregate bound the default splits it evenly."""
    assert _mk_balancer().max_inflight_pages_per_rank is None
    pnode = _mk_balancer(max_inflight_pages=8)
    assert pnode.max_inflight_pages_per_rank == 4
    pnode = _mk_balancer(max_inflight_pages=8,
                         max_inflight_pages_per_rank=7)
    assert pnode.max_inflight_pages_per_rank == 7


# ------------------------------------------- real-packet goldens (jax)


def _tiny_prefill(kv_cache_bits=0, prefix=True):
    import jax.numpy as jnp  # noqa: F401  (lazy: keep module import light)
    import deepspeed_tpu.serving as serving
    from deepspeed_tpu.serving.engine import ContinuousBatcher
    from tests.xproc_serving_worker import build_model
    cfg, params = build_model()
    sv = {"slots": 2, "page_size": 8, "max_pages_per_slot": 8}
    if kv_cache_bits:
        sv["kv_cache_bits"] = kv_cache_bits
    adapter = serving.build_engine(
        "gpt2", cfg, params, config={"serving": sv}).adapter
    return ContinuousBatcher(adapter, role="prefill",
                             prefix_cache=prefix)


def _golden_roundtrip(pcb, reqs):
    from deepspeed_tpu.serving.router import extract_handoff
    from deepspeed_tpu.serving.transport import (encode_packet,
                                                 packet_from_frame)
    for r in reqs:
        pcb.submit(r)
    pcb.step()
    packets = [extract_handoff(pcb, i)
               for i, s in enumerate(pcb.slots) if s.active]
    assert packets
    for packet in packets:
        buf = encode_packet(packet, src=0, dst=1)
        frame, end = decode_frame(buf)
        assert end == len(buf)
        back = packet_from_frame(frame)
        assert back.doc == packet.doc
        assert back.req is None      # rebuilt from the doc on delivery
        assert len(back.kv) == len(packet.kv)
        for a, b in zip(back.kv, packet.kv):
            got = np.asarray(a)
            want = np.asarray(b)
            assert got.dtype == want.dtype and got.shape == want.shape
            np.testing.assert_array_equal(got, want)
        # byte-exact re-encode: the golden property
        assert encode_frame(frame["kind"], frame["doc"], frame["comps"],
                            frame["src"], frame["dst"]) == buf
        assert payload_nbytes(frame["comps"]) == \
            packet.doc["n_data_pages"] * pcb.cache.page_nbytes
    return packets


def test_golden_handoff_packet_fp32_prefix_shared():
    """A real fp32 packet — including one whose prompt pages are
    PREFIX-SHARED in the sending pool — round-trips bytes-exactly,
    and its payload equals n_data_pages * page_nbytes (the counters'
    cost model)."""
    import deepspeed_tpu.serving as serving
    pcb = _tiny_prefill()
    prompt = np.arange(17, dtype=np.int32) % 256
    reqs = [serving.Request(0, prompt, max_new_tokens=4),
            serving.Request(1, prompt.copy(), max_new_tokens=4)]
    packets = _golden_roundtrip(pcb, reqs)
    assert len(packets) == 2
    # both packets carry the SAME prompt-page bytes (the second slot
    # shared the first's full pages): gathers must agree exactly
    for a, b in zip(packets[0].kv, packets[1].kv):
        n_full = 17 // 8
        np.testing.assert_array_equal(
            np.asarray(a)[:, :n_full], np.asarray(b)[:, :n_full])


def test_golden_handoff_packet_int8_pool():
    """The int8-quantized pool layout (code blocks + scale components)
    round-trips bytes-exactly through the same frame."""
    import deepspeed_tpu.serving as serving
    pcb = _tiny_prefill(kv_cache_bits=8, prefix=False)
    assert any(np.dtype(c.dtype) == np.int8 for c in pcb.cache.pool)
    reqs = [serving.Request(0, (np.arange(12, dtype=np.int32) * 7) % 256,
                            max_new_tokens=4)]
    _golden_roundtrip(pcb, reqs)


# ------------------------------------- 2-real-process acceptance (slow)

_XPROC_SCRIPT = """
import sys
from tests.xproc_serving_worker import main
main(["worker"] + sys.argv[1:])
"""


def _parse_rank0(out):
    res, met = {}, None
    for line in out.splitlines():
        if line.startswith("RES "):
            _tag, rid, doc = line.split(" ", 2)
            res[int(rid)] = json.loads(doc)
        elif line.startswith("MET "):
            met = json.loads(line[4:])
    return res, met


def _parse_met(out):
    for line in out.splitlines():
        if line.startswith("MET "):
            return json.loads(line[4:])
    return None


def _colocated_reference(n_reqs, max_new):
    from deepspeed_tpu.serving.engine import ContinuousBatcher
    import deepspeed_tpu.serving as serving
    from tests.xproc_serving_worker import (build_model, build_requests,
                                            serving_config)
    cfg, params = build_model()
    sv = dict(serving_config()["serving"])
    sv.pop("disaggregation")
    adapter = serving.build_engine(
        "gpt2", cfg, params, config={"serving": sv}).adapter
    done = ContinuousBatcher(adapter).serve(
        build_requests(n_reqs, max_new))
    return {rid: r.tokens().tolist() for rid, r in done.items()}


@pytest.mark.slow
def test_two_process_handoff_acceptance(tmp_path):
    """THE acceptance leg: 32+ handoffs prefill-rank -> decode-rank
    over 2 REAL processes, token-identical to the colocated greedy
    run, leak fence clean on both pools, byte counters agreeing
    across the boundary."""
    from tests.test_multiprocess_dist import spawn_workers
    n_reqs, max_new = 32, 6
    out_dir = tmp_path / "out"
    outs = spawn_workers(2, _XPROC_SCRIPT, tmp_path,
                         script_args=(str(out_dir), n_reqs, max_new),
                         timeout=420)
    res, met0 = _parse_rank0(outs[0])
    met1 = _parse_met(outs[1])
    assert met0 and met1, (outs[0][-2000:], outs[1][-2000:])
    # every stream token-identical to the colocated run
    ref = _colocated_reference(n_reqs, max_new)
    assert sorted(res) == sorted(ref)
    for rid, toks in ref.items():
        assert res[rid]["tokens"] == toks, rid
    # >= 32 real cross-process handoffs, none lost, none requeued
    assert met0["stats"]["handoffs"] >= 32
    assert met0["stats"]["lost"] == 0
    assert met1["stats"]["delivered"] == met0["stats"]["handoffs"]
    # leak fence on BOTH pools: every pool drains to num_blocks - 1
    for met in (met0, met1):
        for fence in met["leak_fence"]:
            assert fence["free"] == fence["want"], (met["rank"], fence)
    # byte counters match the packet-size cost model: the sender
    # counts encoded frame lengths, the receiver RECOMPUTES each
    # frame's size from its decoded content (canonical encoding) —
    # equality across the process boundary pins both
    sent = met0["counters"]["router/handoff_bytes_sent"]
    recv = met1["counters"]["router/handoff_bytes_recv"]
    assert sent == recv == met0["stats"]["bytes_sent"] > 0
    # and the payload term: absorbed data pages x page_nbytes, plus a
    # small per-frame header
    payload = met1["absorbed_pages"] * met0["page_nbytes"]
    assert payload < sent < payload + met0["stats"]["handoffs"] * 2048
    # transport term observed on the decode rank for every delivery
    assert met1["transport_s"]["count"] == met1["stats"]["delivered"]
    # TTFT decomposition holds over REAL processes too (ISSUE 19
    # satellite): queue-wait + prefill segments sum to serving/ttft_s
    # up to the sub-ms admit bookkeeping between the two stamps —
    # aggregate form (sum = mean x count lives in the summaries)
    ttft, qw, pf = (met0["ttft_s"], met0["ttft_queue_wait_s"],
                    met0["ttft_prefill_s"])
    assert ttft["count"] == qw["count"] == pf["count"] == n_reqs
    gap = ttft["sum"] - (qw["sum"] + pf["sum"])
    assert 0.0 <= gap <= 0.01 * n_reqs + 0.02 * ttft["sum"], (
        ttft["sum"], qw["sum"], pf["sum"])
    # causal tree across the process boundary (ISSUE 19 acceptance):
    # merge BOTH ranks' exit dumps — every parent_span resolves, and
    # every cross-process handoff renders as one flow pair in the
    # perfetto export (no orphan spans, no unpaired arrows)
    from deepspeed_tpu.telemetry import view
    from deepspeed_tpu.telemetry import perfetto
    dumps = sorted(str(p) for p in out_dir.glob("flight_rank*.jsonl"))
    assert len(dumps) == 2, dumps
    events = []
    for p in dumps:
        _header, evs, _skipped = view.load_dump(p)
        events.extend(evs)
    assert perfetto.orphan_spans(events) == []
    # the decode rank's handoff_in spans all parent onto spans minted
    # on the PREFILL rank (the encode span shipped in the wire doc)
    rank0_spans = set()
    for p in dumps[:1]:
        _h, evs, _s = view.load_dump(p)
        rank0_spans.update(e["span_id"] for e in evs
                           if e.get("span_id"))
    hins = [e for e in events if e["kind"] == "handoff_in"]
    assert len(hins) == met1["stats"]["delivered"]
    assert all(e.get("parent_span") in rank0_spans for e in hins)
    doc = perfetto.export(dumps)
    starts = [e for e in doc["traceEvents"] if e["ph"] == "s"]
    finishes = [e for e in doc["traceEvents"] if e["ph"] == "f"]
    assert len(finishes) == len(hins)
    assert len(starts) >= len(finishes)


@pytest.mark.slow
def test_three_process_wire_cost_per_handoff_world_independent(tmp_path):
    """THE ISSUE-18 wire-cost pin: with targeted addressing a handoff
    payload crosses the wire ONCE no matter how many decode ranks
    exist. Same workload over world=2 and world=3 — per-handoff
    payload bytes (headers excluded, wasted included) within 10%,
    sent == recv EXACT in both worlds, wasted ~0, and the world=3 run
    actually used both decode ranks."""
    from tests.test_multiprocess_dist import spawn_workers
    n_reqs, max_new = 16, 6

    def leg(world, sub):
        out_dir = tmp_path / sub / "out"
        (tmp_path / sub).mkdir(exist_ok=True)
        outs = spawn_workers(world, _XPROC_SCRIPT, tmp_path / sub,
                             script_args=(str(out_dir), n_reqs, max_new),
                             timeout=420)
        res, met0 = _parse_rank0(outs[0])
        dmets = [_parse_met(o) for o in outs[1:]]
        assert met0 and all(dmets), [o[-1500:] for o in outs]
        assert sorted(res) == list(range(n_reqs))
        return met0, dmets

    met2, dmets2 = leg(2, "w2")
    met3, dmets3 = leg(3, "w3")
    for met0, dmets in ((met2, dmets2), (met3, dmets3)):
        # counters agree EXACTLY across the process boundary: the
        # receivers' recomputed frame sizes sum to the sender's
        sent = met0["counters"]["router/handoff_bytes_sent"]
        recv = sum(d["counters"]["router/handoff_bytes_recv"]
                   for d in dmets)
        assert sent == recv > 0
        # targeted mode: no rank received a byte it was not addressed
        for met in [met0] + dmets:
            assert met["stats"]["wasted_bytes"] == 0, met["stats"]
    # the world=3 leg balanced across BOTH decode ranks
    delivered3 = [d["stats"]["delivered"] for d in dmets3]
    assert all(n >= 1 for n in delivered3), delivered3

    def cost_per_handoff(met0, dmets):
        payload = sum(d["absorbed_pages"] for d in dmets) \
            * met0["page_nbytes"]
        wasted = sum(m["stats"]["wasted_bytes"]
                     for m in [met0] + dmets)
        return (payload + wasted) / met0["stats"]["handoffs"]

    c2 = cost_per_handoff(met2, dmets2)
    c3 = cost_per_handoff(met3, dmets3)
    assert abs(c3 / c2 - 1.0) <= 0.10, (c2, c3)


@pytest.mark.slow
def test_supervisor_sigkill_decode_rank_recovers(tmp_path):
    """The fault acceptance leg: the decode-role process SIGKILLs
    itself mid-stream (after 2 deliveries, epoch 0). The supervisor
    detects the death with its serving role attached, respawns the
    2-rank world in place, and the respawned epoch re-serves ONLY the
    unfinished rids from the ledger — every stream finishes
    token-lossless, exactly one latched rank_dead dump, zero orphaned
    trace_ids across the per-role dumps."""
    from deepspeed_tpu.runtime.elastic.supervisor import Supervisor
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    from deepspeed_tpu.telemetry import view
    n_reqs, max_new = 8, 6
    out_dir = str(tmp_path / "out")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))
                + os.pathsep + os.environ.get("PYTHONPATH", "")})
    sup = Supervisor(
        [sys.executable, os.path.join("tests", "xproc_serving_worker.py"),
         out_dir, str(n_reqs), str(max_new), "2"],
        2, heartbeat_dir=str(tmp_path / "hb"),
        dump_dir=str(tmp_path / "sup_dumps"),
        valid_worlds=[2],                 # serving worlds don't shrink:
        roles={0: "prefill", 1: "decode"},  # respawn IN PLACE
        hang_deadline_s=60.0, grace_kill_s=3.0, max_restarts=2,
        backoff_base_s=0.2, backoff_max_s=0.5, poll_s=0.1,
        local_devices=1, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        recorder=FlightRecorder())
    rc = sup.run(deadline_s=480)
    assert rc == 0
    assert sup.restarts == 1 and sup.world == 2
    # the incident names the dead rank's serving role
    inc = sup.incidents[0]
    reasons = inc["reasons"]
    assert reasons.get(1, reasons.get("1")) == "signal:9"
    roles = inc["roles"]
    assert roles.get(1, roles.get("1")) == "decode"
    # exactly ONE latched rank_dead dump (the supervisor's)
    sup_dumps = glob.glob(
        os.path.join(str(tmp_path / "sup_dumps"), "*rank_dead*"))
    assert len(sup_dumps) == 1
    assert glob.glob(os.path.join(out_dir, "*rank_dead*")) == []
    # token-lossless: the final epoch's RES lines carry every request,
    # identical to the colocated greedy run
    res, met0 = _parse_rank0(open(sup.log_paths[(1, 0)]).read())
    ref = _colocated_reference(n_reqs, max_new)
    assert sorted(res) == sorted(ref)
    for rid, toks in ref.items():
        assert res[rid]["tokens"] == toks, rid
    for fence in met0["leak_fence"]:
        assert fence["free"] == fence["want"], fence
    # zero orphaned traces: merge EVERY per-role worker dump; each
    # trace that appears anywhere must close (the router rank is the
    # completion authority — its "finish" events survive the kill)
    dumps = sorted(glob.glob(os.path.join(out_dir, "flight_*.jsonl")))
    assert dumps, os.listdir(out_dir)
    _headers, events, _sk = view.load_dumps(dumps)
    timelines = view.trace_timelines(events)
    assert len(timelines) == n_reqs
    outcomes = {t: view._trace_outcome(evs)
                for t, evs in timelines.items()}
    orphans = {t: o for t, o in outcomes.items() if o == "open"}
    assert not orphans, orphans


@pytest.mark.slow
def test_supervisor_sigkill_one_of_two_decode_ranks_rebalances(tmp_path):
    """ISSUE 18 fault composition: a world=3 serving world (1 prefill
    + 2 decode) loses ONE decode rank to SIGKILL mid-stream. The
    role-aware shrink ladder (``valid_worlds_from_elasticity`` with
    the roles map) steps 3 → 2, the supervisor re-derives rank 1's
    role for the shrunk world, and the respawned epoch re-balances the
    ledger's unfinished rids onto the SURVIVING decode rank — every
    stream token-lossless, exactly one latched rank_dead dump, zero
    orphaned trace_ids."""
    from deepspeed_tpu.runtime.elastic.supervisor import (
        Supervisor, valid_worlds_from_elasticity)
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    from deepspeed_tpu.telemetry import view
    n_reqs, max_new = 8, 6
    roles = {0: "prefill", 1: "decode", 2: "decode"}
    valid = valid_worlds_from_elasticity({}, roles=roles)
    assert valid == [2, 3]     # the serving decode-count ladder
    out_dir = str(tmp_path / "out")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))
                + os.pathsep + os.environ.get("PYTHONPATH", "")})
    sup = Supervisor(
        [sys.executable, os.path.join("tests", "xproc_serving_worker.py"),
         out_dir, str(n_reqs), str(max_new), "2"],
        3, heartbeat_dir=str(tmp_path / "hb"),
        dump_dir=str(tmp_path / "sup_dumps"),
        valid_worlds=valid, roles=roles,
        hang_deadline_s=60.0, grace_kill_s=3.0, max_restarts=2,
        backoff_base_s=0.2, backoff_max_s=0.5, poll_s=0.1,
        local_devices=1, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        recorder=FlightRecorder())
    rc = sup.run(deadline_s=540)
    assert rc == 0
    # shrunk onto the survivor: 3 -> 2, exactly one restart
    assert sup.restarts == 1 and sup.world == 2
    inc = sup.incidents[0]
    reasons = inc["reasons"]
    assert reasons.get(1, reasons.get("1")) == "signal:9"
    assert inc["world"] == 3
    ir = inc["roles"]
    assert ir.get(1, ir.get("1")) == "decode"
    # the shrunk world's re-derived role map still serves
    assert sup.roles_for_world(2) == {0: "prefill", 1: "decode"}
    sup_dumps = glob.glob(
        os.path.join(str(tmp_path / "sup_dumps"), "*rank_dead*"))
    assert len(sup_dumps) == 1
    assert glob.glob(os.path.join(out_dir, "*rank_dead*")) == []
    # token-lossless across the shrink, vs the colocated greedy run
    res, met0 = _parse_rank0(open(sup.log_paths[(1, 0)]).read())
    ref = _colocated_reference(n_reqs, max_new)
    assert sorted(res) == sorted(ref)
    for rid, toks in ref.items():
        assert res[rid]["tokens"] == toks, rid
    for fence in met0["leak_fence"]:
        assert fence["free"] == fence["want"], fence
    # zero orphaned traces across every per-role dump
    dumps = sorted(glob.glob(os.path.join(out_dir, "flight_*.jsonl")))
    assert dumps, os.listdir(out_dir)
    _headers, events, _sk = view.load_dumps(dumps)
    timelines = view.trace_timelines(events)
    assert len(timelines) == n_reqs
    outcomes = {t: view._trace_outcome(evs)
                for t, evs in timelines.items()}
    orphans = {t: o for t, o in outcomes.items() if o == "open"}
    assert not orphans, orphans
