"""Cross-process KV page-handoff transport (ISSUE 17).

Fast tier:

- wire-codec goldens (``test_wire_*``, pure numpy — the subset
  ci/serving_gate.sh runs): byte-exact round-trips for fp and int8
  pool layouts, the versioned-header guard (an unknown version raises
  LOUD instead of silently corrupting old packets/snapshots),
  crc/truncation rejection, forward-compatible extra header keys, and
  the receiver-side packet-size cost model;
- ``test_golden_*``: REAL :class:`HandoffPacket`\\ s extracted from a
  live prefill engine (fp32 + prefix-shared pages, and an int8
  quantized pool) survive encode→decode bytes-exactly.

Slow tier (2 REAL OS processes over the PR-10 ``spawn_workers``
harness / the PR-15 ``Supervisor``; fast single-process loopback
siblings live in tests/test_serving_disagg.py):

- the acceptance leg: prefill-role rank 0 hands off to decode-role
  rank 1, >= 32 cross-process handoffs token-identical to the
  colocated greedy run, leak fence clean on BOTH pools, and the
  ``router/handoff_bytes_{sent,recv}`` counters agreeing across the
  process boundary (recv is recomputed from decoded content — the
  canonical-encoding cost model);
- the fault leg: SIGKILL of the decode-role process mid-stream → the
  supervisor detects it (role-stamped incident), respawns the world,
  and every request finishes token-lossless with exactly one latched
  rank_dead dump and zero orphaned trace_ids across per-role dumps.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

from deepspeed_tpu.serving.transport import (
    FRAME_BASE_NBYTES, WIRE_MAGIC, WIRE_VERSION, WireFormatError,
    _HEAD, decode_frame, decode_frames, encode_frame, frame_nbytes,
    payload_nbytes)

# ------------------------------------------------------- codec goldens


def _mk_comps():
    rs = np.random.RandomState(7)
    return [rs.randn(2, 3, 8, 4).astype(np.float32),
            rs.randint(-128, 128, (2, 3, 8, 4)).astype(np.int8),
            rs.randn(2, 3).astype(np.float16)]


def test_wire_roundtrip_bytes_exact():
    """encode(decode(b)) == b — the canonical-encoding property every
    golden and the receiver-side cost model ride on."""
    doc = {"rid": 3, "prompt": [1, 2, 3], "generated": [9],
           "pos": 4, "last_tok": 9, "n_data_pages": 1,
           "t_sent": 123.25, "trace_id": "abc"}
    buf = encode_frame("packet", doc, _mk_comps(), src=0, dst=1)
    frame, end = decode_frame(buf)
    assert end == len(buf)
    assert frame["kind"] == "packet"
    assert frame["src"] == 0 and frame["dst"] == 1
    assert frame["doc"] == doc
    for a, b in zip(frame["comps"], _mk_comps()):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    re_encoded = encode_frame(frame["kind"], frame["doc"],
                              frame["comps"], frame["src"],
                              frame["dst"])
    assert re_encoded == buf
    assert frame_nbytes(frame) == len(buf)


def test_wire_int8_pool_layout_roundtrip():
    """The quantized pool shape — int8 code blocks + float scale
    rows — survives bytes-exactly (dtype/shape carried per component,
    payloads raw)."""
    rs = np.random.RandomState(3)
    comps = [rs.randint(-128, 128, (2, 6, 8, 16)).astype(np.int8),
             rs.randn(2, 6, 8, 1).astype(np.float32)]
    buf = encode_frame("packet", {"n_data_pages": 6}, comps)
    frame, _ = decode_frame(buf)
    assert [c.dtype.str for c in frame["comps"]] == ["|i1", "<f4"]
    for a, b in zip(frame["comps"], comps):
        np.testing.assert_array_equal(a, b)
    assert encode_frame(frame["kind"], frame["doc"], frame["comps"],
                        frame["src"], frame["dst"]) == buf
    assert payload_nbytes(frame["comps"]) == sum(c.nbytes for c in comps)


def test_wire_unknown_version_raises_loud():
    """The versioned-header contract: a field addition bumps
    WIRE_VERSION and an old reader REFUSES — no silent corruption of
    old packets or serving snapshots."""
    buf = bytearray(encode_frame("done", {"rid": 1}))
    head = _HEAD.unpack_from(buf, 0)
    _HEAD.pack_into(buf, 0, head[0], WIRE_VERSION + 1, head[2], head[3])
    with pytest.raises(WireFormatError, match="version"):
        decode_frame(bytes(buf))
    bad_magic = b"XXXX" + bytes(buf)[4:]
    with pytest.raises(WireFormatError, match="magic"):
        decode_frame(bad_magic)


def test_wire_crc_and_truncation_rejected():
    buf = encode_frame("packet", {"n_data_pages": 1},
                       [np.arange(64, dtype=np.float32)])
    # flip one payload byte -> component crc mismatch
    corrupt = bytearray(buf)
    corrupt[-1] ^= 0xFF
    with pytest.raises(WireFormatError, match="crc"):
        decode_frame(bytes(corrupt))
    # flip one header byte -> header crc mismatch
    corrupt = bytearray(buf)
    corrupt[FRAME_BASE_NBYTES + 2] ^= 0xFF
    with pytest.raises(WireFormatError, match="crc"):
        decode_frame(bytes(corrupt))
    for cut in (3, FRAME_BASE_NBYTES + 4, len(buf) - 8):
        with pytest.raises(WireFormatError, match="truncated"):
            decode_frame(buf[:cut])


def test_wire_forward_compat_extra_header_keys_ignored():
    """A SAME-version reader tolerates forward extensions: unknown
    header keys decode cleanly and are dropped."""
    import zlib
    header = json.dumps(
        {"v": WIRE_VERSION, "kind": "done", "src": 2, "dst": 0,
         "doc": {"rid": 5}, "comps": [], "future_field": [1, 2]},
        sort_keys=True, separators=(",", ":")).encode()
    buf = _HEAD.pack(WIRE_MAGIC, WIRE_VERSION, len(header),
                     zlib.crc32(header) & 0xFFFFFFFF) + header
    frame, end = decode_frame(buf)
    assert end == len(buf)
    assert frame["kind"] == "done" and frame["doc"] == {"rid": 5}
    assert frame["src"] == 2 and frame["comps"] == ()


def test_wire_multiframe_buffer_and_kinds():
    """Frames are self-delimiting: an exchange buffer concatenating a
    packet, a done and a nack decodes back into exactly those three."""
    frames_in = [
        encode_frame("packet", {"rid": 0, "n_data_pages": 2},
                     [np.ones((2, 2, 4), np.float32)], src=0, dst=1),
        encode_frame("done", {"rid": 1, "tokens": [1, 2, 3],
                              "finish_reason": "length"}, src=1, dst=0),
        encode_frame("nack", {"rid": 2, "error": "boom"}, src=1, dst=0),
    ]
    out = decode_frames(b"".join(frames_in))
    assert [f["kind"] for f in out] == ["packet", "done", "nack"]
    assert sum(frame_nbytes(f) for f in out) == \
        sum(len(b) for b in frames_in)


# ------------------------------------------- real-packet goldens (jax)


def _tiny_prefill(kv_cache_bits=0, prefix=True):
    import jax.numpy as jnp  # noqa: F401  (lazy: keep module import light)
    import deepspeed_tpu.serving as serving
    from deepspeed_tpu.serving.engine import ContinuousBatcher
    from tests.xproc_serving_worker import build_model
    cfg, params = build_model()
    sv = {"slots": 2, "page_size": 8, "max_pages_per_slot": 8}
    if kv_cache_bits:
        sv["kv_cache_bits"] = kv_cache_bits
    adapter = serving.build_engine(
        "gpt2", cfg, params, config={"serving": sv}).adapter
    return ContinuousBatcher(adapter, role="prefill",
                             prefix_cache=prefix)


def _golden_roundtrip(pcb, reqs):
    from deepspeed_tpu.serving.router import extract_handoff
    from deepspeed_tpu.serving.transport import (encode_packet,
                                                 packet_from_frame)
    for r in reqs:
        pcb.submit(r)
    pcb.step()
    packets = [extract_handoff(pcb, i)
               for i, s in enumerate(pcb.slots) if s.active]
    assert packets
    for packet in packets:
        buf = encode_packet(packet, src=0, dst=1)
        frame, end = decode_frame(buf)
        assert end == len(buf)
        back = packet_from_frame(frame)
        assert back.doc == packet.doc
        assert back.req is None      # rebuilt from the doc on delivery
        assert len(back.kv) == len(packet.kv)
        for a, b in zip(back.kv, packet.kv):
            got = np.asarray(a)
            want = np.asarray(b)
            assert got.dtype == want.dtype and got.shape == want.shape
            np.testing.assert_array_equal(got, want)
        # byte-exact re-encode: the golden property
        assert encode_frame(frame["kind"], frame["doc"], frame["comps"],
                            frame["src"], frame["dst"]) == buf
        assert payload_nbytes(frame["comps"]) == \
            packet.doc["n_data_pages"] * pcb.cache.page_nbytes
    return packets


def test_golden_handoff_packet_fp32_prefix_shared():
    """A real fp32 packet — including one whose prompt pages are
    PREFIX-SHARED in the sending pool — round-trips bytes-exactly,
    and its payload equals n_data_pages * page_nbytes (the counters'
    cost model)."""
    import deepspeed_tpu.serving as serving
    pcb = _tiny_prefill()
    prompt = np.arange(17, dtype=np.int32) % 256
    reqs = [serving.Request(0, prompt, max_new_tokens=4),
            serving.Request(1, prompt.copy(), max_new_tokens=4)]
    packets = _golden_roundtrip(pcb, reqs)
    assert len(packets) == 2
    # both packets carry the SAME prompt-page bytes (the second slot
    # shared the first's full pages): gathers must agree exactly
    for a, b in zip(packets[0].kv, packets[1].kv):
        n_full = 17 // 8
        np.testing.assert_array_equal(
            np.asarray(a)[:, :n_full], np.asarray(b)[:, :n_full])


def test_golden_handoff_packet_int8_pool():
    """The int8-quantized pool layout (code blocks + scale components)
    round-trips bytes-exactly through the same frame."""
    import deepspeed_tpu.serving as serving
    pcb = _tiny_prefill(kv_cache_bits=8, prefix=False)
    assert any(np.dtype(c.dtype) == np.int8 for c in pcb.cache.pool)
    reqs = [serving.Request(0, (np.arange(12, dtype=np.int32) * 7) % 256,
                            max_new_tokens=4)]
    _golden_roundtrip(pcb, reqs)


# ------------------------------------- 2-real-process acceptance (slow)

_XPROC_SCRIPT = """
import sys
from tests.xproc_serving_worker import main
main(["worker"] + sys.argv[1:])
"""


def _parse_rank0(out):
    res, met = {}, None
    for line in out.splitlines():
        if line.startswith("RES "):
            _tag, rid, doc = line.split(" ", 2)
            res[int(rid)] = json.loads(doc)
        elif line.startswith("MET "):
            met = json.loads(line[4:])
    return res, met


def _parse_met(out):
    for line in out.splitlines():
        if line.startswith("MET "):
            return json.loads(line[4:])
    return None


def _colocated_reference(n_reqs, max_new):
    from deepspeed_tpu.serving.engine import ContinuousBatcher
    import deepspeed_tpu.serving as serving
    from tests.xproc_serving_worker import (build_model, build_requests,
                                            serving_config)
    cfg, params = build_model()
    sv = dict(serving_config()["serving"])
    sv.pop("disaggregation")
    adapter = serving.build_engine(
        "gpt2", cfg, params, config={"serving": sv}).adapter
    done = ContinuousBatcher(adapter).serve(
        build_requests(n_reqs, max_new))
    return {rid: r.tokens().tolist() for rid, r in done.items()}


@pytest.mark.slow
def test_two_process_handoff_acceptance(tmp_path):
    """THE acceptance leg: 32+ handoffs prefill-rank -> decode-rank
    over 2 REAL processes, token-identical to the colocated greedy
    run, leak fence clean on both pools, byte counters agreeing
    across the boundary."""
    from tests.test_multiprocess_dist import spawn_workers
    n_reqs, max_new = 32, 6
    out_dir = tmp_path / "out"
    outs = spawn_workers(2, _XPROC_SCRIPT, tmp_path,
                         script_args=(str(out_dir), n_reqs, max_new),
                         timeout=420)
    res, met0 = _parse_rank0(outs[0])
    met1 = _parse_met(outs[1])
    assert met0 and met1, (outs[0][-2000:], outs[1][-2000:])
    # every stream token-identical to the colocated run
    ref = _colocated_reference(n_reqs, max_new)
    assert sorted(res) == sorted(ref)
    for rid, toks in ref.items():
        assert res[rid]["tokens"] == toks, rid
    # >= 32 real cross-process handoffs, none lost, none requeued
    assert met0["stats"]["handoffs"] >= 32
    assert met0["stats"]["lost"] == 0
    assert met1["stats"]["delivered"] == met0["stats"]["handoffs"]
    # leak fence on BOTH pools: every pool drains to num_blocks - 1
    for met in (met0, met1):
        for fence in met["leak_fence"]:
            assert fence["free"] == fence["want"], (met["rank"], fence)
    # byte counters match the packet-size cost model: the sender
    # counts encoded frame lengths, the receiver RECOMPUTES each
    # frame's size from its decoded content (canonical encoding) —
    # equality across the process boundary pins both
    sent = met0["counters"]["router/handoff_bytes_sent"]
    recv = met1["counters"]["router/handoff_bytes_recv"]
    assert sent == recv == met0["stats"]["bytes_sent"] > 0
    # and the payload term: absorbed data pages x page_nbytes, plus a
    # small per-frame header
    payload = met1["absorbed_pages"] * met0["page_nbytes"]
    assert payload < sent < payload + met0["stats"]["handoffs"] * 2048
    # transport term observed on the decode rank for every delivery
    assert met1["transport_s"]["count"] == met1["stats"]["delivered"]


@pytest.mark.slow
def test_supervisor_sigkill_decode_rank_recovers(tmp_path):
    """The fault acceptance leg: the decode-role process SIGKILLs
    itself mid-stream (after 2 deliveries, epoch 0). The supervisor
    detects the death with its serving role attached, respawns the
    2-rank world in place, and the respawned epoch re-serves ONLY the
    unfinished rids from the ledger — every stream finishes
    token-lossless, exactly one latched rank_dead dump, zero orphaned
    trace_ids across the per-role dumps."""
    from deepspeed_tpu.runtime.elastic.supervisor import Supervisor
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    from deepspeed_tpu.telemetry import view
    n_reqs, max_new = 8, 6
    out_dir = str(tmp_path / "out")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))
                + os.pathsep + os.environ.get("PYTHONPATH", "")})
    sup = Supervisor(
        [sys.executable, os.path.join("tests", "xproc_serving_worker.py"),
         out_dir, str(n_reqs), str(max_new), "2"],
        2, heartbeat_dir=str(tmp_path / "hb"),
        dump_dir=str(tmp_path / "sup_dumps"),
        valid_worlds=[2],                 # serving worlds don't shrink:
        roles={0: "prefill", 1: "decode"},  # respawn IN PLACE
        hang_deadline_s=60.0, grace_kill_s=3.0, max_restarts=2,
        backoff_base_s=0.2, backoff_max_s=0.5, poll_s=0.1,
        local_devices=1, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        recorder=FlightRecorder())
    rc = sup.run(deadline_s=480)
    assert rc == 0
    assert sup.restarts == 1 and sup.world == 2
    # the incident names the dead rank's serving role
    inc = sup.incidents[0]
    reasons = inc["reasons"]
    assert reasons.get(1, reasons.get("1")) == "signal:9"
    roles = inc["roles"]
    assert roles.get(1, roles.get("1")) == "decode"
    # exactly ONE latched rank_dead dump (the supervisor's)
    sup_dumps = glob.glob(
        os.path.join(str(tmp_path / "sup_dumps"), "*rank_dead*"))
    assert len(sup_dumps) == 1
    assert glob.glob(os.path.join(out_dir, "*rank_dead*")) == []
    # token-lossless: the final epoch's RES lines carry every request,
    # identical to the colocated greedy run
    res, met0 = _parse_rank0(open(sup.log_paths[(1, 0)]).read())
    ref = _colocated_reference(n_reqs, max_new)
    assert sorted(res) == sorted(ref)
    for rid, toks in ref.items():
        assert res[rid]["tokens"] == toks, rid
    for fence in met0["leak_fence"]:
        assert fence["free"] == fence["want"], fence
    # zero orphaned traces: merge EVERY per-role worker dump; each
    # trace that appears anywhere must close (the router rank is the
    # completion authority — its "finish" events survive the kill)
    dumps = sorted(glob.glob(os.path.join(out_dir, "flight_*.jsonl")))
    assert dumps, os.listdir(out_dir)
    _headers, events, _sk = view.load_dumps(dumps)
    timelines = view.trace_timelines(events)
    assert len(timelines) == n_reqs
    outcomes = {t: view._trace_outcome(evs)
                for t, evs in timelines.items()}
    orphans = {t: o for t, o in outcomes.items() if o == "open"}
    assert not orphans, orphans
