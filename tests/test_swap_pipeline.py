"""Pipelined swap-tier correctness (PR 5): write-behind + drain fence,
staging-pool byte cache, sliding read window, release-mid-flight, and
engine-level loss parity of pipelined == blocking == in-memory stage 3.

The contract under test: ``pipeline_write`` makes the park asynchronous,
but a swap-in issued immediately after MUST return the updated values
(the drain fence runs before any pending leaf is re-read from disk, and
cache-served leaves read the authoritative staged bytes); releasing a
swapper with writes in flight must wait them out rather than leak
pending aio against freed buffers.
"""

import glob
import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu as dstpu
from tests.simple_model import SimpleModel, random_batch, base_config


def _sh():
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    return mesh, NamedSharding(mesh, P())


def _leaves(seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(64, 32).astype(np.float32), jnp.bfloat16),
            jnp.asarray(rng.randn(1000).astype(np.float32)),
            jnp.asarray(rng.randint(-5, 5, (7,)).astype(np.int32))]


def test_write_behind_then_reread_returns_updated(tmp_path):
    """The core fence: park write-behind, then immediately re-read —
    values are the UPDATED ones, and after an explicit drain the files
    on disk hold the same bytes (durability, not just cache)."""
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
    _, sh = _sh()
    leaves = _leaves()
    sw = PartitionedParamSwapper(str(tmp_path), pipeline_read=True,
                                 pipeline_write=True, buffer_count=4)
    sw.write_all(leaves)
    got = sw.swap_in_device([sh] * 3)
    for step in range(3):
        upd = [jnp.asarray(np.asarray(g, np.float32) * 2 + step, g.dtype)
               for g in got]
        sw.swap_out_device(upd)          # async: returns with writes in
        assert sw.has_pending_writes     # flight on the dedicated handle
        got = sw.swap_in_device([sh] * 3)
        for a, b in zip(upd, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    sw.drain_writes()
    assert not sw.has_pending_writes
    for i, leaf in enumerate(got):
        raw = np.fromfile(sw._path(i), dtype=np.uint8)
        want = np.ascontiguousarray(np.asarray(leaf)).view(np.uint8)
        np.testing.assert_array_equal(raw, want.reshape(-1))
    sw.release()


def test_cache_hit_serves_staged_bytes(tmp_path):
    """A pool large enough to cache every leaf serves the re-read
    without touching the files — proven by corrupting the files after
    the drain and still reading correct values — while the files
    themselves stayed byte-valid at drain time."""
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
    from deepspeed_tpu.telemetry import MetricsRegistry
    _, sh = _sh()
    leaves = _leaves()
    reg = MetricsRegistry()
    sw = PartitionedParamSwapper(str(tmp_path), pipeline_read=True,
                                 pipeline_write=True, buffer_count=3,
                                 registry=reg)
    sw.write_all(leaves)
    got = sw.swap_in_device([sh] * 3)
    upd = [jnp.asarray(np.asarray(g, np.float32) * 3 + 1, g.dtype)
           for g in got]
    sw.swap_out_device(upd)
    sw.drain_writes()
    for i in range(3):                       # rot the files
        with open(sw._path(i), "r+b") as f:
            f.write(b"\xff" * 8)
    again = sw.swap_in_device([sh] * 3)      # served from the pool cache
    for a, b in zip(upd, again):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    snap = reg.snapshot("swap/")
    assert snap["counters"]["swap/cache_hit_bytes"] > 0
    sw.release()


def test_release_mid_flight_leaves_no_pending_aio(tmp_path):
    """release() with writes in flight drains them (no aio completion
    can land in a freed buffer) and clears the pending state."""
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
    _, sh = _sh()
    rng = np.random.RandomState(1)
    leaves = [jnp.asarray(rng.randn(256, 256).astype(np.float32))
              for _ in range(6)]
    sw = PartitionedParamSwapper(str(tmp_path), pipeline_read=True,
                                 pipeline_write=True, buffer_count=3)
    sw.write_all(leaves)
    sw.swap_out_device(leaves)
    assert sw.has_pending_writes
    sw.release()
    assert not sw.has_pending_writes
    assert not sw._wbusy and not sw._wfds
    # the write handle has nothing outstanding: wait() returns 0 done
    assert sw._write_handle().wait() == 0


def test_read_window_any_order_many_leaves(tmp_path):
    """More leaves than staging slots, arbitrary swap schedule: the
    sliding window reassembles every leaf bit-exactly."""
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
    _, sh = _sh()
    rng = np.random.RandomState(2)
    leaves = [jnp.asarray(rng.randn(50 + 7 * i).astype(np.float32))
              for i in range(9)]
    sw = PartitionedParamSwapper(str(tmp_path), pipeline_read=True,
                                 pipeline_write=True, buffer_count=3)
    sw.write_all(leaves)
    order = [8, 6, 7, 0, 1, 2, 5, 3, 4]
    got = sw.swap_in_device([sh] * 9, order=order)
    for a, b in zip(leaves, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a second write+reread cycle mixes cache hits and disk reads
    upd = [jnp.asarray(np.asarray(x) + 1) for x in got]
    sw.swap_out_device(upd)
    got2 = sw.swap_in_device([sh] * 9, order=order)
    for a, b in zip(upd, got2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    sw.release()


def test_staged_leaf_snapshot_contract(tmp_path):
    """The ISSUE-7 snapshot API: after a drained park, ``staged_leaf``
    serves recently parked leaves as byte-exact cache views and the
    rest as their swap-file paths — the contract the engine's
    snapshot-from-parked-leaves path depends on."""
    from deepspeed_tpu.runtime.swap_tensor import PartitionedParamSwapper
    rng = np.random.RandomState(3)
    leaves = [jnp.asarray(rng.randn(32, 16).astype(np.float32))
              for _ in range(4)]
    sw = PartitionedParamSwapper(str(tmp_path), pipeline_read=True,
                                 pipeline_write=True, buffer_count=2)
    sw.write_all(leaves)
    sw.swap_out_device(leaves)           # pool of 2 < 4 leaves
    assert sw.has_pending_writes
    sw.drain_writes()
    sources = {}
    for i, leaf in enumerate(leaves):
        value, source = sw.staged_leaf(i)
        sources[source] = sources.get(source, 0) + 1
        if source == "cache":
            np.testing.assert_array_equal(np.asarray(value),
                                          np.asarray(leaf))
        else:
            raw = np.fromfile(value, np.float32).reshape(32, 16)
            np.testing.assert_array_equal(raw, np.asarray(leaf))
    assert sources.get("cache", 0) >= 1 and sources.get("file", 0) >= 1
    sw.release()


def test_optimizer_swapper_pipeline_write_roundtrip(tmp_path):
    """OptimizerStateSwapper with write-behind stores: prefetch/fetch of
    a pending leaf drains first; moments accumulate across steps exactly
    as the sync path does."""
    from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper
    shapes = [(64, 32), (1000,), (7,)]
    osw = OptimizerStateSwapper(str(tmp_path), pipeline_write=True,
                                buffer_count=3)
    for i, s in enumerate(shapes):
        osw.init_state(i, s)
    for step in range(3):
        osw.prefetch(0)
        for i, s in enumerate(shapes):
            m, v = osw.fetch(i)
            if i + 1 < len(shapes):
                osw.prefetch(i + 1)
            m += 1.0 + step
            v += 2.0 + step
            osw.store(i, m, v)
    for i, s in enumerate(shapes):
        m, v = osw.fetch(i)
        np.testing.assert_allclose(m, np.full(s, 6.0, np.float32))
        np.testing.assert_allclose(v, np.full(s, 9.0, np.float32))
    osw.release()


# ---------------------------------------------------------------------------
# engine-level parity: pipelined == blocking == in-memory stage 3
# ---------------------------------------------------------------------------

def _train(cfg_zero, steps=5):
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 3, **cfg_zero}
    e, _, _, _ = dstpu.initialize(
        config=cfg, model=SimpleModel(),
        mesh=make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    batch = random_batch()
    losses = [float(e.train_batch(batch)) for _ in range(steps)]
    return e, losses


def test_engine_nvme_pipelined_matches_blocking_and_memory(tmp_path):
    """The satellite contract: losses under offload_param device=nvme
    pipelined == blocking == in-memory stage 3 on a tiny model, with
    params genuinely parked (files on disk, device arrays freed) and the
    swap telemetry moving."""
    _, mem = _train({})
    e_b, blocking = _train({
        "offload_param": {"device": "nvme", "nvme_path": str(tmp_path / "b")},
        "offload_optimizer": {"device": "cpu"}})
    e_p, pipelined = _train({
        "offload_param": {"device": "nvme", "nvme_path": str(tmp_path / "p"),
                          "pipeline_read": True, "pipeline_write": True,
                          "buffer_count": 4},
        "offload_optimizer": {"device": "cpu"}})
    np.testing.assert_allclose(blocking, mem, rtol=2e-3)
    np.testing.assert_allclose(pipelined, blocking, rtol=1e-6)
    for e, sub in ((e_b, "b"), (e_p, "p")):
        assert e._params_parked
        for leaf in jax.tree_util.tree_leaves(e.state.params):
            assert leaf.is_deleted()
        assert glob.glob(str(tmp_path / sub) + "/param_swap_*/param_*.swp")
    snap = e_p.telemetry.snapshot("swap/")
    assert snap["counters"]["swap/bytes_written"] > 0
    assert "swap/stall_s" in snap["histograms"]
    assert snap["gauges"].get("swap/staging_bytes", 0) > 0
    e_p.telemetry.reset()


def test_engine_host_runner_park_via_push(tmp_path):
    """HostOffloadOptimizer + pipelined NVMe params: the updated leaves
    park straight from the SIMD step's host output (no h2d push / d2h
    re-read round trip) and training still matches the blocking tier."""
    _, mem = _train({})
    e, got = _train({
        "offload_param": {"device": "nvme", "nvme_path": str(tmp_path),
                          "pipeline_read": True, "pipeline_write": True},
        "offload_optimizer": {"device": "cpu", "stream": "host"}})
    np.testing.assert_allclose(got, mem, rtol=2e-3)
    assert e._params_parked
    # eval + continued training transparently restore residency
    x, _ = random_batch()
    out = e.eval_batch(x)
    assert np.all(np.isfinite(np.asarray(out, np.float32)))
    assert np.isfinite(float(e.train_batch(random_batch())))


@pytest.mark.slow
def test_prefetch_composes_with_nvme_tier(tmp_path):
    """stage3_prefetch + offload_param nvme: the disk→host→device swap
    schedule feeds the in-jit layer-gather pipeline; losses match the
    in-memory prefetch run bit-for-bit at fp32 tolerance."""
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 devices")

    def run(extra_zero):
        cfg = {
            "train_batch_size": 8,
            "zero_optimization": {
                "stage": 3, "stage3_prefetch": True,
                "stage3_prefetch_gather": "ring",
                "stage3_param_persistence_threshold": 0, **extra_zero},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 1000,
        }
        mesh = make_mesh(MeshConfig(data=2), devices=jax.devices()[:2])
        model = GPT2LMHeadModel(GPT2Config(
            vocab_size=512, n_positions=64, n_embd=64, n_layer=2,
            n_head=2, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True))
        e, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
        batch = {"input_ids": np.random.RandomState(0).randint(
            0, 512, (8, 64)).astype(np.int32)}
        losses = [float(e.train_batch(batch)) for _ in range(3)]
        return e, losses

    e0, base = run({})
    assert e0._prefetch_active()
    e1, got = run({"offload_param": {
        "device": "nvme", "nvme_path": str(tmp_path),
        "pipeline_read": True, "pipeline_write": True, "buffer_count": 4}})
    assert e1._prefetch_active(), \
        "stage3_prefetch must compose with the nvme param tier"
    assert e1._params_parked
    np.testing.assert_allclose(got, base, rtol=2e-5)
