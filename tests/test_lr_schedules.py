"""LR schedule tests — the reference's test_lr_schedulers.py analog."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    LRRangeTest, OneCycle, WarmupLR, WarmupDecayLR, get_lr_schedule)


def test_warmup_lr_ramps_then_flat():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10,
                 warmup_type="linear")
    assert float(s.lr_at(0)) == pytest.approx(0.0)
    assert float(s.lr_at(5)) == pytest.approx(0.05)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(100)) == pytest.approx(0.1)


def test_warmup_lr_log_monotone():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=100)
    vals = [float(s.lr_at(t)) for t in range(0, 120, 10)]
    assert all(b >= a - 1e-9 for a, b in zip(vals, vals[1:]))
    assert vals[-1] == pytest.approx(0.1, rel=1e-2)


def test_warmup_decay_lr():
    s = WarmupDecayLR(total_num_steps=100, warmup_min_lr=0.0,
                      warmup_max_lr=0.1, warmup_num_steps=10,
                      warmup_type="linear")
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(55)) == pytest.approx(0.05)
    assert float(s.lr_at(100)) == pytest.approx(0.0, abs=1e-8)
    assert float(s.lr_at(200)) == pytest.approx(0.0, abs=1e-8)


def test_lr_range_test():
    s = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                    lr_range_test_step_rate=1.0)
    assert float(s.lr_at(0)) == pytest.approx(0.01)
    assert float(s.lr_at(10)) == pytest.approx(0.02)
    s2 = LRRangeTest(lr_range_test_min_lr=0.01, lr_range_test_step_size=10,
                     lr_range_test_step_rate=1.0, lr_range_test_staircase=True)
    assert float(s2.lr_at(9)) == pytest.approx(0.01)
    assert float(s2.lr_at(10)) == pytest.approx(0.02)


def test_one_cycle():
    s = OneCycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10)
    assert float(s.lr_at(0)) == pytest.approx(0.01)
    assert float(s.lr_at(10)) == pytest.approx(0.1)
    assert float(s.lr_at(20)) == pytest.approx(0.01)
    # momentum cycles inversely
    assert float(s.mom_at(0)) == pytest.approx(0.9)
    assert float(s.mom_at(10)) == pytest.approx(0.8)


def test_get_lr_schedule_dispatch():
    s = get_lr_schedule("WarmupLR", {"warmup_max_lr": 0.5})
    assert isinstance(s, WarmupLR)
    with pytest.raises(ValueError):
        get_lr_schedule("Nope", {})


def test_torch_style_interface():
    s = WarmupLR(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10,
                 warmup_type="linear")
    s.step()
    s.step()
    assert s.last_batch_iteration == 1
    lr = s.get_lr()[0]
    assert 0 < lr <= 0.1
    sd = s.state_dict()
    s2 = WarmupLR()
    s2.load_state_dict(sd)
    assert s2.last_batch_iteration == 1
