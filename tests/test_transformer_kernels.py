"""Fused transformer layer numerics — the reference's test_cuda_forward.py /
test_cuda_backward.py methodology: fused implementation vs an independently
written reference layer, tolerance-based, fwd and bwd."""

import numpy as np
import jax
from jax.flatten_util import ravel_pytree
import jax.numpy as jnp
import flax.linen as nn
import pytest

from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)
from deepspeed_tpu.ops.transformer.transformer import transformer_layer


def _reference_layer(params, x, mask_bias, cfg):
    """Hand-rolled encoder layer in plain numpy-esque jnp, fp32 throughout.
    Written independently of the fused module (same math, different code) —
    the role tests/unit/modeling.py's HF-style BertLayer plays for the CUDA
    kernels."""
    p = params

    def dense(h, name):
        return h @ p[name]["kernel"] + p[name]["bias"]

    def layernorm(h, name):
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        normed = (h - mu) / np.sqrt(var + cfg.layer_norm_eps)
        return normed * p[name]["scale"] + p[name]["bias"]

    def attention(h):
        qkv = dense(h, "attn_qkvw")
        q, k, v = np.split(qkv, 3, axis=-1)
        B, S, E = q.shape
        H, D = cfg.heads, cfg.head_dim

        def heads(t):
            return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

        scores = heads(q) @ heads(k).transpose(0, 1, 3, 2) / np.sqrt(D)
        if mask_bias is not None:
            scores = scores + mask_bias
        probs = jax.nn.softmax(jnp.asarray(scores), axis=-1)
        ctx = np.asarray(probs) @ heads(v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, E)
        return dense(ctx, "attn_ow")

    def ffn(h):
        inter = dense(h, "inter_w")
        gelu = np.asarray(jax.nn.gelu(jnp.asarray(inter), approximate=False))
        return dense(gelu, "output_w")

    if cfg.pre_layer_norm:
        x = x + attention(layernorm(x, "attn_nw"))
        x = x + ffn(layernorm(x, "norm_w"))
    else:
        x = layernorm(x + attention(x), "attn_nw")
        x = layernorm(x + ffn(x), "norm_w")
    return x


def _make(cfg, B=2, S=16, seed=0):
    rng = jax.random.PRNGKey(seed)
    layer = DeepSpeedTransformerLayer(cfg)
    x = jax.random.normal(jax.random.PRNGKey(seed + 1),
                          (B, S, cfg.hidden_size), jnp.float32)
    params = layer.init(rng, x)["params"]
    return layer, params, x


def _np_params(params):
    return jax.tree.map(lambda a: np.asarray(a, np.float64), params)


@pytest.mark.parametrize("pre_ln", [True, False])
def test_forward_matches_reference(pre_ln):
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4,
                                     num_hidden_layers=2,
                                     pre_layer_norm=pre_ln,
                                     dtype=jnp.float32)
    layer, params, x = _make(cfg)
    fused = layer.apply({"params": params}, x)
    ref = _reference_layer(_np_params(params), np.asarray(x, np.float64),
                           None, cfg)
    np.testing.assert_allclose(np.asarray(fused), ref, rtol=2e-4, atol=2e-4)


def test_forward_with_hf_mask():
    cfg = DeepSpeedTransformerConfig(hidden_size=64, heads=4,
                                     num_hidden_layers=2, huggingface=True,
                                     dtype=jnp.float32)
    layer, params, x = _make(cfg)
    B, S = x.shape[:2]
    valid = np.ones((B, S), np.float32)
    valid[:, S // 2:] = 0.0        # right-pad half the keys
    bias = (1.0 - valid)[:, None, None, :] * -1e9
    fused = layer.apply({"params": params}, x, jnp.asarray(bias))
    ref = _reference_layer(_np_params(params), np.asarray(x, np.float64),
                           bias.astype(np.float64), cfg)
    np.testing.assert_allclose(np.asarray(fused), ref, rtol=2e-4, atol=2e-4)
    # masked keys must not influence valid queries: perturb padded positions
    x2 = np.asarray(x).copy()
    x2[:, S // 2:] += 7.0
    fused2 = layer.apply({"params": params}, jnp.asarray(x2),
                         jnp.asarray(bias))
    np.testing.assert_allclose(np.asarray(fused2[:, 0]),
                               np.asarray(fused[:, 0]), rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("pre_ln", [True, False])
def test_backward_matches_reference(pre_ln):
    """Grad parity (test_cuda_backward.py analog): d(sum(out))/dparams of the
    fused layer vs jax.grad through the reference math."""
    cfg = DeepSpeedTransformerConfig(hidden_size=32, heads=2,
                                     num_hidden_layers=2,
                                     pre_layer_norm=pre_ln,
                                     dtype=jnp.float32)
    layer, params, x = _make(cfg)

    def fused_loss(p):
        return layer.apply({"params": p}, x).sum()

    def ref_loss(p):
        # same reference math expressed in jnp for autodiff
        def dense(h, name):
            return h @ p[name]["kernel"] + p[name]["bias"]

        def layernorm(h, name):
            mu = h.mean(-1, keepdims=True)
            var = ((h - mu) ** 2).mean(-1, keepdims=True)
            return (h - mu) / jnp.sqrt(var + cfg.layer_norm_eps) \
                * p[name]["scale"] + p[name]["bias"]

        def attention(h):
            qkv = dense(h, "attn_qkvw")
            q, k, v = jnp.split(qkv, 3, axis=-1)
            B, S, E = q.shape
            H, D = cfg.heads, cfg.head_dim

            def heads(t):
                return t.reshape(B, S, H, D).transpose(0, 2, 1, 3)

            scores = heads(q) @ heads(k).transpose(0, 1, 3, 2) / np.sqrt(D)
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = (probs @ heads(v)).transpose(0, 2, 1, 3).reshape(B, S, E)
            return dense(ctx, "attn_ow")

        def ffn(h):
            return dense(jax.nn.gelu(dense(h, "inter_w"), approximate=False),
                         "output_w")

        h = x
        if cfg.pre_layer_norm:
            h = h + attention(layernorm(h, "attn_nw"))
            h = h + ffn(layernorm(h, "norm_w"))
        else:
            h = layernorm(h + attention(h), "attn_nw")
            h = layernorm(h + ffn(h), "norm_w")
        return h.sum()

    g_fused = jax.grad(fused_loss)(params)
    g_ref = jax.grad(ref_loss)(params)
    flat_f, _ = ravel_pytree(g_fused)
    flat_r, _ = ravel_pytree(g_ref)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_r),
                               rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("knob", ["normalize_invertible", "gelu_checkpoint",
                                  "attn_dropout_checkpoint"])
def test_memory_knobs_preserve_numerics(knob):
    """The reference's checkpointing kernel variants must be bit-compatible
    with the vanilla path; here the remat policies must be too."""
    base = dict(hidden_size=32, heads=2, num_hidden_layers=2,
                dtype=jnp.float32)
    cfg0 = DeepSpeedTransformerConfig(**base)
    cfg1 = DeepSpeedTransformerConfig(**base, **{knob: True})
    layer0, params, x = _make(cfg0)
    layer1 = transformer_layer(cfg1)

    def loss(layer, p):
        return layer.apply({"params": p}, x, None, True).sum()

    l0, g0 = jax.value_and_grad(lambda p: loss(layer0, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(layer1, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    flat0, _ = ravel_pytree(g0)
    flat1, _ = ravel_pytree(g1)
    np.testing.assert_allclose(np.asarray(flat0), np.asarray(flat1),
                               rtol=1e-5, atol=1e-6)


def test_memory_knob_with_dropout_trains():
    """Regression: remat knobs + nonzero dropout must trace (deterministic
    is a static argnum of the lifted checkpoint) and prob-dropout must keep
    the output row-stochastic pre-@V (checked indirectly: train mode differs
    from eval, eval matches the no-dropout layer)."""
    base = dict(hidden_size=32, heads=2, num_hidden_layers=2,
                dtype=jnp.float32)
    cfg = DeepSpeedTransformerConfig(**base, gelu_checkpoint=True,
                                     hidden_dropout_ratio=0.1,
                                     attn_dropout_ratio=0.1)
    layer = transformer_layer(cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32), jnp.float32)
    params = layer.init({"params": jax.random.PRNGKey(0),
                         "dropout": jax.random.PRNGKey(2)},
                        x, None, True)["params"]

    def loss(p):
        return layer.apply({"params": p}, x, None, False,
                           rngs={"dropout": jax.random.PRNGKey(3)}).sum()

    g = jax.grad(loss)(params)  # must not raise TracerBoolConversionError
    flat, _ = ravel_pytree(g)
    assert np.isfinite(np.asarray(flat)).all()
    # eval mode ignores dropout entirely → matches the dropout-free config
    cfg0 = DeepSpeedTransformerConfig(**base, gelu_checkpoint=True)
    out_eval = layer.apply({"params": params}, x, None, True)
    out_clean = transformer_layer(cfg0).apply({"params": params}, x, None, True)
    np.testing.assert_allclose(np.asarray(out_eval), np.asarray(out_clean),
                               rtol=1e-5, atol=1e-6)
    out_train = layer.apply({"params": params}, x, None, False,
                            rngs={"dropout": jax.random.PRNGKey(3)})
    assert not np.allclose(np.asarray(out_train), np.asarray(out_eval))


@pytest.mark.parametrize("mask_dtype", [np.int32, np.float32, bool])
def test_2d_mask_is_validity_in_any_dtype(mask_dtype):
    """A [B,S] mask is a key-validity mask regardless of dtype (the float
    1.0/0.0 HF form must NOT be read as an additive bias) and matches the
    explicit additive-bias path on valid rows."""
    cfg = DeepSpeedTransformerConfig(hidden_size=32, heads=2,
                                     num_hidden_layers=1, dtype=jnp.float32)
    layer, params, x = _make(cfg)
    B, S = x.shape[:2]
    keep = np.ones((B, S), np.int32)
    keep[:, -4:] = 0
    bias = (1.0 - keep.astype(np.float32))[:, None, None, :] * -1e9
    out_seg = layer.apply({"params": params}, x,
                          jnp.asarray(keep.astype(mask_dtype)))
    out_bias = layer.apply({"params": params}, x, jnp.asarray(bias))
    # padded-query rows differ (segment path masks q-side too); valid rows agree
    np.testing.assert_allclose(np.asarray(out_seg[:, :S - 4]),
                               np.asarray(out_bias[:, :S - 4]),
                               rtol=1e-4, atol=1e-4)
