"""Elasticity tests — mirrors reference tests/unit/test_elastic.py."""

import copy

import pytest

from deepspeed_tpu import elasticity
from deepspeed_tpu.config.config import DeepSpeedConfig
from deepspeed_tpu.elasticity import (
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    highly_composite_numbers,
)
from deepspeed_tpu.version import __version__

base_ds_config = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_chips": 32,
        "max_chips": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


def _config():
    return copy.deepcopy(base_ds_config)


def test_hcn_generation_matches_known_sequence():
    # The 38 smallest highly composite numbers (OEIS A002182), which the
    # reference hard-codes (elasticity/elasticity.py:19).
    expected = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840,
                1260, 1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200,
                27720, 45360, 50400, 55440, 83160, 110880, 166320, 221760,
                277200, 332640, 498960, 554400, 665280, 720720]
    assert highly_composite_numbers(720720) == expected


def test_basic_10k():
    ds_config = _config()
    final_batch_size, valid_chips = compute_elastic_config(ds_config)
    for n in valid_chips:
        assert final_batch_size % n == 0
        batch_per_chip = final_batch_size // n
        assert any(batch_per_chip % mb == 0
                   for mb in ds_config["elasticity"]["micro_batch_sizes"])
    # same answers as the reference test (tests/unit/test_elastic.py:40-41)
    assert len(valid_chips) == 23
    assert final_batch_size == 9792


def test_old_version():
    with pytest.raises(ElasticityError):
        compute_elastic_config(_config(), target_deepspeed_version="0.0")


def test_disabled():
    ds_config = _config()
    ds_config["elasticity"]["enabled"] = False
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config)


def test_valid_world_size():
    final_batch_size, valid_chips, mbsize = compute_elastic_config(
        _config(), world_size=64)
    assert mbsize == 17


def test_invalid_world_size():
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(_config(), world_size=128)


def test_future_elastic_version():
    ds_config = _config()
    ds_config["elasticity"]["version"] = "0.2"
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config)


def test_missing_max_batch():
    ds_config = _config()
    del ds_config["elasticity"]["max_train_batch_size"]
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config)


def test_missing_micro_batch():
    ds_config = _config()
    del ds_config["elasticity"]["micro_batch_sizes"]
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config)


def test_empty_config():
    with pytest.raises(ElasticityError):
        compute_elastic_config({"elasticity": {"enabled": True}})


@pytest.mark.parametrize("key, value", [
    ("micro_batch_sizes", [1, 4, -1, 2, -10]),
    ("min_chips", -1),
    ("max_chips", -1),
    ("micro_batch_sizes", 5),
    ("micro_batch_sizes", ["a", None, 0.5]),
    ("micro_batch_sizes", [2, 0.5, 4]),
])
def test_invalid_config_values(key, value):
    ds_config = _config()
    ds_config["elasticity"][key] = value
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config)


def test_proper_mbsz():
    ds_config = _config()
    ds_config["elasticity"]["max_train_batch_size"] = 32
    ds_config["elasticity"]["micro_batch_sizes"] = [1, 2, 3, 7]
    ds_config["elasticity"]["min_chips"] = 1
    final_batch_size, valid_chips, mbsize = compute_elastic_config(
        ds_config, world_size=7)
    assert mbsize == 3


def test_gpu_alias_keys():
    ds_config = _config()
    section = ds_config["elasticity"]
    section["min_gpus"] = section.pop("min_chips")
    section["max_gpus"] = section.pop("max_chips")
    final_batch_size, valid_chips = compute_elastic_config(ds_config)
    assert final_batch_size == 9792


def test_elastic_config_changed():
    """Batch params in the main config + elasticity must raise unless
    explicitly ignored (reference config.py:693-705)."""
    ds_config = _config()
    ds_config["train_batch_size"] = 4
    with pytest.raises(ElasticityConfigError):
        DeepSpeedConfig(ds_config, world_size=64)

    ds_config["elasticity"]["ignore_non_elastic_batch_info"] = True
    cfg = DeepSpeedConfig(ds_config, world_size=64)
    assert cfg.train_batch_size == 9792
    assert cfg.train_micro_batch_size_per_gpu == 17
    assert cfg.gradient_accumulation_steps == 9792 // (17 * 64)


def test_elasticity_enabled_helper():
    assert elasticity.elasticity_enabled(_config())
    assert not elasticity.elasticity_enabled({})
