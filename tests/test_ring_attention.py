"""Ring attention vs dense reference — exactness over a 4-way seq mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
from deepspeed_tpu.parallel.ring_attention import ring_attention
from deepspeed_tpu.ops.attention import reference_attention


def _qkv(shape=(2, 2, 64, 16), seed=0, dtype=jnp.float32):
    rng = jax.random.PRNGKey(seed)
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(kk, shape, dtype) for kk in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(causal):
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_gradients_match(causal):
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    q, k, v = _qkv(shape=(1, 2, 32, 8), seed=1)

    g_ring = jax.grad(lambda a, b, c: jnp.sum(
        ring_attention(a, b, c, mesh, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        reference_attention(a, b, c, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=5e-4, atol=5e-5)


def test_ring_bf16():
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_ring_single_axis_fallback():
    mesh = make_mesh(MeshConfig(data=8))
    q, k, v = _qkv()
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_indivisible_raises():
    mesh = make_mesh(MeshConfig(seq=4, data=2))
    q, k, v = _qkv(shape=(1, 1, 30, 8))
    with pytest.raises(AssertionError):
        ring_attention(q, k, v, mesh)
