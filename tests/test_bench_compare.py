"""bench.py --compare regression gate (ISSUE 6 satellite): pure-file
comparison path — identity exits 0, a seeded >=10% regression exits
nonzero — against both bench-native result JSON and the driver-captured
BENCH_rXX.json format ({"parsed": {metric, value, ...}}). The compare
path must never import jax (CI runs it on artifact files)."""

import json

import pytest

import bench


def _bench_doc(value=49.0, tokens=19000.0, step_ms=430.0, decode=2700.0,
               rps=18.0, ttft_p99=0.12):
    return {
        "metric": "gpt2_large_774m_zero3_mfu",
        "value": value,
        "unit": "%MFU",
        "vs_baseline": round(value / 45.0, 3),
        "detail": {
            "tokens_per_sec": tokens,
            "step_time_ms": step_ms,
            "bert_base_seq128_samples_per_sec": 620.0,
            "decode": {
                "b32_ctx512_int8kv": {"decode_tokens_per_sec": decode},
                "llama7b_b1_int8": {"skipped": "budget"},
                "serving_continuous_batching": {
                    "requests_per_sec_continuous": rps,
                    "ttft_p99_s": ttft_p99,
                },
            },
            "moe": {"tokens_per_sec": 30000.0},
            "nvme_param_tier": {"steady_step_s": 9.5},
            "sections_skipped": {},
        },
    }


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def _run(prior, cand, extra=()):
    return bench.main(["--compare", prior, "--candidate", cand,
                       *extra])


def test_headline_metrics_extraction_both_formats():
    doc = _bench_doc()
    m = bench.headline_metrics(doc)
    assert m["gpt2_large_774m_zero3_mfu"] == (49.0, +1)
    assert m["step_time_ms"] == (430.0, -1)
    assert m["decode.b32_ctx512_int8kv.decode_tokens_per_sec"] == \
        (2700.0, +1)
    assert m["serving.ttft_p99_s"] == (0.12, -1)
    # skipped sections contribute nothing
    assert not any("llama7b" in k for k in m)
    drv = {"n": 5, "rc": 124, "tail": "...",
           "parsed": {"metric": "gpt2_large_774m_zero3_mfu",
                      "value": 49.37, "unit": "%MFU",
                      "vs_baseline": 1.097}}
    assert bench.headline_metrics(drv) == {
        "gpt2_large_774m_zero3_mfu": (49.37, +1)}
    # a driver doc whose parsed line carries detail (BENCH_r01-r03
    # shape) contributes those metrics too — the extractor recurses
    drv["parsed"]["detail"] = {"step_time_ms": 500.0}
    m = bench.headline_metrics(drv)
    assert m["step_time_ms"] == (500.0, -1)
    # parsed: null (the r04 tail overflow) -> no metrics, vacuous gate
    assert bench.headline_metrics({"n": 4, "parsed": None}) == {}


def test_compare_identity_exits_zero(tmp_path, capsys):
    p = _write(tmp_path, "prior.json", _bench_doc())
    assert _run(p, p) == 0
    out = capsys.readouterr().out
    assert '"regressions": []' in out or '"regressions": [],' in out


def test_compare_seeded_regression_exits_nonzero(tmp_path, capsys):
    prior = _write(tmp_path, "prior.json", _bench_doc())
    cand = _write(tmp_path, "cand.json", _bench_doc(value=49.0 * 0.89))
    rc = _run(prior, cand)
    assert rc != 0
    assert "gpt2_large_774m_zero3_mfu" in capsys.readouterr().out


def test_compare_lower_is_better_regression(tmp_path):
    prior = _write(tmp_path, "prior.json", _bench_doc())
    cand = _write(tmp_path, "cand.json", _bench_doc(ttft_p99=0.3))
    assert _run(prior, cand) != 0
    # ...and an IMPROVEMENT in a lower-is-better metric passes
    cand2 = _write(tmp_path, "cand2.json", _bench_doc(ttft_p99=0.05))
    assert _run(prior, cand2) == 0


def test_compare_improvements_and_small_noise_pass(tmp_path):
    prior = _write(tmp_path, "prior.json", _bench_doc())
    cand = _write(tmp_path, "cand.json",
                  _bench_doc(value=49.0 * 1.2, tokens=19000.0 * 0.97))
    assert _run(prior, cand) == 0       # 3% dip is under the threshold
    assert _run(prior, cand, extra=("--regression-threshold",
                                    "0.01")) != 0


def test_compare_driver_format_prior(tmp_path):
    drv = {"n": 5, "cmd": "python bench.py", "rc": 124, "tail": "…",
           "parsed": {"metric": "gpt2_large_774m_zero3_mfu",
                      "value": 49.37, "unit": "%MFU",
                      "vs_baseline": 1.097}}
    prior = _write(tmp_path, "BENCH_r05.json", drv)
    same = _write(tmp_path, "cand.json", _bench_doc(value=49.37))
    assert _run(prior, same) == 0
    worse = _write(tmp_path, "worse.json", _bench_doc(value=44.0))
    assert _run(prior, worse) != 0


def test_compare_missing_and_extra_metrics_are_reported_not_failed(
        tmp_path, capsys):
    prior = _write(tmp_path, "prior.json", _bench_doc())
    slim = {"metric": "gpt2_large_774m_zero3_mfu", "value": 49.0,
            "unit": "%MFU", "vs_baseline": 1.089, "detail": {}}
    cand = _write(tmp_path, "cand.json", slim)
    assert _run(prior, cand) == 0       # no common regression
    out = capsys.readouterr().out
    assert "only_in_prior" in out


def test_compare_unreadable_file_is_a_usage_error(tmp_path):
    prior = _write(tmp_path, "prior.json", _bench_doc())
    with pytest.raises(SystemExit):
        _run(str(tmp_path / "nope.json"), prior)


def test_provenance_stamp_and_compare_prints_both_sides(tmp_path,
                                                        capsys):
    """ISSUE 12 satellite: results carry meta.provenance (git sha,
    hostname, cpu_count, jax/python versions) and --compare prints
    both sides' — the ±25% box swing stops being rediscovered by hand.
    The stamp itself must stay importable jax-free (the --candidate
    path never imports jax)."""
    prov = bench.provenance(jax_version="9.9.9-test")
    assert set(prov) == {"git_sha", "hostname", "cpu_count",
                         "jax_version", "python_version"}
    assert prov["jax_version"] == "9.9.9-test"
    assert prov["cpu_count"] >= 1 and prov["hostname"]

    with_prov = dict(_bench_doc(), meta={"provenance": prov})
    prior = _write(tmp_path, "prior.json", with_prov)
    cand = _write(tmp_path, "cand.json", _bench_doc())
    assert _run(prior, cand) == 0
    out = capsys.readouterr().out
    assert "prior provenance" in out and "9.9.9-test" in out
    assert "candidate provenance: <none recorded>" in out

    # driver-captured format: provenance beside "parsed" still found
    driver = {"parsed": _bench_doc(), "meta": {"provenance": prov}}
    assert bench._doc_provenance(driver)["hostname"] == prov["hostname"]
