"""Error-path behavior: bad configs and misuse must fail with clear
messages (the verify-probe tier)."""

import numpy as np
import pytest
import jax

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
from tests.simple_model import SimpleModel, random_batch, base_config


def _mesh1():
    return make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])


def test_missing_config_raises():
    with pytest.raises(ValueError, match="deepspeed_config"):
        dstpu.initialize(model=SimpleModel())


def test_unknown_optimizer_raises():
    cfg = base_config()
    cfg["optimizer"] = {"type": "AdaGoober", "params": {}}
    with pytest.raises(ValueError, match="[Uu]nknown optimizer"):
        dstpu.initialize(config=cfg, model=SimpleModel(), mesh=_mesh1())


def test_bad_config_path_raises():
    with pytest.raises((FileNotFoundError, ValueError)):
        dstpu.initialize(config="/nonexistent/ds_config.json",
                         model=SimpleModel(), mesh=_mesh1())


def test_batch_not_divisible_by_gas_raises():
    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["gradient_accumulation_steps"] = 4
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=_mesh1())
    x, y = random_batch(batch_size=6)   # 6 not divisible by gas=4
    with pytest.raises(Exception, match="divisible|gradient_accumulation"):
        engine.train_batch((x, y))


def test_invalid_zero_stage_raises():
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 7}
    with pytest.raises(Exception):
        dstpu.initialize(config=cfg, model=SimpleModel(), mesh=_mesh1())


def test_batch_triangle_conflict_raises():
    cfg = base_config()
    cfg["train_batch_size"] = 8
    cfg["train_micro_batch_size_per_gpu"] = 3
    cfg["gradient_accumulation_steps"] = 2   # 3*2 != 8
    with pytest.raises(Exception, match="batch"):
        dstpu.initialize(config=cfg, model=SimpleModel(), mesh=_mesh1())


def test_offload_rejects_sgd():
    cfg = base_config()
    cfg["optimizer"] = {"type": "SGD", "params": {"lr": 0.1}}
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "cpu"}}
    with pytest.raises(ValueError, match="Adam|LAMB"):
        dstpu.initialize(config=cfg, model=SimpleModel(), mesh=_mesh1())


def test_nvme_offload_requires_path():
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_optimizer": {"device": "nvme"}}
    with pytest.raises(Exception, match="nvme_path"):
        dstpu.initialize(config=cfg, model=SimpleModel(), mesh=_mesh1())
