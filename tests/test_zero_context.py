"""zero.Init / GatheredParameters / TiledLinear / ZeroLinear tests — the
reference's test_zero_context.py and test_zero_tiled.py roles."""

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
from deepspeed_tpu.runtime import zero
from tests.simple_model import SimpleModel


def test_sharded_init_produces_sharded_params():
    mesh = make_mesh(MeshConfig(data=8))
    model = SimpleModel(hidden_dim=64)
    params, shardings = zero.sharded_init(
        model, jax.random.PRNGKey(0), jnp.ones((2, 8)), mesh, stage=3,
        param_persistence_threshold=0)
    kernels = [p for p in jax.tree_util.tree_leaves(params) if p.ndim == 2]
    assert any(any(ax is not None for ax in k.sharding.spec) for k in kernels)


def test_sharded_init_matches_eager_init():
    mesh = make_mesh(MeshConfig(data=8))
    model = SimpleModel(hidden_dim=64)
    params, _ = zero.sharded_init(model, jax.random.PRNGKey(0),
                                  jnp.ones((2, 8)), mesh, stage=3,
                                  param_persistence_threshold=0)
    eager = model.init(jax.random.PRNGKey(0), jnp.ones((2, 8)))["params"]
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(params)),
                    jax.tree_util.tree_leaves(jax.device_get(eager))):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_init_context():
    mesh = make_mesh(MeshConfig(data=8))
    with zero.Init(mesh=mesh, zero_stage=3) as ctx:
        assert zero.Init.current() is ctx
        params = ctx.init(SimpleModel(hidden_dim=64), jax.random.PRNGKey(0),
                          jnp.ones((2, 8)))
    assert zero.Init.current() is None
    assert params is not None


def test_init_context_disabled():
    with zero.Init(enabled=False) as ctx:
        params = ctx.init(SimpleModel(), jax.random.PRNGKey(0),
                          jnp.ones((2, 8)))
    assert params is not None


def test_gathered_parameters():
    mesh = make_mesh(MeshConfig(data=8))
    model = SimpleModel(hidden_dim=64)
    params, _ = zero.sharded_init(model, jax.random.PRNGKey(0),
                                  jnp.ones((2, 8)), mesh, stage=3,
                                  param_persistence_threshold=0)
    with zero.GatheredParameters(params) as full:
        for leaf in jax.tree_util.tree_leaves(full):
            assert isinstance(leaf, np.ndarray)


def test_tiled_linear_matches_dense():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 32))
    tiled = zero.TiledLinear(in_features=32, out_features=48, in_splits=2,
                             out_splits=3)
    variables = tiled.init(jax.random.PRNGKey(1), x)
    out = tiled.apply(variables, x)
    assert out.shape == (4, 48)
    # equivalent dense computation from the tile params
    p = variables["params"]
    # column j of output = sum_i x_i @ W_ij (+ b_0j)
    ref_cols = []
    for j in range(3):
        acc = 0
        for i in range(2):
            tile = p[f"tile_{i}_{j}"]
            xi = x[:, i * 16:(i + 1) * 16]
            acc = acc + xi @ tile["kernel"]
            if i == 0:
                acc = acc + tile["bias"]
        ref_cols.append(acc)
    ref = jnp.concatenate(ref_cols, axis=-1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_tiled_linear_split_sizes():
    assert zero.tiling.split_dim(10, 3) == [4, 3, 3]
    assert zero.tiling.split_dim(9, 3) == [3, 3, 3]


def test_tiled_linear_return_bias():
    x = jnp.ones((2, 8))
    mod = zero.TiledLinearReturnBias(in_features=8, out_features=8,
                                     in_splits=2, out_splits=2)
    variables = mod.init(jax.random.PRNGKey(0), x)
    out, bias = mod.apply(variables, x)
    assert out.shape == (2, 8) and bias is None


def test_zero_linear_matches_dense():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
    zl = zero.ZeroLinear(features=8)
    variables = zl.init(jax.random.PRNGKey(1), x)
    out = zl.apply(variables, x)
    p = variables["params"]
    ref = x @ p["kernel"] + p["bias"]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)
    # gradient flows
    g = jax.grad(lambda v: zl.apply(v, x).sum())(variables)
    assert np.isfinite(
        np.asarray(g["params"]["kernel"])).all()
