"""Real multi-process distributed test — the reference's
@distributed_test(world_size=N) harness (tests/unit/common.py:16): fork N
OS processes, rendezvous through the launcher env contract
(DSTPU_COORDINATOR_*), run a REAL collective over the global mesh, and
fail on bad exits or hangs. No fake backend: this is
jax.distributed.initialize over localhost, the actual multi-host path."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent("""
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed

    init_distributed()   # rendezvous purely from the launcher env contract
    assert jax.process_count() == 2, jax.process_count()

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from deepspeed_tpu.parallel.mesh import shard_map

    devs = jax.devices()             # global device list across processes
    mesh = Mesh(np.asarray(devs), ("data",))
    pid = jax.process_index()

    import functools
    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P("data"),
                       out_specs=P())
    def total(x):
        return jax.lax.psum(jnp.sum(x), "data")

    # each process contributes its process_index+1 on its local shard
    local = jnp.full((1,), float(pid + 1))
    from jax.experimental import multihost_utils
    arr = multihost_utils.host_local_array_to_global_array(
        local, mesh, P("data"))
    out = float(total(arr))
    expected = float(sum(range(1, jax.process_count() + 1)))
    assert out == expected, (out, expected)
    print(f"RANK{pid}_OK", flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_workers(world, script_text, tmp_path, script_args=(),
                  local_devices=1, timeout=240):
    """Reusable multi-process harness (ISSUE 10 satellite): write
    ``script_text`` to disk, fork ``world`` ranked OS processes over the
    launcher env contract (fresh free-port rendezvous, ``local_devices``
    virtual CPU devices each), wait with hang detection (the reference
    harness's common.py:74-88 role), assert every rank exited 0, and
    return the per-rank stdouts."""
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    port = _free_port()
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update({
            "DSTPU_COORDINATOR_ADDR": "127.0.0.1",
            "DSTPU_COORDINATOR_PORT": str(port),
            "DSTPU_NUM_PROCESSES": str(world),
            "DSTPU_PROCESS_ID": str(rank),
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count="
                         f"{local_devices}",
            "PYTHONPATH": REPO_ROOT + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        })
        env.pop("DSTPU_LOCAL_DEVICE_IDS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script)] + [str(a) for a in script_args],
            env=env, cwd=REPO_ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"rank {rank} hung (the reference harness's hang "
                        f"detection, common.py:74-88)")
        outs.append(out)
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    return outs


def test_rendezvous_env_contract_discovery():
    """Fast tier-1 coverage of the launcher env contract the slow
    multi-process tests rendezvous through: discover_rendezvous is pure
    over an environ dict, so the precedence and parsing rules pin here
    without forking processes."""
    from deepspeed_tpu.utils.distributed import discover_rendezvous

    # the DSTPU_* contract (what launcher/launch.py exports)
    addr, num, pid, ids = discover_rendezvous({
        "DSTPU_COORDINATOR_ADDR": "10.0.0.1",
        "DSTPU_COORDINATOR_PORT": "1234",
        "DSTPU_NUM_PROCESSES": "4",
        "DSTPU_PROCESS_ID": "2",
        "DSTPU_LOCAL_DEVICE_IDS": "0,1",
    })
    assert (addr, num, pid) == ("10.0.0.1:1234", 4, 2)
    assert list(ids) == [0, 1]
    # default port fills in; missing device ids stay None
    addr, num, pid, ids = discover_rendezvous(
        {"DSTPU_COORDINATOR_ADDR": "h", "DSTPU_NUM_PROCESSES": "2",
         "DSTPU_PROCESS_ID": "0"})
    assert addr == "h:8476" and ids is None
    # generic COORDINATOR_ADDRESS fallback
    addr, num, pid, _ = discover_rendezvous(
        {"COORDINATOR_ADDRESS": "c:99", "NUM_PROCESSES": "8",
         "PROCESS_ID": "7"})
    assert (addr, num, pid) == ("c:99", 8, 7)
    # MPI discovery requires MASTER_ADDR (no localhost guessing — every
    # rank dialing its own loopback would hang, not fail)
    addr, num, pid, _ = discover_rendezvous(
        {"OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": "1"})
    assert addr is None and (num, pid) == (2, 1)
    addr, _, _, _ = discover_rendezvous(
        {"OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": "1",
         "MASTER_ADDR": "m"})
    assert addr == "m:8476"
    # MPI auto-discovery can be disabled
    addr, num, _, _ = discover_rendezvous(
        {"OMPI_COMM_WORLD_SIZE": "2"}, auto_mpi_discovery=False)
    assert addr is None and num is None
    # empty environment resolves nothing
    assert discover_rendezvous({}) == (None, None, None, None)


@pytest.mark.parametrize("world", [2])
@pytest.mark.slow
def test_two_process_psum_over_launcher_contract(tmp_path, world):
    outs = spawn_workers(world, _WORKER, tmp_path)
    for rank, out in enumerate(outs):
        assert f"RANK{rank}_OK" in out


_ENGINE_WORKER = textwrap.dedent("""
    import os
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed
    init_distributed()

    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config

    assert jax.process_count() == 2
    assert len(jax.devices()) == 8            # 4 local x 2 processes
    mesh = make_mesh(MeshConfig(data=8))      # dp over the GLOBAL mesh
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 2}
    cfg["seed"] = 3
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()                    # identical on every process
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    print("LOSSES", jax.process_index(), ",".join(f"{l:.6f}" for l in losses),
          flush=True)
""")


@pytest.mark.slow
def test_engine_trains_across_two_processes(tmp_path):
    """Full engine training over a 2-process global mesh (dp=8, ZeRO-2):
    the true multi-host path — rendezvous, global batch feeding, GSPMD
    collectives over DCN-style process boundaries."""
    outs = spawn_workers(2, _ENGINE_WORKER, tmp_path, local_devices=4,
                         timeout=300)

    import re
    curves = {}
    for out in outs:
        m = re.search(r"LOSSES (\d+) ([\d.,-]+)", out)
        assert m, out
        curves[int(m.group(1))] = [float(x) for x in m.group(2).split(",")]
    # both processes observe the identical global trajectory
    assert curves[0] == curves[1]

    # and it matches the same config run in ONE process on 8 local devices
    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config
    if len(__import__("jax").devices()) >= 8:
        import jax
        cfg = base_config()
        cfg["zero_optimization"] = {"stage": 2}
        cfg["seed"] = 3
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=SimpleModel(),
            mesh=make_mesh(MeshConfig(data=8), devices=jax.devices()[:8]))
        batch = random_batch()
        ref = [float(engine.train_batch(batch)) for _ in range(3)]
        np.testing.assert_allclose(curves[0], ref, rtol=1e-4, atol=1e-5)


_CKPT_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed
    init_distributed()

    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config

    ckpt_dir = sys.argv[1]
    mesh = make_mesh(MeshConfig(data=8))
    cfg = base_config()
    cfg["zero_optimization"] = {"stage": 3,
                                "stage3_param_persistence_threshold": 0}
    cfg["seed"] = 3

    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    for _ in range(2):
        engine.train_batch(batch)
    engine.save_checkpoint(ckpt_dir, tag="t0")
    cont = float(engine.train_batch(batch))

    # fresh engine, restore, repeat the 3rd step — must match exactly
    engine2, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                        mesh=mesh)
    tag, _ = engine2.load_checkpoint(ckpt_dir, tag="t0")
    assert tag == "t0"
    resumed = float(engine2.train_batch(batch))
    print(f"STEP3 {jax.process_index()} {cont:.6f} {resumed:.6f}",
          flush=True)
""")


@pytest.mark.slow
def test_sharded_checkpoint_two_processes_and_resize(tmp_path):
    """ZeRO-3 sharded save across 2 real processes: each rank writes only
    its own shard windows (no full-tree gather), restore reproduces the
    training trajectory bit-exactly, and the same checkpoint restores into
    a SINGLE-process engine (world-size resize, the reference's elastic
    restore zero/stage1.py:898-1031)."""
    ckpt_dir = tmp_path / "ckpt"
    outs = spawn_workers(2, _CKPT_WORKER, tmp_path,
                         script_args=(ckpt_dir,), local_devices=4,
                         timeout=300)

    import re
    for out in outs:
        m = re.search(r"STEP3 \d+ ([\d.-]+) ([\d.-]+)", out)
        assert m, out
        assert m.group(1) == m.group(2), f"resume diverged: {out}"

    # every rank wrote its own shard files; the optimizer state was never
    # gathered into one file
    import json
    import numpy as np
    tag_dir = ckpt_dir / "t0"
    for rank in range(2):
        assert (tag_dir / f"optim_states_shard_{rank}.npz").exists()
        assert (tag_dir / f"shard_index_{rank}.json").exists()
    per_rank_elems = []
    for rank in range(2):
        with open(tag_dir / f"shard_index_{rank}.json") as f:
            idx = json.load(f)
        key = "optim_states:opt_state/exp_avg/Dense_0/kernel"
        info = idx[key]
        full = int(np.prod(info["shape"]))
        elems = sum(int(np.prod([b - a for a, b in
                                 zip(p["start"], p["stop"])]))
                    for p in info["pieces"])
        per_rank_elems.append(elems)
        assert 0 < elems < full, (rank, elems, full)
    assert sum(per_rank_elems) == int(np.prod(info["shape"]))

    # world-size resize: restore the 2-process checkpoint into THIS
    # single process (8 local devices)
    import jax
    if len(jax.devices()) >= 8:
        import deepspeed_tpu as dstpu
        from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
        from tests.simple_model import SimpleModel, random_batch, base_config
        cfg = base_config()
        cfg["zero_optimization"] = {"stage": 3,
                                    "stage3_param_persistence_threshold": 0}
        cfg["seed"] = 3
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=SimpleModel(),
            mesh=make_mesh(MeshConfig(data=8)))
        tag, _ = engine.load_checkpoint(str(ckpt_dir), tag="t0")
        assert tag == "t0"
        resumed = float(engine.train_batch(random_batch()))
        assert np.isfinite(resumed)


_HIER_WORKER = textwrap.dedent("""
    import json
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed
    init_distributed()

    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config

    assert jax.process_count() == 2
    assert len(jax.devices()) == 8            # 4 local x 2 processes
    mesh = make_mesh(MeshConfig(data=8))
    cfg = base_config()
    # the test_onebit parity recipe (freeze 5, 15 steps, default init):
    # 1-bit momentum compression every step is only contractive when the
    # warmup left the momentum well-scaled — a short freeze on an
    # adversarial init diverges for the FLAT path too, so the pin here
    # would measure the toy problem, not the hierarchy
    cfg["optimizer"] = {"type": "OneBitAdam",
                        "params": {"lr": 1e-2, "freeze_step": 5}}
    # slow_axis 0 = auto: the split must come from the REAL process
    # boundaries (this is the whole point of the test); "always" because
    # SimpleModel's one bucket is far below the auto policy's floor
    cfg["comm"] = {"hierarchy": {"slow_axis": 0, "compression": "always"}}
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()                    # identical on every process
    losses = [float(engine.train_batch(batch)) for _ in range(15)]

    plan = engine.comm_hierarchy
    assert (plan.inter, plan.intra) == (2, 4), plan
    hier, _ = __import__(
        "deepspeed_tpu.parallel.topology",
        fromlist=["derive_data_hierarchy"]).derive_data_hierarchy(mesh)
    assert hier is not None and hier.source == "process", hier
    snap = engine.telemetry.snapshot("comm/")["counters"]
    print("HIER", jax.process_index(), json.dumps({
        "losses": losses,
        "wire": engine._comm_wire_model,
        "counters": snap,
    }), flush=True)
""")


@pytest.mark.slow
def test_hierarchical_compressed_allreduce_two_processes(tmp_path):
    """The tentpole proof leg (ISSUE 10): 2 real processes x 4 devices
    run the hierarchical 1-bit exchange with the slow axis derived from
    the ACTUAL jax.distributed process boundary — intra-host ring hops
    stay uncompressed, the inter-process hop carries sign bits. Pins (a)
    both ranks observe the identical loss trajectory, (b) the trajectory
    matches single-process UNCOMPRESSED Adam within the test_onebit
    convergence envelope, (c) the modeled inter-host bytes-on-wire drop
    ≥ 4x post-freeze."""
    import json as _json
    import re
    outs = spawn_workers(2, _HIER_WORKER, tmp_path, local_devices=4,
                         timeout=300)
    results = {}
    for out in outs:
        m = re.search(r"HIER (\d+) (\{.*\})", out)
        assert m, out
        results[int(m.group(1))] = _json.loads(m.group(2))
    # (a) identical trajectory on both ranks (replicated out-shardings)
    assert results[0]["losses"] == results[1]["losses"]

    # (c) inter-host wire bytes drop ≥4x once the momentum compresses
    wire = results[0]["wire"]["compressed"]
    assert wire["inter_uncompressed"] >= 4 * wire["inter"], wire
    ctr = results[0]["counters"]
    assert ctr["comm/bytes_on_wire/inter"] > 0
    assert ctr["comm/bytes_on_wire/intra"] > 0

    # (b) parity vs single-process uncompressed Adam on 8 local devices
    import jax
    if len(jax.devices()) >= 8:
        import deepspeed_tpu as dstpu
        from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
        from tests.simple_model import SimpleModel, random_batch, \
            base_config
        cfg = base_config()
        cfg["optimizer"] = {"type": "Adam", "params": {"lr": 1e-2}}
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=SimpleModel(),
            mesh=make_mesh(MeshConfig(data=8), devices=jax.devices()[:8]))
        batch = random_batch()
        ref = [float(engine.train_batch(batch)) for _ in range(15)]
        l_onebit, l_exact = results[0]["losses"][-1], ref[-1]
        # the test_onebit convergence pin (compressed tracks exact over
        # a short horizon — error feedback bounds the drift)
        assert abs(l_onebit - l_exact) \
            < 0.5 * max(abs(l_exact), 0.1) + 0.3, (l_onebit, l_exact)


_PF_HIER_WORKER = textwrap.dedent("""
    import json
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed
    init_distributed()

    import numpy as np
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    assert jax.process_count() == 2
    assert len(jax.devices()) == 2            # 1 local x 2 processes
    mesh = make_mesh(MeshConfig(data=2))
    cfg = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_prefetch": True,
                              "stage3_prefetch_gather": "ring",
                              "stage3_param_persistence_threshold": 0},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        # slow_axis 0 = auto: the split must come from the REAL process
        # boundaries; "always" because the tiny model's per-layer RS
        # buffers are far below the auto policy's byte floor
        "comm": {"hierarchy": {"slow_axis": 0, "compression": "always"}},
        "steps_per_print": 1000,
    }
    model = GPT2LMHeadModel(GPT2Config(
        vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=2,
        dtype=jnp.float32, param_dtype=jnp.float32, scan_layers=True))
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
    assert engine._prefetch_active()
    plan = engine._prefetch_hier_plan()
    assert (plan.inter, plan.intra) == (2, 1), plan
    hier, _ = __import__(
        "deepspeed_tpu.parallel.topology",
        fromlist=["derive_data_hierarchy"]).derive_data_hierarchy(mesh)
    assert hier is not None and hier.source == "process", hier

    batch = {"input_ids": np.random.RandomState(0).randint(
        0, 512, (8, 64)).astype(np.int32)}   # identical on every process
    losses = [float(engine.train_batch(batch)) for _ in range(3)]
    snap = engine.telemetry.snapshot("comm/")["counters"]
    print("PFHIER", jax.process_index(), json.dumps({
        "losses": losses,
        "wire": engine._pf_wire_model,
        "counters": snap,
    }), flush=True)
""")


@pytest.mark.slow
def test_stage3_prefetch_hierarchy_two_processes(tmp_path):
    """The ISSUE 16 proof leg: 2 real processes run the two-level ZeRO-3
    prefetch stream with the slow axis derived from the ACTUAL
    jax.distributed process boundary, grad reduce-scatters carrying sign
    bits on the inter-process hop. One virtual device per process — the
    multi-device-per-process GSPMD-over-gloo interleave flake (ROADMAP
    standing backlog, found by PR 15: ≥2 independent cross-process
    collectives nondeterministically abort with ``gloo EnforceNotMet``)
    rules out wider local meshes; the 2x4 split is covered by the
    synthetic-override tests instead. Pins (a) both ranks observe the
    identical loss trajectory, (b) the trajectory matches the SAME
    config run in one process (synthetic 2x1 override), (c) the modeled
    inter-host bytes sit below the flat-ring baseline post-compression
    and the per-link-class counters advanced."""
    import json as _json
    import re
    outs = spawn_workers(2, _PF_HIER_WORKER, tmp_path, local_devices=1,
                         timeout=300)
    results = {}
    for out in outs:
        m = re.search(r"PFHIER (\d+) (\{.*\})", out)
        assert m, out
        results[int(m.group(1))] = _json.loads(m.group(2))
    # (a) identical trajectory on both ranks (replicated out-shardings)
    assert results[0]["losses"] == results[1]["losses"]

    # (c) modeled inter-host bytes down vs the flat-ring baseline, and
    # the ledger advanced per link class
    wire = results[0]["wire"]
    assert 0 < wire["inter"] < wire["inter_uncompressed"], wire
    ctr = results[0]["counters"]
    assert ctr["comm/bytes_on_wire/inter"] > 0
    assert ctr["comm/bytes_on_wire/inter_uncompressed"] > \
        ctr["comm/bytes_on_wire/inter"]

    # (b) parity vs the same recipe in ONE process: synthetic 2x1 split
    # over 2 local devices reproduces the process-derived schedule
    import jax
    if len(jax.devices()) >= 2:
        import numpy as np
        import jax.numpy as jnp
        import deepspeed_tpu as dstpu
        from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        cfg = {
            "train_batch_size": 8,
            "zero_optimization": {
                "stage": 3, "stage3_prefetch": True,
                "stage3_prefetch_gather": "ring",
                "stage3_param_persistence_threshold": 0},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "comm": {"hierarchy": {"slow_axis": 2,
                                   "compression": "always"}},
            "steps_per_print": 1000,
        }
        model = GPT2LMHeadModel(GPT2Config(
            vocab_size=512, n_positions=64, n_embd=64, n_layer=2,
            n_head=2, dtype=jnp.float32, param_dtype=jnp.float32,
            scan_layers=True))
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=model,
            mesh=make_mesh(MeshConfig(data=2), devices=jax.devices()[:2]))
        batch = {"input_ids": np.random.RandomState(0).randint(
            0, 512, (8, 64)).astype(np.int32)}
        ref = [float(engine.train_batch(batch)) for _ in range(3)]
        np.testing.assert_allclose(results[0]["losses"], ref,
                                   rtol=2e-5, atol=1e-5)
        assert engine._pf_wire_model == wire


_STRAGGLER_WORKER = textwrap.dedent("""
    import json, os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deepspeed_tpu.utils.distributed import init_distributed
    init_distributed()

    import numpy as np
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from tests.simple_model import SimpleModel, random_batch, base_config

    dump_dir = sys.argv[1]
    assert jax.process_count() == 2
    mesh = make_mesh(MeshConfig(data=8))
    cfg = base_config()
    cfg["steps_per_print"] = 1      # every step is a cluster fence
    cfg["monitor"] = {
        "enabled": False,
        # the local step-time rule must stay quiet (the injected sleep
        # is a CLUSTER skew, not a local outlier) — only the straggler
        # rule may dump
        "watchdog": {"dump_dir": dump_dir, "step_time_factor": 1000.0,
                     "swap_stall_factor": 1000.0, "check_nan": False,
                     "straggler_factor": 2.0, "straggler_fences": 3,
                     "straggler_min_s": 0.05},
        "cluster": {"enabled": True},
    }
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                       mesh=mesh)
    batch = random_batch()
    rank = jax.process_index()
    for _ in range(10):
        engine.train_batch(batch)
        if rank == 1:
            time.sleep(0.25)        # the injected per-step straggle
    snap = engine.telemetry.snapshot("cluster/")
    wd = engine.watchdog
    dumps = sorted(os.listdir(dump_dir)) if os.path.isdir(dump_dir) \
        else []
    print("STRAGGLER", rank, json.dumps({
        "gauges": snap["gauges"],
        "fences": snap["counters"].get("cluster/fences", 0),
        "agg_fences": engine._cluster.fences,
        "trips": dict(wd.trips),
        "dumps": dumps,
        "table": engine._cluster.last_table,
    }), flush=True)
""")


@pytest.mark.slow
def test_rank_straggler_two_processes(tmp_path):
    """The ISSUE 12 proof leg: 2 real processes x 4 devices, rank 1
    gets an injected 0.25 s per-step sleep. Rank 0's cluster fold must
    (a) show cluster/step_time_s/max tracking the slow rank while the
    min tracks the fast one (the per-rank HOST-arrival component — the
    fenced wall time converges to the slowest rank in synchronous SPMD
    and proves nothing), and (b) produce EXACTLY ONE latched
    rank_straggler dump naming rank 1, via the gloo allgather riding
    the existing steps_per_print fence."""
    import json as _json
    import re
    dump_dir = tmp_path / "flight"
    outs = spawn_workers(2, _STRAGGLER_WORKER, tmp_path,
                         script_args=(dump_dir,), local_devices=4,
                         timeout=300)
    results = {}
    for out in outs:
        m = re.search(r"STRAGGLER (\d+) (\{.*\})", out)
        assert m, out
        results[int(m.group(1))] = _json.loads(m.group(2))

    r0 = results[0]
    # BOTH ranks took part in every exchange (the collective is
    # aligned), but the fold — gauges, skew table, counter, rule —
    # runs on rank 0 only
    assert r0["agg_fences"] >= 8 and results[1]["agg_fences"] >= 8
    assert r0["fences"] >= 8
    assert results[1]["fences"] == 0
    assert "cluster/step_time_s/max" not in results[1]["gauges"]

    g = r0["gauges"]
    assert g["cluster/world_size"] == 2
    # max ~ the injected 0.25 s sleep, min ~ rank 0's dispatch time
    assert g["cluster/step_time_s/argmax_rank"] == 1
    assert g["cluster/step_time_s/max"] >= 0.2, g
    assert g["cluster/step_time_s/min"] < 0.1, g
    assert g["cluster/step_time_s/max"] > 3 * g["cluster/step_time_s/min"]
    per_rank = r0["table"]["metrics"]["step_time_s"]
    assert per_rank[1] > 3 * per_rank[0], per_rank

    # exactly ONE latched rank_straggler dump, on rank 0, naming rank 1
    assert r0["trips"].get("rank_straggler") == 1, r0["trips"]
    assert results[1]["trips"] == {}, results[1]["trips"]
    straggler_dumps = [d for d in r0["dumps"] if "rank_straggler" in d]
    assert len(straggler_dumps) == 1, r0["dumps"]
    assert [d for d in r0["dumps"] if "rank_straggler" not in d] == []
    header = _json.loads(
        open(dump_dir / straggler_dumps[0]).readline())
    assert header["rule"] == "rank_straggler"
    assert header["detail"]["rank"] == 1
    assert header["detail"]["consecutive_fences"] == 3
