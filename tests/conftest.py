"""Test harness: single-process multi-device simulation.

The reference spawns N processes with real NCCL for every distributed test
(tests/unit/common.py:16 @distributed_test). On TPU/JAX we instead force the
CPU backend to expose 8 virtual devices, so every mesh/sharding/collective
path runs in-process (SURVEY §4 'lesson for the TPU rebuild'). This must run
before jax initializes, hence module-level in conftest.
"""

import os

# hard override: the machine env may preset JAX_PLATFORMS to a TPU plugin,
# and a sitecustomize may have imported jax already — set both the env var
# and the live config.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _np_seed():
    np.random.seed(0)


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_cache():
    """Free jitted executables between test MODULES: the full suite
    (300+ tests) accumulates enough XLA CPU executables to OOM-abort the
    compiler partway through on small hosts (the r4 suite died with a
    Fatal abort inside backend_compile at ~70%); per-module clearing
    bounds the live set while keeping intra-module cache hits."""
    yield
    jax.clear_caches()
