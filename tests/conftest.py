"""Test harness: single-process multi-device simulation.

The reference spawns N processes with real NCCL for every distributed test
(tests/unit/common.py:16 @distributed_test). On TPU/JAX we instead force the
CPU backend to expose 8 virtual devices, so every mesh/sharding/collective
path runs in-process (SURVEY §4 'lesson for the TPU rebuild'). This must run
before jax initializes, hence module-level in conftest.
"""

import os

# hard override: the machine env may preset JAX_PLATFORMS to a TPU plugin,
# and a sitecustomize may have imported jax already — set both the env var
# and the live config.
os.environ["JAX_PLATFORMS"] = "cpu"
# The suite is XLA-compile-bound on the CPU backend (tiny programs, hundreds
# of engine builds; the per-module cache clear below re-pays compiles), and
# the tier-1 runner has a hard wall-clock budget. Skipping XLA's expensive
# optimization passes cuts module times ~35% and changes nothing the suite
# asserts (numerics stay fp32-exact enough for every allclose; jaxpr-level
# structure tests never see XLA passes). Export-level so spawned worker
# processes (examples / launcher tests) inherit it; set it to 0 to measure
# with full optimizations.
os.environ.setdefault("JAX_DISABLE_MOST_OPTIMIZATIONS", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# sitecustomize may have imported jax before this file ran, in which case
# the env var above arrived too late for the live config — mirror it, like
# jax_platforms
jax.config.update("jax_disable_most_optimizations",
                  os.environ["JAX_DISABLE_MOST_OPTIMIZATIONS"] == "1")

# NOTE: the persistent compilation cache (jax_compilation_cache_dir) is NOT
# safe here — on the pinned jax 0.4.37 CPU backend, re-loading cached
# executables after clear_caches() segfaults partway through the suite
# (observed in test_model_convergence). Keep compile-cost control to the
# per-module clear below.
import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight end-to-end variants excluded from the "
        "wall-clock-budgeted tier-1 run (run them with -m slow); each has "
        "a faster sibling covering the same subsystem in tier-1")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)


@pytest.fixture(autouse=True)
def _np_seed():
    np.random.seed(0)


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_cache():
    """Free jitted executables between test MODULES: the full suite
    (300+ tests) accumulates enough XLA CPU executables to OOM-abort the
    compiler partway through on small hosts (the r4 suite died with a
    Fatal abort inside backend_compile at ~70%); per-module clearing
    bounds the live set while keeping intra-module cache hits."""
    yield
    jax.clear_caches()
