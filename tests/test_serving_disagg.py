"""Disaggregated prefill/decode serving + SLO router (ISSUE 14).

Covers the role split end to end:

- greedy token parity colocated vs disaggregated, with the page-pool
  leak fence held across 100+ handoffs (every engine's pool drains to
  num_blocks - 1 after the workload + a prefix sweep);
- prefix-locality routing: a prompt whose prefix chain lives on
  replica B routes to B (even when B is the more loaded choice) and
  produces hit_pages > 0 there;
- decode-pool pressure: an exhausted decode pool queues prompts AT THE
  ROUTER (router/decode_blocked) — no engine ever trips
  pool_exhausted mid-flight;
- handoff dedupe: a second request sharing a prompt prefix re-shares
  the decode pool's resident pages (incref, no copy) through the
  refcounted allocator;
- kill-during-handoff: the transport dying between extract and deliver
  replays the request from its wire doc, and the viewer stitches the
  prefill→handoff→decode timeline across per-role dump files with
  zero orphaned traces;
- TTFT attribution: queue-wait/prefill/handoff/first-decode-tick
  components in metrics_snapshot();
- sampled (temperature > 0) parity across the handoff — the persisted
  sample_key replays the identical sampled stream;
- build_router config wiring + colocated fallback.
"""

import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu.serving as serving
from deepspeed_tpu.runtime.elastic import faults
from deepspeed_tpu.serving.engine import ContinuousBatcher
from deepspeed_tpu.serving.router import (DisaggRouter,
                                          router_metric_names)
from deepspeed_tpu.telemetry.recorder import (FlightRecorder,
                                              default_recorder)


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    default_recorder().configure(enabled=True, capacity=4096)
    default_recorder().clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def gpt2_dis():
    """(cfg, params, adapter_for): engines over shared per-geometry
    adapters (compiled programs live on the adapter — tier-1 budget)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                     n_layer=2, n_head=4, dtype=jnp.float32,
                     param_dtype=jnp.float32, scan_layers=True)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    adapters = {}

    def adapter_for(slots=2, **sv_kw):
        sv = {"slots": slots, "page_size": 8, "max_pages_per_slot": 8}
        sv.update(sv_kw)
        key = tuple(sorted(sv.items()))
        if key not in adapters:
            adapters[key] = serving.build_engine(
                "gpt2", cfg, params, config={"serving": sv}).adapter
        return adapters[key]

    return cfg, params, adapter_for


def _mk_router(adapter, n_prefill=1, n_decode=1, **kw):
    pes = [ContinuousBatcher(adapter, role="prefill", prefix_cache=True)
           for _ in range(n_prefill)]
    des = [ContinuousBatcher(adapter, role="decode", prefix_cache=True)
           for _ in range(n_decode)]
    return DisaggRouter(pes, des, **kw)


def _reqs(n, max_new=8, seed=0, temperature=0.0):
    rs = np.random.RandomState(seed)
    lens = rs.choice([5, 9, 14, 21], n)
    return [serving.Request(
        i, rs.randint(0, 256, size=(int(lens[i]),)).astype(np.int32),
        max_new_tokens=max_new, temperature=temperature)
        for i in range(n)]


def _clone(reqs):
    return [serving.Request(r.rid, r.prompt,
                            max_new_tokens=r.max_new_tokens,
                            eos_token_id=r.eos_token_id,
                            temperature=r.temperature,
                            arrival_time=r.arrival_time) for r in reqs]


def _ref_streams(adapter, reqs):
    eng = ContinuousBatcher(adapter)
    return {rid: r.tokens().tolist()
            for rid, r in eng.serve(_clone(reqs)).items()}


# --------------------------------------------------- parity + leak fence


def test_disagg_parity_and_leak_fence_100_handoffs(gpt2_dis):
    """Greedy outputs are token-for-token identical across the
    prefill→decode handoff, and after 100+ handoffs every engine's
    page pool drains to num_blocks - 1 (the acceptance criterion's
    leak fence)."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(104, max_new=2, seed=1)
    ref = _ref_streams(adapter, reqs)
    router = _mk_router(adapter, n_prefill=1, n_decode=2)
    done = router.run(_clone(reqs))
    assert len(done) == len(reqs) and not router.lost
    assert router.stats["handoffs"] >= 100
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid
    for cb in router.prefill_engines + router.decode_engines:
        cb.cache.sweep_prefix_cache()
        assert cb.cache.free_pages == cb.cache.num_blocks - 1, \
            cb.replica_id
    snap = router.metrics_snapshot()
    assert snap["mode"] == "disaggregated"
    assert snap["handoffs"] == router.stats["handoffs"]
    # decode engines never ran a prefill program; prefill engines
    # never committed a decode-tick token
    for dcb in router.decode_engines:
        assert dcb.stats["prefills"] == 0
    for pcb in router.prefill_engines:
        assert pcb.stats["decode_tokens"] == 0
        assert pcb.stats["ticks"] == 0


def test_disagg_sampled_parity_across_handoff(gpt2_dis):
    """temperature > 0: the persisted per-request sample_key makes the
    handed-off continuation identical to the colocated run's — the
    stateless fold_in(sample_key, token_index) keys don't care which
    engine draws them."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(4, max_new=10, seed=2, temperature=0.8)
    ref = _ref_streams(adapter, reqs)
    router = _mk_router(adapter, n_prefill=1, n_decode=1)
    done = router.run(_clone(reqs))
    assert len(done) == len(reqs)
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid


# ------------------------------------------------------ routing policy


def test_router_prefix_locality_routes_to_matching_replica(gpt2_dis):
    """A prompt whose prefix chain lives on replica B must route to B
    — even when B is the MORE loaded SLO choice — and produce
    hit_pages > 0 there (the locality skip of the shared span's
    prefill)."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    router = _mk_router(adapter, n_prefill=2, n_decode=1)
    rs = np.random.RandomState(7)
    shared = rs.randint(0, 256, size=(19,)).astype(np.int32)
    mk = (lambda rid: serving.Request(
        rid, np.concatenate([shared, rs.randint(0, 256, size=(4,))
                             .astype(np.int32)]), max_new_tokens=4))
    # warm: the first shared-prefix request routes by SLO (cold
    # indexes, equal load → engine 0) and registers the chain there
    done = router.run([mk("warm")])
    assert len(done) == 1
    evs = [e for e in default_recorder().events()
           if e["kind"] == "router_route" and e["rid"] == "warm"]
    assert evs and evs[0]["reason"] == "slo"
    home = evs[0]["engine"]
    home_cb = next(cb for cb in router.prefill_engines
                   if cb.replica_id == home)
    other_cb = next(cb for cb in router.prefill_engines
                    if cb.replica_id != home)
    # load the HOME engine with an unrelated prompt, then submit the
    # prefix request in the same round: SLO would pick the idle
    # engine; locality must still pick home
    filler = serving.Request(
        "filler", rs.randint(0, 256, size=(9,)).astype(np.int32),
        max_new_tokens=4)
    router.submit(filler)
    hot = mk("hot")
    router.submit(hot)
    before = home_cb.cache.prefix_stats["hit_pages"]
    while router.pending:
        router.step()
    evs = {e["rid"]: e for e in default_recorder().events()
           if e["kind"] == "router_route"}
    assert evs["hot"]["reason"] == "prefix"
    assert evs["hot"]["engine"] == home
    assert home_cb.cache.prefix_stats["hit_pages"] > before
    assert other_cb.cache.prefix_stats["hit_pages"] == 0
    assert router.done["hot"].tokens().tolist()[:19] == shared.tolist()


def test_router_queues_on_decode_pool_pressure(gpt2_dis):
    """An exhausted decode pool queues prompts AT THE ROUTER (no
    admission — router/decode_blocked counts) instead of tripping
    pool_exhausted mid-flight: with 8 allocatable decode pages and
    ~4-page requests only two can be resident, the packet backlog hits
    the in-flight KV bound, and later prompts wait unadmitted. The
    queue drains as finishes free slots and every request completes
    token-identically."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2, num_blocks=9)
    reqs = _reqs(6, max_new=12, seed=3)       # ~4 pages each
    ref = _ref_streams(adapter, reqs)
    default_recorder().clear()   # the (page-starved) reference engine
    #                              legitimately tripped pool_exhausted
    router = _mk_router(adapter, n_prefill=1, n_decode=1,
                        max_inflight_pages=4)
    done = router.run(_clone(reqs))
    assert len(done) == len(reqs) and not router.lost
    assert router.stats["decode_blocked"] > 0
    kinds = [e["kind"] for e in default_recorder().events()]
    assert "router_block" in kinds
    assert "pool_exhausted" not in kinds      # never mid-flight
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid


def test_handoff_dedupe_reshares_decode_pages(gpt2_dis):
    """Two requests sharing a prompt prefix, served one after the
    other: the second handoff re-shares the decode pool's resident
    prompt pages (admit_prefix incref — hit_pages > 0 on the DECODE
    cache) instead of copying them again."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    rs = np.random.RandomState(11)
    shared = rs.randint(0, 256, size=(17,)).astype(np.int32)
    mk = (lambda rid: serving.Request(
        rid, np.concatenate([shared, rs.randint(0, 256, size=(3,))
                             .astype(np.int32)]), max_new_tokens=4))
    router = _mk_router(adapter, n_prefill=1, n_decode=1)
    dcb = router.decode_engines[0]
    router.run([mk("a")])
    assert dcb.cache.prefix_stats["hit_pages"] == 0
    router.run([mk("b")])
    assert dcb.cache.prefix_stats["hit_pages"] > 0
    # fence still holds with shared resident pages
    for cb in router.prefill_engines + router.decode_engines:
        cb.cache.sweep_prefix_cache()
        assert cb.cache.free_pages == cb.cache.num_blocks - 1


# --------------------------------------------- transport crash + viewer


def test_kill_during_handoff_zero_orphaned_traces(gpt2_dis, tmp_path):
    """The transport dies between extract and deliver (the gathered
    bytes are lost): the router replays the request from its wire doc
    token-for-token, and telemetry/view.py stitches the full
    prefill→handoff→decode timeline per trace_id across PER-ROLE dump
    files with zero orphaned traces — every submitted trace appears
    and closes with a finish."""
    from deepspeed_tpu.telemetry import view

    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(6, max_new=6, seed=5)
    ref = _ref_streams(adapter, reqs)
    default_recorder().clear()
    # per-role recorders: the prefill side's ring (plus the router's
    # routing/requeue events) and the decode side's ring dump to
    # SEPARATE files — the multi-dump merge is what stitches them
    rec_p = FlightRecorder(capacity=4096)
    rec_d = FlightRecorder(capacity=4096)
    pes = [ContinuousBatcher(adapter, role="prefill",
                             prefix_cache=True, recorder=rec_p)]
    des = [ContinuousBatcher(adapter, role="decode",
                             prefix_cache=True, recorder=rec_d)]
    router = DisaggRouter(pes, des, recorder=rec_p)
    work = _clone(reqs)
    for r in work:
        router.submit(r)
    traces = {r.rid: r.trace_id for r in work}
    assert all(traces.values())
    with faults.crash_during_handoff(times=2):
        rounds = 0
        while router.pending and rounds < 500:
            router.step()
            rounds += 1
    done = router.done
    assert len(done) == len(reqs) and not router.lost
    assert router.stats["handoff_requeues"] == 2
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid

    dump_p = tmp_path / "prefill.jsonl"
    dump_d = tmp_path / "decode.jsonl"
    for path, rec in ((dump_p, rec_p), (dump_d, rec_d)):
        with open(path, "w") as fh:
            for ev in rec.events():
                fh.write(json.dumps(ev, default=repr) + "\n")
    _headers, events, _ = view.load_dumps([str(dump_p), str(dump_d)])
    timelines = view.trace_timelines(events)
    # zero orphans: every submitted trace appears and closes finished
    assert set(timelines) == set(traces.values())
    for rid, tid in traces.items():
        evs = timelines[tid]
        assert view._trace_outcome(evs).startswith("finished"), rid
        kinds = [e["kind"] for e in evs]
        assert "router_route" in kinds
        assert "handoff_out" in kinds and "handoff_in" in kinds
        # the handoff crossed a replica boundary: prefill + decode ids
        reps = {e.get("replica") for e in evs
                if e.get("replica") is not None}
        assert len(reps) >= 2, (rid, reps)
    # the crashed requests show the replay chain
    requeued = [tid for tid, evs in timelines.items()
                if any(e["kind"] == "serving_requeue" for e in evs)]
    assert len(requeued) == 2
    text = "\n".join(view.render([str(dump_p), str(dump_d)]))
    assert "disaggregated serving:" in text
    assert "handoff_out" in text and "handoff_in" in text


def test_delivery_crash_unwinds_admitted_pages(gpt2_dis):
    """ISSUE 15 satellite (the bug PR 14's review flagged): a crash at
    ``serving_deliver`` — AFTER the decode pool admitted the packet's
    pages, before scatter/adoption — must unwind the admission instead
    of leaking the pages. The router replays the request from its wire
    doc token-for-token, and the leak fence holds: every engine's pool
    drains back to num_blocks - 1."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(6, max_new=4, seed=9)
    ref = _ref_streams(adapter, reqs)
    router = _mk_router(adapter, n_prefill=1, n_decode=1)
    with faults.crash_during_delivery(times=2):
        done = router.run(_clone(reqs))
    assert len(done) == len(reqs) and not router.lost
    assert router.stats["handoff_requeues"] == 2
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid
    # the leak fence: the unwound admissions returned every page (and
    # left no refcounts or prefix-index entries pointing at
    # never-written blocks)
    for cb in router.prefill_engines + router.decode_engines:
        cb.cache.sweep_prefix_cache()
        assert cb.cache.free_pages == cb.cache.num_blocks - 1, \
            cb.replica_id
        assert not cb.cache._block_entry, cb.replica_id
    evs = [e for e in default_recorder().events()
           if e["kind"] == "serving_requeue"]
    assert len([e for e in evs if e.get("outcome") == "scheduled"]) == 2


def test_delivery_crash_every_attempt_bounded_no_leak(gpt2_dis):
    """A request whose every DELIVERY crashes is dropped after
    max_handoff_retries with the pool intact — the delivery-side twin
    of the poisoned-handoff budget test."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(3, max_new=4, seed=10)
    router = _mk_router(adapter, n_prefill=1, n_decode=1,
                        max_handoff_retries=2)
    with faults.crash_during_delivery(match_rid=0, times=None):
        done = router.run(_clone(reqs))
    assert 0 in router.lost and 0 not in done
    assert sorted(done) == [1, 2]
    for cb in router.prefill_engines + router.decode_engines:
        cb.cache.sweep_prefix_cache()
        assert cb.cache.free_pages == cb.cache.num_blocks - 1


def test_handoff_retry_budget_drops_poisoned_request(gpt2_dis):
    """A request whose every handoff crashes is dropped after
    max_handoff_retries (bounded) — the rest of the traffic
    completes."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(3, max_new=4, seed=6)
    router = _mk_router(adapter, n_prefill=1, n_decode=1,
                        max_handoff_retries=2)
    with faults.crash_during_handoff(match_rid=0, times=None):
        done = router.run(_clone(reqs))
    assert 0 in router.lost and 0 not in done
    assert sorted(done) == [1, 2]
    assert router.stats["lost"] == 1
    evs = [e for e in default_recorder().events()
           if e["kind"] == "serving_requeue"
           and e.get("outcome") == "dropped"]
    assert len(evs) == 1
    # the poisoned request's pages all came back
    for cb in router.prefill_engines + router.decode_engines:
        cb.cache.sweep_prefix_cache()
        assert cb.cache.free_pages == cb.cache.num_blocks - 1


# ------------------------------------------------- attribution + config


def test_ttft_breakdown_components(gpt2_dis):
    """metrics_snapshot decomposes TTFT: colocated engines record
    queue-wait + prefill (no handoff); disaggregated runs additionally
    record handoff + first-decode-tick for every handed-off request."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(4, max_new=6, seed=8)
    eng = ContinuousBatcher(adapter)
    eng.serve(_clone(reqs))
    bd = eng.metrics_snapshot()["ttft_breakdown"]
    assert bd["queue_wait_s"]["count"] == len(reqs)
    assert bd["prefill_s"]["count"] == len(reqs)
    assert bd["handoff_s"]["count"] == 0
    assert bd["transport_s"]["count"] == 0   # no extract/deliver hop
    assert bd["first_decode_tick_s"]["count"] == len(reqs)

    router = _mk_router(adapter, n_prefill=1, n_decode=1)
    router.run(_clone(reqs))
    bd = router.metrics_snapshot()["ttft_breakdown"]
    assert bd["queue_wait_s"]["count"] == len(reqs)
    assert bd["prefill_s"]["count"] == len(reqs)
    assert bd["handoff_s"]["count"] == len(reqs)
    # the wire/move segment (ISSUE 17): extraction stamp -> adoption,
    # observed per delivered handoff even on the in-process fabric
    assert bd["transport_s"]["count"] == len(reqs)
    assert bd["first_decode_tick_s"]["count"] == len(reqs)


def test_build_router_from_config_and_colocated_fallback(gpt2_dis,
                                                         tmp_path):
    """build_router wires the serving.disaggregation/.router blocks;
    decode_replicas 0 (or enabled false) degrades to colocated
    engines behind the same API with identical outputs."""
    cfg, params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(3, max_new=5, seed=9)
    ref = _ref_streams(adapter, reqs)
    sv = {"slots": 2, "page_size": 8, "max_pages_per_slot": 8}
    router = serving.build_router(
        "gpt2", cfg, params,
        config={"serving": {
            **sv,
            "disaggregation": {"prefill_replicas": 1,
                               "decode_replicas": 2},
            "router": {"decode_tick_cap": 2,
                       "max_handoff_retries": 1}}})
    assert len(router.prefill_engines) == 1
    assert len(router.decode_engines) == 2
    assert router.decode_tick_cap == 2
    assert router.max_handoff_retries == 1
    done = router.run(_clone(reqs))
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid

    # blocks build_router would silently drop must raise instead
    with pytest.raises(ValueError, match="speculative"):
        serving.build_router(
            "gpt2", cfg, params,
            config={"serving": {**sv, "disaggregation": {},
                                "speculative": {}}})
    # transport "process" needs a ranked world — build_router builds
    # the in-process fabric only (build_transport_node is the entry)
    with pytest.raises(ValueError, match="build_transport_node"):
        serving.build_router(
            "gpt2", cfg, params,
            config={"serving": {
                **sv,
                "disaggregation": {"transport": "process"}}})

    # serving.elastic now COMPOSES (ISSUE 17 satellite): every role
    # engine gets its own controller snapshotting into a per-replica
    # subdir (N engines in one dir would race the commit-rename)
    import os
    snap_root = str(tmp_path / "snaps")
    el = serving.build_router(
        "gpt2", cfg, params,
        config={"serving": {
            **sv,
            "disaggregation": {"prefill_replicas": 1,
                               "decode_replicas": 2},
            "elastic": {"snapshot_path": snap_root,
                        "grace_secs": 5.0}}})
    try:
        engines = el.prefill_engines + el.decode_engines
        assert all(cb.elastic is not None for cb in engines)
        dirs = {cb.elastic.snapshot_dir for cb in engines}
        assert len(dirs) == len(engines)
        assert dirs == {os.path.join(snap_root, cb.replica_id)
                        for cb in engines}
        done = el.run(_clone(reqs))
        for rid, toks in ref.items():
            assert done[rid].tokens().tolist() == toks, rid
    finally:
        # LIFO close restores the pre-test signal table cleanly (the
        # pool discipline's release() applies when OTHER replicas keep
        # serving; here the whole world retires)
        for cb in reversed(engines):
            cb.elastic.close()

    colo = serving.build_router(
        "gpt2", cfg, params,
        config={"serving": {
            **sv, "disaggregation": {"decode_replicas": 0,
                                     "prefill_replicas": 2}}})
    assert colo.colocated and not colo.decode_engines
    assert all(cb.role == "both" for cb in colo.prefill_engines)
    done = colo.run(_clone(reqs))
    assert colo.stats["handoffs"] == 0
    for rid, toks in ref.items():
        assert done[rid].tokens().tolist() == toks, rid
    snap = colo.metrics_snapshot()
    assert snap["mode"] == "colocated"


def test_router_metric_names_cover_emissions():
    """Every router/* literal the router records must be declared in
    router_metric_names() (the docs pin rides
    tests/test_metric_names.py)."""
    import pathlib
    import re
    pkg = pathlib.Path(serving.__file__).parent
    src = ((pkg / "router.py").read_text()
           + (pkg / "transport.py").read_text())
    emitted = set(re.findall(r'"(router/[a-z0-9_]+)"', src))
    # the f-string family router/{prefix,slo}_routed
    emitted.discard("router/")
    emitted |= {"router/prefix_routed", "router/slo_routed"}
    assert emitted == set(router_metric_names())


# ---------------- cross-process transport: loopback fast siblings
# (ISSUE 17). The 2-REAL-process acceptance legs live in
# tests/test_serving_transport.py (slow tier); these run the SAME node
# state machines and the SAME wire codec through LoopbackFabric in one
# process, so tier-1 exercises every branch the acceptance legs do.


def _mk_loopback(adapter, world=2, prefill_prefix=False,
                 addressing="targeted", **pkw):
    from deepspeed_tpu.serving.transport import (DecodeNode,
                                                 LoopbackFabric,
                                                 PrefillNode)
    fab = LoopbackFabric(world, addressing=addressing)
    pes = [ContinuousBatcher(adapter, role="prefill",
                             prefix_cache=prefill_prefix)]
    pnode = PrefillNode(pes, fab.endpoint(0), **pkw)
    dnodes = [DecodeNode(ContinuousBatcher(adapter, role="decode",
                                           prefix_cache=True),
                         fab.endpoint(r)) for r in range(1, world)]
    pnode.on_tick = lambda _n: [d.tick() for d in dnodes]
    return pnode, dnodes


def _fence_all(pnode, dnodes):
    for cb in pnode.engines + [d.engine for d in dnodes]:
        cb.cache.sweep_prefix_cache()
        assert cb.cache.free_pages == cb.cache.num_blocks - 1, \
            cb.replica_id


def test_loopback_transport_parity_counters_and_fence(gpt2_dis):
    """Fast sibling of the 2-process acceptance: every stream
    token-identical to the colocated run across the encoded-frame
    hop, ``handoff_bytes_sent == handoff_bytes_recv`` (sender counts
    encoded lengths, receiver recomputes from decoded content), leak
    fence clean on every pool."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(12, max_new=6, seed=4)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter, world=3)
    done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert sorted(done) == sorted(ref) and not pnode.lost
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid
    assert pnode.stats["handoffs"] >= len(reqs)
    recv = sum(d.stats["bytes_recv"] for d in dnodes)
    assert pnode.stats["bytes_sent"] == recv > 0
    assert pnode.metrics.counter(
        "router/handoff_bytes_sent").value == pnode.stats["bytes_sent"]
    assert sum(d.metrics.counter("router/handoff_bytes_recv").value
               for d in dnodes) == recv
    _fence_all(pnode, dnodes)


def test_loopback_dedupe_survives_process_boundary(gpt2_dis):
    """The receiving pool's prefix index re-shares resident full
    prompt pages: the SECOND identical prompt's delivery allocates
    fewer fresh pages than the first — content-addressed dedupe
    working across the (loopback) process boundary."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    prompt = (np.arange(21, dtype=np.int32) * 3) % 256
    reqs = [serving.Request(0, prompt, max_new_tokens=4),
            serving.Request(1, prompt.copy(), max_new_tokens=4)]
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter)
    frees = []
    dnodes[0].on_absorb = lambda n: frees.append(
        n.engine.cache.free_pages)
    before = dnodes[0].engine.cache.free_pages
    done = pnode.serve(_clone(reqs), max_ticks=5000)
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid
    assert len(frees) == 2
    delta1 = before - frees[0]
    delta2 = frees[0] - frees[1]
    # 21-token prompt = 2 FULL pages re-shared by the second delivery
    assert delta2 <= delta1 - 2, (delta1, delta2)
    _fence_all(pnode, dnodes)


def test_loopback_delivery_crash_nacks_and_replays(gpt2_dis):
    """A delivery crash on the decode rank unwinds the admission
    (serving_deliver fault point), NACKs with the wire doc, and the
    router replays from the committed stream — bounded, token-lossless,
    no leak."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(6, max_new=5, seed=11)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter)
    with faults.crash_during_delivery(times=2):
        done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert sum(d.stats["nacked"] for d in dnodes) == 2
    assert pnode.stats["handoff_requeues"] == 2
    assert not pnode.lost and sorted(done) == sorted(ref)
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid
    _fence_all(pnode, dnodes)


def test_loopback_retry_budget_drops_poisoned_request(gpt2_dis):
    """A request whose delivery ALWAYS crashes is dropped after
    max_handoff_retries — bounded, recorded, and the rest of the
    workload still finishes token-identically."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(4, max_new=4, seed=13)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter, max_handoff_retries=2)
    with faults.crash_during_delivery(match_rid=0, times=None):
        done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert list(pnode.lost) == [0]
    assert pnode.stats["lost"] == 1
    assert sorted(done) == [1, 2, 3]
    for rid in (1, 2, 3):
        assert done[rid]["tokens"] == ref[rid], rid
    _fence_all(pnode, dnodes)


def test_loopback_backpressure_bounds_inflight_pages(gpt2_dis):
    """``max_inflight_pages`` gates admission on the router rank from
    the decode ranks' exchanged metrics: the latched
    router/decode_blocked fires, the bound holds, and the workload
    still completes token-identically."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(8, max_new=4, seed=5)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter, max_inflight_pages=8)
    seen = []
    orig_tick = pnode.on_tick

    def spy(n):
        seen.append(n._inflight_pages(n.endpoint.fabric._metrics))
        orig_tick(n)

    pnode.on_tick = spy
    done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert pnode.stats["decode_blocked"] >= 1
    assert pnode.metrics.counter("router/decode_blocked").value >= 1
    assert max(seen) <= 8
    assert sorted(done) == sorted(ref) and not pnode.lost
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid


# ---------------------- ISSUE 18: N-rank balancing + targeted wire


def test_loopback_three_rank_balancing_spreads_and_zero_waste(gpt2_dis):
    """The LPT placement actually USES both decode ranks of a world=3
    fabric (each delivers at least one handoff, no rank monopolizes),
    every stream stays token-identical to the colocated run, and in
    targeted addressing mode no rank receives a byte it was not
    addressed — `router/handoff_wasted_bytes` stays 0."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(12, max_new=6, seed=21)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter, world=3)
    done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert sorted(done) == sorted(ref) and not pnode.lost
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid
    delivered = [d.stats["delivered"] for d in dnodes]
    assert all(n >= 1 for n in delivered), delivered
    assert sum(delivered) == pnode.stats["handoffs"]
    for node in [pnode] + dnodes:
        assert node.stats["wasted_bytes"] == 0, node.stats
        assert node.metrics.counter(
            "router/handoff_wasted_bytes").value == 0
    _fence_all(pnode, dnodes)


def test_loopback_broadcast_addressing_counts_wasted_bytes(gpt2_dis):
    """The legacy broadcast wire shape still works (token parity) but
    every dst-addressed frame lands on non-addressed ranks too — the
    wasted-bytes counter makes the O(world × payload) cost visible,
    which is exactly what the targeted mode removes."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(8, max_new=4, seed=22)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter, world=3,
                                 addressing="broadcast")
    done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert sorted(done) == sorted(ref) and not pnode.lost
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid
    # every packet was copied to BOTH decode ranks and the one not
    # addressed counted it wasted — so decode-side waste covers AT
    # LEAST one full extra copy of every packet byte sent (plus the
    # done-frames the decode ranks broadcast at each other)
    wasted = sum(d.stats["wasted_bytes"] for d in dnodes)
    assert wasted >= pnode.stats["bytes_sent"] > 0
    for d in dnodes:
        assert d.metrics.counter(
            "router/handoff_wasted_bytes").value == d.stats["wasted_bytes"]
    _fence_all(pnode, dnodes)


def test_loopback_per_rank_cap_queues_at_router(gpt2_dis):
    """`max_inflight_pages_per_rank` holds packets AT THE ROUTER when
    no decode rank has headroom: the per-rank decode_blocked latch
    fires, the workload still completes token-identically, and the
    held packets drain as MV_ABSORBED_PAGES acknowledges."""
    _cfg, _params, adapter_for = gpt2_dis
    adapter = adapter_for(slots=2)
    reqs = _reqs(8, max_new=4, seed=23)
    ref = _ref_streams(adapter, reqs)
    pnode, dnodes = _mk_loopback(adapter, world=3,
                                 max_inflight_pages_per_rank=3)
    held_depths = []
    orig_tick = pnode.on_tick

    def spy(n):
        held_depths.append(len(n._packets))
        orig_tick(n)

    pnode.on_tick = spy
    done = pnode.serve(_clone(reqs), max_ticks=5000)
    assert pnode.stats["decode_blocked"] >= 1
    assert pnode.metrics.counter("router/decode_blocked").value >= 1
    assert max(held_depths) >= 1   # backpressure queued at the router
    assert sorted(done) == sorted(ref) and not pnode.lost
    for rid, toks in ref.items():
        assert done[rid]["tokens"] == toks, rid
    _fence_all(pnode, dnodes)
    _fence_all(pnode, dnodes)
