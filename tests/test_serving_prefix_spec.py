"""Prefix-sharing paged KV cache + speculative decoding (ISSUE 9).

Covers the two serving optimizations end to end:

- multi-query paged attention (the speculative verify kernel variant):
  per-row position masking vs stepping the single-query kernel;
- prefix index + refcounted allocator: hash-chain matching, COW
  partial-page sharing, eviction under pool pressure, the refcount-0
  sweep (leak fence);
- engine admission through the prefix cache reproduces the unshared
  engine token-for-token (incl. the COW mid-page divergence case and
  shared-page slot reuse with int8 scale pools);
- speculative greedy decoding is token-for-token identical to the
  plain engine for BOTH families and both drafters (n-gram + model);
  sampled requests fall back to the normal tick.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu.serving as serving
from deepspeed_tpu.serving.paged_cache import PagedCacheSpec, PagedKVCache
from deepspeed_tpu.serving.drafter import NGramDrafter, ModelDrafter


@pytest.fixture
def rs():
    return np.random.RandomState(0)


# ------------------------------------------------- multi-query kernel


def _mq_vs_stepped(rs, quantized, R=1):
    """MQ kernel vs the single-query kernel advanced one position per
    step over the SAME pool (no appends needed: all rows pre-exist)."""
    from deepspeed_tpu.ops.pallas.decode import decode_attention_paged
    Lyr, NB, H, P, D = 2, 9, 2, 16, 32
    B, MAXP, K = 3, 4, 4
    if quantized:
        kp = jnp.asarray(rs.randint(-127, 128, (Lyr, NB, H, P, D)),
                         jnp.int8)
        vp = jnp.asarray(rs.randint(-127, 128, (Lyr, NB, H, P, D)),
                         jnp.int8)
        ks = jnp.asarray(np.abs(rs.randn(Lyr, NB, H, 1, P)) * .01 + 1e-3,
                         jnp.float32)
        vs = jnp.asarray(np.abs(rs.randn(Lyr, NB, H, 1, P)) * .01 + 1e-3,
                         jnp.float32)
        kw = dict(k_scale=ks, v_scale=vs)
    else:
        kp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * .3
        vp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * .3
        kw = {}
    pt = np.zeros((B, MAXP), np.int32)
    pt[0, :3] = [2, 4, 6]
    pt[1, :4] = [1, 5, 7, 8]
    pt[2, :1] = [3]
    pos = np.array([20, 33, -1], np.int32)      # slot 2 idle
    q = jnp.asarray(rs.randn(B, H, K * R, D), jnp.float32) * .3
    got = decode_attention_paged(q, kp, vp, pos, jnp.asarray(pt), 1,
                                 rows_per_step=R, **kw)
    for step in range(K):
        rows = q[:, :, step * R:(step + 1) * R, :]
        ref = decode_attention_paged(rows, kp, vp, pos + step,
                                     jnp.asarray(pt), 1, **kw)
        for b in range(B):
            if pos[b] < 0:
                np.testing.assert_array_equal(np.asarray(got[b]), 0.0)
                continue
            np.testing.assert_allclose(
                np.asarray(got[b, :, step * R:(step + 1) * R]),
                np.asarray(ref[b]), rtol=2e-5, atol=2e-5)


def test_mq_paged_attention_matches_stepped_fp(rs):
    _mq_vs_stepped(rs, quantized=False)


@pytest.mark.slow
def test_mq_paged_attention_matches_stepped_int8(rs):
    """Slow tier: the fp/GQA kernel pins cover the masking machinery
    fast, and the int8 scale path is driven end-to-end by the int8
    speculative parity tests."""
    _mq_vs_stepped(rs, quantized=True)


def test_mq_paged_attention_matches_stepped_gqa_rows(rs):
    # grouped-query rows per step (the LLaMA verify layout: step-major)
    _mq_vs_stepped(rs, quantized=False, R=2)


@pytest.mark.slow
@pytest.mark.skipif(
    all(d.platform == "cpu" for d in jax.devices()),
    reason="needs a real TPU chip: exercises the MOSAIC lowering of the "
           "multi-query paged kernel (per-row step masks + page-table "
           "index maps with rows_per_step grouping; interpret-mode "
           "covers numerics only). From an axon session run "
           "`python -m pytest --noconftest -m slow -k real_chip "
           "tests/test_serving_prefix_spec.py`")
def test_decode_attention_multiquery_real_chip_parity(rs):
    """First-real-chip parity for the speculative verify variant of
    ``decode_attention_paged`` with ``interpret=False`` — same layout
    as the fast MQ test, the per-row masking and the widened page
    participation window (`pos + max_step`) lowered through Mosaic."""
    from deepspeed_tpu.ops.pallas.decode import decode_attention_paged
    Lyr, NB, H, P, D = 2, 9, 2, 16, 32
    B, MAXP, K, R = 3, 4, 4, 2
    kp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * .3
    vp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * .3
    pt = np.zeros((B, MAXP), np.int32)
    pt[0, :3] = [2, 4, 6]
    pt[1, :4] = [1, 5, 7, 8]
    pt[2, :1] = [3]
    pos = np.array([20, 33, -1], np.int32)
    q = jnp.asarray(rs.randn(B, H, K * R, D), jnp.float32) * .3
    got = decode_attention_paged(q, kp, vp, pos, jnp.asarray(pt), 1,
                                 rows_per_step=R, interpret=False)
    for step in range(K):
        rows = q[:, :, step * R:(step + 1) * R, :]
        ref = decode_attention_paged(rows, kp, vp, pos + step,
                                     jnp.asarray(pt), 1,
                                     interpret=False)
        for b in range(B):
            if pos[b] < 0:
                np.testing.assert_array_equal(np.asarray(got[b]), 0.0)
                continue
            np.testing.assert_allclose(
                np.asarray(got[b, :, step * R:(step + 1) * R]),
                np.asarray(ref[b]), rtol=2e-5, atol=2e-5)


# -------------------------------------------------- allocator / index


def _toy_cache(num_blocks=12, page=4, slots=3, maxp=8):
    spec = PagedCacheSpec(n_layers=1, kv_heads=1, head_dim=8,
                          page_size=page, slots=slots,
                          max_pages_per_slot=maxp, num_blocks=num_blocks)
    c = PagedKVCache(spec)
    c.enable_prefix_sharing()
    return c


def test_prefix_index_match_refcount_and_sweep():
    c = _toy_cache()
    total = c.free_pages
    prompt = np.arange(11, dtype=np.int32)          # 2 full pages + 3
    plan = c.admit_prefix(0, prompt, total_tokens=13)
    assert plan.start_pos == 0 and plan.cow is None
    c.register_prefix(0, prompt)
    # identical prompt: both full pages shared + COW on the partial
    plan2 = c.admit_prefix(1, prompt, total_tokens=13)
    assert [b for b in plan2.pages[:2]] == plan.pages[:2]
    assert plan2.cow is not None
    src, dst, r = plan2.cow
    assert src == plan.pages[2] and r == 2      # 3 partial tokens -> 2
    assert plan2.start_pos == 2 * 4 + 2         # always >=1 suffix token
    assert c._refcount[plan.pages[0]] == 2
    c.register_prefix(1, prompt)
    # release decrefs; shared pages stay resident (registered)
    c.release(0)
    assert c._refcount[plan.pages[0]] == 1
    c.release(1)
    assert c._refcount[plan.pages[0]] == 0
    assert c.free_pages < total                 # resident, not free
    assert c.cached_pages > 0
    assert c.available_pages == total
    n = c.sweep_prefix_cache()
    assert n == c.cached_pages + n              # cached drained
    assert c.free_pages == total                # leak fence


def test_prefix_page_content_verified_not_just_hashed():
    c = _toy_cache()
    p1 = np.arange(8, dtype=np.int32)
    plan = c.admit_prefix(0, p1, 10)
    c.register_prefix(0, p1)
    # different first page must NOT match (walk breaks at page 0)
    p2 = p1.copy()
    p2[0] += 1
    m = c.match_prefix(p2)
    assert m.shared_blocks == [] and m.start_pos == 0
    # same first page, different continuation: share page 0 only
    p3 = np.concatenate([p1[:4], p1[4:] + 5]).astype(np.int32)
    m3 = c.match_prefix(p3)
    assert m3.shared_blocks == [plan.pages[0]]


def test_prefix_eviction_under_pool_pressure():
    c = _toy_cache(num_blocks=7, maxp=6)        # 6 allocatable pages
    pa = np.arange(9, dtype=np.int32)
    c.admit_prefix(0, pa, 12)                   # 3 pages
    c.register_prefix(0, pa)
    c.release(0)                                # 3 resident cached
    assert c.cached_pages == 3 and c.free_pages == 3
    # an unrelated request needing 5 pages forces LRU eviction
    pb = (np.arange(17) + 40).astype(np.int32)
    plan = c.admit_prefix(1, pb, 20)
    assert plan is not None and len(plan.pages) == 5
    assert c.prefix_stats["evictions"] >= 2
    # and a request that cannot fit even after eviction is refused
    assert c.admit_prefix(2, pb, 20) is None
    assert c.free_pages + c.cached_pages + 5 == 6   # nothing leaked


# ------------------------------------------------------ engine fixture


def _gpt2_cfg():
    from deepspeed_tpu.models.gpt2 import GPT2Config
    return GPT2Config(vocab_size=256, n_positions=128, n_embd=128,
                      n_layer=2, n_head=4, dtype=jnp.float32,
                      param_dtype=jnp.float32, scan_layers=True)


@pytest.fixture(scope="module")
def gpt2_px():
    """(cfg, params, qparams, make): engines over shared per-geometry
    adapters (compiled programs live on the adapter — tier-1 budget)."""
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_inference import (
        convert_gpt2_params, quantize_gpt2_inference_params)
    cfg = _gpt2_cfg()
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    qparams = quantize_gpt2_inference_params(
        convert_gpt2_params(params, cfg))
    adapters = {}

    def make(int8=False, **kw):
        sv = {"slots": 2, "page_size": 16, "max_pages_per_slot": 6}
        sv.update(kw.pop("serving", {}))
        key = (int8, tuple(sorted(sv.items())))
        if key not in adapters:
            eng = serving.build_engine(
                "gpt2", cfg, qparams if int8 else params,
                config={"serving": sv})
            adapters[key] = eng.adapter
        return serving.ContinuousBatcher(adapters[key], **kw)

    return cfg, params, qparams, make


# ------------------------------------------------- prefix-sharing e2e


def test_prefix_admission_matches_unshared(rs, gpt2_px):
    _, _, _, make = gpt2_px
    eng = make(prefix_cache=True)
    plain = make()
    pa = rs.randint(0, 256, size=(40,)).astype(np.int32)
    res_a = eng.serve([serving.Request("a", pa, max_new_tokens=10)])
    ref_a = plain.serve([serving.Request("a", pa, max_new_tokens=10)])
    np.testing.assert_array_equal(res_a["a"].tokens(),
                                  ref_a["a"].tokens())
    free_before = eng.cache.free_pages
    # identical prompt: 2 full pages aliased + COW, only suffix pages
    # fresh — and outputs unchanged
    res_b = eng.serve([serving.Request("b", pa, max_new_tokens=10)])
    plain_b = make()
    ref_b = plain_b.serve([serving.Request("b", pa, max_new_tokens=10)])
    np.testing.assert_array_equal(res_b["b"].tokens(),
                                  ref_b["b"].tokens())
    st = eng.cache.prefix_stats
    assert st["hit_pages"] == 2 and st["cow_hits"] == 1
    assert st["cow_rows"] == 7          # 8 partial tokens, 1 left over
    snap = eng.metrics_snapshot()["prefix_cache"]
    assert snap["pages_saved"] == 2
    assert snap["hit_rate"] == pytest.approx(39 / 80)
    # the second admission took only fresh pages for suffix+generation
    assert free_before - eng.cache.free_pages <= 0  # B reused resident
    #   pages then released; resident set unchanged or larger


def test_prefix_cow_divergence_mid_page(rs, gpt2_px):
    """Two requests share 36 of 40 tokens (divergence INSIDE the 3rd
    page): the sharer must COW the partial page and reproduce its solo
    output exactly."""
    _, _, _, make = gpt2_px
    eng = make(prefix_cache=True)
    pa = rs.randint(0, 256, size=(40,)).astype(np.int32)
    pc = pa.copy()
    pc[36:] = (pc[36:] + 7) % 256
    eng.serve([serving.Request("a", pa, max_new_tokens=10)])
    res_c = eng.serve([serving.Request("c", pc, max_new_tokens=10)])
    plain = make()
    ref_c = plain.serve([serving.Request("c", pc, max_new_tokens=10)])
    np.testing.assert_array_equal(res_c["c"].tokens(),
                                  ref_c["c"].tokens())
    st = eng.cache.prefix_stats
    assert st["cow_hits"] == 1 and st["cow_rows"] == 4   # matched 36..39


@pytest.mark.parametrize("kv_bits", [
    # the fp-pool variant rides the slow tier: the int8 variant covers
    # the same shared-page lifecycle PLUS the scale pools, and the
    # fp surface is pinned fast by test_prefix_admission_matches_unshared
    pytest.param(0, marks=pytest.mark.slow),
    8,
])
def test_prefix_shared_slot_reuse_no_stale_kv(rs, kv_bits, gpt2_px):
    """Two concurrent requests share a prefix; the first finishes and
    its slot is IMMEDIATELY reused by an unrelated longer request; the
    survivor's continuation (tokens + final logits) must match a solo
    run — shared pages must not be reaped or overwritten while the
    survivor still holds a reference (incl. int8 scale pools)."""
    _, _, _, make = gpt2_px
    sv = {"kv_cache_bits": kv_bits} if kv_bits else {}
    eng = make(int8=bool(kv_bits), serving=sv, prefix_cache=True)
    shared = rs.randint(0, 256, size=(36,)).astype(np.int32)
    pz = rs.randint(0, 256, size=(60,)).astype(np.int32)
    # short sharer finishes first; long sharer keeps decoding; then an
    # unrelated request takes the freed slot while the survivor runs
    res = eng.serve([
        serving.Request("short", shared, max_new_tokens=2),
        serving.Request("long", shared, max_new_tokens=10),
        serving.Request("other", pz, max_new_tokens=8),
    ])
    solo = make(int8=bool(kv_bits), serving=sv, prefix_cache=True)
    ref = solo.serve([serving.Request("long", shared,
                                      max_new_tokens=10)])
    np.testing.assert_array_equal(res["long"].tokens(),
                                  ref["long"].tokens())


def test_prefix_cow_disabled_page_aligned_only(rs, gpt2_px):
    """cow: false shares only FULL pages — the cache never matches
    partial pages (no phantom cow_hits stats, no device page copy) and
    outputs are unchanged."""
    _, _, _, make = gpt2_px
    eng = make(prefix_cache=True, prefix_cow=False)
    pa = rs.randint(0, 256, size=(40,)).astype(np.int32)
    eng.serve([serving.Request("a", pa, max_new_tokens=10)])
    res = eng.serve([serving.Request("b", pa, max_new_tokens=10)])
    ref = make().serve([serving.Request("b", pa, max_new_tokens=10)])
    np.testing.assert_array_equal(res["b"].tokens(), ref["b"].tokens())
    st = eng.cache.prefix_stats
    assert st["cow_hits"] == 0 and st["cow_rows"] == 0
    assert st["hit_pages"] == 2     # page-aligned share still happened


def test_prefix_pool_occupancy_returns_to_baseline(rs, gpt2_px):
    """Leak fence (ISSUE 9 satellite): a full hot-prefix workload
    drains, every refcount returns to 0, and the refcount-0 sweep
    restores the whole pool to the free list."""
    _, _, _, make = gpt2_px
    eng = make(prefix_cache=True)
    base = eng.cache.free_pages
    sysp = rs.randint(0, 256, size=(36,)).astype(np.int32)
    reqs = [serving.Request(i, np.concatenate(
        [sysp, rs.randint(0, 256, size=(4,)).astype(np.int32)]),
        max_new_tokens=6) for i in range(6)]
    res = eng.serve(reqs)
    assert len(res) == 6
    assert all(not s.active for s in eng.slots)
    assert int(eng.cache._refcount.sum()) == 0
    assert eng.cache.free_pages + eng.cache.cached_pages == base
    eng.cache.sweep_prefix_cache()
    assert eng.cache.free_pages == base
    assert eng.metrics_snapshot()["prefix_cache"]["hit_rate"] > 0.5


# --------------------------------------------------- speculative e2e


def test_spec_greedy_parity_gpt2(rs, gpt2_px):
    _, _, _, make = gpt2_px
    eng = make(drafter=NGramDrafter(2), spec_tokens=3)
    plain = make()
    lens, news = (7, 19, 30), (24, 9, 17)
    prompts = [rs.randint(0, 256, size=(s,)).astype(np.int32)
               for s in lens]
    res = eng.serve([serving.Request(i, p, max_new_tokens=n)
                     for i, (p, n) in enumerate(zip(prompts, news))])
    ref = plain.serve([serving.Request(i, p, max_new_tokens=n)
                       for i, (p, n) in enumerate(zip(prompts, news))])
    for i in range(3):
        np.testing.assert_array_equal(res[i].tokens(), ref[i].tokens())
    assert eng.stats["spec_rounds"] > 0
    snap = eng.metrics_snapshot()["speculative"]
    assert snap["proposed"] > 0 and 0.0 <= snap["accept_rate"] <= 1.0


def test_spec_greedy_parity_gpt2_eos(rs, gpt2_px):
    """EOS inside a committed window must stop at its FIRST occurrence
    exactly like the plain engine (commits past EOS discarded)."""
    _, _, _, make = gpt2_px
    plain = make()
    p = rs.randint(0, 256, size=(9,)).astype(np.int32)
    full = plain.serve([serving.Request("r", p, max_new_tokens=16)])["r"]
    eos = int(full.generated[5])
    ref = make().serve([serving.Request("r", p, max_new_tokens=16,
                                        eos_token_id=eos)])["r"]
    got = make(drafter=NGramDrafter(2), spec_tokens=3).serve(
        [serving.Request("r", p, max_new_tokens=16,
                         eos_token_id=eos)])["r"]
    assert got.finish_reason == ref.finish_reason
    assert got.generated == ref.generated


def test_spec_greedy_parity_gpt2_int8(rs, gpt2_px):
    _, _, _, make = gpt2_px
    sv = {"kv_cache_bits": 8}
    eng = make(int8=True, serving=sv, drafter=NGramDrafter(2),
               spec_tokens=3)
    plain = make(int8=True, serving=sv)
    p = rs.randint(0, 256, size=(13,)).astype(np.int32)
    res = eng.serve([serving.Request(0, p, max_new_tokens=20)])
    ref = plain.serve([serving.Request(0, p, max_new_tokens=20)])
    np.testing.assert_array_equal(res[0].tokens(), ref[0].tokens())


@pytest.fixture(scope="module")
def gpt2_drafter():
    """(dcfg, dparams, adapter): the small drafter model shared by the
    model-drafter tests (compiled programs live on the adapter —
    tier-1 budget)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.serving.adapters import GPT2ServingAdapter
    from deepspeed_tpu.serving.paged_cache import PagedCacheSpec
    dcfg = GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                      n_layer=1, n_head=2, dtype=jnp.float32,
                      param_dtype=jnp.float32, scan_layers=True)
    dparams = jax.jit(GPT2LMHeadModel(dcfg).init)(
        jax.random.PRNGKey(1), np.zeros((1, 8), np.int32))["params"]
    dspec = PagedCacheSpec(n_layers=1, kv_heads=2, head_dim=32,
                           page_size=16, max_pages_per_slot=6, slots=2,
                           dtype=jnp.float32)
    return dcfg, dparams, GPT2ServingAdapter(dcfg, dparams, dspec)


def test_spec_model_drafter_parity(rs, gpt2_px, gpt2_drafter):
    """A REAL (smaller) drafter model through its own paged cache:
    outputs identical, drafter rollback tracked by pointer moves. The
    target rides the module's shared adapter; build_engine's model-
    drafter wiring is asserted separately (construction is compile-
    free) to keep the compile budget on the drafter alone."""
    cfg, params, _, make = gpt2_px
    dcfg, dparams, dadapter = gpt2_drafter
    built = serving.build_engine(
        "gpt2", cfg, params,
        config={"serving": {"slots": 2, "page_size": 16,
                            "max_pages_per_slot": 6,
                            "speculative": {"tokens": 3,
                                            "drafter": "model"}}},
        drafter_model_config=dcfg, drafter_params=dparams)
    assert isinstance(built.drafter, ModelDrafter)
    assert built.drafter.cache.num_blocks == 2 * 6 + 1  # fully provisioned
    eng = make(drafter=ModelDrafter(dadapter), spec_tokens=3)
    plain = make()
    lens, news = (7, 19), (18, 9)
    prompts = [rs.randint(0, 256, size=(s,)).astype(np.int32)
               for s in lens]
    res = eng.serve([serving.Request(i, p, max_new_tokens=n)
                     for i, (p, n) in enumerate(zip(prompts, news))])
    ref = plain.serve([serving.Request(i, p, max_new_tokens=n)
                       for i, (p, n) in enumerate(zip(prompts, news))])
    for i in range(2):
        np.testing.assert_array_equal(res[i].tokens(), ref[i].tokens())
    # drafter cache drained with the requests
    assert all(p == -1 for p in eng.drafter.pos)
    assert eng.drafter.cache.free_pages == \
        eng.drafter.cache.num_blocks - 1


def test_spec_drafter_realigns_after_plain_tick_fallback(rs, gpt2_px,
                                                         gpt2_drafter):
    """Plain-tick fallbacks (here: a sampled sibling) commit tokens the
    drafter never drafted; observe_plain must teacher-force them
    through the ModelDrafter's own cache so its pos/KV stay aligned and
    spec rounds resume cleanly once the sibling drains — without it the
    drafter attends unwritten rows and accept rate silently collapses
    for the rest of the request."""
    _, _, _, make = gpt2_px
    _, _, dadapter = gpt2_drafter
    eng = make(drafter=ModelDrafter(dadapter), spec_tokens=3)
    p_g = rs.randint(0, 256, size=(9,)).astype(np.int32)
    p_s = rs.randint(0, 256, size=(12,)).astype(np.int32)
    eng.submit(serving.Request("g", p_g, max_new_tokens=12))
    eng.submit(serving.Request("s", p_s, max_new_tokens=4,
                               temperature=0.7))
    done = {}
    for _ in range(64):
        for r in eng.step():
            done[r.rid] = r
        g_slot = next((i for i, s in enumerate(eng.slots)
                       if s.active and s.request.rid == "g"), None)
        if g_slot is not None:
            assert eng.drafter.pos[g_slot] == eng.slots[g_slot].pos
        if len(done) == 2:
            break
    assert len(done) == 2
    # the sampled sibling forced plain ticks, then spec rounds resumed
    assert eng.stats["spec_rounds"] > 0
    assert eng.stats["ticks"] > eng.stats["spec_rounds"]
    ref = make().serve([serving.Request("g", p_g, max_new_tokens=12)])
    np.testing.assert_array_equal(done["g"].tokens(), ref["g"].tokens())


def test_spec_verify_window_honors_tokens(gpt2_px):
    """The verify window is exactly tokens+1 in steady state — no pow2
    rounding-down of the configured K — and pow2-clamps only when the
    min remaining budget is smaller (compile-free white-box check)."""
    _, _, _, make = gpt2_px
    eng = make(spec_tokens=4)
    eng.slots[0].request = serving.Request(
        0, np.arange(4, dtype=np.int32), max_new_tokens=20)
    eng.slots[0].pos = 4
    assert eng._pick_verify_rows() == 5          # exact tokens + 1
    eng.slots[0].request.generated = [1] * 17    # rem = 3 clamps
    assert eng._pick_verify_rows() == 2
    eng.slots[0].request.generated = [1] * 19    # rem = 1: no window
    assert eng._pick_verify_rows() == 1


def test_spec_llama_parity_both_storages(rs):
    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.models.llama_inference import \
        random_int8_serving_params
    cfg = LlamaConfig(vocab_size=256, hidden_size=128, n_layers=2,
                      n_heads=4, n_kv_heads=2, intermediate_size=256,
                      max_seq_len=128, dtype=jnp.float32,
                      param_dtype=jnp.float32)
    sparams = random_int8_serving_params(cfg)
    # int8 KV fast; the fp-cache variant's unique surface (GQA rows
    # through the fp MQ kernel) is pinned by the fast kernel test
    for kv_bits in (8,):
        eng = serving.build_engine(
            "llama", cfg, sparams,
            config={"serving": {"slots": 2, "page_size": 16,
                                "max_pages_per_slot": 6,
                                "kv_cache_bits": kv_bits,
                                "speculative": {"tokens": 3}}})
        plain = serving.ContinuousBatcher(eng.adapter)
        p = rs.randint(0, 256, size=(21,)).astype(np.int32)
        res = eng.serve([serving.Request(0, p, max_new_tokens=14)])
        ref = plain.serve([serving.Request(0, p, max_new_tokens=14)])
        np.testing.assert_array_equal(res[0].tokens(), ref[0].tokens())


def test_prefix_llama_parity(rs):
    """LLaMA prefix-cache hit parity: the suffix prefill's GQA prefix
    K/V gather + RoPE at absolute positions (the LLaMA twin of the
    GPT-2 prefix e2e tests) — a second request sharing 2 full pages +
    a COW partial page decodes token-for-token like an unshared run."""
    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.models.llama_inference import \
        random_int8_serving_params
    cfg = LlamaConfig(vocab_size=256, hidden_size=128, n_layers=2,
                      n_heads=4, n_kv_heads=2, intermediate_size=256,
                      max_seq_len=128, dtype=jnp.float32,
                      param_dtype=jnp.float32)
    sparams = random_int8_serving_params(cfg)
    eng = serving.build_engine(
        "llama", cfg, sparams,
        config={"serving": {"slots": 2, "page_size": 16,
                            "max_pages_per_slot": 6,
                            "prefix_cache": {"cow": True}}})
    plain = serving.ContinuousBatcher(eng.adapter)
    shared = rs.randint(0, 256, size=(40,)).astype(np.int32)
    pa = np.concatenate([shared, rs.randint(0, 256, size=(3,))
                         .astype(np.int32)])
    pb = np.concatenate([shared, rs.randint(0, 256, size=(3,))
                         .astype(np.int32)])
    res = eng.serve([serving.Request("a", pa, max_new_tokens=10)])
    ref = plain.serve([serving.Request("a", pa, max_new_tokens=10)])
    np.testing.assert_array_equal(res["a"].tokens(), ref["a"].tokens())
    res_b = eng.serve([serving.Request("b", pb, max_new_tokens=10)])
    ref_b = plain.serve([serving.Request("b", pb, max_new_tokens=10)])
    np.testing.assert_array_equal(res_b["b"].tokens(),
                                  ref_b["b"].tokens())
    assert eng.cache.prefix_stats["hit_pages"] >= 2
    assert eng.cache.prefix_stats["cow_hits"] >= 1


@pytest.mark.slow
def test_spec_llama_parity_fp_cache(rs):
    """fp-cache LLaMA spec parity (slow tier: the int8 sibling keeps
    the whole LLaMA spec stack in tier-1; this pins the fp MQ kernel
    e2e)."""
    from deepspeed_tpu.models.llama import LlamaConfig
    from deepspeed_tpu.models.llama_inference import \
        random_int8_serving_params
    cfg = LlamaConfig(vocab_size=256, hidden_size=128, n_layers=2,
                      n_heads=4, n_kv_heads=2, intermediate_size=256,
                      max_seq_len=128, dtype=jnp.float32,
                      param_dtype=jnp.float32)
    sparams = random_int8_serving_params(cfg)
    eng = serving.build_engine(
        "llama", cfg, sparams,
        config={"serving": {"slots": 2, "page_size": 16,
                            "max_pages_per_slot": 6,
                            "speculative": {"tokens": 3}}})
    plain = serving.ContinuousBatcher(eng.adapter)
    p = rs.randint(0, 256, size=(21,)).astype(np.int32)
    res = eng.serve([serving.Request(0, p, max_new_tokens=14)])
    ref = plain.serve([serving.Request(0, p, max_new_tokens=14)])
    np.testing.assert_array_equal(res[0].tokens(), ref[0].tokens())


def test_spec_temperature_falls_back_to_plain_tick(rs, gpt2_px):
    """Sampled requests make every decode step take the normal tick
    (greedy-only verify): same rng stream => identical outputs."""
    _, _, _, make = gpt2_px
    p = rs.randint(0, 256, size=(11,)).astype(np.int32)
    req = lambda: serving.Request(0, p, max_new_tokens=8,  # noqa: E731
                                  temperature=0.8)
    eng = make(drafter=NGramDrafter(2), spec_tokens=3)
    plain = make()
    res = eng.serve([req()])
    ref = plain.serve([req()])
    np.testing.assert_array_equal(res[0].tokens(), ref[0].tokens())
    assert eng.stats["spec_rounds"] == 0


def test_ngram_drafter_propose():
    d = NGramDrafter(1, ngram_max=3, ngram_min=1)
    d.admit(0, np.array([5, 6, 7, 5, 6], np.int32), 7, 32)
    # history ...5 6 7 5 6 7 — trailing [6, 7] matched at 1: continue 5 6
    np.testing.assert_array_equal(d.draft([0], 2)[0], [5, 6])
    d.commit(0, [9], 0, 9)               # history now ends ... 7 9: no
    np.testing.assert_array_equal(      # n-gram hit -> repeat-last
        d.draft([0], 3)[0], [9, 9, 9])
    # plain-tick realignment: committed tokens append to the history
    d.observe_plain([0], np.array([[9], [1]], np.int32),
                    np.array([[1], [2]], np.int32))
    np.testing.assert_array_equal(d._hist[0][-2:], [1, 2])


def test_serving_subblock_config_validation():
    from deepspeed_tpu.config.config import (ServingConfig,
                                             DeepSpeedConfigError)
    sc = ServingConfig({"serving": {
        "prefix_cache": {}, "speculative": {"tokens": 4}}})
    assert sc.prefix_cache.enabled and sc.prefix_cache.cow
    assert sc.speculative.enabled and sc.speculative.tokens == 4
    assert sc.speculative.drafter == "ngram"
    off = ServingConfig({"serving": {}})
    assert not off.prefix_cache.enabled and not off.speculative.enabled
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"speculative": {"tokens": 0}}})
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"speculative": {"drafter": "oracle"}}})
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"speculative": {
            "ngram_max": 1, "ngram_min": 2}}})
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"prefix_cache": "yes"}})
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"speculative": 8}})
    with pytest.raises(ValueError, match="drafter_model_config"):
        from deepspeed_tpu.models.gpt2 import GPT2Config
        serving.build_engine(
            "gpt2", _gpt2_cfg(), {},
            config={"serving": {"speculative": {"drafter": "model"}}})
