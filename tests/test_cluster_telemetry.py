"""Cluster telemetry plane (ISSUE 12): cross-rank aggregation fold,
the rank_straggler watchdog rule, the engine fence integration, and
the live /metrics endpoint.

The 2-real-process proof leg (injected per-step sleep on rank 1 →
exactly one latched dump naming rank 1) lives in
tests/test_multiprocess_dist.py::test_rank_straggler_two_processes
(slow); everything here is fast and in-process — the fold and rule
logic are pure host code, so the single-process engine exercises the
same code path minus the allgather.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from deepspeed_tpu.telemetry.anomaly import StragglerRule, Watchdog
from deepspeed_tpu.telemetry.cluster import (CLUSTER_METRICS,
                                             ClusterAggregator,
                                             cluster_metric_names,
                                             collect_local)
from deepspeed_tpu.telemetry.recorder import FlightRecorder
from deepspeed_tpu.telemetry.registry import MetricsRegistry
from deepspeed_tpu.telemetry.serve import (MetricsServer,
                                           start_metrics_server)


# ------------------------------------------------------ straggler rule

def test_straggler_rule_leave_one_out_median():
    """With 2 ranks a whole-cluster median includes the straggler and
    a 10x-slow rank only reaches ~1.8x it — the leave-one-out median
    is what lets factor=2 fire at world 2."""
    rule = StragglerRule(factor=2.0, fences=1)
    # rank 1 is 10x rank 0: vs the OTHER rank's median (0.05) -> trip
    det = rule.observe([0.05, 0.5])
    assert det is not None and det["rank"] == 1
    assert det["peer_median"] == pytest.approx(0.05)
    assert det["world"] == 2


def test_straggler_rule_needs_consecutive_fences_latches_and_rearms():
    rule = StragglerRule(factor=2.0, fences=3)
    fast, slow = [0.01, 0.012, 0.011, 0.01], [0.01, 0.012, 0.2, 0.01]
    assert rule.observe(fast) is None
    assert rule.observe(slow) is None          # streak 1
    assert rule.observe(slow) is None          # streak 2
    det = rule.observe(slow)                   # streak 3 -> trip
    assert det is not None and det["rank"] == 2
    assert det["consecutive_fences"] == 3
    assert rule.observe(slow) is None          # latched: no second trip
    assert rule.observe(fast) is None          # normal fence re-arms
    for _ in range(2):
        assert rule.observe(slow) is None
    det = rule.observe(slow)                   # fresh episode trips
    assert det is not None and det["rank"] == 2


def test_straggler_rule_unmeasured_fences_break_consecutiveness():
    """A rank that skips measurement (NaN/None) resets its own streak,
    and an uncomparable fence (<2 measured ranks) resets everyone's —
    slow fences separated by unmeasured gaps must not count as
    CONSECUTIVE (the commit-fence exchange deliberately reports
    step_time as unmeasured for exactly this reason)."""
    # per-rank reset: others still comparable, ONE rank unmeasured
    rule = StragglerRule(factor=2.0, fences=2)
    slow = [0.01, 0.012, 0.3]
    assert rule.observe(slow) is None            # streak 1
    assert rule.observe([0.01, 0.012, None]) is None  # rank 2 skips
    assert rule.observe(slow) is None            # streak restarts at 1
    assert rule.observe(slow) is not None        # NOW consecutive
    # global reset: an uncomparable fence (<2 measured) clears everyone
    rule2 = StragglerRule(factor=2.0, fences=2)
    assert rule2.observe(slow) is None           # streak 1
    assert rule2.observe(
        [0.01, float("nan"), float("nan")]) is None   # uncomparable
    assert rule2.observe(slow) is None           # streak restarted
    assert rule2.observe(slow) is not None


def test_straggler_rule_min_value_floor_and_small_world():
    rule = StragglerRule(factor=2.0, min_value=0.05, fences=1)
    # 3x skew but under the absolute floor: dispatch noise, no trip
    for _ in range(5):
        assert rule.observe([0.001, 0.003]) is None
    # a single rank (or all-NaN peers) has nothing to compare against
    assert StragglerRule(fences=1).observe([0.5]) is None
    assert StragglerRule(fences=1).observe([0.5, None]) is None


def test_watchdog_rank_straggler_dump_names_the_rank(tmp_path):
    rec = FlightRecorder(capacity=64)
    rec.record("step", step=1)
    reg = MetricsRegistry()
    wd = Watchdog(str(tmp_path), recorder=rec, registry=reg,
                  source="train", straggler_factor=2.0,
                  straggler_fences=2, straggler_min_s=0.05)
    slow = [0.01, 0.3, 0.012, 0.011]
    assert wd.observe_rank_step_times(slow, step=4) is None   # streak 1
    path = wd.observe_rank_step_times(slow, step=8)           # trip
    assert path is not None and "rank_straggler" in path
    assert wd.observe_rank_step_times(slow, step=12) is None  # latched
    files = [f for f in os.listdir(tmp_path) if "rank_straggler" in f]
    assert len(files) == 1
    header = json.loads(open(path).readline())
    assert header["rule"] == "rank_straggler"
    assert header["detail"]["rank"] == 1
    assert header["detail"]["consecutive_fences"] == 2
    assert reg.counter("watchdog/trips/rank_straggler").value == 1


# ---------------------------------------------------------------- fold

def test_cluster_fold_stats_skew_table_and_ring_event():
    reg = MetricsRegistry()
    rec = FlightRecorder(capacity=64)
    wd = Watchdog("/tmp/_unused_dumps", recorder=rec, registry=reg,
                  straggler_factor=2.0, straggler_fences=1,
                  straggler_min_s=0.05, max_dumps=-1)
    agg = ClusterAggregator(registry=reg, recorder=rec, watchdog=wd)
    agg.world, agg.rank = 4, 0
    n = len(CLUSTER_METRICS)
    mat = np.full((4, n), np.nan, np.float32)
    mat[:, 0] = [0.1, 0.1, 0.9, 0.1]        # step_time_s: rank 2 slow
    mat[:, 3] = [2.0, 2.1, 1.9, 2.05]       # loss
    # swap_stall_s stays all-NaN: no rank has a swap tier
    agg._fold(mat, step=10)
    g = reg.snapshot()["gauges"]
    assert g["cluster/step_time_s/min"] == pytest.approx(0.1)
    assert g["cluster/step_time_s/max"] == pytest.approx(0.9, rel=1e-5)
    assert g["cluster/step_time_s/median"] == pytest.approx(0.1)
    assert g["cluster/step_time_s/argmax_rank"] == 2
    assert g["cluster/loss/argmax_rank"] == 1
    assert "cluster/swap_stall_s/max" not in g          # all-NaN column
    table = agg.last_table
    assert table["metrics"]["swap_stall_s"] == [None] * 4
    assert table["metrics"]["step_time_s"][2] == pytest.approx(
        0.9, rel=1e-5)
    evs = [e for e in rec.events() if e["kind"] == "cluster_fence"]
    assert len(evs) == 1 and evs[0]["world"] == 4
    # the watchdog rule rode the fold (fences=1 -> immediate trip)
    assert wd.trips.get("rank_straggler") == 1


def test_collect_local_reads_registry_and_overrides_win():
    reg = MetricsRegistry()
    reg.histogram("train/step_time_s").observe(0.2)
    reg.gauge("memory/host_max_rss_mb").set(123.0)
    reg.gauge("comm/bytes_per_step/inter").set(4 * 2**20)
    vals = collect_local(reg, loss=1.5)
    assert vals["step_time_s"] == pytest.approx(0.2)
    assert vals["loss"] == 1.5
    assert vals["host_rss_mb"] == 123.0
    assert vals["comm_inter_mb"] == pytest.approx(4.0)
    assert np.isnan(vals["swap_stall_s"])       # never observed
    vals = collect_local(reg, overrides={"step_time_s": 0.7,
                                         "swap_stall_s": None})
    assert vals["step_time_s"] == 0.7
    assert np.isnan(vals["swap_stall_s"])


def test_single_process_exchange_degenerates_to_local_fold():
    reg = MetricsRegistry()
    agg = ClusterAggregator(registry=reg, recorder=FlightRecorder(64))
    mat = agg.exchange({"step_time_s": 0.25, "loss": 3.0}, step=2)
    assert mat.shape == (1, len(CLUSTER_METRICS))
    g = reg.snapshot()["gauges"]
    assert g["cluster/world_size"] == 1
    assert g["cluster/step_time_s/min"] == g["cluster/step_time_s/max"] \
        == pytest.approx(0.25)
    assert g["cluster/step_time_s/argmax_rank"] == 0
    assert reg.snapshot()["counters"]["cluster/fences"] == 1
    assert agg.last_fence_ts is not None


# -------------------------------------------------- engine integration

def test_engine_boundary_folds_cluster_gauges_and_gate_off():
    import deepspeed_tpu as dstpu
    from tests.simple_model import SimpleModel, random_batch, base_config

    cfg = base_config()
    cfg["steps_per_print"] = 2
    engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel())
    assert engine._cluster is not None          # default ON
    # the registry is process-wide: earlier tests' engines may have
    # folded fences already — assert the delta, not the absolute
    base = engine.telemetry.snapshot("cluster/")["counters"].get(
        "cluster/fences", 0)
    batch = random_batch()
    for _ in range(6):
        engine.train_batch(batch)
    snap = engine.telemetry.snapshot("cluster/")
    assert snap["counters"]["cluster/fences"] == base + 3
    g = snap["gauges"]
    assert g["cluster/world_size"] == 1
    # single-process: the fenced window mean is the packed step time
    assert g["cluster/step_time_s/max"] == pytest.approx(
        engine._tel_last_step_s)
    assert g["cluster/loss/max"] > 0
    # the skew table mirrors the fold
    assert engine._cluster.last_table["world"] == 1
    assert engine._tel_last_fence_ts is not None
    # host-arrival component measured alongside the fenced window
    h = engine.telemetry.snapshot()["histograms"]["train/host_step_s"]
    assert h["count"] >= 1

    # gate off: no aggregator, no cluster gauges from THIS engine
    cfg2 = base_config()
    cfg2["steps_per_print"] = 2
    cfg2["monitor"] = {"enabled": False, "cluster": {"enabled": False}}
    engine2, _, _, _ = dstpu.initialize(config=cfg2, model=SimpleModel())
    assert engine2._cluster is None


def test_serve_port_config_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfigError,
                                             MonitorConfig)
    ok = MonitorConfig({"monitor": {"serve_port": 9100,
                                    "cluster": {"enabled": False}}})
    assert ok.serve_port == 9100 and not ok.cluster.enabled
    assert MonitorConfig({}).serve_port == 0
    assert MonitorConfig({}).cluster.enabled
    with pytest.raises(DeepSpeedConfigError):
        MonitorConfig({"monitor": {"serve_port": 123456}})
    from deepspeed_tpu.config.config import WatchdogConfig
    with pytest.raises(DeepSpeedConfigError):
        WatchdogConfig({"watchdog": {"dump_dir": "/tmp/x",
                                     "straggler_factor": 1.0}})
    with pytest.raises(DeepSpeedConfigError):
        WatchdogConfig({"watchdog": {"dump_dir": "/tmp/x",
                                     "straggler_fences": 0}})


# ------------------------------------------------------ live endpoint

def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10)


def test_metrics_server_serves_prometheus_and_healthz(tmp_path):
    reg = MetricsRegistry()
    reg.counter("train/steps").inc(3)
    agg = ClusterAggregator(registry=reg, recorder=FlightRecorder(64))
    agg.exchange({"step_time_s": 0.1, "loss": 2.0}, step=4)
    wd = Watchdog(str(tmp_path), recorder=FlightRecorder(64),
                  registry=reg, straggler_fences=1, min_samples=1)
    wd.observe_rank_step_times([0.1, 5.0], step=4)   # one trip on file
    srv = MetricsServer(0, registry=reg, watchdog=wd,
                        fence_age_fn=lambda: agg.last_fence_ts).start()
    try:
        r = _get(srv.port, "/metrics")
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.read().decode()
        assert "# TYPE train_steps counter" in body
        assert "cluster_step_time_s_max" in body
        assert "watchdog_trips_rank_straggler 1" in body
        h = json.loads(_get(srv.port, "/healthz").read())
        assert h["ok"] is True
        assert h["watchdog_trips"] == 1
        assert h["watchdog"]["trips"]["rank_straggler"] == 1
        assert 0 <= h["last_fence_age_s"] < 60
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.port, "/nope")
    finally:
        srv.stop()


def test_start_metrics_server_degrades_on_bind_conflict():
    reg = MetricsRegistry()
    first = start_metrics_server(0, registry=reg)
    assert first is not None
    try:
        second = start_metrics_server(first.port, registry=reg)
        assert second is None          # warns, returns None, run lives
    finally:
        first.stop()


def test_trace_outcome_recognizes_terminal_drop():
    """A request the pool dropped after max_retries is TERMINAL — the
    viewer must not report it as 'open' (it is the trace an operator
    hunts for)."""
    from deepspeed_tpu.telemetry import view
    evs = [{"kind": "admit", "trace": "t", "rid": 1},
           {"kind": "serving_requeue", "trace": "t", "rid": 1,
            "outcome": "dropped", "attempts": 4}]
    assert view._trace_outcome(evs) == "lost (dropped after 4 attempts)"
    assert view._trace_outcome(evs[:1]) == "open"


def test_registry_peek_apis_never_create_metrics():
    reg = MetricsRegistry()
    assert reg.peek_gauge("x/y") is None
    assert reg.peek_histogram_last("x/y") is None
    assert reg.peek_histogram_values("x/y") == []
    assert reg.snapshot() == {"counters": {}, "gauges": {},
                              "histograms": {}}
    reg.histogram("x/y").observe(1.5)
    assert reg.peek_histogram_last("x/y") == 1.5
    assert reg.peek_histogram_values("x/y") == [1.5]


def test_cluster_metric_names_cover_the_fold():
    names = set(cluster_metric_names())
    assert "cluster/step_time_s/argmax_rank" in names
    assert "cluster/world_size" in names and "cluster/fences" in names
    assert len(names) == len(CLUSTER_METRICS) * 5 + 2
