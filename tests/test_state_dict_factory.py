"""State-dict factory tests — reference test_configurable_parallel.py role:
checkpoint load across changed TP degree (merge + split, incl. fused QKV
block layout), quantize-on-load, zero_to_fp32 CLI."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.runtime.state_dict_factory import (
    SDLoaderFactory, SDLoaderBase, WeightQuantization, save_tp_sharded,
    _merge_qkv, _split_qkv)


def _fused_layer_params(E=8, F=32, seed=0):
    rs = np.random.RandomState(seed)
    return {
        "attn_qkvw": {"kernel": rs.randn(E, 3 * E).astype(np.float32),
                      "bias": rs.randn(3 * E).astype(np.float32)},
        "attn_ow": {"kernel": rs.randn(E, E).astype(np.float32),
                    "bias": rs.randn(E).astype(np.float32)},
        "inter_w": {"kernel": rs.randn(E, F).astype(np.float32),
                    "bias": rs.randn(F).astype(np.float32)},
        "output_w": {"kernel": rs.randn(F, E).astype(np.float32),
                     "bias": rs.randn(E).astype(np.float32)},
        "attn_nw": {"scale": np.ones(E, np.float32),
                    "bias": np.zeros(E, np.float32)},
    }


def _model_tree(seed=0):
    rs = np.random.RandomState(seed)
    return {
        "wte": rs.randn(64, 8).astype(np.float32),     # vocab-parallel
        "encoder": {"layer_0": _fused_layer_params(seed=seed + 1),
                    "layer_1": _fused_layer_params(seed=seed + 2)},
        "ln_f": {"scale": np.ones(8, np.float32),
                 "bias": np.zeros(8, np.float32)},
    }


def _assert_trees_equal(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_qkv_merge_split_inverse():
    full = np.random.RandomState(0).randn(8, 24).astype(np.float32)
    shards = [_split_qkv(full, 4, r, 1) for r in range(4)]
    assert all(s.shape == (8, 6) for s in shards)
    np.testing.assert_allclose(_merge_qkv(shards, 1), full)


@pytest.mark.parametrize("src_mp,dst_mp", [(4, 2), (2, 4), (4, 1), (1, 4),
                                           (2, 2)])
def test_tp_reshard_roundtrip(tmp_path, src_mp, dst_mp):
    """Export at src_mp, load every dst rank, re-merge → original tree."""
    tree = _model_tree()
    paths = save_tp_sharded(tree, str(tmp_path), src_mp)
    assert len(paths) == src_mp
    loader = SDLoaderFactory.get_sd_loader(paths)
    ranks = [loader.load(dst_mp, r) for r in range(dst_mp)]
    # merging the dst shards back must reproduce the full tree
    merged = SDLoaderBase([None] * dst_mp)._merge_shards(ranks) \
        if dst_mp > 1 else ranks[0]
    _assert_trees_equal(merged, tree)


def test_merged_shards_contiguous_qkv_semantics(tmp_path):
    """4→2 merge: each dst rank's qkv kernel must hold contiguous
    [q;k;v] halves, not interleaved src blocks."""
    tree = {"l": {"attn_qkvw": {"kernel": np.arange(8 * 24, dtype=np.float32)
                                .reshape(8, 24)}}}
    paths = save_tp_sharded(tree, str(tmp_path), 4)
    loader = SDLoaderFactory.get_sd_loader(paths)
    half0 = loader.load(2, 0)["l"]["attn_qkvw"]["kernel"]
    full = tree["l"]["attn_qkvw"]["kernel"]
    q, k, v = np.split(full, 3, axis=1)
    expect = np.concatenate([q[:, :4], k[:, :4], v[:, :4]], axis=1)
    np.testing.assert_allclose(half0, expect)


def test_replicated_leaves_survive_reshard(tmp_path):
    tree = _model_tree()
    paths = save_tp_sharded(tree, str(tmp_path), 4)
    loader = SDLoaderFactory.get_sd_loader(paths)
    r0 = loader.load(2, 0)
    np.testing.assert_allclose(r0["ln_f"]["scale"], tree["ln_f"]["scale"])
    np.testing.assert_allclose(
        r0["encoder"]["layer_0"]["attn_nw"]["bias"],
        tree["encoder"]["layer_0"]["attn_nw"]["bias"])
    # vocab-parallel embedding is half the rows
    assert r0["wte"].shape == (32, 8)


def test_quantize_on_load(tmp_path):
    tree = _model_tree()
    paths = save_tp_sharded(tree, str(tmp_path), 1)
    loader = SDLoaderFactory.get_sd_loader(paths)
    qtree = loader.load(1, 0, quantize=True, quantize_bits=8,
                        quantize_groups=4)
    w = tree["encoder"]["layer_0"]["inter_w"]["kernel"]
    wq = qtree["encoder"]["layer_0"]["inter_w"]["kernel"]
    err = np.abs(w - wq).max()
    assert 0 < err < np.abs(w).max() / 50
    # 1-D params untouched
    np.testing.assert_allclose(
        qtree["encoder"]["layer_0"]["attn_qkvw"]["bias"],
        tree["encoder"]["layer_0"]["attn_qkvw"]["bias"])


def test_weight_quantization_mlp_extra_grouping():
    wq = WeightQuantization(bits=8, groups=4, mlp_extra_grouping=True)
    assert wq._groups_for(["encoder", "inter_w", "kernel"]) == 8
    assert wq._groups_for(["encoder", "attn_qkvw", "kernel"]) == 4


def test_zero_to_fp32_cli(tmp_path):
    """End-to-end: engine checkpoint → CLI → consolidated fp32 npz matching
    live params."""
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.utils import zero_to_fp32
    from tests.simple_model import SimpleModel, random_batch, base_config
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    engine, _, _, _ = dstpu.initialize(config=base_config(),
                                       model=SimpleModel(), mesh=mesh)
    engine.train_batch(random_batch(batch_size=8))
    ckpt = str(tmp_path / "ckpt")
    engine.save_checkpoint(ckpt, tag="step1")
    out = str(tmp_path / "consolidated.npz")
    zero_to_fp32.main([ckpt, out])
    with np.load(out) as data:
        flat = {k: data[k] for k in data.files}
    assert all(v.dtype == np.float32 for v in flat.values())
    live = jax.tree_util.tree_leaves(jax.device_get(engine.state.params))
    total_live = sum(int(np.prod(np.asarray(l).shape)) for l in live)
    total_saved = sum(int(np.prod(v.shape)) for v in flat.values())
    assert total_live == total_saved
    # the recovery script rides along with the checkpoint (reference
    # engine.py:1873-1881)
    assert os.path.isfile(os.path.join(ckpt, "zero_to_fp32.py"))
