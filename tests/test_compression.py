"""1-bit compressed allreduce tests — the TPU analog of the reference's
tests/onebit/test_nccl_backend.py (compressed allreduce vs dense allreduce)
on a forced multi-device CPU mesh."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import compression as comp
from deepspeed_tpu.parallel.mesh import shard_map


def _mesh(n):
    from jax.sharding import Mesh
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip(f"need {n} devices")
    return Mesh(np.asarray(devs), ("data",))


def test_pack_unpack_roundtrip():
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    signs = comp.unpack_signs(comp.pack_signs(x))
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_padded_numel():
    assert comp.padded_numel(1, 4) == 32
    assert comp.padded_numel(32, 4) == 32
    assert comp.padded_numel(33, 4) == 64


_RUN_CACHE = {}


def _run_allreduce(mesh, bufs, wes, ses):
    # build+jit the shard_map program once per mesh: rebuilding the closure
    # per call would recompile on every loop iteration
    key = id(mesh)
    if key not in _RUN_CACHE:
        @jax.jit
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("data"), P("data"), P("data")),
                           out_specs=(P("data"), P("data"), P("data")))
        def run(buf, we, se):
            out, we2, se2 = comp.compressed_allreduce(
                buf[0], we[0], se[0], "data")
            return out[None], we2[None], se2[None]
        _RUN_CACHE[key] = run
    return _RUN_CACHE[key](bufs, wes, ses)


def test_compressed_allreduce_approximates_mean():
    n, numel = 4, 256
    mesh = _mesh(n)
    rng = np.random.RandomState(1)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))
    wes = jnp.zeros((n, numel), jnp.float32)
    ses = jnp.zeros((n, numel // n), jnp.float32)

    out, we2, se2 = _run_allreduce(mesh, bufs, wes, ses)
    out = np.asarray(out)
    # identical result on every device
    for i in range(1, n):
        np.testing.assert_array_equal(out[0], out[i])
    exact = np.asarray(bufs).mean(axis=0)
    # 1-bit quantization is coarse on one shot, but signs of large entries
    # must mostly agree and magnitude must be in the right ballpark
    big = np.abs(exact) > np.abs(exact).mean()
    agree = (np.sign(out[0][big]) == np.sign(exact[big])).mean()
    assert agree > 0.8, agree
    # errors are recorded (non-zero) and bounded
    assert float(jnp.abs(we2).max()) > 0
    assert np.isfinite(np.asarray(we2)).all()
    assert np.isfinite(np.asarray(se2)).all()


def test_error_feedback_drives_accumulated_mean_to_exact():
    """With a CONSTANT input, error feedback makes the time-average of the
    compressed result converge to the true mean (the error-compensation
    contract of the reference backend)."""
    n, numel = 4, 256   # same shapes as the test above → shared compile
    mesh = _mesh(n)
    rng = np.random.RandomState(2)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))
    exact = np.asarray(bufs).mean(axis=0)

    wes = jnp.zeros((n, numel), jnp.float32)
    ses = jnp.zeros((n, numel // n), jnp.float32)
    acc = np.zeros(numel, np.float64)
    steps = 60
    for _ in range(steps):
        out, wes, ses = _run_allreduce(mesh, bufs, wes, ses)
        acc += np.asarray(out[0], np.float64)
    avg = acc / steps
    err = np.abs(avg - exact).max() / (np.abs(exact).max() + 1e-9)
    assert err < 0.15, err


def test_tree_allreduce_shapes_and_padding():
    n = 4
    mesh = _mesh(n)
    tree = {"a": jnp.ones((4, 8)), "b": jnp.full((2,), -1.0)}
    wes, ses = comp.init_error_states(tree, n)
    assert wes["a"].shape == (comp.padded_numel(32, n),)
    assert ses["b"].shape == (comp.padded_numel(2, n) // n,)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P()), out_specs=(P(), P("data"), P("data")),
        check_vma=False)
    def run(tree, wes, ses):
        wes = jax.tree_util.tree_map(lambda x: x[0], wes)
        ses = jax.tree_util.tree_map(lambda x: x[0], ses)
        out, we2, se2 = comp.tree_compressed_allreduce(
            tree, wes, ses, "data")
        bump = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x[None], t)
        return out, bump(we2), bump(se2)

    wes_b = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (n,) + x.shape), wes)
    ses_b = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (n,) + x.shape), ses)
    out, _, _ = run(tree, wes_b, ses_b)
    assert out["a"].shape == (4, 8)
    assert out["b"].shape == (2,)
    # "a" needs no padding: a constant-sign constant-magnitude buffer
    # round-trips 1-bit compression exactly (scale == the constant)
    np.testing.assert_allclose(np.asarray(out["a"]), np.ones((4, 8)),
                               rtol=1e-5)
    # "b" is padded 2→32; the padding zeros dilute the one-shot scale
    # (error feedback recovers it over steps) — only the sign is exact here
    assert (np.asarray(out["b"]) < 0).all()


# ---------------------------------------------------------------------------
# hierarchical link-aware exchange (ISSUE 10)
# ---------------------------------------------------------------------------

def _hier_mesh(inter, intra):
    from jax.sharding import Mesh
    n = inter * intra
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip(f"need {n} devices")
    return Mesh(np.asarray(devs).reshape(inter, intra),
                ("data_inter", "data_intra"))


def test_hierarchical_allreduce_matches_flat_mean():
    """The uncompressed two-level path (fast-axis ring RS/AG around a
    slow-axis pmean of the chunk) is exact: it must match the flat mean
    over all devices to fp32 ring-order rounding."""
    inter, intra, numel = 2, 4, 128
    n = inter * intra
    mesh = _hier_mesh(inter, intra)
    rng = np.random.RandomState(3)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=P(("data_inter", "data_intra")),
        out_specs=P(("data_inter", "data_intra")), check_vma=False)
    def run(buf):
        return comp.hierarchical_allreduce(
            buf[0], "data_inter", "data_intra")[None]

    out = np.asarray(run(bufs))
    exact = np.asarray(bufs).mean(axis=0)
    for i in range(n):
        np.testing.assert_allclose(out[i], exact, rtol=1e-5, atol=1e-6)


def test_hierarchical_compressed_matches_flat_compressed_quality():
    """The hierarchical 1-bit exchange approximates the global mean with
    the same one-shot quality contract as the flat compressed path
    (sign agreement on large entries) and yields the identical result
    on every device."""
    inter, intra = 2, 4
    n = inter * intra
    numel = 512            # divisible by 8*inter*intra
    mesh = _hier_mesh(inter, intra)
    rng = np.random.RandomState(4)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))
    wes = jnp.zeros((n, numel // intra), jnp.float32)
    ses = jnp.zeros((n, numel // n), jnp.float32)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(("data_inter", "data_intra")),) * 3,
        out_specs=(P(("data_inter", "data_intra")),) * 3, check_vma=False)
    def run(buf, we, se):
        out, we2, se2 = comp.hierarchical_compressed_allreduce(
            buf[0], we[0], se[0], "data_inter", "data_intra")
        return out[None], we2[None], se2[None]

    out, we2, se2 = run(bufs, wes, ses)
    out = np.asarray(out)
    for i in range(1, n):
        np.testing.assert_array_equal(out[0], out[i])
    exact = np.asarray(bufs).mean(axis=0)
    big = np.abs(exact) > np.abs(exact).mean()
    agree = (np.sign(out[0][big]) == np.sign(exact[big])).mean()
    assert agree > 0.8, agree
    assert float(jnp.abs(we2).max()) > 0
    assert np.isfinite(np.asarray(we2)).all()
    assert np.isfinite(np.asarray(se2)).all()


def test_hierarchical_error_feedback_converges():
    """Error feedback over the slow hop only: with a constant input the
    time-average of the hierarchical compressed result converges to the
    true mean (same contract as the flat exchange — the uncompressed
    fast hop must not break the compensation loop)."""
    inter, intra = 2, 4
    n = inter * intra
    numel = 512
    mesh = _hier_mesh(inter, intra)
    rng = np.random.RandomState(5)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))
    exact = np.asarray(bufs).mean(axis=0)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(("data_inter", "data_intra")),) * 3,
        out_specs=(P(("data_inter", "data_intra")),) * 3, check_vma=False)
    def run(buf, we, se):
        out, we2, se2 = comp.hierarchical_compressed_allreduce(
            buf[0], we[0], se[0], "data_inter", "data_intra")
        return out[None], we2[None], se2[None]

    wes = jnp.zeros((n, numel // intra), jnp.float32)
    ses = jnp.zeros((n, numel // n), jnp.float32)
    acc = np.zeros(numel, np.float64)
    steps = 60
    for _ in range(steps):
        out, wes, ses = run(bufs, wes, ses)
        acc += np.asarray(out[0], np.float64)
    avg = acc / steps
    err = np.abs(avg - exact).max() / (np.abs(exact).max() + 1e-9)
    assert err < 0.15, err


def test_bucketed_hierarchical_policy_and_wire_bytes():
    """Per-bucket policy + the trace-time cost model: 'never' must be
    bit-comparable to the exact two-level mean, 'auto' compresses only
    buckets over the byte floor, and the modeled slow-hop bytes drop
    >= 4x when compression is on."""
    from deepspeed_tpu.parallel import overlap
    inter, intra = 2, 4
    mesh = _hier_mesh(inter, intra)
    n = inter * intra
    plan = lambda policy, floor=0: overlap.HierarchyPlan(  # noqa: E731
        inter_axis="data_inter", intra_axis="data_intra",
        inter=inter, intra=intra, compression=policy,
        min_bucket_bytes=floor, bucket_elems=200)
    tree = {"a": jnp.asarray(np.random.RandomState(6).randn(16, 16),
                             jnp.float32),
            "b": jnp.asarray(np.random.RandomState(7).randn(40),
                             jnp.float32)}
    leaves = jax.tree_util.tree_leaves(tree)
    shapes = [l.shape for l in leaves]
    buckets = overlap.plan_buckets(shapes, 200, n)
    assert len(buckets) == 2     # 256-elem leaf overflows the 200 budget

    # auto with a floor between the two buckets compresses only the big
    flags = overlap.plan_bucket_compression(
        buckets, plan("auto", floor=256 * 4))
    assert flags == [True, False], (flags, [b.padded for b in buckets])

    wire_on = overlap.hierarchy_wire_bytes(buckets, [True, True],
                                           plan("always"))
    wire_off = overlap.hierarchy_wire_bytes(buckets, [False, False],
                                            plan("never"))
    assert wire_off["inter"] == wire_off["inter_uncompressed"]
    assert wire_on["inter_uncompressed"] >= 4 * wire_on["inter"], wire_on

    # 'never' policy: the bucketed exchange equals the exact flat mean
    p = plan("never")
    wes, ses = overlap.hierarchical_error_states(tree, p)
    assert wes == [None, None]   # nothing compressed -> no error state

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=P(),
                       out_specs=P(), check_vma=False)
    def run_never(tree):
        out, _, _ = overlap.bucketed_hierarchical_compressed_allreduce(
            tree, [None, None], [None, None], p)
        return out

    out = run_never(tree)   # replicated input -> mean is the input
    for got, want in zip(jax.tree_util.tree_leaves(out), leaves):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
