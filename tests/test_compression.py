"""1-bit compressed allreduce tests — the TPU analog of the reference's
tests/onebit/test_nccl_backend.py (compressed allreduce vs dense allreduce)
on a forced multi-device CPU mesh."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel import compression as comp
from deepspeed_tpu.parallel.mesh import shard_map


def _mesh(n):
    from jax.sharding import Mesh
    devs = jax.devices()[:n]
    if len(devs) < n:
        pytest.skip(f"need {n} devices")
    return Mesh(np.asarray(devs), ("data",))


def test_pack_unpack_roundtrip():
    x = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    signs = comp.unpack_signs(comp.pack_signs(x))
    np.testing.assert_array_equal(np.asarray(signs),
                                  np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_padded_numel():
    assert comp.padded_numel(1, 4) == 32
    assert comp.padded_numel(32, 4) == 32
    assert comp.padded_numel(33, 4) == 64


_RUN_CACHE = {}


def _run_allreduce(mesh, bufs, wes, ses):
    # build+jit the shard_map program once per mesh: rebuilding the closure
    # per call would recompile on every loop iteration
    key = id(mesh)
    if key not in _RUN_CACHE:
        @jax.jit
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("data"), P("data"), P("data")),
                           out_specs=(P("data"), P("data"), P("data")))
        def run(buf, we, se):
            out, we2, se2 = comp.compressed_allreduce(
                buf[0], we[0], se[0], "data")
            return out[None], we2[None], se2[None]
        _RUN_CACHE[key] = run
    return _RUN_CACHE[key](bufs, wes, ses)


def test_compressed_allreduce_approximates_mean():
    n, numel = 4, 256
    mesh = _mesh(n)
    rng = np.random.RandomState(1)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))
    wes = jnp.zeros((n, numel), jnp.float32)
    ses = jnp.zeros((n, numel // n), jnp.float32)

    out, we2, se2 = _run_allreduce(mesh, bufs, wes, ses)
    out = np.asarray(out)
    # identical result on every device
    for i in range(1, n):
        np.testing.assert_array_equal(out[0], out[i])
    exact = np.asarray(bufs).mean(axis=0)
    # 1-bit quantization is coarse on one shot, but signs of large entries
    # must mostly agree and magnitude must be in the right ballpark
    big = np.abs(exact) > np.abs(exact).mean()
    agree = (np.sign(out[0][big]) == np.sign(exact[big])).mean()
    assert agree > 0.8, agree
    # errors are recorded (non-zero) and bounded
    assert float(jnp.abs(we2).max()) > 0
    assert np.isfinite(np.asarray(we2)).all()
    assert np.isfinite(np.asarray(se2)).all()


def test_error_feedback_drives_accumulated_mean_to_exact():
    """With a CONSTANT input, error feedback makes the time-average of the
    compressed result converge to the true mean (the error-compensation
    contract of the reference backend)."""
    n, numel = 4, 256   # same shapes as the test above → shared compile
    mesh = _mesh(n)
    rng = np.random.RandomState(2)
    bufs = jnp.asarray(rng.randn(n, numel).astype(np.float32))
    exact = np.asarray(bufs).mean(axis=0)

    wes = jnp.zeros((n, numel), jnp.float32)
    ses = jnp.zeros((n, numel // n), jnp.float32)
    acc = np.zeros(numel, np.float64)
    steps = 60
    for _ in range(steps):
        out, wes, ses = _run_allreduce(mesh, bufs, wes, ses)
        acc += np.asarray(out[0], np.float64)
    avg = acc / steps
    err = np.abs(avg - exact).max() / (np.abs(exact).max() + 1e-9)
    assert err < 0.15, err


def test_tree_allreduce_shapes_and_padding():
    n = 4
    mesh = _mesh(n)
    tree = {"a": jnp.ones((4, 8)), "b": jnp.full((2,), -1.0)}
    wes, ses = comp.init_error_states(tree, n)
    assert wes["a"].shape == (comp.padded_numel(32, n),)
    assert ses["b"].shape == (comp.padded_numel(2, n) // n,)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P()), out_specs=(P(), P("data"), P("data")),
        check_vma=False)
    def run(tree, wes, ses):
        wes = jax.tree_util.tree_map(lambda x: x[0], wes)
        ses = jax.tree_util.tree_map(lambda x: x[0], ses)
        out, we2, se2 = comp.tree_compressed_allreduce(
            tree, wes, ses, "data")
        bump = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x[None], t)
        return out, bump(we2), bump(se2)

    wes_b = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (n,) + x.shape), wes)
    ses_b = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (n,) + x.shape), ses)
    out, _, _ = run(tree, wes_b, ses_b)
    assert out["a"].shape == (4, 8)
    assert out["b"].shape == (2,)
    # "a" needs no padding: a constant-sign constant-magnitude buffer
    # round-trips 1-bit compression exactly (scale == the constant)
    np.testing.assert_allclose(np.asarray(out["a"]), np.ones((4, 8)),
                               rtol=1e-5)
    # "b" is padded 2→32; the padding zeros dilute the one-shot scale
    # (error feedback recovers it over steps) — only the sign is exact here
    assert (np.asarray(out["b"]) < 0).all()
