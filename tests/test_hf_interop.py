"""HF GPT-2 → deepspeed_tpu conversion tests: a randomly initialized
transformers FlaxGPT2LMHeadModel must produce (near-)identical logits
through our model after param conversion, and train under the engine."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

transformers = pytest.importorskip("transformers")


def _hf_tiny():
    from transformers import GPT2Config as HFConfig, FlaxGPT2LMHeadModel
    hf_cfg = HFConfig(vocab_size=512, n_positions=128, n_embd=64,
                      n_layer=2, n_head=2, resid_pdrop=0.0,
                      embd_pdrop=0.0, attn_pdrop=0.0)
    return FlaxGPT2LMHeadModel(hf_cfg, seed=0)


def test_converted_logits_match_hf():
    from deepspeed_tpu.models.hf_interop import from_hf_gpt2
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel

    hf_model = _hf_tiny()
    ids = np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32)
    ref = np.asarray(hf_model(ids).logits)

    for scan in (True, False):
        cfg, params = from_hf_gpt2(hf_model, dtype=jnp.float32,
                                   scan_layers=scan)
        got = GPT2LMHeadModel(cfg).apply({"params": params},
                                         jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4,
                                   atol=2e-4, err_msg=f"scan={scan}")


def test_hf_model_trains_under_engine():
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.hf_interop import from_hf_gpt2
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    hf_model = _hf_tiny()
    cfg, params = from_hf_gpt2(hf_model, dtype=jnp.float32,
                               scan_layers=True)
    engine, _, _, _ = dstpu.initialize(
        config={"train_batch_size": 4,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=GPT2LMHeadModel(cfg),
        model_parameters=params,
        mesh=make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    batch = {"input_ids": np.random.RandomState(0)
             .randint(0, 512, (4, 32)).astype(np.int32)}
    l0 = float(engine.train_batch(batch))
    for _ in range(8):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_converted_params_serve_through_inference_stack():
    from deepspeed_tpu.models.hf_interop import from_hf_gpt2
    from deepspeed_tpu.models.gpt2_inference import generate

    hf_model = _hf_tiny()
    cfg, params = from_hf_gpt2(hf_model, dtype=jnp.float32,
                               scan_layers=True)
    ids = np.random.RandomState(0).randint(0, 512, (1, 8)).astype(np.int32)
    out = generate(cfg, params, ids, max_new_tokens=4)
    assert out.shape == (1, 12)
    # greedy continuation must match HF's own greedy pick for the 1st token
    hf_logits = np.asarray(_hf_tiny()(ids).logits)
    assert int(out[0, 8]) == int(hf_logits[0, -1].argmax())
