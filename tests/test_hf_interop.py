"""HF GPT-2 → deepspeed_tpu conversion tests: a randomly initialized
transformers FlaxGPT2LMHeadModel must produce (near-)identical logits
through our model after param conversion, and train under the engine."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

transformers = pytest.importorskip("transformers")


def _hf_tiny():
    from transformers import GPT2Config as HFConfig, FlaxGPT2LMHeadModel
    hf_cfg = HFConfig(vocab_size=512, n_positions=128, n_embd=64,
                      n_layer=2, n_head=2, resid_pdrop=0.0,
                      embd_pdrop=0.0, attn_pdrop=0.0)
    return FlaxGPT2LMHeadModel(hf_cfg, seed=0)


def test_converted_logits_match_hf():
    from deepspeed_tpu.models.hf_interop import from_hf_gpt2
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel

    hf_model = _hf_tiny()
    ids = np.random.RandomState(0).randint(0, 512, (2, 16)).astype(np.int32)
    ref = np.asarray(hf_model(ids).logits)

    for scan in (True, False):
        cfg, params = from_hf_gpt2(hf_model, dtype=jnp.float32,
                                   scan_layers=scan)
        got = GPT2LMHeadModel(cfg).apply({"params": params},
                                         jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4,
                                   atol=2e-4, err_msg=f"scan={scan}")


def test_hf_model_trains_under_engine():
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.hf_interop import from_hf_gpt2
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    hf_model = _hf_tiny()
    cfg, params = from_hf_gpt2(hf_model, dtype=jnp.float32,
                               scan_layers=True)
    engine, _, _, _ = dstpu.initialize(
        config={"train_batch_size": 4,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=GPT2LMHeadModel(cfg),
        model_parameters=params,
        mesh=make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    batch = {"input_ids": np.random.RandomState(0)
             .randint(0, 512, (4, 32)).astype(np.int32)}
    l0 = float(engine.train_batch(batch))
    for _ in range(8):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_converted_params_serve_through_inference_stack():
    from deepspeed_tpu.models.hf_interop import from_hf_gpt2
    from deepspeed_tpu.models.gpt2_inference import generate

    hf_model = _hf_tiny()
    cfg, params = from_hf_gpt2(hf_model, dtype=jnp.float32,
                               scan_layers=True)
    ids = np.random.RandomState(0).randint(0, 512, (1, 8)).astype(np.int32)
    out = generate(cfg, params, ids, max_new_tokens=4)
    assert out.shape == (1, 12)
    # greedy continuation must match HF's own greedy pick for the 1st token
    hf_logits = np.asarray(_hf_tiny()(ids).logits)
    assert int(out[0, 8]) == int(hf_logits[0, -1].argmax())


def test_converted_bert_matches_hf():
    """Our BertModel with converted HF weights reproduces the HF flax BERT
    hidden states and pooler output — the BERT analog of the GPT-2 interop
    (and a numerics cross-check of the fused encoder layer against an
    independent implementation)."""
    import jax.numpy as jnp
    from transformers import BertConfig as HFBertConfig, FlaxBertModel
    from deepspeed_tpu.models.bert import BertModel
    from deepspeed_tpu.models.hf_interop import from_hf_bert

    hf_cfg = HFBertConfig(vocab_size=128, hidden_size=32,
                          num_hidden_layers=2, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=64,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    hf = FlaxBertModel(hf_cfg, seed=0)
    cfg, params = from_hf_bert(hf, dtype=jnp.float32)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (2, 16)).astype(np.int32)
    mask = np.ones((2, 16), np.int32)
    mask[1, 10:] = 0

    ours_seq, ours_pooled = BertModel(cfg).apply(
        {"params": params}, ids, mask)
    hf_out = hf(input_ids=ids, attention_mask=mask)
    # compare only unmasked positions: hidden states AT padding positions
    # are implementation-defined (HF attends padding queries to the valid
    # keys; our kernel path masks them out entirely)
    valid = mask.astype(bool)
    np.testing.assert_allclose(
        np.asarray(ours_seq, np.float32)[valid],
        np.asarray(hf_out.last_hidden_state)[valid],
        rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(ours_pooled, np.float32),
                               np.asarray(hf_out.pooler_output),
                               rtol=2e-4, atol=2e-4)


def test_converted_bert_scan_layout_matches_unrolled():
    import jax.numpy as jnp
    from transformers import BertConfig as HFBertConfig, FlaxBertModel
    from deepspeed_tpu.models.bert import BertModel
    from deepspeed_tpu.models.hf_interop import from_hf_bert

    hf = FlaxBertModel(HFBertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64), seed=1)
    cfg_u, params_u = from_hf_bert(hf, dtype=jnp.float32)
    cfg_s, params_s = from_hf_bert(hf, dtype=jnp.float32, scan_layers=True)
    ids = np.random.RandomState(1).randint(0, 128, (2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    a, _ = BertModel(cfg_u).apply({"params": params_u}, ids, mask)
    b, _ = BertModel(cfg_s).apply({"params": params_s}, ids, mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)
