"""Launcher-layer tests — hostfile parsing, include/exclude filters, world
info encode/decode, runner command construction, per-host env contract, and
the rendezvous discovery in utils/distributed (reference behaviors:
launcher/runner.py:120-241, launcher/launch.py:66-168)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.launcher import launch as ds_launch
from deepspeed_tpu.launcher import runner as ds_runner
from deepspeed_tpu.launcher.multinode_runner import (
    OpenMPIRunner,
    PDSHRunner,
    SSHRunner,
)
from deepspeed_tpu.utils.distributed import discover_rendezvous

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_hostfile(tmp_path, text):
    path = tmp_path / "hostfile"
    path.write_text(textwrap.dedent(text))
    return str(path)


def test_fetch_hostfile(tmp_path):
    path = _write_hostfile(tmp_path, """\
        # comment
        worker-0 slots=4

        worker-1 slots=8
    """)
    pool = ds_runner.fetch_hostfile(path)
    assert list(pool.items()) == [("worker-0", 4), ("worker-1", 8)]


def test_fetch_hostfile_missing_returns_none(tmp_path):
    assert ds_runner.fetch_hostfile(str(tmp_path / "nope")) is None


def test_fetch_hostfile_rejects_bad_lines(tmp_path):
    path = _write_hostfile(tmp_path, "worker-0 4\n")
    with pytest.raises(ValueError):
        ds_runner.fetch_hostfile(path)


def test_fetch_hostfile_rejects_duplicates(tmp_path):
    path = _write_hostfile(tmp_path, "w0 slots=4\nw0 slots=2\n")
    with pytest.raises(ValueError):
        ds_runner.fetch_hostfile(path)


def _pool(**kw):
    import collections
    return collections.OrderedDict(kw)


def test_include_filter_whole_host_and_slots():
    active = ds_runner.parse_inclusion_exclusion(
        _pool(a=4, b=4), "a@b:0,2", "")
    assert active == {"a": [0, 1, 2, 3], "b": [0, 2]}


def test_exclude_filter():
    active = ds_runner.parse_inclusion_exclusion(_pool(a=4, b=2), "", "b:0")
    assert active == {"a": [0, 1, 2, 3], "b": [1]}


def test_exclude_whole_host_drops_it():
    active = ds_runner.parse_inclusion_exclusion(_pool(a=2, b=2), "", "b")
    assert active == {"a": [0, 1]}


def test_include_exclude_mutually_exclusive():
    with pytest.raises(ValueError):
        ds_runner.parse_resource_filter({"a": [0]}, "a", "a")


def test_filter_unknown_host_raises():
    with pytest.raises(ValueError):
        ds_runner.parse_inclusion_exclusion(_pool(a=1), "zz", "")
    with pytest.raises(ValueError):
        ds_runner.parse_inclusion_exclusion(_pool(a=1), "a:7", "")


def test_world_info_roundtrip():
    info = {"w0": [0, 1], "w1": [0]}
    assert ds_runner.decode_world_info(
        ds_runner.encode_world_info(info)) == info


def test_runner_cmds_contain_launch_module():
    args = ds_runner.parse_args(
        ["--hostfile", "/nonexistent", "--coordinator_addr", "w0",
         "train.py", "--lr", "0.1"])
    info = ds_runner.encode_world_info({"w0": [0], "w1": [0]})
    resources = _pool(w0=[0], w1=[0])

    ssh_cmd = SSHRunner(args, info).get_cmd(dict(os.environ), resources)
    assert ssh_cmd[:2] == ["bash", "-c"]
    assert "deepspeed_tpu.launcher.launch" in ssh_cmd[2]
    assert "--node_rank=1" in ssh_cmd[2]

    pdsh_cmd = PDSHRunner(args, info).get_cmd(dict(os.environ), resources)
    assert pdsh_cmd[0] == "pdsh"
    assert "--node_rank=%n" in pdsh_cmd

    mpi_cmd = OpenMPIRunner(args, info).get_cmd(dict(os.environ), resources)
    assert mpi_cmd[0] == "mpirun"
    assert "--node_rank=ompi" in mpi_cmd


def test_runner_export_collection(tmp_path, monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.setenv("LIBTPU_INIT_ARGS", "--foo")
    monkeypatch.setenv("SOME_RANDOM_VAR", "1")
    monkeypatch.chdir(tmp_path)
    (tmp_path / ".dstpu_env").write_text("EXTRA_VAR=42\n# c\n")
    exports = ds_runner.collect_exports()
    assert exports["JAX_PLATFORMS"] == "tpu"
    assert exports["LIBTPU_INIT_ARGS"] == "--foo"
    assert exports["EXTRA_VAR"] == "42"
    assert "SOME_RANDOM_VAR" not in exports


def test_launch_child_env_contract():
    info = ds_runner.encode_world_info({"hostA": [0, 1], "hostB": [2, 3]})
    args = ds_launch.parse_args(
        ["--node_rank", "1", "--coordinator_addr", "hostA",
         "--coordinator_port", "1234", "--world_info", info, "t.py"])
    env, node_rank, nnodes = ds_launch.build_child_env(args, environ={})
    assert (node_rank, nnodes) == (1, 2)
    assert env["DSTPU_COORDINATOR_ADDR"] == "hostA"
    assert env["DSTPU_COORDINATOR_PORT"] == "1234"
    assert env["DSTPU_NUM_PROCESSES"] == "2"
    assert env["DSTPU_PROCESS_ID"] == "1"
    assert env["DSTPU_LOCAL_DEVICE_IDS"] == "2,3"
    assert env["TPU_VISIBLE_CHIPS"] == "2,3"


def test_launch_ompi_node_rank():
    info = ds_runner.encode_world_info({"a": [0], "b": [0]})
    args = ds_launch.parse_args(["--node_rank", "ompi",
                                 "--world_info", info, "t.py"])
    env, node_rank, _ = ds_launch.build_child_env(
        args, environ={"OMPI_COMM_WORLD_RANK": "1"})
    assert node_rank == 1
    assert env["DSTPU_PROCESS_ID"] == "1"


def test_discover_rendezvous_priority():
    # launcher contract wins
    addr, num, pid, ids = discover_rendezvous({
        "DSTPU_COORDINATOR_ADDR": "h0", "DSTPU_COORDINATOR_PORT": "99",
        "DSTPU_NUM_PROCESSES": "4", "DSTPU_PROCESS_ID": "3",
        "DSTPU_LOCAL_DEVICE_IDS": "0,1",
        "OMPI_COMM_WORLD_SIZE": "8"})
    assert (addr, num, pid, ids) == ("h0:99", 4, 3, [0, 1])
    # MPI fallback
    addr, num, pid, ids = discover_rendezvous({
        "OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": "1",
        "MASTER_ADDR": "m", "MASTER_PORT": "5"})
    assert (addr, num, pid) == ("m:5", 2, 1)
    # MPI without a MASTER_ADDR must not guess a loopback coordinator
    addr, num, pid, ids = discover_rendezvous({
        "OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": "1"})
    assert addr is None and (num, pid) == (2, 1)
    # auto_mpi_discovery=False disables the OMPI branch entirely
    assert discover_rendezvous(
        {"OMPI_COMM_WORLD_SIZE": "2", "OMPI_COMM_WORLD_RANK": "1",
         "MASTER_ADDR": "m"}, auto_mpi_discovery=False) == \
        (None, None, None, None)
    # nothing set
    assert discover_rendezvous({}) == (None, None, None, None)


def test_exports_are_shell_quoted():
    args = ds_runner.parse_args(
        ["--coordinator_addr", "w0", "train.py"])
    info = ds_runner.encode_world_info({"w0": [0], "w1": [0]})
    runner = SSHRunner(args, info)
    runner.add_export("XLA_FLAGS", "--xla_a --xla_b")
    cmd = runner.get_cmd(dict(os.environ), _pool(w0=[0], w1=[0]))
    # the remote command is one quoted ssh operand; unwrap that layer and
    # check the export inside it survives with its spaces intact
    import shlex
    remote_ops = [tok for tok in shlex.split(cmd[2])
                  if tok.startswith("export XLA_FLAGS=")]
    assert remote_ops, cmd[2]
    assert "export XLA_FLAGS='--xla_a --xla_b';" in remote_ops[0]


def test_localhost_hostfile_stays_local(tmp_path):
    """A hostfile naming only localhost must not require sshd."""
    path = _write_hostfile(tmp_path, "localhost slots=2\n")
    script = tmp_path / "ok.py"
    script.write_text("print('LOCAL_OK')\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", path, str(script)],
        capture_output=True, text=True, env=env, timeout=120,
        cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr
    assert "LOCAL_OK" in out.stdout


def test_single_host_end_to_end(tmp_path):
    """runner → launch → user script, all local subprocesses; the user
    script asserts the env contract and prints it back."""
    script = tmp_path / "train.py"
    script.write_text(textwrap.dedent("""\
        import json, os
        print(json.dumps({k: os.environ[k] for k in (
            "DSTPU_COORDINATOR_ADDR", "DSTPU_NUM_PROCESSES",
            "DSTPU_PROCESS_ID")}))
    """))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", str(tmp_path / "none"), str(script)],
        capture_output=True, text=True, env=env, timeout=120,
        cwd=REPO_ROOT)
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["DSTPU_COORDINATOR_ADDR"] == "127.0.0.1"
    assert payload["DSTPU_NUM_PROCESSES"] == "1"
    assert payload["DSTPU_PROCESS_ID"] == "0"


def test_launch_propagates_child_failure(tmp_path):
    script = tmp_path / "boom.py"
    script.write_text("import sys; sys.exit(3)\n")
    info = ds_runner.encode_world_info({"localhost": [0]})
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.launch",
         f"--world_info={info}", "--node_rank=0", str(script)],
        capture_output=True, text=True, env=env, timeout=120,
        cwd=REPO_ROOT)
    assert out.returncode != 0
