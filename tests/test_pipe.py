"""Pipeline tests — the reference's test_pipe_schedule.py / test_pipe_module.py
roles: schedule invariants and module partitioning/execution."""

import numpy as np
import jax.numpy as jnp
import flax.linen as nn
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.runtime.pipe import schedule as S
from deepspeed_tpu.runtime.pipe.module import (
    PipelineModule, LayerSpec, partition_uniform, partition_balanced)
from tests.simple_model import base_config, random_batch


def _flat(sched):
    return [c for step in sched.steps() for c in step]


def test_train_schedule_counts():
    for stages in (2, 4):
        for mb in (2, 4, 8):
            for stage_id in range(stages):
                sched = S.TrainSchedule(micro_batches=mb, stages=stages,
                                        stage_id=stage_id)
                cmds = _flat(sched)
                fwd = [c for c in cmds if isinstance(c, S.ForwardPass)]
                bwd = [c for c in cmds if isinstance(c, S.BackwardPass)]
                assert len(fwd) == mb
                assert len(bwd) == mb
                assert sum(isinstance(c, S.OptimizerStep) for c in cmds) == 1


def test_train_schedule_fwd_before_bwd_per_buffer():
    sched = S.TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seen_fwd = set()
    for step in sched.steps():
        for cmd in step:
            if isinstance(cmd, S.ForwardPass):
                seen_fwd.add(cmd.buffer_id)
            if isinstance(cmd, S.BackwardPass):
                assert cmd.buffer_id in seen_fwd


def test_train_schedule_send_recv_pairing():
    """Across adjacent stages, sends on stage s must match recvs on s+1."""
    mb, stages = 4, 2
    s0 = _flat(S.TrainSchedule(mb, stages, 0))
    s1 = _flat(S.TrainSchedule(mb, stages, 1))
    sends0 = sum(isinstance(c, S.SendActivation) for c in s0)
    recvs1 = sum(isinstance(c, S.RecvActivation) for c in s1)
    assert sends0 == recvs1 == mb
    sends_g1 = sum(isinstance(c, S.SendGrad) for c in s1)
    recvs_g0 = sum(isinstance(c, S.RecvGrad) for c in s0)
    assert sends_g1 == recvs_g0 == mb


def test_inference_schedule():
    sched = S.InferenceSchedule(micro_batches=3, stages=2, stage_id=0)
    cmds = _flat(sched)
    assert sum(isinstance(c, S.ForwardPass) for c in cmds) == 3
    assert sum(isinstance(c, S.LoadMicroBatch) for c in cmds) == 3
    assert not any(isinstance(c, S.BackwardPass) for c in cmds)


def test_num_pipe_buffers():
    # reference pipe/schedule.py:243-247: stages - stage_id + 1, >= 2
    sched = S.TrainSchedule(micro_batches=8, stages=4, stage_id=0)
    assert sched.num_pipe_buffers() == 5
    sched = S.TrainSchedule(micro_batches=8, stages=4, stage_id=3)
    assert sched.num_pipe_buffers() == 2
    sched = S.TrainSchedule(micro_batches=2, stages=4, stage_id=0)
    assert sched.num_pipe_buffers() == 2


def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 2) == [0, 4, 7]


def test_partition_balanced():
    parts = partition_balanced([1, 1, 1, 100], 2)
    assert parts[0] == 0 and parts[-1] == 4
    # the heavy item must sit alone in the last part
    assert parts[1] == 3
    parts = partition_balanced([1] * 8, 4)
    assert parts == [0, 2, 4, 6, 8]


def test_pipeline_module_runs():
    layers = [LayerSpec(nn.Dense, 16) for _ in range(4)]
    pipe = PipelineModule(layers=layers, num_stages=2,
                          partition_method="uniform")
    import jax
    x = jnp.ones((2, 16))
    variables = pipe.init(jax.random.PRNGKey(0), x)
    out = pipe.apply(variables, x)
    assert out.shape == (2, 16)
    assert pipe.parts == [0, 2, 4]


def test_pipeline_module_parameters_partition():
    layers = [LayerSpec(nn.Dense, 4), LayerSpec(nn.Dense, 64),
              LayerSpec(nn.Dense, 4), LayerSpec(nn.Dense, 4)]
    pipe = PipelineModule(layers=layers, num_stages=2,
                          partition_method="parameters")
    import jax
    pipe.init(jax.random.PRNGKey(0), jnp.ones((2, 64)))
    assert pipe.parts[0] == 0 and pipe.parts[-1] == 4
    assert len(pipe.parts) == 3


def test_pipeline_engine_single_stage_trains():
    import jax

    def loss_fn(out, y):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    layers = [LayerSpec(nn.Dense, 32), LayerSpec(nn.Dense, 4)]
    pipe = PipelineModule(layers=layers, num_stages=1, loss_fn=loss_fn)
    engine, _, _, _ = dstpu.initialize(
        config=base_config(), model=pipe,
        mesh=make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    x, y = random_batch(batch_size=8)
    l0 = float(engine.train_batch((x, y)))
    for _ in range(20):
        l1 = float(engine.train_batch((x, y)))
    assert l1 < l0


def test_pipeline_module_finds_homogeneous_trunk():
    layers = [LayerSpec(nn.Dense, 32),            # prefix (different width)
              LayerSpec(nn.Dense, 16), LayerSpec(nn.Dense, 16),
              LayerSpec(nn.Dense, 16), LayerSpec(nn.Dense, 16),
              LayerSpec(nn.Dense, 4)]             # suffix
    pipe = PipelineModule(layers=layers, num_stages=2)
    assert pipe._find_homogeneous_trunk() == (1, 5)


def test_pipeline_module_lowered_apply_matches_sequential():
    """The SPMD lowering (stage-stacked trunk + 1F1B executor) computes
    exactly what the sequential module computes."""
    import jax
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig

    def build():
        layers = [LayerSpec(nn.Dense, 16)] + \
            [LayerSpec(nn.Dense, 16) for _ in range(4)] + \
            [LayerSpec(nn.Dense, 4)]
        return PipelineModule(layers=layers, partition_method="uniform")

    x = jnp.asarray(np.random.RandomState(0).randn(4, 16), jnp.float32)
    seq = build()
    seq_vars = seq.init(jax.random.PRNGKey(0), x)
    ref = seq.apply(seq_vars, x)

    if len(jax.devices()) < 2:
        import pytest
        pytest.skip("need 2 devices")
    mesh = make_mesh(MeshConfig(pipe=2), devices=jax.devices()[:2])
    low = build().lower_to_spmd(mesh, num_microbatches=2)
    low_vars = low.init(jax.random.PRNGKey(0), x)
    assert "trunk_stages" in low_vars["params"]
    got = low.apply(low_vars, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # round-trip back to the sequential layout (checkpoint interop)
    flat = low.unstack_trunk(low_vars["params"])
    for i in range(1, 5):
        np.testing.assert_allclose(
            np.asarray(flat[f"layer_{i}"]["kernel"]),
            np.asarray(seq_vars["params"][f"layer_{i}"]["kernel"]))


def test_pipeline_module_trains_pipe2xdp_matches_pipe1():
    """VERDICT #3 done-condition: a non-GPT-2 LayerSpec model trains under
    the engine on pipe=2 x dp=2 with losses matching the pipe=1 run."""
    import jax
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")

    def loss_fn(out, y):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

    def run(mesh_cfg, n_dev):
        layers = [LayerSpec(nn.Dense, 32)] + \
            [LayerSpec(nn.Dense, 32) for _ in range(4)] + \
            [LayerSpec(nn.Dense, 4)]
        pipe = PipelineModule(layers=layers, loss_fn=loss_fn,
                              num_microbatches=2)
        mesh = make_mesh(mesh_cfg, devices=jax.devices()[:n_dev])
        engine, _, _, _ = dstpu.initialize(
            config=base_config(), model=pipe, mesh=mesh)
        x, y = random_batch(batch_size=8)
        return [float(engine.train_batch((x, y))) for _ in range(8)]

    base = run(MeshConfig(data=1), 1)
    got = run(MeshConfig(pipe=2, data=2), 4)
    assert got[-1] < got[0] - 0.1, got
    np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("mesh_cfg,n_dev", [
    ("pipe2", 2),          # pipe-only mesh -> interleaved schedule
    ("pipe2xdp2", 4),      # live data axis  -> uniform schedule
])
def test_tied_weights_pipe2_matches_pipe1(mesh_cfg, n_dev):
    """VERDICT r3 item 7: a model with tied embedding/unembedding
    (TiedLayerSpec) trained at pipe=2 matches the pipe=1 grads and loss
    trajectory.

    Design note (the replicated-prefix/suffix equivalence): the SPMD
    lowering excludes tied specs from the stage-stacked trunk — tied
    layers run in the prefix/suffix, replicated over the pipe axis, and
    both uses read the SAME ``params['tied'][key]`` subtree. Autodiff
    therefore sums the embedding-use and unembedding-use cotangents into
    one tied gradient automatically — the role of the reference's
    ReduceTiedGrads all-reduce over the tied-owner group
    (deepspeed/runtime/pipe/module.py:412-480) with no communication
    beyond what GSPMD already inserts.
    """
    import jax
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    from deepspeed_tpu.runtime.pipe.module import TiedLayerSpec
    if len(jax.devices()) < n_dev:
        pytest.skip(f"need {n_dev} devices")

    V, D = 32, 16

    def unembed(module, p, x):
        return x @ p["embedding"].T

    def build():
        layers = [TiedLayerSpec("embed", nn.Embed, V, D)] + \
            [LayerSpec(nn.Dense, D) for _ in range(4)] + \
            [TiedLayerSpec("embed", nn.Embed, V, D, forward_fn=unembed)]
        return PipelineModule(layers=layers, partition_method="uniform",
                              num_microbatches=2)

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randint(0, V, (4, 8)), jnp.int32)
    y = jnp.asarray(rs.randint(0, V, (4, 8)), jnp.int32)

    def ce(out, y):
        logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()

    # ---- grad parity: pipe=2 vs sequential, same weights ----
    seq = build()
    seq_vars = seq.init(jax.random.PRNGKey(0), x)
    g_seq = jax.grad(
        lambda p: ce(seq.apply({"params": p}, x), y))(seq_vars["params"])

    cfg = MeshConfig(pipe=2) if mesh_cfg == "pipe2" \
        else MeshConfig(pipe=2, data=2)
    mesh = make_mesh(cfg, devices=jax.devices()[:n_dev])
    low = build().lower_to_spmd(mesh, num_microbatches=2)
    low_vars = low.init(jax.random.PRNGKey(0), x)
    assert "trunk_stages" in low_vars["params"]
    assert "embed" in low_vars["params"]["tied"]
    g_pipe = jax.jit(jax.grad(
        lambda p: ce(low.apply({"params": p}, x), y)))(low_vars["params"])

    # tied gradient: the single shared subtree carries the summed
    # embedding + unembedding cotangents
    np.testing.assert_allclose(
        np.asarray(g_pipe["tied"]["embed"]["embedding"]),
        np.asarray(g_seq["tied"]["embed"]["embedding"]),
        rtol=1e-4, atol=1e-5)
    # trunk gradients match layer-for-layer after unstacking
    flat = low.unstack_trunk(g_pipe)
    for i in range(1, 5):
        np.testing.assert_allclose(
            np.asarray(flat[f"layer_{i}"]["kernel"]),
            np.asarray(g_seq[f"layer_{i}"]["kernel"]),
            rtol=1e-4, atol=1e-5)

    # ---- loss-trajectory parity through the engine ----
    def run(mesh):
        pipe = build()
        engine, _, _, _ = dstpu.initialize(
            config=base_config(), model=pipe, mesh=mesh,
            loss_fn=lambda params, batch, rng, keep_prob: ce(
                pipe.apply({"params": params}, batch[0]), batch[1]))
        return [float(engine.train_batch((x, y))) for _ in range(6)]

    base = run(make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    got = run(mesh)
    assert got[-1] < got[0] - 0.05, got
    np.testing.assert_allclose(got, base, rtol=2e-3, atol=2e-3)


def test_pipeline_lowering_triggers_from_config_mesh():
    """pipe>1 coming from the config's mesh section (no mesh kwarg) must
    still lower the module — not silently train un-pipelined."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    layers = [LayerSpec(nn.Dense, 32) for _ in range(4)]
    pipe = PipelineModule(layers=layers, num_microbatches=2)
    cfg = base_config()
    cfg["mesh"] = {"pipe": 2, "data": 4}
    cfg["train_batch_size"] = 8
    engine, _, _, _ = dstpu.initialize(config=cfg, model=pipe)
    assert pipe._spmd_mesh is not None
    x, y = random_batch(batch_size=8)
    loss = float(engine.train_batch((x, y)))
    assert np.isfinite(loss)
    assert "trunk_stages" in engine.state.params
