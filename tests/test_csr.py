"""CSR sparse-gradient tests (reference tests/unit/test_csr.py + the sparse
allreduce path of engine.py:1444-1515): compression roundtrip, addition,
and the compressed data-parallel reduction vs a dense sum on the 8-device
mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.runtime.csr_tensor import CSRTensor, sparse_all_reduce


def _sparse_grad(rs, V=64, E=8, touched=6):
    g = np.zeros((V, E), np.float32)
    rows = rs.choice(V, touched, replace=False)
    g[rows] = rs.randn(touched, E)
    return g


def test_from_dense_roundtrip():
    rs = np.random.RandomState(0)
    g = _sparse_grad(rs)
    csr = CSRTensor.from_dense(jnp.asarray(g), max_rows=16)
    assert int(csr.nnz_rows) == 6
    np.testing.assert_allclose(np.asarray(csr.to_dense()), g)


def test_roundtrip_when_max_rows_exceeds_vocab():
    rs = np.random.RandomState(1)
    g = _sparse_grad(rs, V=8, E=4, touched=3)
    csr = CSRTensor.from_dense(jnp.asarray(g), max_rows=32)
    np.testing.assert_allclose(np.asarray(csr.to_dense()), g)


def test_add_merges_duplicates():
    rs = np.random.RandomState(2)
    a, b = _sparse_grad(rs), _sparse_grad(rs)
    ca = CSRTensor.from_dense(jnp.asarray(a), max_rows=16)
    cb = CSRTensor.from_dense(jnp.asarray(b), max_rows=16)
    np.testing.assert_allclose(np.asarray(ca.add(cb).to_dense()), a + b,
                               rtol=1e-6)


def test_csr_is_pytree():
    csr = CSRTensor.from_dense(jnp.ones((4, 2)), max_rows=4)
    leaves, treedef = jax.tree_util.tree_flatten(csr)
    assert len(leaves) == 2
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.dense_shape == (4, 2)
    # works under jit
    dense = jax.jit(lambda c: c.to_dense())(csr)
    np.testing.assert_allclose(np.asarray(dense), np.ones((4, 2)))


def test_sparse_all_reduce_matches_dense_sum(devices8):
    rs = np.random.RandomState(3)
    W, V, E = 8, 64, 8
    grads = np.stack([_sparse_grad(rs, V, E, touched=5) for _ in range(W)])
    mesh = Mesh(np.array(devices8).reshape(W), ("data",))
    g_sh = jax.device_put(
        jnp.asarray(grads), NamedSharding(mesh, P("data", None, None)))
    out = sparse_all_reduce(g_sh, mesh, "data", max_rows=16)
    np.testing.assert_allclose(np.asarray(out), grads.sum(0), rtol=1e-5,
                               atol=1e-6)


def test_sparse_all_reduce_overlapping_rows(devices8):
    """Ranks touching the SAME rows must sum, not overwrite."""
    W, V, E = 8, 16, 4
    grads = np.zeros((W, V, E), np.float32)
    grads[:, 3] = 1.0          # all ranks touch row 3
    grads[:, 7] = 2.0
    mesh = Mesh(np.array(devices8).reshape(W), ("data",))
    g_sh = jax.device_put(
        jnp.asarray(grads), NamedSharding(mesh, P("data", None, None)))
    out = np.asarray(sparse_all_reduce(g_sh, mesh, "data", max_rows=4))
    np.testing.assert_allclose(out[3], np.full(E, 8.0))
    np.testing.assert_allclose(out[7], np.full(E, 16.0))
    assert np.abs(out).sum() == pytest.approx(8.0 * E + 16.0 * E)


def test_engine_sparse_gradients_match_dense(monkeypatch):
    """sparse_gradients=true exchanges embedding grads as compressed rows
    inside the train step (reference engine.py:1459-1515); the trajectory
    must match the dense-psum engine exactly (the row budget covers every
    touched row)."""
    import jax
    import jax.numpy as jnp
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
    if len(jax.devices()) < 4:
        import pytest
        pytest.skip("need 4 devices")

    def run(sparse):
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "sparse_gradients": sparse,
            "gradient_clipping": 1.0,
            "steps_per_print": 1000, "seed": 11,
        }
        mesh = make_mesh(MeshConfig(data=4), devices=jax.devices()[:4])
        # untied embeddings: a tied LM head makes d(loss)/d(wte) dense
        # (every vocab row), which is exactly what the model's
        # sparse_grad_params property guards against
        model = GPT2LMHeadModel(gpt2_tiny(tie_word_embeddings=False))
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}
        losses = [float(engine.train_batch(batch)) for _ in range(6)]
        return losses, engine

    dense_losses, _ = run(False)
    sparse_losses, engine = run(True)
    assert sparse_losses[-1] < sparse_losses[0] - 0.3
    # first steps must match to float precision; later steps may drift by
    # reduction-order noise amplified through training (same convention as
    # test_zero's stage-parity tests)
    np.testing.assert_allclose(sparse_losses[:2], dense_losses[:2],
                               rtol=1e-4)
    np.testing.assert_allclose(sparse_losses, dense_losses, rtol=1e-2,
                               atol=1e-2)
    # the sparse engine really took the explicit-comm path
    assert engine._sparse_grad_active()
