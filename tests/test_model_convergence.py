"""End-to-end convergence tests — the reference's tests/model tier
(Megatron_GPT2 run_func_test.py compares loss curves across parallelism
configs; test_pipe.py compares pipeline vs DP convergence). Here: the same
tiny GPT-2 trained under different mesh/ZeRO configurations must produce
matching loss trajectories, since ZeRO/DP/TP re-sharding is mathematically
a no-op."""

import numpy as np
import pytest
import jax

import deepspeed_tpu as dstpu
from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig


def _train(mesh_cfg, zero_stage, steps=8, n_devices=1, seed=7):
    devs = jax.devices()[:n_devices]
    if len(devs) < n_devices:
        pytest.skip(f"need {n_devices} devices")
    mesh = make_mesh(mesh_cfg, devices=devs)
    cfg = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": zero_stage},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "steps_per_print": 1000,
        "seed": seed,
    }
    model = GPT2LMHeadModel(gpt2_tiny())
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}
    return [float(engine.train_batch(batch)) for _ in range(steps)]


def test_gpt2_converges():
    losses = _train(MeshConfig(data=1), zero_stage=0, steps=15)
    assert losses[-1] < losses[0] - 0.5, losses


def test_bf16_grad_accum_matches_fp32():
    """bf16 accumulation buffers (data_types.grad_accum_dtype) track the
    fp32-accumulated trajectory within bf16 rounding noise."""
    def run(accum):
        mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        cfg = {
            "train_batch_size": 8,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "data_types": {"grad_accum_dtype": accum},
            "steps_per_print": 1000, "seed": 3,
        }
        model = GPT2LMHeadModel(gpt2_tiny())
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}
        return [float(engine.train_batch(batch)) for _ in range(6)]

    base = run("fp32")
    got = run("bf16")
    assert got[-1] < got[0] - 0.3, got
    np.testing.assert_allclose(got, base, rtol=3e-2, atol=3e-2)


@pytest.mark.slow
def test_bf16_grad_dtype_matches_fp32():
    """grad_dtype=bf16 (params cast once inside the differentiated fn, all
    cotangents bf16) tracks the fp32-grad trajectory within rounding."""
    def run(gd):
        mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "data_types": {"grad_dtype": gd},
            "steps_per_print": 1000, "seed": 5,
        }
        model = GPT2LMHeadModel(gpt2_tiny())
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}
        return [float(engine.train_batch(batch)) for _ in range(6)]

    base = run("fp32")
    got = run("bf16")
    assert got[-1] < got[0] - 0.3, got
    np.testing.assert_allclose(got, base, rtol=3e-2, atol=3e-2)


@pytest.mark.slow
def test_bf16_moment_dtype_converges():
    """moment_dtype=bf16 (half-storage Adam moments) still converges and
    tracks fp32 moments closely over a short horizon."""
    def run(md):
        mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-2, "moment_dtype": md}},
            "steps_per_print": 1000, "seed": 5,
        }
        model = GPT2LMHeadModel(gpt2_tiny())
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
        rng = np.random.RandomState(0)
        batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}
        return [float(engine.train_batch(batch)) for _ in range(8)]

    base = run("fp32")
    got = run("bf16")
    assert got[-1] < got[0] - 0.5, got
    np.testing.assert_allclose(got, base, rtol=5e-2, atol=5e-2)


def test_chunked_lm_loss_matches_full():
    """The fused chunked head+loss must equal lm_loss(logits) — value AND
    gradients — including a pad remainder and ignore_index masking."""
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import (
        GPT2Config, GPT2LMHeadModel, lm_loss)
    cfg = dict(vocab_size=512, n_positions=96, n_embd=64, n_layer=2,
               n_head=2, dtype=jnp.float32)
    full = GPT2LMHeadModel(GPT2Config(**cfg))
    # chunk=40 does not divide B*(S-1)=3*95=285 → exercises padding
    fused = GPT2LMHeadModel(GPT2Config(**cfg, loss_chunk=40))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 512, (3, 96)).astype(np.int32)
    labels = np.where(rng.rand(3, 96) < 0.1, -100, ids).astype(np.int32)
    params = full.init(jax.random.PRNGKey(0), ids)["params"]

    def loss_full(p):
        return lm_loss(full.apply({"params": p}, ids), labels)

    def loss_fused(p):
        return fused.apply({"params": p}, ids, labels=labels)

    v1, g1 = jax.value_and_grad(loss_full)(params)
    v2, g2 = jax.value_and_grad(loss_fused)(params)
    np.testing.assert_allclose(v1, v2, rtol=1e-6)
    for k in g1:
        np.testing.assert_allclose(
            jax.tree_util.tree_leaves(g1[k])[0],
            jax.tree_util.tree_leaves(g2[k])[0], rtol=2e-4, atol=1e-6,
            err_msg=k)


@pytest.mark.slow
def test_zero_stages_match_single_device():
    base = _train(MeshConfig(data=1), zero_stage=0)
    for stage in (1, 2, 3):
        got = _train(MeshConfig(data=1), zero_stage=stage)
        # step 1 must match to float precision; later steps may drift by
        # reduction-order noise amplified through training (chaotic)
        np.testing.assert_allclose(got[0], base[0], rtol=1e-5,
                                   err_msg=f"stage {stage}")
        np.testing.assert_allclose(got, base, rtol=1e-2, atol=1e-2,
                                   err_msg=f"stage {stage}")


@pytest.mark.slow
def test_dp_zero_matches_single_device():
    """ZeRO sharding over a real data axis must not change the math
    (the reference's DP-vs-pipe convergence methodology).

    Slow (ISSUE 8 tier-1 wall consolidation): 4 engine compiles,
    ~21 s. Tier-1 keeps the same subsystem pinned by
    tests/test_zero.py::test_zero_stage_matches_stage0 (dp-mesh stage
    parity per stage) and tests/test_prefetch.py's dp8 engine-parity
    pins; the single-device-vs-dp4 drift bound re-runs with -m slow."""
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    base = _train(MeshConfig(data=1), zero_stage=0)
    for stage in (0, 2, 3):
        got = _train(MeshConfig(data=4), zero_stage=stage, n_devices=4)
        np.testing.assert_allclose(got[0], base[0], rtol=1e-4,
                                   err_msg=f"dp=4 stage {stage}")
        np.testing.assert_allclose(got, base, rtol=2e-2, atol=2e-2,
                                   err_msg=f"dp=4 stage {stage}")


@pytest.mark.slow
def test_tp_matches_single_device():
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    base = _train(MeshConfig(data=1), zero_stage=0)
    got = _train(MeshConfig(data=2, model=2), zero_stage=0, n_devices=4)
    np.testing.assert_allclose(got[0], base[0], rtol=1e-4)
    np.testing.assert_allclose(got, base, rtol=2e-2, atol=2e-2)


@pytest.mark.slow
def test_bert_tp_matches_single_device():
    """BERT gets Megatron specs from the sharding registry (VERDICT: TP
    derivation must not be GPT-2-only) — tp run matches single-device."""
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    import jax.numpy as jnp
    from deepspeed_tpu.models.bert import (
        BertConfig, BertForSequenceClassification)

    def run(mesh_cfg, n_dev):
        cfg_m = BertConfig(vocab_size=512, hidden_size=64,
                           num_hidden_layers=2, num_attention_heads=4,
                           intermediate_size=128, max_position_embeddings=64,
                           dtype=jnp.float32)
        model = BertForSequenceClassification(cfg_m, num_labels=4)

        def loss_fn(params, batch):
            x, y = batch
            import jax.numpy as jnp
            logits = model.apply({"params": params}, x,
                                 jnp.ones_like(x))
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

        cfg = {"train_batch_size": 8,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "steps_per_print": 1000, "seed": 7}
        mesh = make_mesh(mesh_cfg, devices=jax.devices()[:n_dev])
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model,
                                           loss_fn=loss_fn, mesh=mesh)
        rng = np.random.RandomState(0)
        batch = (rng.randint(0, 512, (8, 32)).astype(np.int32),
                 rng.randint(0, 4, (8,)).astype(np.int32))
        losses = [float(engine.train_batch(batch)) for _ in range(6)]
        return losses, engine

    base, _ = run(MeshConfig(data=1), 1)
    got, engine = run(MeshConfig(data=2, model=2), 4)
    assert engine._param_tp_specs is not None, "registry gave BERT no specs"
    np.testing.assert_allclose(got[0], base[0], rtol=1e-4)
    np.testing.assert_allclose(got, base, rtol=2e-2, atol=2e-2)


def test_tp_without_rules_warns():
    """A model-axis mesh with a rule-less model must announce the TP no-op
    loudly instead of silently replicating. (The package logger doesn't
    propagate to root, so attach a handler directly instead of caplog.)"""
    if len(jax.devices()) < 2:
        pytest.skip("need 2 devices")
    import logging
    from deepspeed_tpu.utils.logging import logger as dlog
    from tests.simple_model import SimpleModel, random_batch, base_config
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    dlog.addHandler(handler)
    try:
        cfg = base_config()
        cfg["train_batch_size"] = 8
        mesh = make_mesh(MeshConfig(model=2), devices=jax.devices()[:2])
        engine, _, _, _ = dstpu.initialize(config=cfg, model=SimpleModel(),
                                           mesh=mesh)
        engine.train_batch(random_batch())
    finally:
        dlog.removeHandler(handler)
    assert any("REPLICATED across the model axis" in r.getMessage()
               for r in records), [r.getMessage() for r in records]


@pytest.mark.slow
def test_elastic_checkpoint_across_mesh_resize(tmp_path):
    """Save under one parallel layout, restore under another, training must
    continue identically — the reference's elastic-checkpoint contract
    (zero/stage1.py:854 merge/re-split across changed dp;
    state_dict_factory.py:272 TP resharding). GSPMD arrays make this a
    device_put onto the new mesh's shardings."""
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel

    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")

    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}

    def make(mesh_cfg, stage, n_dev):
        mesh = make_mesh(mesh_cfg, devices=jax.devices()[:n_dev])
        cfg = {"train_batch_size": 8,
               "zero_optimization": {"stage": stage},
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "steps_per_print": 1000, "seed": 11}
        engine, _, _, _ = dstpu.initialize(
            config=cfg, model=GPT2LMHeadModel(gpt2_tiny()), mesh=mesh)
        return engine

    # train 3 steps on dp=1/stage0, save
    e1 = make(MeshConfig(data=1), 0, 1)
    for _ in range(3):
        e1.train_batch(batch)
    e1.save_checkpoint(str(tmp_path), tag="t")
    ref = [float(e1.train_batch(batch)) for _ in range(4)]

    # restore on dp=4/stage3 and on dp=2×tp=2, continue: same losses
    for mesh_cfg, stage, n in ((MeshConfig(data=4), 3, 4),
                               (MeshConfig(data=2, model=2), 1, 4)):
        e2 = make(mesh_cfg, stage, n)
        e2.load_checkpoint(str(tmp_path), tag="t")
        got = [float(e2.train_batch(batch)) for _ in range(4)]
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3,
                                   err_msg=f"{mesh_cfg} stage{stage}")


# ----------------------------------------------------- loss-curve matrix

# First-5-step goldens for the flagship tiny-GPT-2 config, generated on the
# CPU backend with fixed seeds. The tripwire against cross-feature numerics
# drift — the role of the reference's Megatron GPT-2 loss-curve matrix
# (tests/model/Megatron_GPT2/run_func_test.py). Offload rows differ from
# fused rows in step >=1 because the offload tier rests device params in
# compute dtype (bf16/fp16 roundtrip after each update) while the fused
# path keeps fp32 params; both are pinned.
#
# The goldens are host-μarch sensitive: XLA's CPU codegen vectorizes
# reductions differently per ISA (an AVX-512 box drifts every bf16/fp16
# cell up to ~1.2% from these AVX2-era values by step 5), so they are an
# ENVELOPE at _GOLDEN_ENVELOPE_RTOL, not a tight pin. The tight pin is
# in-process: every (stage, offload) cell must match its cell's stage-0
# trajectory computed on THIS host at _CROSS_STAGE_RTOL — resharding and
# the offload tier must be numerical no-ops regardless of ISA.
_MATRIX_GOLDENS = {
    # (dtype, stage, offload): losses
    ("bf16", 0, False): [6.24387, 5.84568, 5.66218, 5.42843, 5.57283],
    ("bf16", 0, True):  [6.24387, 5.84643, 5.66272, 5.42983, 5.57112],
    ("bf16", 2, False): [6.24387, 5.84568, 5.66218, 5.42843, 5.57283],
    ("bf16", 2, True):  [6.24387, 5.84643, 5.66272, 5.42983, 5.57112],
    ("bf16", 3, False): [6.24387, 5.84568, 5.66216, 5.42868, 5.57227],
    ("bf16", 3, True):  [6.24387, 5.84643, 5.66278, 5.42994, 5.57109],
    ("fp16", 0, False): [6.24387, 5.84568, 5.66218, 5.42843, 5.57283],
    ("fp16", 0, True):  [6.24383, 5.84774, 5.68697, 5.46854, 5.58664],
    ("fp16", 2, False): [6.24387, 5.84568, 5.66218, 5.42843, 5.57283],
    ("fp16", 2, True):  [6.24383, 5.84774, 5.68697, 5.46854, 5.58664],
    ("fp16", 3, False): [6.24387, 5.84568, 5.66216, 5.42868, 5.57227],
    ("fp16", 3, True):  [6.24383, 5.84774, 5.68693, 5.46832, 5.58652],
}


_GOLDEN_ENVELOPE_RTOL = 2.5e-2
_CROSS_STAGE_RTOL = 2e-3

# stage-0 trajectories per (dtype, offload), computed once on this host —
# the reference every stage-2/3 cell is tightly compared against
_matrix_stage0_cache = {}


def _matrix_train(dtype, stage, offload):
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    cfg = {
        "train_batch_size": 8,
        "zero_optimization": {"stage": stage},
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "steps_per_print": 1000, "seed": 11,
    }
    if dtype == "bf16":
        cfg["bf16"] = {"enabled": True}
    else:
        # scale_power 8: 2^16 overflows real fp16 grads for several steps
        # (correct dynamic-loss-scale behavior, but the matrix wants the
        # trajectory, not the warmup skips)
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    if offload:
        cfg["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
    model = GPT2LMHeadModel(gpt2_tiny())
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 512, (8, 64)).astype(np.int32)}
    return [float(engine.train_batch(batch)) for _ in range(5)]


@pytest.mark.parametrize("dtype", ["bf16", "fp16"])
@pytest.mark.parametrize("stage", [0, 2, 3])
@pytest.mark.parametrize("offload", [False, True])
@pytest.mark.slow
def test_flagship_loss_matrix(dtype, stage, offload):
    """VERDICT r3 item 10: every {stage} x {dtype} x {offload} cell of the
    flagship config reproduces its pinned 5-step trajectory (as a cross-host
    envelope), and ZeRO stages within a (dtype, offload) cell agree tightly
    with the stage-0 trajectory computed on this host."""
    got = _matrix_train(dtype, stage, offload)
    golden = _MATRIX_GOLDENS[(dtype, stage, offload)]
    np.testing.assert_allclose(got, golden, rtol=_GOLDEN_ENVELOPE_RTOL,
                               err_msg=f"{dtype} stage{stage} offload={offload}")
    # cross-stage consistency: resharding must be a numerical no-op
    if (dtype, offload) not in _matrix_stage0_cache:
        _matrix_stage0_cache[(dtype, offload)] = (
            got if stage == 0 else _matrix_train(dtype, 0, offload))
    base = _matrix_stage0_cache[(dtype, offload)]
    np.testing.assert_allclose(got, base, rtol=_CROSS_STAGE_RTOL,
                               err_msg=f"stage{stage} vs stage0 drift")
