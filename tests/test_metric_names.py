"""Observability fast gate (ISSUE 12 satellites, wired into
ci/telemetry_gate.sh):

- metric-name drift guard: every metric name documented in
  docs/observability.md's tables must still be emitted by the code,
  and every ``cluster/*`` name the code can emit must be documented —
  the docs stop rotting per PR;
- prometheus_text grammar round-trip: the exposition page (HELP/TYPE
  lines, escaped label values, histogram quantile gauges, the new
  cluster gauges) must parse under the openmetrics line grammar a real
  scraper applies;
- viewer import guard: ``import deepspeed_tpu.telemetry.view`` must
  succeed with jax IMPORT-POISONED — the viewer is documented as
  stdlib-only ("runs anywhere the dump landed") and the lazy package
  root (PEP 562) is what keeps that true; this test enforces it.

Everything here is fast and accelerator-free.
"""

import os
import pathlib
import re
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
DOCS = REPO / "docs" / "observability.md"
PKG = REPO / "deepspeed_tpu"

# metric-name shape: subsystem/metric[/...], possibly with one-or-more
# {a,b,c} alternation groups (the docs' compact row form)
_NAME_RE = re.compile(r"^[a-z0-9_]+(/[a-z0-9_{},]+)+$")


def _expand(name):
    """`a/{b,c}/d` -> [`a/b/d`, `a/c/d`] (repeatedly)."""
    m = re.search(r"\{([^{}]*)\}", name)
    if not m:
        return [name]
    out = []
    for alt in m.group(1).split(","):
        out.extend(_expand(name[:m.start()] + alt + name[m.end():]))
    return out


def documented_metric_names():
    """Backticked metric names from the first cell of every markdown
    table row in docs/observability.md, alternations expanded."""
    names = set()
    for line in DOCS.read_text().splitlines():
        if not line.startswith("| `"):
            continue
        first_cell = line.split("|")[1]
        for tok in re.findall(r"`([^`]+)`", first_cell):
            if _NAME_RE.match(tok):
                names.update(_expand(tok))
    assert names, "no metric tables found — did observability.md move?"
    return names


def _package_source():
    return "\n".join(p.read_text() for p in sorted(PKG.rglob("*.py")))


def test_documented_metric_names_are_emitted():
    """Every documented name must appear in the package source — either
    as the full literal, or (for the f-string-built families like
    ``span/<tag>`` and ``memory/<key>``) as the literal tail after the
    subsystem prefix. A doc row whose metric was renamed in code fails
    here instead of rotting."""
    src = _package_source()
    missing = []
    for name in sorted(documented_metric_names()):
        if name.startswith(("cluster/", "slo/")):
            continue   # pinned exactly (both directions) by the
            #            programmatic tests below — they are f-string
            #            built, so no literal to find here
        tail = name.split("/", 1)[1]
        if name in src or tail in src:
            continue
        missing.append(name)
    assert not missing, (
        "documented in docs/observability.md but not found in the "
        "code (renamed? removed?): " + ", ".join(missing))


def test_cluster_metric_names_documented_both_directions():
    """The ``cluster/*`` namespace is pinned EXACTLY: emitted ⊆
    documented (an undocumented new gauge fails) and documented ⊆
    emitted (a doc row for a dropped gauge fails). cluster.py is
    importable jax-free, so this runs without an accelerator."""
    from deepspeed_tpu.telemetry.cluster import cluster_metric_names
    emitted = set(cluster_metric_names())
    documented = {n for n in documented_metric_names()
                  if n.startswith("cluster/")}
    assert emitted - documented == set(), (
        "emitted but undocumented cluster/* names — add them to the "
        "docs/observability.md cluster table: "
        + ", ".join(sorted(emitted - documented)))
    assert documented - emitted == set(), (
        "documented but no longer emitted cluster/* names: "
        + ", ".join(sorted(documented - emitted)))


def test_slo_metric_names_documented_both_directions():
    """The ``slo/*`` namespace (ISSUE 19) is pinned EXACTLY like
    cluster/*: emitted ⊆ documented and documented ⊆ emitted, against
    ``telemetry.slo.slo_metric_names()``. slo.py is stdlib-only, so
    this runs anywhere."""
    from deepspeed_tpu.telemetry.slo import slo_metric_names
    emitted = set(slo_metric_names())
    documented = {n for n in documented_metric_names()
                  if n.startswith("slo/")}
    assert emitted - documented == set(), (
        "emitted but undocumented slo/* names — add them to the "
        "docs/observability.md slo table: "
        + ", ".join(sorted(emitted - documented)))
    assert documented - emitted == set(), (
        "documented but no longer emitted slo/* names: "
        + ", ".join(sorted(documented - emitted)))


def test_cluster_fences_counts_on_every_rank(monkeypatch):
    """The PR-12 asymmetry fix (ISSUE 19 satellite), pinned: the
    ``cluster/fences`` counter increments in ``exchange()`` on EVERY
    rank — a non-zero rank's registry must show its fences, not 0
    (the old behavior: only the rank-0 fold counted)."""
    import numpy as np
    from deepspeed_tpu.telemetry.cluster import (CLUSTER_METRICS,
                                                 ClusterAggregator)
    from deepspeed_tpu.telemetry.registry import MetricsRegistry

    world, me = 3, 1          # a NON-fold rank
    mat = np.zeros((world, len(CLUSTER_METRICS)), np.float32)
    monkeypatch.setattr(
        "deepspeed_tpu.utils.distributed.allgather_host_floats",
        lambda vec: (mat, me))
    reg = MetricsRegistry()
    agg = ClusterAggregator(registry=reg)
    for _ in range(4):
        agg.exchange({"step_time_s": 0.1})
    assert agg.rank == 1 and agg.fences == 4
    assert reg.counter("cluster/fences").value == 4
    # and the fold-side gauges did NOT appear on this rank
    assert reg.peek_gauge("cluster/step_time_s/max") is None


def test_router_metric_names_documented_both_directions():
    """The ``router/*`` namespace (ISSUE 14) is pinned EXACTLY like
    cluster/*: emitted ⊆ documented and documented ⊆ emitted, against
    ``serving.router.router_metric_names()``."""
    from deepspeed_tpu.serving.router import router_metric_names
    emitted = set(router_metric_names())
    documented = {n for n in documented_metric_names()
                  if n.startswith("router/")}
    assert emitted - documented == set(), (
        "emitted but undocumented router/* names — add them to the "
        "docs/observability.md router table: "
        + ", ".join(sorted(emitted - documented)))
    assert documented - emitted == set(), (
        "documented but no longer emitted router/* names: "
        + ", ".join(sorted(documented - emitted)))


def test_handoff_serving_metric_names_documented():
    """The handoff/TTFT-attribution additions to the serving/*
    namespace (ISSUE 14) must be documented — and stay emitted (the
    generic documented→source test covers the reverse direction)."""
    documented = documented_metric_names()
    for name in ("serving/ttft_queue_wait_s", "serving/ttft_prefill_s",
                 "serving/handoff_s", "serving/transport_s",
                 "serving/transport_encode_s",
                 "serving/transport_collective_s",
                 "serving/transport_decode_s",
                 "serving/first_decode_tick_s",
                 "serving/handoffs_out", "serving/handoffs_in"):
        assert name in documented, (
            f"{name} missing from the docs/observability.md serving "
            f"table")
        assert name in _package_source(), name


def test_o_direct_metric_names_documented():
    """The O_DIRECT swap-tier additions (ISSUE 20): the device-truth
    bandwidth gauges and the buffered-fallback breadcrumb counter must
    stay documented AND emitted."""
    documented = documented_metric_names()
    for name in ("swap/device_read_mb_s", "swap/device_write_mb_s",
                 "swap/o_direct_fallback"):
        assert name in documented, (
            f"{name} missing from the docs/observability.md swap table")
        assert name in _package_source(), name


# ------------------------------------------------------- prometheus page

# the exposition-format line grammar a real scraper applies
# (https://prometheus.io/docs/instrumenting/exposition_formats/):
_PROM_METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_PROM_LABELS = r'\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"' \
               r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\}'
_PROM_VALUE = r"[-+]?(?:[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?|" \
              r"[Nn]a[Nn]|[+-]?[Ii]nf)"
SAMPLE_LINE = re.compile(
    rf"^({_PROM_METRIC_NAME})(?:{_PROM_LABELS})? ({_PROM_VALUE})"
    rf"(?: [0-9]+)?$")
HELP_LINE = re.compile(rf"^# HELP ({_PROM_METRIC_NAME}) .*$")
TYPE_LINE = re.compile(
    rf"^# TYPE ({_PROM_METRIC_NAME}) "
    rf"(counter|gauge|summary|histogram|untyped)$")


def test_prometheus_text_roundtrips_the_openmetrics_grammar():
    from deepspeed_tpu.telemetry.registry import (MetricsRegistry,
                                                  prometheus_text)
    from deepspeed_tpu.telemetry.cluster import (ClusterAggregator,
                                                 cluster_metric_names)
    reg = MetricsRegistry()
    reg.counter("train/steps").inc(7)
    reg.gauge("serving/page_pool_occupancy").set(0.25)
    # histogram -> summary family with quantile label gauges
    h = reg.histogram("serving/ttft_s")
    for v in (0.1, 0.2, 0.4, 1.5):
        h.observe(v)
    # a name needing mangling + a digit-leading name
    reg.gauge("weird-metric.name/with spaces").set(1.0)
    reg.counter("0starts_with_digit/x").inc()
    # the new cluster gauges via a real fold (world of 3, one NaN rank)
    agg = ClusterAggregator(registry=reg)
    agg.world = 3
    agg.rank = 0
    import numpy as np
    mat = np.asarray(
        [[0.1, 0.0, 0.0, 2.0, 100.0, 1.0, 0.5],
         [0.3, 0.0, 0.0, 2.1, 110.0, 1.0, 0.5],
         [np.nan, np.nan, np.nan, np.nan, np.nan, np.nan, np.nan]],
        np.float32)
    agg._fold(mat, step=4)

    text = prometheus_text(reg)
    families = {}
    last_help = None
    for line in text.strip().splitlines():
        m = HELP_LINE.match(line)
        if m:
            last_help = m.group(1)
            continue
        m = TYPE_LINE.match(line)
        if m:
            # HELP must immediately precede TYPE for the same family
            assert m.group(1) == last_help, line
            families[m.group(1)] = m.group(2)
            continue
        m = SAMPLE_LINE.match(line)
        assert m, f"line fails the exposition grammar: {line!r}"
        base = re.sub(r"_(sum|count)$", "", m.group(1)) \
            if m.group(1).endswith(("_sum", "_count")) else m.group(1)
        assert base in families or m.group(1) in families, (
            f"sample before its # TYPE header: {line!r}")
    # quantile-labeled summary lines present and parseable
    assert 'serving_ttft_s{quantile="0.5"}' in text
    assert families["serving_ttft_s"] == "summary"
    # cluster gauges made it onto the page, mangled names intact
    assert "cluster_step_time_s_max" in families
    n_cluster = sum(1 for f in families if f.startswith("cluster_"))
    assert n_cluster >= len(cluster_metric_names()) - 1  # fences is a
    #         counter emitted by exchange(), not _fold — tolerate ±1


def test_prometheus_label_escaping_survives_a_scraper_regex():
    from deepspeed_tpu.telemetry.registry import (_prom_escape_label,
                                                  _prom_escape_help)
    nasty = 'a"b\\c\nd'
    esc = _prom_escape_label(nasty)
    line = f'metric{{rule="{esc}"}} 1.0'
    assert SAMPLE_LINE.match(line), line
    assert "\n" not in esc
    help_line = f"# HELP metric {_prom_escape_help(nasty)}"
    assert HELP_LINE.match(help_line), help_line


# ------------------------------------------------------ viewer jax-free

def test_viewer_import_chain_is_stdlib_only(tmp_path):
    """ISSUE 12 satellite: the dump viewer's documented stdlib-only
    contract, ENFORCED — `import deepspeed_tpu.telemetry.view` in a
    fresh interpreter with BOTH jax and numpy import-poisoned via
    stubs first on sys.path ("runs anywhere the dump landed" includes
    machines with neither). The package root AND telemetry/__init__
    resolve their public surfaces lazily (PEP 562) precisely so this
    passes; an eager jax/numpy import anywhere in the chain fails
    here. telemetry.serve (stdlib http.server) must ride along;
    telemetry.cluster legitimately needs numpy and is exempt."""
    for name in ("jax", "numpy"):
        (tmp_path / f"{name}.py").write_text(
            f"raise ImportError('poisoned: the viewer must not import "
            f"{name}')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}" \
        + (os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    r = subprocess.run(
        [sys.executable, "-c",
         "import deepspeed_tpu.telemetry.view as v; "
         "import deepspeed_tpu.telemetry.serve; "
         "import deepspeed_tpu.telemetry.slo; "
         "import deepspeed_tpu.telemetry.perfetto as p; "
         "print('STDLIB_OK', callable(v.render) and "
         "callable(p.export))"],
        env=env, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (
        f"viewer import chain pulled jax/numpy (or crashed):\n{r.stderr}")
    assert "STDLIB_OK True" in r.stdout


def test_viewer_render_accepts_a_single_pathlike(tmp_path):
    """The pre-ISSUE-12 render(path) signature keeps working for str
    AND PathLike single arguments next to the new list form."""
    import pathlib

    from deepspeed_tpu.telemetry import view
    p = tmp_path / "d.jsonl"
    p.write_text('{"kind": "loss", "step": 1, "loss": 2.0, "ts": 1.0, '
                 '"seq": 1}\n')
    for arg in (str(p), pathlib.Path(p), [str(p)]):
        out = "\n".join(view.render(arg))
        assert "per-step phase attribution" in out


def test_lazy_package_root_still_resolves_the_public_surface():
    """The PEP 562 root must behave exactly like the old eager imports
    for real users: attribute access resolves and caches."""
    import deepspeed_tpu as dstpu
    assert callable(dstpu.initialize)
    assert callable(dstpu.add_config_arguments)
    assert dstpu.DeepSpeedConfig is not None
    assert dstpu.MeshConfig is not None
    assert dstpu.zero is not None          # deepspeed.zero parity alias
    # subpackage attributes the eager root implicitly bound must stay
    # reachable (`d.parallel.mesh.make_mesh` was valid user code)
    assert dstpu.parallel.mesh.make_mesh is not None
    assert dstpu.config.config.DeepSpeedConfig is dstpu.DeepSpeedConfig
    assert "DeepSpeedEngine" in dir(dstpu)
    with pytest.raises(AttributeError):
        dstpu.no_such_symbol_anywhere
