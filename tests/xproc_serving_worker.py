"""Shared cross-process serving worker (ISSUE 17).

One ranked OS process of the ``transport: "process"`` fabric: rank 0
runs the router + prefill engine (:class:`PrefillNode`), every other
rank one decode engine (:class:`DecodeNode`). The SAME module backs

- the 2-real-process acceptance tests (tests/test_serving_transport.py,
  launched through the PR-10 ``spawn_workers`` harness),
- the supervisor SIGKILL fault acceptance (launched as the
  ``Supervisor`` worker command with ``roles={0: "prefill", ...}``),
- the bench xproc leg (tests/perf/serving_bench.py
  ``run_disagg_xproc_bench``).

Stdout protocol (machine-parsed by all three callers), one line each::

    RES <rid> <json done-doc>    per finished request   (rank 0 only)
    MET <json>                   final stats + metric summaries

Filesystem under ``out_dir`` (argv[1]):

- ``ledger.json``   rank 0: every submitted request's wire doc,
  written ATOMICALLY before serving starts (replica_pool.save_ledger)
  — the PR-11 pool-ledger discipline applied across processes. A
  respawned epoch reloads it and re-serves ONLY the unfinished rids.
- ``results.jsonl`` rank 0: append-only finished streams (fsynced per
  line, so a SIGKILL between lines loses at most the request it was
  mid-appending — which the ledger then replays).
- ``flight_rank*.jsonl``  per-rank/per-epoch recorder dumps
  (``Watchdog.force_dump`` at clean exit; a SIGKILLed rank writes
  nothing — the router rank's "finish" authority closes its traces).

Env contract: the spawn_workers / Supervisor variables
(``DSTPU_COORDINATOR_*``, ``DSTPU_PROCESS_ID`` ...) plus the
supervisor's ``DSTPU_RESTART_EPOCH`` / ``DSTPU_HEARTBEAT_DIR`` /
``DSTPU_SERVING_ROLE``. argv: ``out_dir [n_reqs] [max_new]
[kill_after] [slots] [num_blocks] [addressing] [tick_cap]`` —
``kill_after >= 0`` arms a RANK-1 decode self-SIGKILL after that many
deliveries, EPOCH 0 ONLY (the fault under test; pinned to rank 1 so a
D>=2 world loses exactly one decode rank). ``slots``/``num_blocks``
size the engine geometry per leg (ISSUE 18: the default 2-slot pool
made the bench TTFT tail pure queue wait — benches must say which
geometry they measured); ``addressing`` picks the wire mode
(targeted|broadcast); ``tick_cap > 0`` overrides
``serving.router.decode_tick_cap`` (the scale-out bench uses 1 so
streams stay resident long enough to saturate every rank's slots).
"""

import json
import os
import signal
import sys

import jax

jax.config.update("jax_platforms", "cpu")

from deepspeed_tpu.utils.distributed import init_distributed  # noqa: E402

REQ_SEED = 1
VOCAB = 256
PROMPT_LENS = (5, 9, 14, 21)


def build_model():
    """The tiny deterministic GPT-2 the serving tests share (the
    ``gpt2_dis`` fixture geometry) — every rank builds identical
    params from PRNGKey(0)."""
    import numpy as np
    import jax.numpy as jnp
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config(vocab_size=VOCAB, n_positions=128, n_embd=64,
                     n_layer=2, n_head=4, dtype=jnp.float32,
                     param_dtype=jnp.float32, scan_layers=True)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    return cfg, params


def serving_config(slots=2, num_blocks=0, addressing="targeted",
                   tick_cap=0):
    sv = {"slots": int(slots), "page_size": 8,
          "max_pages_per_slot": 8,
          "disaggregation": {"transport": "process",
                             "addressing": str(addressing)}}
    if int(num_blocks) > 0:
        sv["num_blocks"] = int(num_blocks)
    if int(tick_cap) > 0:
        sv["router"] = {"decode_tick_cap": int(tick_cap)}
    return {"serving": sv}


def build_requests(n_reqs, max_new):
    import numpy as np
    import deepspeed_tpu.serving as serving
    rs = np.random.RandomState(REQ_SEED)
    lens = rs.choice(PROMPT_LENS, n_reqs)
    return [serving.Request(
        i, rs.randint(0, VOCAB, size=(int(L),)).astype(np.int32),
        max_new_tokens=max_new) for i, L in enumerate(lens)]


def _append_result(path, doc):
    # crash-safe append: one fsynced line per finished stream
    with open(path, "a") as fh:
        fh.write(json.dumps(doc) + "\n")
        fh.flush()
        os.fsync(fh.fileno())


def _load_results(path):
    out = {}
    if os.path.exists(path):
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    doc = json.loads(line)
                    out[doc["rid"]] = doc
    return out


def main(argv):
    out_dir = argv[1]
    n_reqs = int(argv[2]) if len(argv) > 2 else 8
    max_new = int(argv[3]) if len(argv) > 3 else 6
    kill_after = int(argv[4]) if len(argv) > 4 else -1
    slots = int(argv[5]) if len(argv) > 5 else 2
    num_blocks = int(argv[6]) if len(argv) > 6 else 0
    addressing = argv[7] if len(argv) > 7 else "targeted"
    tick_cap = int(argv[8]) if len(argv) > 8 else 0
    os.makedirs(out_dir, exist_ok=True)

    init_distributed()
    rank = int(jax.process_index())
    world = int(jax.process_count())
    epoch = int(os.environ.get("DSTPU_RESTART_EPOCH", "0"))

    from deepspeed_tpu.runtime.elastic.hang import HangWatchdog
    from deepspeed_tpu.telemetry.anomaly import Watchdog
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    from deepspeed_tpu.telemetry.registry import MetricsRegistry
    import deepspeed_tpu.serving as serving
    from deepspeed_tpu.serving import elastic, replica_pool
    from deepspeed_tpu.serving.engine import ensure_trace_id

    rec = FlightRecorder()
    reg = MetricsRegistry()
    hw = None
    hb_dir = os.environ.get("DSTPU_HEARTBEAT_DIR")
    if hb_dir:
        # beat-only watchdog: a generous deadline and no dispatch marks
        # — the supervisor needs the liveness file, not hang detection
        hw = HangWatchdog(600.0, rank=rank, world=world, recorder=rec,
                          heartbeat_dir=hb_dir,
                          heartbeat_interval_s=0.1, restart_epoch=epoch)

    cfg, params = build_model()
    node = serving.build_transport_node(
        "gpt2", cfg, params,
        config=serving_config(slots, num_blocks, addressing, tick_cap),
        registry=reg, recorder=rec)

    def _hist(name):
        return reg.histogram(name).summary()

    def _slot_util(stats):
        cap = stats.get("slot_cap_ticks", 0)
        return (stats.get("slot_busy_ticks", 0) / cap) if cap else 0.0

    if rank == 0:
        ledger_path = os.path.join(out_dir, "ledger.json")
        results_path = os.path.join(out_dir, "results.jsonl")
        finished = _load_results(results_path)
        docs = replica_pool.load_ledger(ledger_path)
        if docs is None:
            reqs = build_requests(n_reqs, max_new)
            for r in reqs:
                ensure_trace_id(r)   # the ledgered trace identity is
                #                      the one every epoch's events use
            replica_pool.save_ledger(
                ledger_path, {r.rid: elastic._req_doc(r) for r in reqs})
        else:
            # respawned epoch: replay ONLY the unfinished rids from
            # their ledger docs (greedy replay is token-lossless), and
            # re-record the already-finished streams so THIS epoch's
            # dump closes every trace the incident interrupted
            reqs = [elastic.resume_request(doc)
                    for rid, doc in sorted(docs.items(),
                                           key=lambda kv: int(kv[0]))
                    if str(rid) not in {str(k) for k in finished}]
            for doc in finished.values():
                rec.record("finish", rid=doc["rid"],
                           trace=doc.get("trace_id"),
                           reason=doc.get("finish_reason"),
                           generated=doc.get("generated"))
        node.on_done = lambda doc: _append_result(results_path, doc)
        done = dict(node.serve(reqs))
        for rid, doc in finished.items():
            done.setdefault(int(rid) if str(rid).isdigit() else rid,
                            doc)
        for rid in sorted(done, key=int):
            print("RES", rid, json.dumps(done[rid]), flush=True)
        met = {"rank": rank, "epoch": epoch, "role": "prefill",
               "stats": node.stats,
               "counters": reg.snapshot()["counters"],
               "ttft_s": reg.histogram("serving/ttft_s").summary(),
               "ttft_queue_wait_s": _hist("serving/ttft_queue_wait_s"),
               "ttft_prefill_s": _hist("serving/ttft_prefill_s"),
               "transport_encode_s": _hist("serving/transport_encode_s"),
               "transport_collective_s": _hist(
                   "serving/transport_collective_s"),
               "slot_util": _slot_util(node.stats),
               "slots": slots,
               "page_nbytes": node.engines[0].cache.page_nbytes,
               "leak_fence": _fence(node.engines)}
    else:
        if kill_after >= 0 and epoch == 0 and rank == 1:
            def _boom(n):
                if n.stats["delivered"] >= kill_after:
                    # mid-stream by construction: the request just
                    # adopted has generated nothing on this rank yet
                    os.kill(os.getpid(), signal.SIGKILL)
            node.on_absorb = _boom
        node.run()
        met = {"rank": rank, "epoch": epoch, "role": "decode",
               "stats": node.stats,
               "counters": reg.snapshot()["counters"],
               "transport_s": _hist("serving/transport_s"),
               "transport_collective_s": _hist(
                   "serving/transport_collective_s"),
               "transport_decode_s": _hist("serving/transport_decode_s"),
               "slot_util": _slot_util(node.stats),
               "slots": slots,
               "decode_tokens": node.engine.stats["decode_tokens"],
               "absorbed_pages": node.absorbed_pages,
               "done": node.done_count,
               "leak_fence": _fence([node.engine])}

    wd = Watchdog(out_dir, recorder=rec, registry=reg,
                  source=f"rank{rank}e{epoch}")
    wd.force_dump("worker_exit")
    print("MET", json.dumps(met), flush=True)
    if hw is not None:
        hw.stop()


def _fence(engines):
    """num_blocks - 1 free pages after a sweep on every pool = no leak
    survived the run (the PR-14 invariant, now held across processes)."""
    out = []
    for cb in engines:
        cb.cache.sweep_prefix_cache()
        out.append({"replica": cb.replica_id,
                    "free": int(cb.cache.free_pages),
                    "want": int(cb.cache.num_blocks - 1)})
    return out


if __name__ == "__main__":
    main(sys.argv)
