"""ZeRO-3 layer-wise parameter-gather prefetch pipeline tests
(parallel/prefetch.py + the engine's ``stage3_prefetch`` train path).

The numerics contract: the double-buffered per-layer gather scan (and
its reverse re-gather + reduce-scatter backward) must reproduce the
fused GSPMD stage-3 path at fp32 rounding tolerance — losses AND
updated (sharded-at-rest) params, across layer counts, mesh shapes,
gather modes, and gradient accumulation. Plus: the functional
``prefetch_apply`` twin pins to ``model.apply`` exactly, the gating
falls back where the pipeline can't run, and the live gathered-param
accounting (the ``stage3_max_live_parameters`` observable) reports the
structural 2-layer double buffer.
"""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu as dstpu
from deepspeed_tpu.parallel import prefetch
from deepspeed_tpu.parallel.mesh import shard_map, make_mesh, MeshConfig
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

N = 8


def _mesh():
    devs = jax.devices()
    assert len(devs) >= N
    return Mesh(np.asarray(devs[:N]), ("data",))


# ---------------------------------------------------------------------------
# plan + packing units
# ---------------------------------------------------------------------------

def test_plan_from_specs():
    leaves = [jnp.zeros((4, 16, 32)), jnp.zeros((4, 8)), jnp.zeros((3,))]
    specs = [P(None, None, "data"), P(None, "data"), P()]
    plan = prefetch.plan_from_specs(leaves, specs, "data", N)
    assert plan == [(2, 4), (1, 1), None]


def test_build_layer_plan_rejects_layer_dim_shard():
    leaves = [jnp.zeros((8, 4))]
    with pytest.raises(AssertionError):
        prefetch.build_layer_plan(leaves, [(0, 1)], N)


def test_chunk_major_roundtrip():
    full = jnp.arange(2 * 24).reshape(2, 24).astype(jnp.float32)
    chunks = prefetch._chunks_from_full(full, 1, N)
    assert chunks.shape == (N, 2, 3)
    back = prefetch._full_from_chunks(chunks, 1)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(full))


@pytest.mark.parametrize("mode", ["ring", "fused"])
def test_gather_scatter_leaf_roundtrip(mode):
    """gather_leaf rebuilds the full leaf from per-device shards, and
    scatter_grad of a replicated cotangent returns each device n x its
    own chunk (the SUM-over-axis contract)."""
    mesh = _mesh()
    full = jnp.asarray(
        np.random.RandomState(0).randn(6, N * 4).astype(np.float32))

    @jax.jit
    @functools.partial(shard_map, mesh=mesh,
                       in_specs=P(None, "data"),
                       out_specs=(P(None, None, "data"), P(None, "data")),
                       check_vma=False)
    def run(shard):
        g = prefetch.gather_leaf(shard, (1, 4), "data", N, mode)
        s = prefetch.scatter_grad(g, (1, 4), "data", N, mode)
        return g[:, :, None], s

    gathered, scattered = run(full)
    for dev in range(N):
        np.testing.assert_allclose(np.asarray(gathered[:, :, dev]),
                                   np.asarray(full), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(scattered),
                               np.asarray(full) * N, rtol=1e-5)


# ---------------------------------------------------------------------------
# the prefetched scan vs a plain scan (grads included)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["ring", "fused"])
def test_prefetched_scan_matches_plain_scan(mode):
    L, D = 3, 16
    mesh = _mesh()
    r = np.random.RandomState(0)
    W = jnp.asarray(r.randn(L, D, D).astype(np.float32)) * 0.3
    B = jnp.asarray(r.randn(L, D).astype(np.float32)) * 0.1
    x0 = jnp.asarray(r.randn(4, D).astype(np.float32))

    def body(x, lt):
        return jnp.tanh(x @ lt["w"] + lt["b"])

    def ref_loss(params, x):
        def step(c, wb):
            return body(c, {"w": wb[0], "b": wb[1]}), None
        y, _ = jax.lax.scan(step, x, (params["w"], params["b"]))
        return jnp.sum(y ** 2)

    ref_g = jax.grad(ref_loss)({"w": W, "b": B}, x0)
    plan = [None, (2, D // N)]        # leaves order: b, w

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=({"b": P(), "w": P(None, None, "data")}, P()),
        out_specs=(P(), {"b": P(), "w": P(None, None, "data")}),
        check_vma=False)
    def run(shards, x):
        sfn = prefetch.make_prefetched_scan(body, plan, "data", N,
                                            mode=mode)
        loss, g = jax.value_and_grad(
            lambda sh: jnp.sum(sfn(x, sh) ** 2))(shards)
        return loss, g

    loss, g = run({"w": W, "b": B}, x0)
    np.testing.assert_allclose(float(loss),
                               float(ref_loss({"w": W, "b": B}, x0)),
                               rtol=1e-5)
    # x replicated here, so every device computed the full loss: sharded
    # leaves come back as the SUM over the axis (N x), replicated local
    np.testing.assert_allclose(np.asarray(g["w"]),
                               np.asarray(ref_g["w"]) * N,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g["b"]), np.asarray(ref_g["b"]),
                               rtol=1e-4, atol=1e-5)


def test_prefetched_scan_all_replicated_degenerate():
    """Persistence threshold can leave every layer leaf replicated — the
    scan must degrade to a plain gather-free scan with local grads."""
    L, D = 2, 8
    mesh = _mesh()
    r = np.random.RandomState(1)
    W = jnp.asarray(r.randn(L, D, D).astype(np.float32)) * 0.3
    x0 = jnp.asarray(r.randn(2, D).astype(np.float32))

    def body(x, lt):
        return jnp.tanh(x @ lt["w"])

    def ref_loss(w, x):
        y, _ = jax.lax.scan(lambda c, wi: (body(c, {"w": wi}), None), x, w)
        return jnp.sum(y ** 2)

    @jax.jit
    @functools.partial(shard_map, mesh=mesh, in_specs=(P(), P()),
                       out_specs=(P(), P()), check_vma=False)
    def run(w, x):
        sfn = prefetch.make_prefetched_scan(body, [None], "data", N)
        return jax.value_and_grad(
            lambda sh: jnp.sum(sfn(x, sh) ** 2))({"w": w})

    loss, g = run(W, x0)
    np.testing.assert_allclose(float(loss), float(ref_loss(W, x0)),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g["w"]),
                               np.asarray(jax.grad(ref_loss)(W, x0)),
                               rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# the functional model twin
# ---------------------------------------------------------------------------

def _naive_scan(body, x, h):
    def step(c, lp):
        return body(c, lp), None
    y, _ = jax.lax.scan(step, x, h)
    return y


@pytest.mark.parametrize("tie,chunk", [(True, 0), (False, 0), (True, 16)])
def test_prefetch_apply_matches_model_apply(tie, chunk):
    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=64, n_layer=2,
                     n_head=2, dtype=jnp.float32, param_dtype=jnp.float32,
                     scan_layers=True, tie_word_embeddings=tie,
                     loss_chunk=chunk)
    model = GPT2LMHeadModel(cfg)
    ids = np.random.RandomState(0).randint(0, 512, (2, 32)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    labels = ids if chunk else None
    ref = model.apply({"params": params}, ids, labels=labels)
    got = model.prefetch_apply(params, ids, _naive_scan, labels=labels)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=1e-6, atol=1e-6)
    assert model.prefetch_layer_subtree == "h"


def test_prefetch_contract_gated_off():
    # unrolled layers / MoE / dropout cannot offer the layered contract
    assert GPT2LMHeadModel(GPT2Config(scan_layers=False)) \
        .prefetch_layer_subtree is None
    assert GPT2LMHeadModel(GPT2Config(moe_experts=4)) \
        .prefetch_layer_subtree is None
    assert GPT2LMHeadModel(GPT2Config(dropout=0.1)) \
        .prefetch_layer_subtree is None


# ---------------------------------------------------------------------------
# engine integration: stage3_prefetch == fused GSPMD stage 3
# ---------------------------------------------------------------------------

def _gpt2_tiny(n_layer=2, **kw):
    base = dict(vocab_size=512, n_positions=64, n_embd=64, n_layer=n_layer,
                n_head=2, dtype=jnp.float32, param_dtype=jnp.float32,
                scan_layers=True)
    base.update(kw)
    return GPT2Config(**base)


def _train(prefetch_on, data=N, n_layer=2, steps=3, gas=1, mode="ring",
           optimizer=None, bf16=False, model=None, cm=None):
    cfg = {
        "train_batch_size": 8 * gas,
        "gradient_accumulation_steps": gas,
        "zero_optimization": {"stage": 3, "stage3_prefetch": prefetch_on,
                              "stage3_prefetch_gather": mode,
                              "stage3_param_persistence_threshold": 0,
                              **({"collective_matmul": cm} if cm else {})},
        "optimizer": optimizer or {"type": "AdamW",
                                   "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
    }
    if bf16:
        cfg["bf16"] = {"enabled": True}
        cfg["data_types"] = {"grad_dtype": "bf16"}
    mesh = make_mesh(MeshConfig(data=data), devices=jax.devices()[:data])
    model = model if model is not None \
        else GPT2LMHeadModel(_gpt2_tiny(n_layer, dtype=(
            jnp.bfloat16 if bf16 else jnp.float32)))
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
    batch = {"input_ids": np.random.RandomState(0).randint(
        0, 512, (8 * gas, 64)).astype(np.int32)}
    losses = [float(engine.train_batch(batch)) for _ in range(steps)]
    params = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32),
                                    engine.state.params)
    return engine, losses, params


_BASELINE = {}


def _fused_baseline(data=N, n_layer=2, gas=1, bf16=False):
    key = (data, n_layer, gas, bf16)
    if key not in _BASELINE:
        eng, losses, params = _train(False, data=data, n_layer=n_layer,
                                     gas=gas, bf16=bf16)
        assert not eng._prefetch_active()
        _BASELINE[key] = (losses, params)
    return _BASELINE[key]


def _assert_matches(got, want, rtol=2e-5, atol=1e-5):
    loss_g, params_g = got
    loss_w, params_w = want
    np.testing.assert_allclose(loss_g, loss_w, rtol=rtol)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(params_g),
            jax.tree_util.tree_leaves_with_path(params_w)):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                   err_msg=jax.tree_util.keystr(pa))


def test_engine_prefetch_matches_fused_dp8():
    loss_b, params_b = _fused_baseline()
    eng, loss_p, params_p = _train(True)
    assert eng._prefetch_active()
    _assert_matches((loss_p, params_p), (loss_b, params_b))
    # the stage3_max_live_parameters observable: exactly the structural
    # 2-layer double buffer + the step-persistent outer gathers
    stats = eng.prefetch_live_param_stats()
    leaves = jax.tree_util.tree_leaves_with_path(params_p)
    h_elems = sum(int(np.prod(l.shape[1:])) for p, l in leaves
                  if "h" == getattr(p[0], "key", None))
    outer_elems = sum(int(np.prod(l.shape)) for p, l in leaves
                      if getattr(p[0], "key", None) != "h")
    assert stats["layers"] == 2
    assert stats["live_param_elements"] == 2 * h_elems + outer_elems
    from deepspeed_tpu.utils import memory as memory_lib
    assert memory_lib.live_gathered_param_bytes() == \
        stats["live_param_bytes"]


def test_engine_prefetch_fused_matmul_matches_ring_dp8():
    """ISSUE 8 engine-parity pin: ``stage3_prefetch_gather:
    fused_matmul`` — the dominant projection kernels streamed through
    the tile-granular fused all-gather+matmul / matmul+reduce-scatter
    path — reproduces the fused-GSPMD baseline (and hence ring mode,
    pinned against the same baseline above) to fp32 rounding: losses
    AND updated sharded-at-rest params over 3 Adam steps."""
    loss_b, params_b = _fused_baseline()
    eng, loss_p, params_p = _train(True, mode="fused_matmul",
                                   cm={"backend": "lax",
                                       "min_shard_bytes": 0})
    assert eng._prefetch_active()
    stats = eng.prefetch_live_param_stats()
    # the 4 projection kernels (c_attn/c_proj/c_fc/c_proj) stream;
    # their full weights never materialize in the live window
    assert stats["fused_leaves_per_layer"] == 4
    assert stats["fused_stream_bytes"] > 0
    _assert_matches((loss_p, params_p), (loss_b, params_b))


def test_engine_fused_matmul_below_threshold_falls_back_to_ring():
    """min_shard_bytes gating: when no layer leaf qualifies (the tiny
    model's shards are far below the default 64 KiB threshold) the
    mode degrades to the packed ring gather — same numerics, fallback
    logged, zero fused leaves in the stats."""
    loss_b, params_b = _fused_baseline()
    eng, loss_p, params_p = _train(True, mode="fused_matmul")
    assert eng._prefetch_active()
    assert eng.prefetch_live_param_stats()["fused_leaves_per_layer"] == 0
    _assert_matches((loss_p, params_p), (loss_b, params_b))


@pytest.mark.slow
def test_engine_prefetch_matches_fused_dp2_l3_fused_gather():
    """Different mesh shape, odd layer count, fused-collective mode
    (slow: the dp8 ring test is the tier-1 engine-parity pin; this
    variant re-pays two full engine compiles for mesh/mode coverage)."""
    loss_b, params_b = _fused_baseline(data=2, n_layer=3)
    eng, loss_p, params_p = _train(True, data=2, n_layer=3, mode="fused")
    assert eng._prefetch_active()
    _assert_matches((loss_p, params_p), (loss_b, params_b))


@pytest.mark.slow
def test_engine_prefetch_matches_fused_gas2():
    """Gradient accumulation: sharded grads accumulate in shard space
    across microbatches (per-micro reduce-scatter inside the scan)."""
    loss_b, params_b = _fused_baseline(gas=2)
    eng, loss_p, params_p = _train(True, gas=2)
    assert eng._prefetch_active()
    _assert_matches((loss_p, params_p), (loss_b, params_b))


@pytest.mark.slow
def test_engine_prefetch_bf16_grads_trains():
    """grad_dtype=bf16 (the headline-bench recipe): gathers move bf16
    bytes, the step stays finite and close to the fused bf16 path."""
    loss_b, _ = _fused_baseline(bf16=True)
    eng, loss_p, _ = _train(True, bf16=True)
    assert eng._prefetch_active()
    assert np.isfinite(loss_p).all()
    np.testing.assert_allclose(loss_p, loss_b, rtol=5e-2)


@pytest.mark.slow
def test_engine_fused_matmul_bf16_grads_trains():
    """fused_matmul under grad_dtype=bf16 — the configuration where
    fused-leaf dW comes back in the PARAM dtype (one bf16 rounding of
    the kernel's fp32 accumulation; make_prefetched_scan docstring):
    the step stays finite and tracks the fused bf16 baseline."""
    loss_b, _ = _fused_baseline(bf16=True)
    eng, loss_p, _ = _train(True, bf16=True, mode="fused_matmul",
                            cm={"backend": "lax", "min_shard_bytes": 0})
    assert eng._prefetch_active()
    assert eng.prefetch_live_param_stats()["fused_leaves_per_layer"] == 4
    assert np.isfinite(loss_p).all()
    np.testing.assert_allclose(loss_p, loss_b, rtol=5e-2)


def test_engine_prefetch_gating():
    # single-device data axis → nothing sharded, fused path
    eng, losses, _ = _train(True, data=1, steps=1)
    assert not eng._prefetch_active()
    assert np.isfinite(losses).all()
    # LAMB's per-tensor trust ratio is not elementwise → fused fallback
    eng, _, _ = _train(True, steps=1, optimizer={
        "type": "Lamb", "params": {"lr": 1e-3}})
    assert not eng._prefetch_active()
    # a model without the layered contract (unrolled layers) → fallback
    eng, _, _ = _train(True, steps=1, model=GPT2LMHeadModel(
        _gpt2_tiny(scan_layers=False)))
    assert not eng._prefetch_active()


def test_prefetch_config_validation():
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_prefetch": True,
                              "stage3_prefetch_gather": "fused"}},
        world_size=1)
    assert cfg.zero_config.stage3_prefetch
    assert cfg.zero_config.stage3_prefetch_gather == "fused"
    assert "stage3_prefetch" in cfg.zero_config.repr_dict()
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {
            "stage": 3, "stage3_prefetch": True,
            "stage3_prefetch_gather": "fused_matmul",
            "collective_matmul": {"backend": "lax", "tile_m": 64,
                                  "min_shard_bytes": 1024,
                                  "vmem_budget_bytes": 4 << 20}}},
        world_size=1)
    assert cfg.zero_config.stage3_prefetch_gather == "fused_matmul"
    assert cfg.zero_config.collective_matmul_backend == "lax"
    assert cfg.zero_config.collective_matmul_tile_m == 64
    assert cfg.zero_config.collective_matmul_min_shard_bytes == 1024
    assert cfg.zero_config.collective_matmul_vmem_budget_bytes == 4 << 20
    assert cfg.zero_config.repr_dict()["collective_matmul"][
        "backend"] == "lax"
    assert cfg.zero_config.repr_dict()["collective_matmul"][
        "vmem_budget_bytes"] == 4 << 20
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {
                             "stage": 3, "stage3_prefetch_gather": "tree"}},
                        world_size=1)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {
                             "stage": 3,
                             "collective_matmul": {"backend": "mosaic"}}},
                        world_size=1)
    # the sub-block must be a dict (a bare backend string is a plausible
    # shorthand mistake), and the numeric knobs are range-checked
    for bad_cm in ("lax",
                   {"min_shard_bytes": -1},
                   {"vmem_budget_bytes": 0}):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"train_batch_size": 8,
                             "zero_optimization": {
                                 "stage": 3,
                                 "collective_matmul": bad_cm}},
                            world_size=1)
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {"stage": 2,
                                               "stage3_prefetch": True}},
                        world_size=1)
