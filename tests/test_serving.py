"""Continuous-batching serving engine tests (deepspeed_tpu/serving).

Covers the acceptance surface of the paged-KV subsystem:

- the paged attention kernel matches the dense stacked kernels when the
  pool blocks are laid out to mirror a contiguous cache (both storages);
- end-to-end paged serving reproduces the static-batch fused decode
  paths token-for-token (greedy) for GPT-2 (bf16 + int8w/int8kv) and
  LLaMA (GQA, int8 weights, both cache storages);
- slot/page reuse: admitting a request into a slot just freed by a
  LONGER request must not read stale K/V codes or stale int8
  per-position scale arrays;
- the host-side page allocator's accounting and the `serving` config
  block's validation.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu.serving as serving
from deepspeed_tpu.serving.paged_cache import (PagedCacheSpec, PagedKVCache,
                                               TRASH_BLOCK)


@pytest.fixture
def rs():
    return np.random.RandomState(0)


# ------------------------------------------------------- kernel parity


def test_paged_attention_matches_dense_fp(rs):
    from deepspeed_tpu.ops.pallas.decode import (
        decode_attention_paged, decode_attention_fp_stacked)
    Lyr, NB, H, P, D = 2, 9, 4, 16, 64
    B, R, MAXP = 3, 2, 4
    L = MAXP * P
    kp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * 0.3
    vp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * 0.3
    q = jnp.asarray(rs.randn(B, H, R, D), jnp.float32) * 0.3
    pt = np.zeros((B, MAXP), np.int32)
    pt[0, :2] = [3, 5]
    pt[1, :4] = [1, 2, 7, 8]
    pt[2, :1] = [6]
    pos = np.array([20, 60, -1], np.int32)   # slot 2 idle
    got = decode_attention_paged(q, kp, vp, pos, jnp.asarray(pt), 1)
    k_dense = np.zeros((Lyr, B, H, L, D), np.float32)
    v_dense = np.zeros((Lyr, B, H, L, D), np.float32)
    for b in range(B):
        for p in range(MAXP):
            k_dense[:, b, :, p * P:(p + 1) * P] = np.asarray(kp)[:, pt[b, p]]
            v_dense[:, b, :, p * P:(p + 1) * P] = np.asarray(vp)[:, pt[b, p]]
    for b in range(B):
        if pos[b] < 0:
            # idle slots must emit zeros, not stale/garbage context
            np.testing.assert_array_equal(np.asarray(got[b]), 0.0)
            continue
        ref = decode_attention_fp_stacked(
            q[b:b + 1], jnp.asarray(k_dense[:, b:b + 1]),
            jnp.asarray(v_dense[:, b:b + 1]), int(pos[b]), 1)
        np.testing.assert_allclose(np.asarray(got[b:b + 1]),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_paged_attention_matches_dense_int8(rs):
    from deepspeed_tpu.ops.pallas.decode import (
        decode_attention_paged, decode_attention_int8_stacked)
    Lyr, NB, H, P, D = 2, 7, 2, 16, 32
    B, MAXP = 2, 3
    L = MAXP * P
    kc = jnp.asarray(rs.randint(-127, 128, (Lyr, NB, H, P, D)), jnp.int8)
    vc = jnp.asarray(rs.randint(-127, 128, (Lyr, NB, H, P, D)), jnp.int8)
    ks = jnp.asarray(np.abs(rs.randn(Lyr, NB, H, 1, P)) * 0.01 + 1e-3,
                     jnp.float32)
    vs = jnp.asarray(np.abs(rs.randn(Lyr, NB, H, 1, P)) * 0.01 + 1e-3,
                     jnp.float32)
    q = jnp.asarray(rs.randn(B, H, 1, D), jnp.float32) * 0.3
    pt = np.zeros((B, MAXP), np.int32)
    pt[0, :3] = [2, 4, 6]
    pt[1, :2] = [1, 5]
    pos = np.array([40, 17], np.int32)
    got = decode_attention_paged(q, kc, vc, pos, jnp.asarray(pt), 0,
                                 k_scale=ks, v_scale=vs)
    kcd = np.zeros((Lyr, B, H, L, D), np.int8)
    vcd = np.zeros((Lyr, B, H, L, D), np.int8)
    ksd = np.zeros((Lyr, B, H, 1, L), np.float32)
    vsd = np.zeros((Lyr, B, H, 1, L), np.float32)
    for b in range(B):
        for p in range(MAXP):
            kcd[:, b, :, p * P:(p + 1) * P] = np.asarray(kc)[:, pt[b, p]]
            vcd[:, b, :, p * P:(p + 1) * P] = np.asarray(vc)[:, pt[b, p]]
            ksd[:, b, :, 0, p * P:(p + 1) * P] = \
                np.asarray(ks)[:, pt[b, p], :, 0]
            vsd[:, b, :, 0, p * P:(p + 1) * P] = \
                np.asarray(vs)[:, pt[b, p], :, 0]
    for b in range(B):
        ref = decode_attention_int8_stacked(
            q[b:b + 1], jnp.asarray(kcd[:, b:b + 1]),
            jnp.asarray(ksd[:, b:b + 1]), jnp.asarray(vcd[:, b:b + 1]),
            jnp.asarray(vsd[:, b:b + 1]), int(pos[b]), 0)
        np.testing.assert_allclose(np.asarray(got[b:b + 1]),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
@pytest.mark.skipif(
    all(d.platform == "cpu" for d in jax.devices()),
    reason="needs a real TPU chip: exercises the MOSAIC lowering of the "
           "paged kernel's scalar-prefetched page-table index maps "
           "(interpret-mode covers numerics only). conftest.py FORCE-pins "
           "the suite to the CPU backend, so from an axon session run it "
           "bypassing conftest: `python -m pytest --noconftest -m slow "
           "-k real_chip tests/test_serving.py` (the test is "
           "self-contained — no conftest fixtures)")
def test_paged_attention_real_chip_matches_dense(rs):
    """First-real-chip parity for ``decode_attention_paged`` with
    ``interpret=False``: the page-table gathers live in Pallas BLOCK
    INDEX MAPS (pt[b, pb] indexing inside a scalar-prefetch closure),
    which interpret mode never lowers through Mosaic — a lowering bug
    there (e.g. dynamic block indices on the pool dim) would pass every
    CPU test and crash or corrupt on hardware. Same layout as
    test_paged_attention_matches_dense_fp, interpret forced OFF."""
    from deepspeed_tpu.ops.pallas.decode import (
        decode_attention_paged, decode_attention_fp_stacked)
    Lyr, NB, H, P, D = 2, 9, 4, 16, 64
    B, R, MAXP = 3, 2, 4
    L = MAXP * P
    kp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * 0.3
    vp = jnp.asarray(rs.randn(Lyr, NB, H, P, D), jnp.float32) * 0.3
    q = jnp.asarray(rs.randn(B, H, R, D), jnp.float32) * 0.3
    pt = np.zeros((B, MAXP), np.int32)
    pt[0, :2] = [3, 5]
    pt[1, :4] = [1, 2, 7, 8]
    pt[2, :1] = [6]
    pos = np.array([20, 60, -1], np.int32)
    got = decode_attention_paged(q, kp, vp, pos, jnp.asarray(pt), 1,
                                 interpret=False)
    k_dense = np.zeros((Lyr, B, H, L, D), np.float32)
    v_dense = np.zeros((Lyr, B, H, L, D), np.float32)
    for b in range(B):
        for p in range(MAXP):
            k_dense[:, b, :, p * P:(p + 1) * P] = np.asarray(kp)[:, pt[b, p]]
            v_dense[:, b, :, p * P:(p + 1) * P] = np.asarray(vp)[:, pt[b, p]]
    for b in range(B):
        if pos[b] < 0:
            np.testing.assert_array_equal(np.asarray(got[b]), 0.0)
            continue
        ref = decode_attention_fp_stacked(
            q[b:b + 1], jnp.asarray(k_dense[:, b:b + 1]),
            jnp.asarray(v_dense[:, b:b + 1]), int(pos[b]), 1,
            interpret=False)
        np.testing.assert_allclose(np.asarray(got[b:b + 1]),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------- allocator


def test_page_allocator_accounting():
    spec = PagedCacheSpec(n_layers=1, kv_heads=1, head_dim=8,
                          page_size=4, slots=2, max_pages_per_slot=4,
                          num_blocks=6)       # undersubscribed pool
    cache = PagedKVCache(spec)
    total = cache.free_pages
    assert total == spec.resolved_num_blocks() - 1   # trash reserved
    pages = cache.admit(0, total_tokens=9)           # 3 pages of 4
    assert len(pages) == 3 and TRASH_BLOCK not in pages
    assert cache.free_pages == total - 3
    assert list(cache.page_table[0][:3]) == pages
    # exhaust: slot 1 wants 3 pages but only 2 remain in the pool
    left = cache.free_pages
    assert left == 2
    assert cache.admit(1, total_tokens=9) is None
    assert cache.free_pages == left                  # nothing leaked
    cache.release(0)
    assert cache.free_pages == total
    assert all(cache.page_table[0] == TRASH_BLOCK)


def test_serving_config_block_validation():
    from deepspeed_tpu.config.config import (ServingConfig,
                                             DeepSpeedConfigError)
    sc = ServingConfig({"serving": {"slots": 4, "page_size": 64,
                                    "kv_cache_bits": 8}})
    assert sc.enabled and sc.slots == 4 and sc.page_size == 64
    assert sc.kv_cache_bits == 8
    assert not ServingConfig({}).enabled
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"kv_cache_bits": 4}})
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"slots": 0}})
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig({"serving": {"slots": 8, "num_blocks": 4}})


# --------------------------------------------------------- GPT-2 e2e


# Engines are built through a MODULE-scoped adapter factory: compiled
# tick/prefill programs live on the adapter (per-adapter cache — see
# adapters.py), so tests sharing a geometry share its compiles instead
# of re-paying interpret-mode compilation per test (tier-1 wall
# budget). The slot-reuse test keeps its own page-8 geometry on purpose
# (stale rows must span pages).


def _gpt2_cfg():
    from deepspeed_tpu.models.gpt2 import GPT2Config
    return GPT2Config(vocab_size=256, n_positions=128, n_embd=128,
                      n_layer=2, n_head=4, dtype=jnp.float32,
                      param_dtype=jnp.float32, scan_layers=True)


def _gpt2_params(cfg):
    from deepspeed_tpu.models.gpt2 import GPT2LMHeadModel
    return jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]


@pytest.fixture(scope="module")
def gpt2_serving():
    """(cfg, params, qparams, make): make(**serving_kw) returns a fresh
    engine whose adapter (and compiled programs) is shared per distinct
    serving geometry across the module's tests."""
    from deepspeed_tpu.models.gpt2_inference import (
        convert_gpt2_params, quantize_gpt2_inference_params)
    cfg = _gpt2_cfg()
    params = _gpt2_params(cfg)
    qparams = quantize_gpt2_inference_params(
        convert_gpt2_params(params, cfg))
    adapters = {}

    def make(int8=False, **kw):
        sv = {"slots": 2, "page_size": 16, "max_pages_per_slot": 6, **kw}
        key = (int8, tuple(sorted(sv.items())))
        if key not in adapters:
            eng = serving.build_engine(
                "gpt2", cfg, qparams if int8 else params,
                config={"serving": sv})
            adapters[key] = eng.adapter
            return eng
        return serving.ContinuousBatcher(adapters[key])

    return cfg, params, qparams, make


def test_gpt2_paged_serving_matches_generate(rs, gpt2_serving):
    from deepspeed_tpu.models.gpt2_inference import generate
    cfg, params, _, make = gpt2_serving
    eng = make()
    lens = (7, 19, 30)
    news = (12, 5, 9)
    prompts = [rs.randint(0, 256, size=(s,)).astype(np.int32)
               for s in lens]
    res = eng.serve([serving.Request(i, p, max_new_tokens=n)
                     for i, (p, n) in enumerate(zip(prompts, news))])
    for i, (p, n) in enumerate(zip(prompts, news)):
        ref = np.asarray(generate(cfg, params, p[None], max_new_tokens=n,
                                  max_out_tokens=128)[0])
        np.testing.assert_array_equal(res[i].tokens(), ref)
    # all three served through the same compiled tick
    assert eng.stats["prefills"] == 3
    assert eng.stats["decode_tokens"] == sum(news) - 3


def test_gpt2_paged_serving_int8_matches_generate(rs, gpt2_serving):
    from deepspeed_tpu.models.gpt2_inference import generate
    cfg, _, qparams, make = gpt2_serving
    eng = make(int8=True, kv_cache_bits=8)
    p = rs.randint(0, 256, size=(13,)).astype(np.int32)
    res = eng.serve([serving.Request(0, p, max_new_tokens=8)])
    ref = np.asarray(generate(cfg, qparams, p[None], max_new_tokens=8,
                              max_out_tokens=128, quantize_bits=8,
                              kv_cache_bits=8)[0])
    np.testing.assert_array_equal(res[0].tokens(), ref)


def test_gpt2_more_requests_than_slots(rs, gpt2_serving):
    """5 requests through 2 slots: freed slots re-admit mid-flight and
    every request still matches a solo run. The oracle is a fresh paged
    engine serving each request ALONE (dense-path parity is pinned by
    test_gpt2_paged_serving_matches_generate; the property here is
    scheduler correctness under slot contention — and the solo engine
    shares every compiled program, where generate() would compile one
    decode program per distinct length)."""
    _, _, _, make = gpt2_serving
    eng = make()
    lens = (5, 21, 11, 3, 17)
    news = (9, 2, 6, 11, 4)
    prompts = [rs.randint(0, 256, size=(s,)).astype(np.int32)
               for s in lens]
    res = eng.serve([serving.Request(i, p, max_new_tokens=n)
                     for i, (p, n) in enumerate(zip(prompts, news))])
    assert len(res) == 5
    # a second batcher over the SAME adapter shares its compiled
    # tick/prefill programs (fresh cache, fresh scheduler state)
    solo = serving.ContinuousBatcher(eng.adapter)
    for i, (p, n) in enumerate(zip(prompts, news)):
        ref = solo.serve([serving.Request("s", p, max_new_tokens=n)])
        np.testing.assert_array_equal(res[i].tokens(),
                                      ref["s"].tokens())


def test_eos_frees_slot_early(rs, gpt2_serving):
    _, _, _, make = gpt2_serving

    def run(eos):
        eng = make()
        p = rs.randint(0, 256, size=(9,)).astype(np.int32)
        return eng.serve([serving.Request("r", p, max_new_tokens=12,
                                          eos_token_id=eos)])["r"]

    rs = np.random.RandomState(7)
    full = run(eos=None)
    assert full.finish_reason == "length"
    assert len(full.generated) == 12
    # declare a later generated token the "eos": generation must stop at
    # its FIRST occurrence and report the eos finish reason
    rs = np.random.RandomState(7)
    eos_tok = int(full.generated[3])
    first = full.generated.index(eos_tok)
    stopped = run(eos=eos_tok)
    assert stopped.finish_reason == "eos"
    assert stopped.generated == full.generated[:first + 1]


# --------------------------------------------------- slot-reuse / stale


@pytest.mark.parametrize("kv_bits", [0, 8])
def test_slot_reuse_no_stale_kv(rs, kv_bits, gpt2_serving):
    """Admit short request B into the slot (and pages — the free list is
    LIFO) a LONGER request A just released: B's tokens and final-step
    logits must match a fresh-cache engine that only ever saw B. Catches
    stale K/V rows AND stale int8 per-position scale arrays beyond B's
    length (kv_bits=8)."""
    _, _, _, make = gpt2_serving
    pb = rs.randint(0, 256, size=(6,)).astype(np.int32)
    pa = rs.randint(0, 256, size=(40,)).astype(np.int32)

    used = make(slots=1, page_size=8, max_pages_per_slot=8,
                kv_cache_bits=kv_bits)
    res_a = used.serve([serving.Request("a", pa, max_new_tokens=14)])
    assert used.cache.free_pages == \
        used.cache.spec.resolved_num_blocks() - 1
    res_b = used.serve([serving.Request("b", pb, max_new_tokens=5)])
    logits_b = np.asarray(used.last_logits[0])

    fresh = serving.ContinuousBatcher(used.adapter)   # fresh pool+pages
    ref_b = fresh.serve([serving.Request("b", pb, max_new_tokens=5)])
    ref_logits = np.asarray(fresh.last_logits[0])

    np.testing.assert_array_equal(res_b["b"].tokens(), ref_b["b"].tokens())
    np.testing.assert_allclose(logits_b, ref_logits, rtol=1e-5, atol=1e-5)


# ----------------------------------------------------------- LLaMA e2e


def _llama_cfg():
    from deepspeed_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=256, hidden_size=128, n_layers=2,
                       n_heads=4, n_kv_heads=2, intermediate_size=256,
                       max_seq_len=128, dtype=jnp.float32,
                       param_dtype=jnp.float32)


@pytest.mark.parametrize("kv_bits", [
    # the fp-cache variant rides the slow tier: its unique surface (GQA
    # query rows through the fp paged kernel) is pinned fast by
    # test_paged_attention_matches_dense_fp, and the int8 e2e keeps the
    # whole LLaMA serving stack in tier-1
    pytest.param(0, marks=pytest.mark.slow),
    8,
])
def test_llama_paged_serving_matches_fast_generate(rs, kv_bits):
    from deepspeed_tpu.models.llama_inference import (
        llama_fast_generate, random_int8_serving_params)
    cfg = _llama_cfg()
    sparams = random_int8_serving_params(cfg)
    eng = serving.build_engine(
        "llama", cfg, sparams,
        config={"serving": {"slots": 2, "page_size": 16,
                            "max_pages_per_slot": 6,
                            "kv_cache_bits": kv_bits}})
    lens = (21, 9)
    news = (6, 10)
    prompts = [rs.randint(0, 256, size=(s,)).astype(np.int32)
               for s in lens]
    res = eng.serve([serving.Request(i, p, max_new_tokens=n)
                     for i, (p, n) in enumerate(zip(prompts, news))])
    for i, (p, n) in enumerate(zip(prompts, news)):
        ref = np.asarray(llama_fast_generate(
            cfg, sparams, p[None], max_new_tokens=n, max_out_tokens=128,
            kv_cache_bits=kv_bits)[0])
        np.testing.assert_array_equal(res[i].tokens(), ref)
