"""1F1B SPMD pipeline executor tests.

Tier 1: the closed-form tick mapping agrees with TrainSchedule's generated
instruction stream for every (tick, stage) — schedule.py is the executable
contract of the executor, not documentation.
Tier 2: forward and gradients through pipeline_1f1b match the sequential
(pipe=1) execution on an 8-device CPU mesh.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig
from deepspeed_tpu.parallel.pipeline_1f1b import (
    _tick_to_micro_batch, num_pipe_buffers, pipeline_1f1b)
from deepspeed_tpu.runtime.pipe import schedule as pipe_schedule


# ---------------------------------------------------------- tier 1: schedule

@pytest.mark.parametrize("stages,micro", [(2, 2), (2, 4), (4, 4), (4, 8),
                                          (3, 5), (8, 8)])
def test_closed_form_matches_train_schedule(stages, micro):
    """For every stage, replay TrainSchedule and check each ForwardPass /
    BackwardPass lands exactly where the executor's closed form puts it."""
    for stage in range(stages):
        sched = pipe_schedule.TrainSchedule(
            micro_batches=micro, stages=stages, stage_id=stage)
        for tick, cmds in enumerate(sched.steps()):
            fwd = [c for c in cmds
                   if isinstance(c, pipe_schedule.ForwardPass)]
            bwd = [c for c in cmds
                   if isinstance(c, pipe_schedule.BackwardPass)]
            m, is_fwd = _tick_to_micro_batch(tick, stage, stages)
            m, is_fwd = int(m), bool(is_fwd)
            valid = 0 <= m < micro
            if fwd:
                assert is_fwd and valid, (stages, micro, stage, tick)
                # buffer ids wrap at num_pipe_buffers; micro-batch identity
                # is the tick math itself
                assert fwd[0].buffer_id == m % sched.num_pipe_buffers()
            elif bwd:
                assert (not is_fwd) and valid, (stages, micro, stage, tick)
                assert bwd[0].buffer_id == m % sched.num_pipe_buffers()
            else:
                assert not valid, (stages, micro, stage, tick, m, is_fwd)


def test_num_pipe_buffers_bounds_reference():
    """Uniform executor buffer count covers every stage's reference need
    (stages - stage_id + 1, schedule.py:243-247), capped by micro."""
    for stages in (2, 3, 4, 8):
        for micro in (stages, 2 * stages):
            need = max(min(stages - s + 1, micro) for s in range(stages))
            assert num_pipe_buffers(stages, micro) >= need


# ------------------------------------------------------- tier 2: numerics

def _stage_fn(params, x):
    # two "layers" per stage: y = tanh(x @ w + b), applied per layer
    def layer(x, wb):
        w, b = wb
        return jnp.tanh(x @ w + b)
    y, _ = jax.lax.scan(lambda h, wb: (layer(h, wb), None), x, params)
    return y


def _stage_params(key, S, layers_per_stage, d):
    k1, k2 = jax.random.split(key)
    w = jax.random.normal(k1, (S, layers_per_stage, d, d)) * 0.3
    b = jax.random.normal(k2, (S, layers_per_stage, d)) * 0.1
    return (w, b)


@pytest.mark.parametrize("pp,micro", [(2, 4), (4, 4), (4, 6)])
def test_1f1b_matches_sequential(pp, micro):
    devs = jax.devices()
    if len(devs) < pp:
        pytest.skip(f"need {pp} devices")
    d, mb = 16, 4
    params = _stage_params(jax.random.PRNGKey(0), pp, 2, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (micro, mb, d))
    tgt = jax.random.normal(jax.random.PRNGKey(2), (micro, mb, d))

    def loss_pipe(params, x):
        mesh = make_mesh(MeshConfig(pipe=pp), devices=devs[:pp])
        out = pipeline_1f1b(_stage_fn, params, x, mesh)
        return jnp.mean((out - tgt) ** 2)

    def loss_seq(params, x):
        def apply_all(h):
            for s in range(pp):
                local = jax.tree_util.tree_map(lambda p: p[s], params)
                h = _stage_fn(local, h)
            return h
        out = jax.lax.map(apply_all, x)
        return jnp.mean((out - tgt) ** 2)

    v1, g1 = jax.jit(jax.value_and_grad(loss_pipe, argnums=(0, 1)))(params, x)
    v2, g2 = jax.jit(jax.value_and_grad(loss_seq, argnums=(0, 1)))(params, x)
    np.testing.assert_allclose(v1, v2, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("interleave", [True, False])
def test_both_backward_programs_match_sequential(interleave):
    """The interleaved 1F1B replay and the uniform-tick variant produce
    identical gradients (they execute the same math in different orders)."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("need 4 devices")
    d, mb, micro = 16, 4, 5
    params = _stage_params(jax.random.PRNGKey(3), 4, 2, d)
    x = jax.random.normal(jax.random.PRNGKey(4), (micro, mb, d))
    tgt = jax.random.normal(jax.random.PRNGKey(5), (micro, mb, d))

    def loss(params, x):
        mesh = make_mesh(MeshConfig(pipe=4), devices=devs[:4])
        out = pipeline_1f1b(_stage_fn, params, x, mesh,
                            interleave=interleave)
        return jnp.mean((out - tgt) ** 2)

    def loss_seq(params, x):
        def apply_all(h):
            for s in range(4):
                local = jax.tree_util.tree_map(lambda p: p[s], params)
                h = _stage_fn(local, h)
            return h
        return jnp.mean((jax.lax.map(apply_all, x) - tgt) ** 2)

    v1, g1 = jax.jit(jax.value_and_grad(loss, argnums=(0, 1)))(params, x)
    v2, g2 = jax.jit(jax.value_and_grad(loss_seq, argnums=(0, 1)))(params, x)
    np.testing.assert_allclose(v1, v2, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_forced_interleave_with_sharded_params_raises():
    """interleave=True forced on a mesh with live non-pipe axes AND ZeRO/TP
    specs on the stage params is a guaranteed deadlock (collectives inside
    diverging lax.cond branches) — the executor must refuse, not warn
    (VERDICT r3 item 9)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("need 4 devices")
    d = 16
    params = _stage_params(jax.random.PRNGKey(0), 2, 2, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 4, d))
    mesh = make_mesh(MeshConfig(pipe=2, model=2), devices=devs[:4])
    # ZeRO/TP-style spec: shard the weight over the live 'model' axis
    sharded = (
        jax.device_put(params[0], NamedSharding(
            mesh, P("pipe", None, "model", None))),
        jax.device_put(params[1], NamedSharding(mesh, P("pipe"))),
    )
    with pytest.raises(ValueError, match="deadlock"):
        pipeline_1f1b(_stage_fn, sharded, x, mesh, interleave=True)
    # replicated params on the same mesh: maybe-collective-free body, the
    # warning path — must still build and run
    repl = jax.device_put(
        params, NamedSharding(mesh, P()))
    out = jax.jit(
        lambda p, xx: pipeline_1f1b(_stage_fn, p, xx, mesh,
                                    interleave=True))(repl, x)
    assert out.shape == x.shape


def test_forced_interleave_with_collective_body_raises_under_jit():
    """Tracer params carry no .sharding, so the spec check alone can't
    protect the jitted path — the body jaxpr scan must catch explicit
    collectives over live non-pipe axes (ring-attention-style bodies)."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("need 4 devices")
    d = 16
    params = _stage_params(jax.random.PRNGKey(0), 2, 2, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 4, d))
    mesh = make_mesh(MeshConfig(pipe=2, model=2), devices=devs[:4])

    def collective_stage(p, xx):
        y = _stage_fn(p, xx)
        return jax.lax.psum(y, "model")

    with pytest.raises(ValueError, match="deadlock"):
        jax.jit(lambda p, xx: pipeline_1f1b(
            collective_stage, p, xx, mesh, interleave=True))(params, x)


def test_1f1b_single_stage_fallback():
    params = _stage_params(jax.random.PRNGKey(0), 1, 2, 8)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 2, 8))
    mesh = make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
    out = pipeline_1f1b(_stage_fn, params, x, mesh)
    local = jax.tree_util.tree_map(lambda p: p[0], params)
    ref = jax.lax.map(lambda xx: _stage_fn(local, xx), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


# ----------------------------------------- tier 3: InferenceSchedule executes

@pytest.mark.parametrize("stages,micro", [(2, 3), (4, 4), (3, 5)])
def test_inference_schedule_closed_form(stages, micro):
    """InferenceSchedule's instruction stream is the executable contract of
    the forward fill/drain program: stage i computes micro m at tick
    t = m + i (schedule.py:138)."""
    for stage in range(stages):
        sched = pipe_schedule.InferenceSchedule(
            micro_batches=micro, stages=stages, stage_id=stage)
        for tick, cmds in enumerate(sched.steps()):
            fwd = [c for c in cmds
                   if isinstance(c, pipe_schedule.ForwardPass)]
            m = tick - stage            # the program's fill/drain mapping
            if fwd:
                assert 0 <= m < micro, (stages, micro, stage, tick)
                assert fwd[0].buffer_id == m % sched.num_pipe_buffers()
            else:
                assert not (0 <= m < micro), (stages, micro, stage, tick)


@pytest.mark.parametrize("pp,micro", [(2, 4), (4, 4)])
def test_pipeline_infer_matches_sequential(pp, micro):
    """pipeline_infer (the executed InferenceSchedule) reproduces the
    sequential forward exactly."""
    from deepspeed_tpu.parallel.pipeline_1f1b import pipeline_infer
    devs = jax.devices()
    if len(devs) < pp:
        pytest.skip(f"need {pp} devices")
    d, mb = 16, 4
    params = _stage_params(jax.random.PRNGKey(3), pp, 2, d)
    x = jax.random.normal(jax.random.PRNGKey(4), (micro, mb, d))
    mesh = make_mesh(MeshConfig(pipe=pp), devices=devs[:pp])
    out_pipe = jax.jit(
        lambda p, xx: pipeline_infer(_stage_fn, p, xx, mesh))(params, x)

    def apply_all(h):
        for s in range(pp):
            local = jax.tree_util.tree_map(lambda p: p[s], params)
            h = _stage_fn(local, h)
        return h
    out_seq = jax.lax.map(apply_all, x)
    np.testing.assert_allclose(np.asarray(out_pipe), np.asarray(out_seq),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_multistage_decode_matches_single_device():
    """Multi-stage greedy decode through the InferenceSchedule program
    produces the same tokens and logits as the single-device model
    (VERDICT r2 item 5 done-condition)."""
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("need 2 devices")
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.models.gpt2_pipe import GPT2PipeModel

    cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=32, n_layer=4,
                     n_head=2, dtype=jnp.float32, scan_layers=True)
    mesh = make_mesh(MeshConfig(pipe=2), devices=devs[:2])
    pipe_model = GPT2PipeModel(cfg, mesh, num_microbatches=2)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 8)),
                      jnp.int32)
    variables = pipe_model.init(jax.random.PRNGKey(0), ids)

    # single-device reference shares the SAME weights (unstack the stages)
    ref_model = GPT2LMHeadModel(cfg)
    ref_params = pipe_model._unstacked(variables["params"])

    logits_pipe = pipe_model.apply(variables, ids, inference=True)
    logits_ref = ref_model.apply({"params": ref_params}, ids)
    np.testing.assert_allclose(np.asarray(logits_pipe, np.float32),
                               np.asarray(logits_ref, np.float32),
                               rtol=2e-4, atol=2e-5)

    out_pipe = pipe_model.generate(variables, ids, max_new_tokens=4)
    # greedy single-device decode by full re-forward
    ref_ids = ids
    for _ in range(4):
        lg = ref_model.apply({"params": ref_params}, ref_ids)
        nxt = jnp.argmax(lg[:, -1, :].astype(jnp.float32), axis=-1)
        ref_ids = jnp.concatenate(
            [ref_ids, nxt[:, None].astype(ref_ids.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out_pipe), np.asarray(ref_ids))
