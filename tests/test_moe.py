"""MoE / expert-parallel tests: routing invariants, capacity truncation,
single-expert equivalence to a dense MLP, expert sharding, training e2e."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deepspeed_tpu.moe import MoE, MoEMLP, TopKGate
from deepspeed_tpu.moe.layer import expert_shardings
from deepspeed_tpu.parallel import mesh as mesh_lib


def test_gate_dispatch_invariants():
    gate = TopKGate(num_experts=4, k=1, capacity_factor=2.0)
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8).astype(np.float32))
    (dispatch, combine, aux), _ = gate.init_with_output(
        jax.random.PRNGKey(0), x)
    d = np.asarray(dispatch)
    # each token lands in at most one (expert, slot); slots not oversubscribed
    assert d.sum(axis=(1, 2)).max() <= 1.0 + 1e-6
    assert d.sum(axis=0).max() <= 1.0 + 1e-6      # one token per slot
    assert float(aux) > 0
    # combine weights only where dispatched
    c = np.asarray(combine)
    assert ((c > 0) <= (d > 0)).all()


def test_gate_capacity_truncation():
    # capacity 1 with 16 tokens and 2 experts → at most 2 tokens dispatched
    gate = TopKGate(num_experts=2, k=1, capacity_factor=1.0 / 8.0)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 4).astype(np.float32))
    (dispatch, _, _), _ = gate.init_with_output(jax.random.PRNGKey(0), x)
    assert float(np.asarray(dispatch).sum()) <= 2.0 + 1e-6


def test_single_expert_equals_dense_mlp():
    """One expert with capacity >= tokens routes everything through one FFN
    — output must equal applying that FFN densely."""
    H, F = 8, 16
    moe = MoE(num_experts=1, d_ff=F, capacity_factor=64.0,
              dtype=jnp.float32)
    x = jnp.asarray(np.random.RandomState(2).randn(2, 4, H)
                    .astype(np.float32))
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    out = moe.apply({"params": params}, x)

    wi = params["experts"]["wi"][0]
    wo = params["experts"]["wo"][0]
    import flax.linen as nn
    ref = nn.gelu(x.reshape(-1, H) @ wi) @ wo
    np.testing.assert_allclose(np.asarray(out).reshape(-1, H),
                               np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_topk2_routes_more_mass():
    H = 8
    x = jnp.asarray(np.random.RandomState(3).randn(2, 8, H)
                    .astype(np.float32))
    out1 = MoE(num_experts=4, d_ff=16, k=1, dtype=jnp.float32)
    out2 = MoE(num_experts=4, d_ff=16, k=2, dtype=jnp.float32)
    p1 = out1.init(jax.random.PRNGKey(0), x)["params"]
    y1, aux1 = out1.apply({"params": p1}, x, mutable=["losses"])
    p2 = out2.init(jax.random.PRNGKey(0), x)["params"]
    y2, _ = out2.apply({"params": p2}, x, mutable=["losses"])
    assert np.isfinite(np.asarray(y1)).all()
    assert np.isfinite(np.asarray(y2)).all()
    # k=2 combines two experts per token → generally different output
    assert not np.allclose(np.asarray(y1), np.asarray(y2))


def test_expert_sharding_specs():
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4),
                              devices=jax.devices()[:4]) \
        if len(jax.devices()) >= 4 else pytest.skip("need 4 devices")
    moe = MoE(num_experts=4, d_ff=16)
    x = jnp.ones((2, 4, 8), jnp.bfloat16)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    specs = expert_shardings(params, mesh)
    from jax.sharding import PartitionSpec as P
    assert specs["experts"]["wi"] == P("data")
    assert specs["experts"]["wo"] == P("data")
    assert specs["gate"]["wg"]["kernel"] == P()


def test_moe_trains_expert_parallel():
    """e2e: a tiny classifier with an MoE block trains on a dp=4 mesh with
    experts sharded over the axis."""
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    import flax.linen as nn
    import deepspeed_tpu as dstpu
    from tests.simple_model import random_batch, base_config

    class MoENet(nn.Module):
        @nn.compact
        def __call__(self, x):                 # [B, 8]
            h = nn.Dense(8)(x)[:, None, :]     # [B, 1, 8]
            h = h + MoE(num_experts=4, d_ff=16, dtype=jnp.float32)(h)
            return nn.Dense(4)(h[:, 0])

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4),
                              devices=jax.devices()[:4])
    cfg = base_config()
    engine, _, _, _ = dstpu.initialize(config=cfg, model=MoENet(),
                                       mesh=mesh)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(15):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


@pytest.mark.slow
def test_gpt2_moe_trains():
    """GPT-2 with MoE FFNs (moe_experts>0) trains end to end, expert
    parallel over the data axis."""
    if len(jax.devices()) < 4:
        pytest.skip("need 4 devices")
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel

    cfg = {"train_batch_size": 4,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "steps_per_print": 1000}
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=4),
                              devices=jax.devices()[:4])
    model = GPT2LMHeadModel(gpt2_tiny(moe_experts=4, dtype=jnp.float32))
    engine, _, _, _ = dstpu.initialize(config=cfg, model=model, mesh=mesh)
    batch = {"input_ids": np.random.RandomState(0)
             .randint(0, 512, (4, 32)).astype(np.int32)}
    l0 = float(engine.train_batch(batch))
    for _ in range(10):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0


def test_topk2_slots_do_not_collide():
    """Regression: round-2 assignments must land AFTER round-1 occupants of
    the same expert — no two tokens may share an (expert, slot)."""
    gate = TopKGate(num_experts=2, k=2, capacity_factor=4.0)
    x = jnp.asarray(np.random.RandomState(5).randn(12, 8).astype(np.float32))
    (dispatch, _, _), _ = gate.init_with_output(jax.random.PRNGKey(0), x)
    d = np.asarray(dispatch)
    # every (expert, slot) holds at most one token
    assert d.sum(axis=0).max() <= 1.0 + 1e-6
    # with k=2 and 2 experts, every token is dispatched twice (capacity 48)
    np.testing.assert_allclose(d.sum(axis=(1, 2)), 2.0)


def test_moe_aux_loss_reaches_engine_objective():
    """The sown load-balance loss must flow into the training loss (router
    gets balancing gradients)."""
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.gpt2 import gpt2_tiny, GPT2LMHeadModel

    batch = {"input_ids": np.random.RandomState(0)
             .randint(0, 512, (4, 32)).astype(np.int32)}
    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=1),
                              devices=jax.devices()[:1])

    def loss_of(aux_coeff):
        cfg = {"train_batch_size": 4, "seed": 9,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}}
        model = GPT2LMHeadModel(gpt2_tiny(moe_experts=4, dtype=jnp.float32,
                                          moe_aux_coeff=aux_coeff))
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model,
                                           mesh=mesh)
        return float(engine.train_batch(batch))

    # a large aux coefficient must visibly raise the reported loss
    assert loss_of(10.0) > loss_of(0.0) + 0.5


def test_moe_trains_on_dedicated_expert_axis():
    """EP on an expert axis independent of data (VERDICT: expert != data
    factorization): data=2 x expert=4 — batch shards over data, expert
    kernels shard over 'expert'."""
    if len(jax.devices()) < 8:
        pytest.skip("need 8 devices")
    import flax.linen as nn
    import deepspeed_tpu as dstpu
    from tests.simple_model import random_batch, base_config
    from deepspeed_tpu.moe import expert_shardings

    class MoENet(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(8)(x)[:, None, :]
            h = h + MoE(num_experts=4, d_ff=16, dtype=jnp.float32)(h)
            return nn.Dense(4)(h[:, 0])

    mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=2, expert=4),
                              devices=jax.devices()[:8])
    cfg = base_config()
    cfg["train_batch_size"] = 8
    engine, _, _, _ = dstpu.initialize(config=cfg, model=MoENet(),
                                       mesh=mesh)
    batch = random_batch()
    l0 = float(engine.train_batch(batch))
    for _ in range(15):
        l1 = float(engine.train_batch(batch))
    assert l1 < l0
    # specs put expert kernels on the dedicated axis, not data
    import jax as _jax
    from jax.sharding import PartitionSpec as P
    specs = expert_shardings(_jax.device_get(engine.state.params), mesh)
    leaves = [s for path, s in
              _jax.tree_util.tree_flatten_with_path(specs)[0]
              if "experts" in str(path)]
    assert leaves and all(s == P(mesh_lib.EXPERT_AXIS) for s in leaves), specs


def test_apply_with_losses_balances_router_in_custom_loss():
    """The documented custom-loss path (moe.apply_with_losses) feeds the
    aux term into the objective; with it the router's load-balance loss
    improves vs a custom loss that drops it (the VERDICT #8 failure
    mode)."""
    import flax.linen as nn
    import deepspeed_tpu as dstpu
    from deepspeed_tpu.moe import apply_with_losses
    from tests.simple_model import random_batch, base_config

    class MoENet(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.Dense(8)(x)[:, None, :]
            h = h + MoE(num_experts=4, d_ff=16, dtype=jnp.float32,
                        # biased gate init so balance must be LEARNED
                        )(h)
            return nn.Dense(4)(h[:, 0])

    def make_loss(with_aux):
        model = MoENet()

        def loss_fn(params, batch):
            x, y = batch
            out, aux = apply_with_losses(model, {"params": params}, x)
            logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
            return nll + (0.1 * aux if with_aux else 0.0)
        return MoENet(), loss_fn

    def run(with_aux, steps=25):
        model, loss_fn = make_loss(with_aux)
        mesh = mesh_lib.make_mesh(mesh_lib.MeshConfig(data=1),
                                  devices=jax.devices()[:1])
        cfg = base_config()
        cfg["optimizer"] = {"type": "Adam", "params": {"lr": 3e-3}}
        engine, _, _, _ = dstpu.initialize(config=cfg, model=model,
                                           loss_fn=loss_fn, mesh=mesh)
        batch = random_batch()
        for _ in range(steps):
            engine.train_batch(batch)
        # measure the router's current balance (aux term) out-of-band
        x, _ = batch
        _, aux = apply_with_losses(model, {"params": jax.device_get(
            engine.state.params)}, jnp.asarray(x))
        return float(aux)

    aux_with = run(True)
    aux_without = run(False)
    # training WITH the aux term must end at least as balanced; a custom
    # loss that drops it has nothing pushing the router toward balance
    assert aux_with <= aux_without + 1e-3, (aux_with, aux_without)
