"""LLaMA family tests: tiny-model training through the engine, HF logit
parity (the cross-check methodology of models/hf_interop.from_hf_bert),
GQA head expansion, and TP sharding via the registered rules.

Reference role: deepspeed/module_inject/containers/llama.py serves HF
LLaMA; tests/unit model tests validate injected weights against the HF
forward the same way."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu as dstpu
from deepspeed_tpu.models.llama import (
    LlamaConfig, LlamaForCausalLM, llama_tiny, from_hf_llama)
from deepspeed_tpu.parallel.mesh import make_mesh, MeshConfig


def _batch(rs, cfg, bs=8, seq=32):
    return {"input_ids": rs.randint(0, cfg.vocab_size, (bs, seq))
            .astype(np.int32)}


def test_llama_trains_loss_falls():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    engine, _, _, _ = dstpu.initialize(
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
                "steps_per_print": 1000},
        model=model,
        mesh=make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    rs = np.random.RandomState(0)
    batch = _batch(rs, cfg)
    losses = [float(engine.train_batch(batch)) for _ in range(12)]
    assert losses[-1] < losses[0] - 0.5, losses


@pytest.mark.slow
def test_llama_zero3_matches_stage0():
    """ZeRO-3 sharded llama training must match unsharded numerics —
    the generic partitioner has to handle the scan-stacked GQA tree."""
    cfg = llama_tiny()
    rs = np.random.RandomState(1)
    batch = _batch(rs, cfg, bs=4)

    def run(stage, n_dev):
        model = LlamaForCausalLM(cfg)
        engine, _, _, _ = dstpu.initialize(
            config={"train_batch_size": 4,
                    "zero_optimization": {"stage": stage},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000, "seed": 5},
            model=model,
            mesh=make_mesh(MeshConfig(data=n_dev),
                           devices=jax.devices()[:n_dev]))
        return [float(engine.train_batch(batch)) for _ in range(4)]

    base = run(0, 1)
    sharded = run(3, 4)
    np.testing.assert_allclose(sharded, base, rtol=2e-4, atol=2e-4)


def test_llama_gqa_matches_mha_when_heads_equal():
    """n_kv_heads == n_heads must behave exactly like plain MHA (the
    repeat is a no-op); and GQA (fewer kv heads) must produce finite,
    shape-correct logits."""
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, 512, (2, 16)), jnp.int32)
    cfg_gqa = llama_tiny(n_kv_heads=2)
    model = LlamaForCausalLM(cfg_gqa)
    params = jax.jit(model.init)(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, 512)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # kv kernels really are at the reduced width
    kshape = jax.tree_util.tree_leaves(
        params["layers"]["blk"]["attn"]["k_proj"])[0].shape
    assert kshape[-1] == 2 * cfg_gqa.head_dim


def test_llama_chunked_loss_matches_full():
    cfg = llama_tiny(loss_chunk=16)
    cfg_full = llama_tiny()
    rs = np.random.RandomState(3)
    ids = jnp.asarray(rs.randint(0, 512, (2, 32)), jnp.int32)
    m1, m2 = LlamaForCausalLM(cfg), LlamaForCausalLM(cfg_full)
    params = jax.jit(m1.init)(jax.random.PRNGKey(0), ids)["params"]
    l_chunk = m1.apply({"params": params}, ids, labels=ids)
    l_full = m2.apply({"params": params}, ids, labels=ids)
    np.testing.assert_allclose(float(l_chunk), float(l_full), rtol=1e-6)


def test_llama_tp_matches_single_device(devices8):
    """Registered TP rules shard q/k/v/gate/up column- and o/down
    row-parallel; model-axis training must match single-device losses."""
    cfg = llama_tiny(n_kv_heads=4)   # TP over kv heads needs divisibility
    rs = np.random.RandomState(4)
    batch = _batch(rs, cfg, bs=4)

    def run(model_par, n_dev):
        model = LlamaForCausalLM(cfg)
        engine, _, _, _ = dstpu.initialize(
            config={"train_batch_size": 4,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "steps_per_print": 1000, "seed": 7},
            model=model,
            mesh=make_mesh(MeshConfig(data=1, model=model_par),
                           devices=jax.devices()[:n_dev]))
        return [float(engine.train_batch(batch)) for _ in range(3)]

    base = run(1, 1)
    tp = run(2, 2)
    np.testing.assert_allclose(tp, base, rtol=2e-4, atol=2e-4)


def test_llama_matches_hf_logits():
    """Random tiny HF LlamaForCausalLM vs this model under imported
    weights: logits must agree to fp32 tolerance (same RoPE convention,
    RMSNorm epsilon, SiLU-gated MLP)."""
    transformers = pytest.importorskip("transformers")
    import torch
    hf_cfg = transformers.LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=352,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = llama_tiny(n_kv_heads=2)
    params = from_hf_llama(hf, cfg)
    rs = np.random.RandomState(5)
    ids = rs.randint(0, 512, (2, 24)).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids.astype(np.int64))).logits.numpy()
    got = np.asarray(LlamaForCausalLM(cfg).apply(
        {"params": jax.tree_util.tree_map(jnp.asarray, params)},
        jnp.asarray(ids)))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.slow
def test_llama_cached_decode_matches_reforward():
    """Greedy KV-cache generation must equal argmax over full re-forwards
    (the gpt2_inference serving contract; RoPE positions are absolute so
    cached K/V match recomputed ones exactly in fp32)."""
    from deepspeed_tpu.models.llama import llama_generate
    cfg = llama_tiny(n_kv_heads=2)
    rs = np.random.RandomState(6)
    prompt = rs.randint(0, 512, (2, 20)).astype(np.int32)
    params = jax.jit(LlamaForCausalLM(cfg).init)(
        jax.random.PRNGKey(0), jnp.asarray(prompt[:, :8]))["params"]
    toks = llama_generate(cfg, params, prompt, max_new_tokens=6,
                          max_out_tokens=64)
    model = LlamaForCausalLM(cfg)
    cur = jnp.asarray(prompt)
    for _ in range(6):
        logits = model.apply({"params": params}, cur)
        cur = jnp.concatenate(
            [cur, jnp.argmax(logits[:, -1], axis=-1)[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(cur))
