"""ZeRO-Infinity segment-streamed trainer (runtime/zero/infinity.py):
the streamed step must reproduce plain full-resident training — same
forward, same grads, same Adam — and the NVMe at-rest tier must
round-trip the parameters.

Reference role: the reference validates stage3/ZeRO-Infinity against
plain torch training the same way (tests/unit/test_zero.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime.zero.infinity import InfinityEngine


def _tiny_cfg(**kw):
    return GPT2Config(vocab_size=512, n_positions=64, n_embd=64,
                      n_layer=4, n_head=2, dtype=jnp.float32,
                      param_dtype=jnp.float32, scan_layers=True, **kw)


def _ref_adam_loop(model, params, batch, steps, lr, betas, eps):
    """Full-resident reference: value_and_grad + textbook Adam in fp32."""
    beta1, beta2 = betas

    def loss_fn(p):
        return model.apply({"params": p}, batch["input_ids"],
                           labels=batch["input_ids"])

    m = jax.tree.map(lambda l: jnp.zeros_like(l, jnp.float32), params)
    v = jax.tree.map(lambda l: jnp.zeros_like(l, jnp.float32), params)
    losses = []
    p = jax.tree.map(lambda l: l.astype(jnp.float32), params)
    for t in range(1, steps + 1):
        loss, g = jax.value_and_grad(loss_fn)(p)
        losses.append(float(loss))
        bc1 = 1.0 - beta1 ** t
        bc2 = 1.0 - beta2 ** t
        m = jax.tree.map(lambda mm, gg: beta1 * mm + (1 - beta1)
                         * gg.astype(jnp.float32), m, g)
        v = jax.tree.map(lambda vv, gg: beta2 * vv + (1 - beta2)
                         * (gg.astype(jnp.float32) ** 2), v, g)
        p = jax.tree.map(
            lambda pp, mm, vv: pp - lr * (mm / bc1)
            / (jnp.sqrt(vv / bc2) + eps), p, m, v)
    return losses


@pytest.mark.slow
def test_streamed_step_matches_full_resident_training():
    cfg = _tiny_cfg()
    model = GPT2LMHeadModel(cfg)
    rng = np.random.RandomState(0)
    batch = {"input_ids": rng.randint(0, 512, size=(2, 32))
             .astype(np.int32)}
    params = jax.jit(model.init)(jax.random.PRNGKey(0),
                                 batch["input_ids"])["params"]
    lr, betas, eps = 1e-3, (0.9, 0.999), 1e-8
    ref_losses = _ref_adam_loop(model, params, batch, 4, lr, betas, eps)

    eng = InfinityEngine(cfg, params, segments=2, lr=lr, betas=betas,
                         eps=eps, moment_dtype=jnp.float32)
    got = [eng.train_batch(batch) for _ in range(4)]
    np.testing.assert_allclose(got, ref_losses, rtol=2e-4, atol=2e-5)
    assert got[-1] < got[0], got


def test_streamed_segment_counts_equivalent():
    """K=1, K=2, K=4 must produce the same trajectory — segmentation is
    a memory plan, not a numerics change."""
    cfg = _tiny_cfg()
    model = GPT2LMHeadModel(cfg)
    rng = np.random.RandomState(1)
    batch = {"input_ids": rng.randint(0, 512, size=(2, 24))
             .astype(np.int32)}
    params = jax.jit(model.init)(jax.random.PRNGKey(1),
                                 batch["input_ids"])["params"]
    runs = {}
    for k in (1, 2, 4):
        eng = InfinityEngine(cfg, params, segments=k,
                             moment_dtype=jnp.float32)
        runs[k] = [eng.train_batch(batch) for _ in range(3)]
    np.testing.assert_allclose(runs[1], runs[2], rtol=1e-5)
    np.testing.assert_allclose(runs[1], runs[4], rtol=1e-5)


def test_nvme_at_rest_roundtrip(tmp_path):
    """Params rest on NVMe from step zero; park_to_nvme refreshes the
    files after training and restore_from_nvme rebuilds the masters —
    a fresh engine restored from disk continues with the same loss."""
    cfg = _tiny_cfg()
    model = GPT2LMHeadModel(cfg)
    rng = np.random.RandomState(2)
    batch = {"input_ids": rng.randint(0, 512, size=(2, 24))
             .astype(np.int32)}
    params = jax.jit(model.init)(jax.random.PRNGKey(2),
                                 batch["input_ids"])["params"]
    eng = InfinityEngine(cfg, params, segments=2, nvme_path=str(tmp_path),
                         moment_dtype=jnp.float32,
                         park_threshold_bytes=0)   # no per-step park
    assert eng.params_on_disk_bytes() > 0
    losses = [eng.train_batch(batch) for _ in range(3)]
    eng.park_to_nvme()
    del eng

    # a FRESH engine cold-starts from the durable files (stable sub-dir
    # + meta sidecar — the cross-process restart path): its next loss
    # continues from the parked params, well below the from-scratch
    # first loss (moments reset on cold start)
    eng2 = InfinityEngine(cfg, params, segments=2,
                          nvme_path=str(tmp_path),
                          moment_dtype=jnp.float32,
                          park_threshold_bytes=0, restore_params=True)
    l_next = eng2.train_batch(batch)
    assert l_next < losses[0], (l_next, losses)


def test_per_step_park_under_threshold(tmp_path):
    """Small models keep the r4 semantics: params re-park to disk after
    every step (files mtime advances)."""
    import os
    cfg = _tiny_cfg()
    model = GPT2LMHeadModel(cfg)
    rng = np.random.RandomState(3)
    batch = {"input_ids": rng.randint(0, 512, size=(2, 16))
             .astype(np.int32)}
    params = jax.jit(model.init)(jax.random.PRNGKey(3),
                                 batch["input_ids"])["params"]
    eng = InfinityEngine(cfg, params, segments=2, nvme_path=str(tmp_path),
                         moment_dtype=jnp.float32)
    assert eng.param_bytes <= eng._park_threshold
    p0 = eng._swapper._path(0)
    t0 = os.path.getmtime(p0)
    eng.train_batch(batch)
    assert os.path.getmtime(p0) > t0
