"""Dataloader tests — reference test_data.py role."""

import numpy as np

from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader, default_collate)
from tests.simple_model import random_dataset


def test_loader_batches():
    data = random_dataset(n=32, dim=4)
    loader = DeepSpeedDataLoader(data, batch_size=8)
    batches = list(loader)
    assert len(batches) == 4
    x, y = batches[0]
    assert x.shape == (8, 4) and y.shape == (8,)


def test_loader_dp_sharding_disjoint():
    data = random_dataset(n=32, dim=4)
    seen = []
    for rank in range(4):
        loader = DeepSpeedDataLoader(data, batch_size=4,
                                     data_parallel_world_size=4,
                                     data_parallel_rank=rank, shuffle=False)
        for x, y in loader:
            seen.extend(x[:, 0].tolist())
    assert len(seen) == 32
    assert len(set(np.round(seen, 6))) == len(seen)  # disjoint coverage


def test_loader_reshuffles_per_epoch():
    data = random_dataset(n=16, dim=4)
    loader = DeepSpeedDataLoader(data, batch_size=16)
    (x1, _), = list(loader)
    (x2, _), = list(loader)
    assert not np.array_equal(x1, x2)


def test_repeating_loader():
    loader = RepeatingLoader([1, 2, 3])
    out = [next(iter_val) for iter_val, _ in [(loader, i) for i in range(7)]]
    assert out == [1, 2, 3, 1, 2, 3, 1]


def test_default_collate_dict():
    samples = [{"a": np.ones(3), "b": 1} for _ in range(4)]
    batch = default_collate(samples)
    assert batch["a"].shape == (4, 3)
    assert batch["b"].shape == (4,)
