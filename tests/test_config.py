"""Config tests — modeled on the reference's test_config.py/test_ds_config.py
coverage of the batch triangle (config.py:837) and section parsing."""

import json

import pytest

from deepspeed_tpu.config.config import DeepSpeedConfig, DeepSpeedConfigError


def test_batch_triangle_all_given():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 8,
    }, world_size=1)
    assert cfg.train_batch_size == 32


def test_batch_triangle_infers_gas():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "train_micro_batch_size_per_gpu": 4,
    }, world_size=2)
    assert cfg.gradient_accumulation_steps == 4


def test_batch_triangle_infers_micro():
    cfg = DeepSpeedConfig({
        "train_batch_size": 32,
        "gradient_accumulation_steps": 4,
    }, world_size=2)
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_triangle_infers_train_batch():
    cfg = DeepSpeedConfig({
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 2,
    }, world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_triangle_only_train_batch():
    cfg = DeepSpeedConfig({"train_batch_size": 16}, world_size=4)
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 1


def test_batch_triangle_mismatch_raises():
    with pytest.raises(AssertionError):
        DeepSpeedConfig({
            "train_batch_size": 33,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 8,
        }, world_size=1)


def test_batch_none_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({}, world_size=1)


def test_zero_section():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage": 2, "reduce_bucket_size": 1000,
                              "offload_optimizer": {"device": "cpu"}},
    }, world_size=1)
    assert cfg.zero_enabled
    assert cfg.zero_optimization_stage == 2
    assert cfg.zero_config.reduce_bucket_size == 1000
    assert cfg.zero_config.offload_optimizer.enabled
    assert cfg.zero_config.cpu_offload


def test_zero_legacy_bool():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "zero_optimization": True},
                          world_size=1)
    assert cfg.zero_optimization_stage == 1


def test_zero_invalid_stage():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "zero_optimization": {"stage": 5}}, world_size=1)


def test_fp16_bf16_exclusive():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "fp16": {"enabled": True},
                         "bf16": {"enabled": True}}, world_size=1)


def test_fp16_section_defaults():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "fp16": {"enabled": True}},
                          world_size=1)
    assert cfg.fp16_enabled
    assert cfg.initial_scale_power == 32
    assert cfg.loss_scale_window == 1000


def test_precision_key():
    cfg = DeepSpeedConfig({"train_batch_size": 8, "precision": "bfloat16"},
                          world_size=1)
    assert cfg.bf16_enabled and not cfg.fp16_enabled


def test_optimizer_scheduler_sections():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 0.001, "betas": [0.8, 0.99]}},
        "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
    }, world_size=1)
    assert cfg.optimizer_name == "adam"
    assert cfg.optimizer_params["betas"] == [0.8, 0.99]
    assert cfg.scheduler_name == "WarmupLR"


def test_config_from_json_file(tmp_path):
    path = tmp_path / "ds_config.json"
    path.write_text(json.dumps({"train_batch_size": 8}))
    cfg = DeepSpeedConfig(str(path), world_size=1)
    assert cfg.train_batch_size == 8


def test_config_from_json_string():
    cfg = DeepSpeedConfig('{"train_batch_size": 8}', world_size=1)
    assert cfg.train_batch_size == 8


def test_sparse_attention_section():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "sparse_attention": {"mode": "bigbird", "block": 32,
                             "num_random_blocks": 2},
    }, world_size=1)
    sa = cfg.sparse_attention_config
    assert sa.enabled and sa.mode == "bigbird" and sa.block == 32
    assert sa.num_random_blocks == 2


def test_micro_batch_per_chip_alias():
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_chip": 4}, world_size=2)
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.train_batch_size == 8


def test_comm_hierarchy_section():
    from deepspeed_tpu.config.config import DeepSpeedConfig
    # absent block -> disabled, defaults resolved
    cfg = DeepSpeedConfig({"train_batch_size": 8})
    h = cfg.comm_config.hierarchy
    assert not h.enabled and h.slow_axis == 0 and h.compression == "auto"
    # presence enables; "auto" aliases slow_axis 0
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "comm": {"hierarchy": {"slow_axis": "auto"}}})
    h = cfg.comm_config.hierarchy
    assert h.enabled and h.slow_axis == 0
    assert h.min_bucket_bytes == 1 << 16
    # explicit knobs
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "comm": {"hierarchy": {"enabled": True, "slow_axis": 2,
                               "compression": "always",
                               "min_bucket_bytes": 4096}}})
    h = cfg.comm_config.hierarchy
    assert (h.slow_axis, h.compression, h.min_bucket_bytes) \
        == (2, "always", 4096)


def test_comm_hierarchy_validation_errors():
    import pytest
    from deepspeed_tpu.config.config import (DeepSpeedConfig,
                                             DeepSpeedConfigError)
    base = {"train_batch_size": 8}
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base, "comm": {"hierarchy": {"slow_axis": 1}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base,
                         "comm": {"hierarchy": {"compression": "maybe"}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base, "comm": {"hierarchy":
                                          {"min_bucket_bytes": -1}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base, "comm": {"hierarchy": "yes"}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base,
                         "comm": {"hierarchy": {"slow_axis": "fast"}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base, "comm": {"hierarchy":
                                          {"min_bucket_bytes": "64k"}}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({**base, "comm": []})


def test_serving_disaggregation_section():
    from deepspeed_tpu.config.config import ServingConfig
    sc = ServingConfig({"serving": {"disaggregation": {
        "prefill_replicas": 2, "decode_replicas": 3,
        "dedupe_pages": False}}})
    dg = sc.disaggregation
    assert dg.enabled and dg.prefill_replicas == 2
    assert dg.decode_replicas == 3 and not dg.dedupe_pages
    assert dg.transport == "inproc"
    # absent block: disabled, colocated defaults
    off = ServingConfig({"serving": {}}).disaggregation
    assert not off.enabled
    # decode_replicas 0 is the documented colocated fallback
    colo = ServingConfig({"serving": {"disaggregation": {
        "decode_replicas": 0}}}).disaggregation
    assert colo.enabled and colo.decode_replicas == 0


def test_serving_disaggregation_validation_errors():
    from deepspeed_tpu.config.config import ServingConfig

    def cfg(d):
        return ServingConfig({"serving": {"disaggregation": d}})

    with pytest.raises(DeepSpeedConfigError):
        cfg("prefill")                           # not a dict
    with pytest.raises(DeepSpeedConfigError):
        cfg({"prefill_replicas": 0})             # >= 1
    with pytest.raises(DeepSpeedConfigError):
        cfg({"decode_replicas": -1})             # >= 0
    with pytest.raises(DeepSpeedConfigError):
        cfg({"prefill_replicas": "many"})        # not an int
    with pytest.raises(DeepSpeedConfigError):
        cfg({"transport": "grpc"})               # inproc only (so far)


def test_serving_router_section_and_validation_errors():
    from deepspeed_tpu.config.config import ServingConfig
    rt = ServingConfig({"serving": {"router": {
        "prefix_routing": False, "queue_weight": 2.0,
        "ttft_weight": 0.5, "ttft_window": 8,
        "max_handoff_retries": 1, "decode_tick_cap": 2,
        "max_inflight_pages": 64,
        "decode_schedule": "fifo"}}}).router
    assert not rt.prefix_routing and rt.queue_weight == 2.0
    assert rt.ttft_window == 8 and rt.max_handoff_retries == 1
    assert rt.decode_tick_cap == 2 and rt.max_inflight_pages == 64
    assert rt.decode_schedule == "fifo"
    # defaults without the block
    d = ServingConfig({"serving": {}}).router
    assert d.prefix_routing and d.decode_schedule == "lpt"
    assert d.max_inflight_pages == 0        # 0 = 2x decode pools

    def cfg(r):
        return ServingConfig({"serving": {"router": r}})

    with pytest.raises(DeepSpeedConfigError):
        cfg(["lpt"])                             # not a dict
    with pytest.raises(DeepSpeedConfigError):
        cfg({"ttft_window": 0})                  # >= 1
    with pytest.raises(DeepSpeedConfigError):
        cfg({"queue_weight": "heavy"})           # not a number
    with pytest.raises(DeepSpeedConfigError):
        cfg({"max_handoff_retries": -1})         # >= 0
    with pytest.raises(DeepSpeedConfigError):
        cfg({"decode_tick_cap": 0})              # >= 1
    with pytest.raises(DeepSpeedConfigError):
        cfg({"decode_schedule": "sjf"})          # lpt|fifo
