"""Model e2e regression tier — the reference's tests/model/ role
(Megatron_GPT2/run_func_test.py loss-curve assertions, BingBertSquad
test_e2e_squad.py): each examples/ script runs as a real subprocess with a
tiny config on CPU devices, and the printed loss curve must fall.

Marked with the same pattern as the rest of the suite (CPU devices forced in
the child env, not inherited state), ~1-2 min each.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, *args, devices=8, timeout=240):
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{script} failed rc={proc.returncode}\n--- stdout\n{proc.stdout}"
        f"\n--- stderr\n{proc.stderr[-3000:]}")
    return proc.stdout


def _losses(stdout, script):
    first = re.search(r"first loss: ([\d.]+)", stdout)
    final = re.search(r"final loss[^:]*: ([\d.]+)", stdout)
    assert first and final, f"{script} printed no loss curve:\n{stdout}"
    return float(first.group(1)), float(final.group(1))


def test_example_cifar10():
    out = _run_example("cifar10_train.py", "--steps", "20", devices=1)
    first, final = _losses(out, "cifar10")
    assert final < first, (first, final)


def test_example_gpt2_pretrain_zero2():
    out = _run_example("gpt2_pretrain.py", "--model", "tiny", "--steps", "8",
                       "--batch", "8", "--seq", "64", "--repeat-batch",
                       devices=2)
    first, final = _losses(out, "gpt2_pretrain")
    assert final < first, (first, final)


@pytest.mark.slow
def test_example_gpt2_pipeline():
    out = _run_example("gpt2_pipeline.py", "--steps", "8", "--pipe", "2",
                       "--data", "2", devices=4)
    first, final = _losses(out, "gpt2_pipeline")
    assert final < first, (first, final)


def test_example_bert_squad():
    out = _run_example("bert_squad_finetune.py", "--steps", "8",
                       "--seq", "64", "--repeat-batch", devices=1)
    first, final = _losses(out, "bert_squad")
    assert final < first, (first, final)


@pytest.mark.slow
def test_example_observability_demo(tmp_path):
    """The ISSUE-4 acceptance artifact end to end in a subprocess: a
    20-step run emits the JSONL snapshot stream, the scalar events, the
    Prometheus dump, and a non-empty XLA trace window."""
    out_dir = str(tmp_path / "tel")
    out = _run_example("observability_demo.py", "--out", out_dir,
                       "--steps", "12", devices=1)
    assert os.path.getsize(os.path.join(out_dir,
                                        "telemetry_rank0.jsonl")) > 0
    assert os.path.getsize(os.path.join(out_dir, "metrics.prom")) > 0
    assert any(files for _, _, files
               in os.walk(os.path.join(out_dir, "trace")))
    assert '"train/steps": 12.0' in out
    assert '"train/mfu"' in out and '"train/step_time_s"' in out
    assert '"span/train/forward"' in out   # per-phase span times


def test_example_llama_pretrain():
    out = _run_example("llama_pretrain.py", "--steps", "8", "--batch", "8",
                       "--seq", "64", "--hidden", "128", "--layers", "2",
                       "--heads", "4", "--kv-heads", "2", "--repeat-batch",
                       devices=2)
    first, final = _losses(out, "llama_pretrain")
    assert final < first, (first, final)
