"""Distributed trace plane (ISSUE 19): causal span ids across the
prefill -> transport -> decode -> finish lifecycle, the Perfetto
exporter, and the dump-header provenance stamp.

The loopback legs run the REAL node state machines (the same ones the
2-process acceptance drives) in one process, so tier-1 pins the causal
tree — every ``parent_span`` in a complete dump set resolves to some
event's ``span_id``, zero orphans — without paying a process spawn.
"""

import json

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu.serving as serving
from deepspeed_tpu.serving.engine import ContinuousBatcher
from deepspeed_tpu.telemetry.perfetto import export, orphan_spans
from deepspeed_tpu.telemetry.recorder import default_recorder
from deepspeed_tpu.telemetry.spans import new_span_id


@pytest.fixture(autouse=True)
def _clean():
    default_recorder().configure(enabled=True, capacity=4096)
    default_recorder().clear()
    yield


@pytest.fixture(scope="module")
def gpt2_adapter():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config(vocab_size=256, n_positions=128, n_embd=64,
                     n_layer=2, n_head=4, dtype=jnp.float32,
                     param_dtype=jnp.float32, scan_layers=True)
    params = jax.jit(GPT2LMHeadModel(cfg).init)(
        jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))["params"]
    return serving.build_engine(
        "gpt2", cfg, params,
        config={"serving": {"slots": 2, "page_size": 8,
                            "max_pages_per_slot": 8}}).adapter


def _reqs(n, max_new=4, seed=0):
    rs = np.random.RandomState(seed)
    lens = rs.choice([5, 9, 14], n)
    return [serving.Request(
        i, rs.randint(0, 256, size=(int(lens[i]),)).astype(np.int32),
        max_new_tokens=max_new) for i in range(n)]


def _mk_loopback(adapter, world=2):
    from deepspeed_tpu.serving.transport import (DecodeNode,
                                                 LoopbackFabric,
                                                 PrefillNode)
    fab = LoopbackFabric(world, addressing="targeted")
    pnode = PrefillNode(
        [ContinuousBatcher(adapter, role="prefill")], fab.endpoint(0))
    dnodes = [DecodeNode(ContinuousBatcher(adapter, role="decode",
                                           prefix_cache=True),
                         fab.endpoint(r)) for r in range(1, world)]
    pnode.on_tick = lambda _n: [d.tick() for d in dnodes]
    return pnode, dnodes


# ------------------------------------------------------------ span ids


def test_span_ids_unique_and_process_prefixed():
    ids = [new_span_id() for _ in range(500)]
    assert len(set(ids)) == 500
    # one shared process prefix, monotone suffixes — merged dumps from
    # DIFFERENT processes cannot collide (prefix carries the pid +
    # a random nonce), ids within one process never repeat
    prefixes = {i.rsplit("-", 1)[0] for i in ids}
    assert len(prefixes) == 1


def test_ensure_trace_id_mints_root_span_once():
    from deepspeed_tpu.serving.engine import ensure_trace_id
    req = serving.Request(0, np.arange(5, dtype=np.int32),
                          max_new_tokens=2)
    ensure_trace_id(req)
    first = (req.trace_id, req.span_id)
    assert req.span_id is not None
    ensure_trace_id(req)
    assert (req.trace_id, req.span_id) == first


def test_span_id_rides_the_wire_doc():
    from deepspeed_tpu.serving import elastic
    from deepspeed_tpu.serving.engine import ensure_trace_id
    req = serving.Request(7, np.arange(9, dtype=np.int32),
                          max_new_tokens=3)
    ensure_trace_id(req)
    doc = elastic._req_doc(req)
    assert doc["span_id"] == req.span_id
    back = elastic.resume_request(json.loads(json.dumps(doc)))
    assert back.span_id == req.span_id
    assert back.trace_id == req.trace_id


# ------------------------------------------- causal tree, zero orphans


def test_loopback_causal_tree_zero_orphans(gpt2_adapter):
    """THE acceptance pin, loopback form: serve through the real
    handoff path and every handoff renders as one causal tree under
    its trace_id — every parent_span resolves, the chain admit(root)
    -> handoff_out -> transport_encode -> handoff_in is parented
    exactly, and finish parents on the root."""
    pnode, _dnodes = _mk_loopback(gpt2_adapter, world=3)
    done = pnode.serve(_reqs(8, max_new=4), max_ticks=5000)
    assert len(done) == 8 and not pnode.lost
    events = default_recorder().events()
    assert orphan_spans(events) == []

    by_id = {ev["span_id"]: ev for ev in events
             if ev.get("span_id") is not None}
    roots = {ev["rid"]: ev["span_id"] for ev in events
             if ev.get("kind") == "admit"
             and ev.get("span_id") is not None}
    assert len(roots) == 8
    # admit is the ROOT: no parent
    for ev in events:
        if ev.get("kind") == "admit":
            assert ev.get("parent_span") is None
    hops = 0
    for ev in events:
        kind = ev.get("kind")
        if kind == "handoff_out":
            assert ev["parent_span"] == roots[ev["rid"]], ev
        elif kind == "transport_encode":
            parent = by_id[ev["parent_span"]]
            assert parent["kind"] == "handoff_out", parent
        elif kind == "handoff_in":
            hops += 1
            # walk up: encode -> handoff_out -> root
            enc = by_id[ev["parent_span"]]
            assert enc["kind"] == "transport_encode"
            out = by_id[enc["parent_span"]]
            assert out["kind"] == "handoff_out"
            assert out["parent_span"] == roots[ev["rid"]]
        elif kind == "finish":
            assert ev["parent_span"] == roots[ev["rid"]], ev
    assert hops >= 8


def test_orphan_spans_flags_missing_parent():
    events = [
        {"kind": "admit", "span_id": "a-1", "rid": 0},
        {"kind": "handoff_out", "span_id": "a-2", "parent_span": "a-1",
         "rid": 0},
        {"kind": "handoff_in", "span_id": "b-1", "parent_span": "a-9",
         "rid": 0},
    ]
    bad = orphan_spans(events)
    assert [o["parent_span"] for o in bad] == ["a-9"]
    events.append({"kind": "transport_encode", "span_id": "a-9"})
    assert orphan_spans(events) == []


# ---------------------------------------------- ttft segments (sat. 4)


def test_loopback_ttft_segments_sum_to_ttft(gpt2_adapter):
    """Per-role TTFT attribution stays sound through the transport
    path: on the prefill role, queue_wait + prefill account for
    ttft_s (the only gap is the sub-ms admit bookkeeping between the
    two timers)."""
    pnode, _dnodes = _mk_loopback(gpt2_adapter, world=2)
    done = pnode.serve(_reqs(10, max_new=3, seed=2), max_ticks=5000)
    assert len(done) == 10
    reg = pnode.engines[0].metrics
    ttft = reg.peek_histogram_values("serving/ttft_s")
    qw = reg.peek_histogram_values("serving/ttft_queue_wait_s")
    pf = reg.peek_histogram_values("serving/ttft_prefill_s")
    assert len(ttft) == len(qw) == len(pf) == 10
    gap = sum(ttft) - (sum(qw) + sum(pf))
    assert 0.0 <= gap <= 0.05 + 0.02 * sum(ttft), \
        (sum(ttft), sum(qw), sum(pf))
    # per-request decomposition, paired by rid through the ring: the
    # admit event's wait_s + the prefill event's prefill_s account for
    # that request's ttft_s up to the admit-bookkeeping sliver
    waits = {ev["rid"]: ev["wait_s"]
             for ev in default_recorder().events()
             if ev.get("kind") == "admit"}
    n = 0
    for ev in default_recorder().events():
        if ev.get("kind") != "prefill":
            continue
        n += 1
        seg = waits[ev["rid"]] + ev["prefill_s"]
        assert seg <= ev["ttft_s"] + 1e-6, ev
        assert ev["ttft_s"] - seg <= 0.01 + 0.1 * ev["ttft_s"], ev
    assert n == 10


# --------------------------------------------------- perfetto exporter


def _golden_dumps(tmp_path):
    """Two synthetic per-rank dumps with fixed timestamps — the same
    shape the CI golden uses (ci/make_perfetto_golden.py)."""
    r0 = [
        {"kind": "dump_header", "rule": "worker_exit", "dump_id": 1,
         "source": "rank0e0", "ts": 100.0,
         "provenance": {"git_sha": "abc1234", "hostname": "hostA"},
         "restart_epoch": 0},
        {"ts": 100.0, "kind": "admit", "rid": 0, "trace": "t0",
         "replica": 0, "span_id": "p0-1", "seq": 1},
        {"ts": 100.2, "kind": "prefill", "rid": 0, "trace": "t0",
         "replica": 0, "prefill_s": 0.15, "span_id": "p0-2",
         "parent_span": "p0-1", "seq": 2},
        {"ts": 100.3, "kind": "handoff_out", "rid": 0, "trace": "t0",
         "replica": 0, "span_id": "p0-3", "parent_span": "p0-1",
         "seq": 3},
        {"ts": 100.31, "kind": "transport_encode", "rid": 0,
         "trace": "t0", "dst": 1, "nbytes": 4096, "dur_s": 0.01,
         "span_id": "p0-4", "parent_span": "p0-3", "seq": 4},
        {"ts": 100.9, "kind": "finish", "rid": 0, "trace": "t0",
         "replica": 0, "reason": "length", "span_id": "p0-5",
         "parent_span": "p0-1", "seq": 5},
    ]
    r1 = [
        {"kind": "dump_header", "rule": "worker_exit", "dump_id": 1,
         "source": "rank1e0", "ts": 100.0,
         "provenance": {"git_sha": "abc1234", "hostname": "hostA"},
         "restart_epoch": 0},
        {"ts": 100.4, "kind": "handoff_in", "rid": 0, "trace": "t0",
         "replica": 0, "span_id": "d1-1", "parent_span": "p0-4",
         "seq": 1},
        {"ts": 100.5, "kind": "tick", "steps": 1, "active": 1,
         "tick_s": 0.05, "replica": 0, "seq": 2},
    ]
    paths = []
    for name, evs in (("r0.jsonl", r0), ("r1.jsonl", r1)):
        p = tmp_path / name
        p.write_text("\n".join(json.dumps(e) for e in evs) + "\n")
        paths.append(str(p))
    return paths


def test_perfetto_export_processes_slices_and_flows(tmp_path):
    paths = _golden_dumps(tmp_path)
    doc = export(paths)
    evs = doc["traceEvents"]
    # ranks as processes, named with provenance
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e["ph"] == "M" and e["name"] == "process_name"}
    assert pnames == {0: "rank 0 hostA abc1234",
                      1: "rank 1 hostA abc1234"}
    # duration events became complete slices with recorder-end
    # timestamps shifted back by their duration
    slices = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(slices) == {"prefill", "transport_encode", "tick"}
    assert slices["prefill"]["dur"] == 150000.0
    assert slices["prefill"]["ts"] == pytest.approx(
        (100.2 - 0.15 - 100.0) * 1e6)
    # one flow arrow out of rank 0 into rank 1
    s = [e for e in evs if e["ph"] == "s"]
    f = [e for e in evs if e["ph"] == "f"]
    assert len(s) == len(f) == 1
    assert s[0]["id"] == f[0]["id"]
    assert s[0]["pid"] == 0 and f[0]["pid"] == 1
    # span identity rides in args
    admits = [e for e in evs if e["ph"] == "i" and e["name"] == "admit"]
    assert admits[0]["args"]["span_id"] == "p0-1"
    # zero orphans across the merged pair
    merged = []
    for p in paths:
        with open(p) as fh:
            merged += [json.loads(l) for l in fh if l.strip()]
    assert orphan_spans(
        [e for e in merged if e.get("kind") != "dump_header"]) == []


def test_perfetto_export_is_deterministic(tmp_path):
    from deepspeed_tpu.telemetry import perfetto
    paths = _golden_dumps(tmp_path)
    assert perfetto.dumps(export(paths)) == perfetto.dumps(export(paths))


def test_view_cli_perfetto_format(tmp_path):
    from deepspeed_tpu.telemetry import view
    paths = _golden_dumps(tmp_path)
    out = tmp_path / "trace.json"
    rc = view.main(paths + ["--format", "perfetto", "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["displayTimeUnit"] == "ms"
    assert any(e["ph"] == "X" for e in doc["traceEvents"])


# ------------------------------- transport SLO feed + fabric health


def test_loopback_slo_feed_exports_gauges(gpt2_adapter):
    """The transport-level wiring: a PrefillNode with an attached SLO
    plane feeds its own TTFT segments (role prefill) and the decode
    ranks' exchanged MV_TICK_S (role decode) once per exchange, and
    the windowed ``slo/*`` gauges land on the rank-0 registry."""
    from deepspeed_tpu.telemetry.slo import SloPlane
    pnode, _dnodes = _mk_loopback(gpt2_adapter, world=2)
    pnode.slo = SloPlane(min_samples=1)
    done = pnode.serve(_reqs(6, max_new=4, seed=3), max_ticks=5000)
    assert len(done) == 6
    reg = pnode.metrics
    assert reg.peek_gauge("slo/window_s") == pnode.slo.window_s
    assert reg.peek_gauge("slo/prefill/ttft_s/samples") >= 6
    assert reg.peek_gauge("slo/prefill/queue_wait_s/samples") >= 6
    assert reg.peek_gauge("slo/prefill/transport_s/samples") >= 6
    assert reg.peek_gauge("slo/decode/tick_s/samples") >= 1
    assert reg.peek_gauge("slo/prefill/ttft_s/burn_rate") is not None
    # and the recommendation derives purely from those gauges
    from deepspeed_tpu.telemetry.slo import roles_signal
    assert set(roles_signal(reg, min_samples=1)) == {"decode",
                                                     "prefill"}


def test_peer_fabric_liveness_doc():
    from deepspeed_tpu.utils.distributed import PeerFabric
    fab = object.__new__(PeerFabric)    # no collective construction
    fab.rank, fab.world = 0, 3
    fab._out, fab._in = {1: object()}, {}
    fab.last_send_ts, fab.last_recv_ts = {1: 0.0}, {}
    doc = fab.liveness()
    assert doc["rank"] == 0 and doc["world"] == 3
    assert set(doc["peers"]) == {"1", "2"}
    p1 = doc["peers"]["1"]
    assert p1["out_connected"] and not p1["in_connected"]
    assert p1["last_send_age_s"] > 0
    assert p1["last_recv_age_s"] is None
    assert doc["peers"]["2"] == {"out_connected": False,
                                 "in_connected": False,
                                 "last_send_age_s": None,
                                 "last_recv_age_s": None}


def test_healthz_reports_fabric_liveness():
    """Satellite 2 end-to-end: /healthz carries the targeted-fabric
    doc through the endpoint's ``fabric_health`` hook (pre-build here
    — the single-process shape; the per-peer form is pinned above)."""
    import urllib.request
    from deepspeed_tpu.serving.transport import ProcessEndpoint
    from deepspeed_tpu.telemetry.serve import MetricsServer
    ep = ProcessEndpoint(addressing="targeted")
    srv = MetricsServer(0, registry=None,
                        extra_health_fn=ep.fabric_health).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz") as r:
            doc = json.loads(r.read())
    finally:
        srv.stop()
    assert doc["ok"] is True
    assert doc["fabric"] == {"built": False, "addressing": "targeted"}


# ------------------------------------------- dump-header provenance


def test_watchdog_dump_header_carries_provenance(tmp_path, monkeypatch):
    from deepspeed_tpu.telemetry.anomaly import Watchdog
    from deepspeed_tpu.telemetry.recorder import FlightRecorder
    from deepspeed_tpu.telemetry.registry import MetricsRegistry
    monkeypatch.setenv("DSTPU_RESTART_EPOCH", "3")
    rec = FlightRecorder()
    rec.record("admit", rid=0)
    wd = Watchdog(str(tmp_path), recorder=rec,
                  registry=MetricsRegistry(), source="rank0e3")
    path = wd.force_dump("unit")
    with open(path) as fh:
        header = json.loads(fh.readline())
    assert header["kind"] == "dump_header"
    assert header["restart_epoch"] == 3
    prov = header["provenance"]
    # the full stamp shape, whichever path (bench.provenance or the
    # inline fallback) produced it
    assert set(prov) >= {"git_sha", "hostname", "python_version"}
    assert prov["hostname"]
